"""Production control plane (this PR): coordinator lease failover +
the closed-loop autoscaler (balance/control_plane.py,
balance/autoscaler.py).

Unit tier: the succession rule and term fencing, the MINIPS_AUTOSCALE
spec parser, the autoscaler's hysteresis/cool-down state machine
against fakes, the multi-entry (and rank-0-targeting) MINIPS_CHAOS_KILL
grammar, heartbeat lease stamps, and the stale-ex-coordinator plan
fence over a real loopback bus.

Drill tier:

- FAILOVER (fast): a 3-proc SSP run with the seeded SIGKILL aimed at
  RANK 0 (the lease holder) completes — rank 1 takes the lease exactly
  once, issues the old holder's death plan, the corpse's ranges restore
  from the elastic checkpoint, no step is lost, survivors bitwise-agree.
- CLOSED LOOP (slow): storm → shed → autoscaler admits the standby
  (heat-aware placement) → sheds fall → rank 0 SIGKILLed → successor
  keeps the loop → traffic ebbs → the autoscaler drains its own growth
  → survivors finish with bitwise agreement.
- BITWISE (in-proc lockstep): MINIPS_AUTOSCALE armed on a calm run is
  bitwise-equal to off (hysteresis idle, zero membership changes).
"""

from __future__ import annotations

import sys
import time

import numpy as np
import pytest

from minips_tpu import launch
from minips_tpu.balance.autoscaler import AutoscaleConfig, Autoscaler
from minips_tpu.balance.control_plane import (CoordinatorLease,
                                              successor_of)
from minips_tpu.comm.chaos import KillSpec

APP = "minips_tpu.apps.sharded_ps_example"


# ------------------------------------------------------------- the lease
def test_successor_rule_is_lowest_live_rank():
    assert successor_of({3, 1, 2}) == 1
    assert successor_of({5}) == 5
    assert successor_of(set()) is None


def test_lease_succession_advances_term_once_and_is_idempotent():
    lease = CoordinatorLease(0)
    assert lease.current() == (0, 0)
    assert lease.succeed(0, {1, 2, 3}) == 1
    assert lease.current() == (1, 1)
    # a second verdict against the OLD holder (raced from another
    # thread's view) is a no-op: the lease already moved on
    assert lease.succeed(0, {1, 2, 3}) == 1
    assert lease.current() == (1, 1)
    assert lease.successions == 1
    # chain: the successor itself dies
    assert lease.succeed(1, {2, 3}) == 2
    assert lease.current() == (2, 2)
    # nobody left: genuinely unrecoverable
    assert lease.succeed(2, set()) is None


def test_lease_fences_stale_terms_and_observes_newer():
    lease = CoordinatorLease(0)
    assert lease.admit({})                       # unstamped: pass
    assert lease.admit({"lt": 0, "lh": 0})       # current term: pass
    assert lease.observe({"lt": 2, "lh": 1})     # newer term learned
    assert lease.current() == (2, 1)
    assert not lease.observe({"lt": 1, "lh": 0})  # older: ignored
    assert lease.current() == (2, 1)
    assert not lease.admit({"lt": 1, "lh": 0})   # stale term: fenced
    assert not lease.admit({"lt": 0})
    assert lease.fenced == 2
    assert lease.admit({"lt": 2})                # current again: pass


# ----------------------------------------------------- MINIPS_AUTOSCALE
def test_autoscale_config_parses_and_rejects_garbage():
    c = AutoscaleConfig.parse("1")
    assert c.up_shed == 1.0 and c.up_after == 2 and c.cool == 4
    c = AutoscaleConfig.parse(
        "up_shed=8,up_p99_ms=50,imb=2.0,up_after=3,down_after=9,"
        "cool=5,max_live=6")
    assert (c.up_shed, c.up_p99_ms, c.imb) == (8.0, 50.0, 2.0)
    assert (c.up_after, c.down_after, c.cool, c.max_live) == (3, 9, 5, 6)
    with pytest.raises(ValueError, match="unknown knob"):
        AutoscaleConfig.parse("explode=1")
    with pytest.raises(ValueError, match="k=v"):
        AutoscaleConfig.parse("up_shed")
    with pytest.raises(ValueError, match="bad value"):
        AutoscaleConfig.parse("up_shed=abc")
    with pytest.raises(ValueError, match="up_shed"):
        AutoscaleConfig.parse("up_shed=0")
    with pytest.raises(ValueError, match="streak"):
        AutoscaleConfig.parse("up_after=0")
    with pytest.raises(ValueError, match="max/mean"):
        AutoscaleConfig.parse("imb=0.5")


# ------------------------------------------------ MINIPS_CHAOS_KILL list
def test_kill_spec_accepts_rank0_and_entry_lists():
    # rank 0 — the lease holder — is a legal seeded-kill target now
    ks = KillSpec.parse("7:rank=0,step=12")
    assert ks.resolve(3) == (0, 12)
    # multi-entry: each rank= opens an entry, its step= binds to it
    ks2 = KillSpec.parse("7:rank=0,step=12,rank=2,step=20-25")
    assert ks2.resolve(3) == (0, 12)  # first-entry view unchanged
    all3 = ks2.resolve_all(3)
    assert all3[0] == (0, 12)
    r, s = all3[1]
    assert r == 2 and 20 <= s <= 25
    assert ks2.resolve_all(3) == ks2.resolve_all(3)  # deterministic
    # entry 0 draws from the exact pre-list rng stream: a committed
    # single-kill spec's verdict cannot move under the new grammar
    old = KillSpec.parse("77:rank=-1,step=10-20").resolve(3)
    new = KillSpec.parse("77:rank=-1,step=10-20,rank=1,step=5"
                         ).resolve_all(3)[0]
    assert old == new
    with pytest.raises(ValueError, match="both"):
        KillSpec.parse("1:rank=1,rank=2,step=3")  # entry 1 lacks step
    with pytest.raises(ValueError, match="both"):
        KillSpec.parse("1:step=3")  # step before any rank


# ------------------------------------------------- autoscaler state machine
class _FakeLease:
    def current(self):
        return (0, 0)

    def stamp(self):
        return {"lt": 0, "lh": 0}


class _FakeMB:
    def __init__(self, live):
        self._live = set(live)
        self.coord = 0
        self.hold_joins = False
        self.lease = _FakeLease()
        self.pending = 1
        self.credits = 0

    def live_view(self):
        return set(self._live)

    def pending_joins(self):
        return self.pending

    def grant_join(self):
        self.credits += 1


class _FakeRB:
    def __init__(self):
        self.reports = {}

    def heat_reports(self, name):
        return {r: dict(rep) for r, rep in self.reports.items()}


class _FakeBus:
    my_id = 0

    def __init__(self):
        self.sent = []

    def send(self, to, kind, payload):
        self.sent.append((int(to), kind))


class _FakeTrainer:
    def __init__(self):
        self.tables = {"w": None}
        self.rebalancer = _FakeRB()
        self.bus = _FakeBus()


def _mk_autoscaler(spec: str):
    tr = _FakeTrainer()
    mb = _FakeMB({0, 1, 2})
    a = Autoscaler(tr, mb, AutoscaleConfig.parse(spec))
    return tr, mb, a


def _feed(tr, shed_total: float) -> None:
    tr.rebalancer.reports = {
        r: {"total": 10.0, "sv": {"shed": shed_total}} for r in (0, 1, 2)}


def test_autoscaler_hysteresis_admits_then_drains_grown_rank():
    tr, mb, a = _mk_autoscaler(
        "up_shed=5,up_after=2,down_after=3,cool=1")
    assert mb.hold_joins  # construction arms the membership hold
    _feed(tr, 0.0)
    a.on_tick()           # baseline observation: no delta, calm
    assert a.counters["admits"] == 0
    _feed(tr, 10.0)
    a.on_tick()           # +30 sheds fleet-wide: hot tick 1 — no flap
    assert mb.credits == 0
    _feed(tr, 20.0)
    a.on_tick()           # hot tick 2: the admit fires
    assert mb.credits == 1 and a.counters["admits"] == 1
    assert a.shed_rate_pre == 30.0
    mb._live.add(3)       # the membership plane admits rank 3
    _feed(tr, 30.0)
    a.on_tick()           # cool-down tick: still +30, recorded not acted
    assert mb.credits == 1
    assert a.shed_rate_post is None  # no drain yet: no post evidence
    for _ in range(3):    # sheds flat: calm streak
        a.on_tick()
    # down_after=3 calm ticks: drain the GROWN rank (3), never 0-2
    assert tr.bus.sent == [(3, "mbDr")]
    assert a.counters["drains"] == 1
    # the loop's evidence pair: pressure forced the admit, measured
    # calm preceded the drain — post strictly below pre by construction
    assert a.shed_rate_post == 0.0
    assert a.shed_rate_post < a.shed_rate_pre


def test_autoscaler_never_drains_initial_fleet_or_coordinator():
    tr, mb, a = _mk_autoscaler("up_shed=5,up_after=1,down_after=1,cool=0")
    _feed(tr, 0.0)
    for _ in range(5):
        a.on_tick()  # calm forever: nothing grown, nothing to drain
    assert tr.bus.sent == [] and a.counters["drains"] == 0


def test_autoscaler_only_acts_on_the_lease_holder():
    tr, mb, a = _mk_autoscaler("up_shed=5,up_after=1,cool=0")
    mb.coord = 1  # somebody else holds the lease
    _feed(tr, 0.0)
    a.on_tick()
    _feed(tr, 50.0)
    a.on_tick()
    assert mb.credits == 0 and a.counters["admits"] == 0


def test_autoscaler_respects_max_live_and_empty_queue():
    tr, mb, a = _mk_autoscaler("up_shed=5,up_after=1,cool=0,max_live=3")
    _feed(tr, 0.0)
    a.on_tick()
    _feed(tr, 50.0)
    a.on_tick()
    assert mb.credits == 0  # 3 live already: the cap holds
    tr2, mb2, a2 = _mk_autoscaler("up_shed=5,up_after=1,cool=0")
    mb2.pending = 0
    _feed(tr2, 0.0)
    a2.on_tick()
    _feed(tr2, 50.0)
    a2.on_tick()
    assert mb2.credits == 0  # hot with nobody to admit: no flap


# ------------------------------------------------ the fences, on a real bus
def _mk_lockstep_pair(elastic="1", autoscale=""):
    from tests.conftest import mk_loopback_buses

    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)

    buses = mk_loopback_buses(2)
    tables = [ShardedTable("t", 64, 2, buses[i], i, 2, updater="sgd",
                           lr=0.5, pull_timeout=20.0)
              for i in range(2)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], 2,
                                 staleness=0, gate_timeout=30.0,
                                 rebalance="", serve="",
                                 elastic=elastic, autoscale=autoscale)
                for i in range(2)]
    return buses, tables, trainers


def test_stale_ex_coordinator_plan_is_fenced_by_lease_term():
    """THE fence drill: rank 1 has moved to lease term 1 (a partition
    healed after succession); ex-coordinator rank 0, still on term 0,
    broadcasts a plan — rank 1 must drop it unadopted and count it."""
    buses, tables, trainers = _mk_lockstep_pair()
    try:
        mb1 = trainers[1].membership
        assert mb1.lease.observe({"lt": 1, "lh": 1})
        mb1._retarget(1)
        assert mb1.coord == 1
        rb0 = trainers[0].rebalancer
        rb1 = trainers[1].rebalancer
        ep0 = tables[0].router.epoch
        rb0.issue_plan("t", ep0 + 1, {0: 1})  # stamped lt=0: stale
        deadline = time.monotonic() + 5.0
        while rb1.stale_plans_fenced < 1:
            assert time.monotonic() < deadline, "fence never counted"
            time.sleep(0.01)
        assert not rb1.has_pending("t")        # never staged
        assert tables[1].router.epoch == ep0   # never adopted
        assert mb1.lease.stats()["fenced"] >= 1
    finally:
        for b in buses:
            b.close()


def test_lease_beat_retargets_coordinator_and_self_fences():
    """The partition-return self fence: an (ex-)coordinator that hears
    a newer term on a heartbeat stamp stops being the coordinator in
    its own view — _coord_step's rank!=coord guard disarms it."""
    buses, tables, trainers = _mk_lockstep_pair()
    try:
        mb0 = trainers[0].membership
        assert mb0.coord == 0 and mb0.rank == 0
        mb0._on_lease_beat(1, {"t": 0.0, "lt": 3, "lh": 1})
        assert mb0.coord == 1
        assert trainers[0].rebalancer.coord == 1
        assert mb0.lease.current() == (3, 1)
    finally:
        for b in buses:
            b.close()


def test_heartbeat_stall_knob_parses_and_forgives(monkeypatch):
    """Observer-stall forgiveness (MINIPS_HEARTBEAT stall=): a monitor
    whose own sweep gapped longer than the stall budget was in a coma
    and cannot date peer silence — it re-baselines instead of
    convicting; a REAL death is re-detected one timeout after waking."""
    from tests.conftest import mk_loopback_buses

    from minips_tpu.comm.heartbeat import (HeartbeatMonitor,
                                           liveness_knobs, stall_knob)

    monkeypatch.delenv("MINIPS_HEARTBEAT", raising=False)
    assert stall_knob() == 0.0  # off by default
    monkeypatch.setenv("MINIPS_HEARTBEAT",
                       "interval=0.05,timeout=1.0,stall=2.0")
    assert liveness_knobs(0.2, 5.0) == (0.05, 1.0)  # stall is separate
    assert stall_knob() == 2.0
    buses = mk_loopback_buses(2)
    try:
        fake = [0.0]
        mon = HeartbeatMonitor(buses[0], [0, 1], interval=0.05,
                               timeout=1.0, clock=lambda: fake[0])
        assert mon.stall == 2.0
        mon._on_beat(1, {})
        fake[0] = 0.5
        assert mon.check() == set()      # baseline sweep
        fake[0] = 5.5                    # 5s coma: silence 5 > timeout
        assert mon.check() == set()      # ...but gap 5 > stall: forgive
        fake[0] = 5.6
        assert mon.check() == set()      # re-baselined, peer alive
        fake[0] = 6.7                    # regular sweeps, real silence
        assert mon.check() == {1}        # re-detected from the wake-up
    finally:
        for b in buses:
            b.close()


def test_quiesce_releases_unadmitted_standby():
    """mbEnd: a run that finishes CALM (the autoscaler never admitted)
    must release the waiting standby cleanly — without it the orphan
    watches the fleet's heartbeats die and convicts the world."""
    from tests.conftest import mk_loopback_buses

    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)

    buses = mk_loopback_buses(2)
    try:
        tables = [ShardedTable("t", 64, 2, buses[i], i, 2,
                               updater="sgd", pull_timeout=10.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], 2,
                                     staleness=0, rebalance="",
                                     serve="", elastic="live=0")
                    for i in range(2)]
        mb1 = trainers[1].membership
        assert mb1.i_am_standby
        trainers[0].membership.quiesce()  # coordinator finalize
        deadline = time.monotonic() + 5.0
        while not mb1._fleet_done:
            assert time.monotonic() < deadline, "mbEnd never arrived"
            time.sleep(0.01)
        assert mb1.standby_loop(None, timeout=5.0) == -1
    finally:
        for b in buses:
            b.close()


def test_heartbeat_carries_lease_stamp():
    """Satellite wiring: the monitor merges payload_extra into every
    beat and peers' on_beat_extra observes it — the lease's transport."""
    from tests.conftest import mk_loopback_buses

    from minips_tpu.comm.heartbeat import HeartbeatMonitor

    buses = mk_loopback_buses(2)
    seen: list[dict] = []
    try:
        m0 = HeartbeatMonitor(buses[0], [0, 1], interval=0.02,
                              timeout=5.0)
        m1 = HeartbeatMonitor(buses[1], [0, 1], interval=0.02,
                              timeout=5.0)
        m0.payload_extra = lambda: {"lt": 7, "lh": 1}
        m1.on_beat_extra = lambda s, p: seen.append((s, p))
        m0.start()
        deadline = time.monotonic() + 5.0
        while not any(p.get("lt") == 7 for _s, p in seen):
            assert time.monotonic() < deadline, "stamped beat never seen"
            time.sleep(0.01)
        s, p = next((s, p) for s, p in seen if p.get("lt") == 7)
        assert s == 0 and p["lh"] == 1
        m0.stop()
        m1.stop()
    finally:
        for b in buses:
            b.close()


# ----------------------------------------------- in-proc bitwise lockstep
def _lockstep_run(elastic: str, autoscale: str):
    """The armed-idle-vs-off bitwise harness (test_membership pattern):
    2-rank threads-as-nodes BSP with disjoint cross-shard key sets."""
    import threading

    buses, tables, trainers = _mk_lockstep_pair(elastic=elastic,
                                                autoscale=autoscale)
    for t in tables:
        t._w[...] = np.arange(32 * 2, dtype=np.float32
                              ).reshape(32, 2) / 7.0
    keysets = [np.array([33, 40, 33, 47]), np.array([1, 8, 1, 15])]
    errs: list = []
    finals: list = [None, None]

    def worker(r):
        try:
            for _ in range(5):
                rows = tables[r].pull(keysets[r])
                tables[r].push(keysets[r], 0.1 * rows + 1.0)
                trainers[r].tick()
            trainers[r].finalize(timeout=20.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    try:
        ths = [threading.Thread(target=worker, args=(r,))
               for r in (0, 1)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=60.0)
        assert not errs, errs
        assert finals[0] is not None
        np.testing.assert_array_equal(finals[0], finals[1])
        # armed-idle means IDLE: the hysteresis never tripped
        a = trainers[0].autoscaler
        if a is not None:
            st = a.stats()
            assert st["admits"] == 0 and st["drains"] == 0
        return finals[0]
    finally:
        for b in buses:
            b.close()


def test_autoscale_armed_idle_is_bitwise_equal_to_off():
    """Acceptance: MINIPS_AUTOSCALE armed on a calm run is BITWISE
    equal to off — the loop's tax is report fields, never numerics."""
    off = _lockstep_run("1", "")
    on = _lockstep_run("1", "1")
    np.testing.assert_array_equal(off, on)


def test_autoscale_requires_elastic():
    from tests.conftest import mk_loopback_buses

    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)

    buses = mk_loopback_buses(2)
    try:
        t = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd")
        with pytest.raises(ValueError, match="MINIPS_ELASTIC"):
            ShardedPSTrainer({"t": t}, buses[0], 2, rebalance="",
                             serve="", elastic="", autoscale="1")
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------- process drills
def _run_raw(n, extra, env, timeout=200.0):
    return launch.run_local_job_raw(
        n, [sys.executable, "-m", APP] + extra, base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                   **env},
        timeout=timeout, kill_on_failure=False)


BASE = ["--model", "sparse", "--mode", "ssp", "--staleness", "2",
        "--iters", "30", "--batch", "64"]


def test_coordinator_kill_drill_successor_completes(tmp_path):
    """THE failover drill: seeded SIGKILL of RANK 0 — the lease holder
    — at clock 12. Rank 1 succeeds deterministically (term 1, exactly
    once), issues the old holder's death plan, the corpse's ranges
    restore from the elastic checkpoint, both survivors finish all 30
    steps (no step lost) and agree bitwise."""
    ck = str(tmp_path / "ck")
    rc, events = _run_raw(
        3, BASE + ["--checkpoint-dir", ck, "--checkpoint-every", "5"],
        {"MINIPS_ELASTIC": "1",
         "MINIPS_CHAOS_KILL": "7:rank=0,step=12",
         "MINIPS_HEARTBEAT": "interval=0.1,timeout=1.0"})
    dones = {r: ev[-1] for r, ev in enumerate(events)
             if ev and ev[-1].get("event") == "done"}
    assert set(dones) == {1, 2}, (rc, events)
    for d in dones.values():
        assert d["clock"] == 30                  # zero lost steps
        assert d["max_skew_seen"] <= 3           # SSP bound held
        assert d["frames_dropped"] == 0
        assert d["wire_frames_lost"] == 0
        assert np.isfinite(d["loss_last"])
        m = d["membership"]
        assert m["dead"] == [0] and m["live"] == [1, 2]
        # the lease moved exactly once, to the lowest live rank
        assert m["coord"] == 1
        assert m["lease"]["term"] == 1
        assert m["lease"]["holder"] == 1
    # the corpse's ranges restored from the elastic checkpoint
    assert sum(d["membership"]["blocks_restored"]
               for d in dones.values()) >= 1
    sums = {d["param_sum"] for d in dones.values()}
    norms = {d["param_norm"] for d in dones.values()}
    assert len(sums) == 1 and len(norms) == 1, (sums, norms)


@pytest.mark.slow
def test_closed_loop_autoscale_with_coordinator_failover(tmp_path):
    """The ROADMAP's closed-loop acceptance drill, everything composed:
    rank 0 is SIGKILLed early and rank 1 takes the lease → a pull
    storm trips admission shedding → the SUCCESSOR's autoscaler admits
    the standby (heat-aware placement, mbJ re-targeted at the new
    holder) → shed pressure falls → traffic ebbs → the autoscaler
    drains its own growth → survivors finish with no step lost and
    bitwise agreement. Every piece of autoscaler evidence lives on
    rank 1, which survives — killing the holder AFTER the admit would
    bury the admit counter with the corpse."""
    ck = str(tmp_path / "ck")
    iters = 60
    rc, events = _run_raw(
        4, ["--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", str(iters), "--batch", "64",
            "--checkpoint-dir", ck, "--checkpoint-every", "5",
            # rank 1 (the successor) paces the fleet so the serve rate
            # below clears steady traffic on any host speed — only the
            # storm sheds, so calm is CLEAN calm (the rate-sizing
            # lesson: an undersized bucket sheds training pulls and the
            # drain's calm streak never builds). The storm is sized for
            # the POST-KILL fleet: with rank 0 dead only rank 2 storms
            # rank 1 over the wire, so 12 pulls/step against rate=60
            # sheds decisively at any plausible step rate (6/150 let a
            # slow host's 2-trainer storm fit INSIDE the bucket — the
            # run then finished without ever admitting the standby)
            "--slow-rank", "1", "--slow-ms", "15",
            "--storm-from", "14", "--storm-until", "34",
            "--storm-pulls", "12", "--storm-keys", "64"],
        {"MINIPS_ELASTIC": "live=0-2",
         "MINIPS_AUTOSCALE": "up_shed=4,up_after=2,down_after=4,cool=2",
         "MINIPS_SERVE": "rate=60,burst=8,min_heat=1e9",
         "MINIPS_CHAOS_KILL": "7:rank=0,step=8",
         # timeout 6s + observer-stall forgiveness, not the 3-proc
         # drills' bare 1s: the post-kill restore + storm are seconds
         # of CPU-heavy work, and on an oversubscribed (1-core CI)
         # host a starved OBSERVER process must not convict peers of
         # its own coma — observed: 1s split-brained the survivors,
         # 3s and even 6s false-killed the idle standby
         "MINIPS_HEARTBEAT": "interval=0.1,timeout=6.0,stall=2.0"},
        timeout=400.0)
    by_event = {r: (ev[-1] if ev else {}) for r, ev in enumerate(events)}
    dones = {r: d for r, d in by_event.items()
             if d.get("event") == "done"}
    assert set(dones) == {1, 2}, (rc, by_event)
    # the standby was admitted by the autoscaler, then drained by it
    assert by_event[3].get("event") == "drained", by_event[3]
    for r, d in dones.items():
        assert d["clock"] == iters               # no step lost
        assert d["wire_frames_lost"] == 0
        assert np.isfinite(d["loss_last"])
        m = d["membership"]
        assert m["dead"] == [0]                  # the kill landed
        assert m["coord"] == 1                   # the lease moved...
        assert m["lease"]["term"] == 1           # ...exactly once
        assert m["left"] == [3]                  # the drain completed
    # restored ranges: the successor owned the old holder's death
    assert sum(d["membership"]["blocks_restored"]
               for d in dones.values()) >= 1
    # the SUCCESSOR's autoscaler did the whole loop: the storm-window
    # admit (under recorded shed load) and the post-ebb drain
    a1 = dones[1].get("autoscale") or {}
    assert a1.get("admits", 0) >= 1, by_event
    assert a1.get("drains", 0) >= 1, by_event
    assert (a1.get("shed_rate_pre") or 0) > 0, a1
    # shed pressure fell after the admit (heat-aware placement moved
    # the hot range onto the joiner), and p99 recovered once traffic
    # ebbed: the last-observed p99 sits at or under the storm watermark
    if a1.get("shed_rate_post") is not None:
        assert a1["shed_rate_post"] <= a1["shed_rate_pre"], a1
    if a1.get("p99_hot_ms") and a1.get("p99_last_ms") is not None:
        assert a1["p99_last_ms"] <= a1["p99_hot_ms"] * 1.01, a1
    # survivors agree bitwise
    assert len({d["param_sum"] for d in dones.values()}) == 1, dones
