"""Merge per-rank wire traces into ONE cross-rank Chrome trace.

``python -m minips_tpu.obs.merge <dir-or-files...> [-o merged.json]
[--xla <logdir>]``

Three jobs:

1. **Clock alignment.** Every rank stamps events with its own
   ``time.monotonic()``. On one host those clocks share an epoch, but
   the merge must not assume it (multi-host runs, containers with
   per-namespace clocks) — so offsets are ESTIMATED from the heartbeat
   exchange the stack already runs: every rank records an ``hb``
   instant per received beat carrying the sender's send timestamp
   (comm/heartbeat.py). For a rank pair (a, b), with
   ``d_ab = min over a's receipts of (t_recv_a − t_sent_b)`` and the
   symmetric ``d_ba``, the one-way delays cancel:
   ``offset_a − offset_b = (d_ab − d_ba) / 2`` — the classic NTP
   two-sample estimate, min-filtered against scheduling jitter. Rank 0
   is the reference; ranks without bidirectional samples merge with
   offset 0 and a note in the summary.

2. **Flow linking.** The tracer's flow events carry ids both ends
   derived independently (``tracer.flow_id``); the merger counts the
   ids that appear with an 's' phase on one rank and an 'f' phase on
   another — the cross-rank arrows. ``flows_linked`` in the summary is
   what the TRACE-TAX bench gate asserts (>= 1), and per-(src→dst)
   pair counts let the acceptance drill check one flow per remote
   owner.

3. **XLA interleave** (``--xla <logdir>``): the newest
   ``*.trace.json.gz`` the profiler wrote (utils/trace_analysis.py) is
   appended with its pids offset past the rank pids, so device compute
   and wire activity share one timeline. XLA traces carry their own
   epoch; they are shifted so their first event aligns with the first
   wire event — coarse, but the intra-trace timing is what matters.

Exit 0 with a one-line JSON summary on stdout; nonzero when no rank
trace was found or the output could not be written.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from typing import Optional

__all__ = ["load_rank_traces", "estimate_offsets_us", "merge_traces",
           "main"]

# device-trace pids are offset past any plausible rank pid; the report
# uses the same constant to keep XLA processes out of the rank table
XLA_PID_BASE = 10_000


def load_rank_traces(paths: list[str]) -> dict[int, dict]:
    """``{rank: trace doc}`` from explicit files and/or directories
    (directories glob ``trace-rank*.json``)."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "trace-rank*.json"))))
        else:
            files.append(p)
    out: dict[int, dict] = {}
    for f in files:
        with open(f) as fh:
            doc = json.load(fh)
        rank = int((doc.get("otherData") or {}).get("rank", len(out)))
        out[rank] = doc
    return out


def _hb_samples(traces: dict[int, dict]) -> dict[tuple[int, int], float]:
    """``{(receiver, sender): min(t_recv − t_sent) in us}`` over every
    recorded heartbeat receipt."""
    best: dict[tuple[int, int], float] = {}
    for rank, doc in traces.items():
        for e in doc.get("traceEvents", ()):
            if e.get("name") != "hb" or e.get("ph") != "i":
                continue
            a = e.get("args") or {}
            snd = a.get("from")
            t_sent = a.get("t_sent")
            if snd is None or t_sent is None:
                continue
            d = float(e["ts"]) - float(t_sent) * 1e6
            key = (rank, int(snd))
            if key not in best or d < best[key]:
                best[key] = d
    return best


def estimate_offsets_us(traces: dict[int, dict]
                        ) -> tuple[dict[int, float], list[int]]:
    """Per-rank clock offset vs rank 0 (``aligned = ts − offset``), and
    the ranks that lacked bidirectional heartbeat data (offset 0)."""
    ranks = sorted(traces)
    if not ranks:
        return {}, []
    ref = ranks[0]
    best = _hb_samples(traces)
    offsets = {ref: 0.0}
    unaligned: list[int] = []
    for r in ranks:
        if r == ref:
            continue
        d_r_ref = best.get((r, ref))     # ref's beats as seen at r
        d_ref_r = best.get((ref, r))     # r's beats as seen at ref
        if d_r_ref is None or d_ref_r is None:
            offsets[r] = 0.0
            unaligned.append(r)
        else:
            offsets[r] = (d_r_ref - d_ref_r) / 2.0
    return offsets, unaligned


def _link_flows(events: list[dict]) -> tuple[int, dict[str, int]]:
    """Count flow ids seen with 's' on one pid and 'f' on a different
    pid; also per ``"src->dst"`` pair counts."""
    starts: dict[int, set] = defaultdict(set)
    ends: dict[int, set] = defaultdict(set)
    for e in events:
        if e.get("ph") == "s":
            starts[e.get("id")].add(e.get("pid"))
        elif e.get("ph") == "f":
            ends[e.get("id")].add(e.get("pid"))
    linked = 0
    pairs: dict[str, int] = defaultdict(int)
    for fid, spids in starts.items():
        for epid in ends.get(fid, ()):
            for spid in spids:
                if spid != epid:
                    linked += 1
                    pairs[f"{spid}->{epid}"] += 1
    return linked, dict(sorted(pairs.items()))


def _load_xla(logdir: str, t_base_us: float) -> list[dict]:
    from minips_tpu.utils.trace_analysis import latest_trace_file

    import gzip

    path = latest_trace_file(logdir)
    if path is None:
        return []
    with gzip.open(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    t0 = min((float(e["ts"]) for e in events
              if "ts" in e and e.get("ph") != "M"), default=0.0)
    out = []
    for e in events:
        e = dict(e)
        if "pid" in e:
            e["pid"] = XLA_PID_BASE + int(e["pid"])
        if "ts" in e and e.get("ph") != "M":
            e["ts"] = float(e["ts"]) - t0 + t_base_us
        out.append(e)
    return out


def merge_traces(paths: list[str], *, xla_logdir: Optional[str] = None
                 ) -> tuple[dict, dict]:
    """(merged trace doc, summary dict). Raises FileNotFoundError when
    no rank trace exists under ``paths``."""
    traces = load_rank_traces(paths)
    if not traces:
        raise FileNotFoundError(
            f"no trace-rank*.json under {paths!r}")
    offsets, unaligned = estimate_offsets_us(traces)
    merged: list[dict] = []
    for rank, doc in sorted(traces.items()):
        off = offsets.get(rank, 0.0)
        for e in doc.get("traceEvents", ()):
            if "ts" in e and e.get("ph") != "M":
                e = dict(e)
                e["ts"] = round(float(e["ts"]) - off, 3)
            merged.append(e)
    linked, pairs = _link_flows(merged)
    t_base = min((float(e["ts"]) for e in merged
                  if "ts" in e and e.get("ph") != "M"), default=0.0)
    xla_events = 0
    if xla_logdir:
        xe = _load_xla(xla_logdir, t_base)
        xla_events = len(xe)
        merged.extend(xe)
    summary = {
        "ranks": sorted(traces),
        "events": sum(len(d.get("traceEvents", ())) for d in
                      traces.values()),
        "flows_linked": linked,
        "flow_pairs": pairs,
        "clock_offsets_us": {str(r): round(o, 1)
                             for r, o in sorted(offsets.items())},
        "unaligned_ranks": unaligned,
        "xla_events": xla_events,
    }
    doc = {"traceEvents": merged, "displayTimeUnit": "ms",
           "otherData": summary}
    return doc, summary


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge per-rank MINIPS_TRACE files into one "
                    "cross-rank Chrome trace")
    ap.add_argument("paths", nargs="+",
                    help="trace dirs and/or trace-rank*.json files")
    ap.add_argument("-o", "--out", default=None,
                    help="merged output (default: "
                         "<first dir>/merged_trace.json)")
    ap.add_argument("--xla", default=None, metavar="LOGDIR",
                    help="interleave the newest *.trace.json.gz under "
                         "LOGDIR (profiler output) on the same "
                         "timeline")
    args = ap.parse_args(argv)
    try:
        doc, summary = merge_traces(args.paths, xla_logdir=args.xla)
    except FileNotFoundError as e:
        print(f"merge: {e}", file=sys.stderr)
        return 1
    out = args.out
    if out is None:
        base = args.paths[0]
        base = base if os.path.isdir(base) else os.path.dirname(base)
        out = os.path.join(base or ".", "merged_trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    summary["merged"] = out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
