"""GPipe-style pipeline parallelism over a mesh axis.

Beyond parity (the reference has no pipeline parallelism, SURVEY.md §2.2).
Mechanics: layer weights are STACKED along a leading depth axis and that
axis is sharded over ``axis_name`` — each device owns ``depth/k``
consecutive layers (one pipeline stage). Microbatches flow stage-to-stage
with ``ppermute`` under one ``lax.scan`` over ``M + k - 1`` ticks (the
GPipe schedule: k-1 bubble ticks); every tick each stage applies its local
layers to whatever activation just arrived. Devices in the bubble compute
on don't-care values that are never read — on TPU a predicated skip would
break the static schedule, so the waste is the standard (k-1)/(M+k-1)
bubble fraction, amortized by more microbatches.

Autodiff: take ``jax.grad`` OUTSIDE the shard_map — scan and ppermute both
transpose, so the backward pipeline (activations flowing in reverse) is
derived automatically; tests prove exact grad parity with the unsharded
model.
"""

from __future__ import annotations

from typing import Callable

import jax

import jax.numpy as jnp
from minips_tpu.utils import jaxcompat
from minips_tpu.utils.jaxcompat import axis_size as _axis_size


def gpipe(
    stage_fn: Callable[[jnp.ndarray], jnp.ndarray],
    x_microbatches: jnp.ndarray,
    *,
    axis_name: str,
) -> jnp.ndarray:
    """Run [M, ...] microbatches through the k-stage pipeline.

    ``stage_fn`` must already be bound (via shard_map slicing) to THIS
    device's layers, and must map one microbatch activation [mb, ...] to
    the same shape. Stage 0 consumes ``x_microbatches[t]`` at tick t; the
    last stage's outputs are collected and broadcast, so the return value
    [M, ...] is valid on every device (replicated).
    """
    k = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    # stage i sends to stage i+1; the wrap edge (k-1 -> 0) carries values
    # stage 0 never reads
    perm = [(i, (i + 1) % k) for i in range(k)]
    # fresh zeros are axis-invariant; the scan carry becomes varying after
    # one tick, so pre-cast both (shard_map VMA tracking)
    out0 = jaxcompat.pcast(jnp.zeros_like(x_microbatches), axis_name,
                           to="varying")
    buf0 = jaxcompat.pcast(jnp.zeros_like(x_microbatches[0]), axis_name,
                           to="varying")

    def tick(carry, t):
        buf_in, outputs = carry
        mb = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, mb, buf_in)
        y = stage_fn(x)
        # last stage files microbatch (t - k + 1) when it is in range
        o = t - (k - 1)
        upd = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(o, 0, M - 1), axis=0)
        outputs = jnp.where((o >= 0) & (idx == k - 1), upd, outputs)
        return (jax.lax.ppermute(y, axis_name, perm), outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0),
                                   jnp.arange(M + k - 1))
    # broadcast the last stage's collected outputs to every device
    return jax.lax.psum(
        jnp.where(idx == k - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)


def stack_layers(layers: list) -> dict:
    """Stack a list of identically-structured layer pytrees into one pytree
    with a leading depth axis per leaf — the shardable layout ``gpipe``
    wants (shard dim 0 over the pipeline axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked: dict) -> list:
    """Inverse of ``stack_layers``."""
    depth = jax.tree.leaves(stacked)[0].shape[0]
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(depth)]
