"""SparseTable — fixed-capacity hashed embedding replacing MapStorage.

The reference's sparse path is ``MapStorage<Val>`` — a per-server
``std::map<key, val>`` grown on demand (SURVEY.md §2 "KVTable storage").
TPUs have no dynamic dictionaries: XLA needs static shapes. The TPU-native
equivalent (SURVEY.md §7.1) is a fixed-slot embedding matrix
``[num_slots, dim]`` with multiplicative hashing of the (unbounded) feature
id space onto slots — the standard "hashing trick" used by production CTR
systems for exactly this workload family (Criteo W&D/DeepFM,
BASELINE.json:10).

Sharding: rows are range-partitioned across the mesh ``data`` axis
(``PartitionSpec('data', None)``) — the same contiguous-range server
partition as the reference's RangeManager, but expressed as a sharding so
XLA GSPMD inserts the gather/scatter collectives (SURVEY.md §2.3; PAPERS.md
SparCML is the sparse-collective analog).

``pull(keys)`` is a row gather; ``push(keys, grads)`` scatter-adds duplicate
keys (reference ``Add`` semantics) and applies the server-side updater.
Per-row lazy updates for Adagrad keep push cost O(batch · dim) instead of
O(num_slots · dim) — the reference's per-key server update has the same
sparsity property.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.parallel.mesh import DATA_AXIS

_HASH_MULT = np.uint32(2654435761)  # Knuth multiplicative hash


def hash_to_slots(keys: jnp.ndarray, num_slots: int, salt: int = 0) -> jnp.ndarray:
    """Hash arbitrary int feature ids onto [0, num_slots). num_slots must be
    a power of two (masked multiply-shift hash, cheap on VPU)."""
    assert num_slots & (num_slots - 1) == 0, "num_slots must be a power of 2"
    k = keys.astype(jnp.uint32)
    h = (k * _HASH_MULT) ^ (k >> 16) ^ jnp.uint32(salt)
    return (h & jnp.uint32(num_slots - 1)).astype(jnp.int32)


class SparseTable:
    """Hashed, sharded embedding table with server-side SGD/Adagrad on push."""

    def __init__(
        self,
        num_slots: int,
        dim: int,
        mesh: Mesh,
        *,
        name: str = "sparse0",
        updater: str = "sgd",
        lr: float = 0.05,
        init_scale: float = 0.01,
        adagrad_init: float = 0.1,
        salt: int = 0,
        seed: int = 0,
        dtype=jnp.float32,
        use_pallas: Optional[bool] = None,
    ):
        if updater not in ("sgd", "adagrad"):
            raise ValueError("sparse updater must be 'sgd' or 'adagrad'")
        self.name = name
        self.mesh = mesh
        self.num_slots = int(num_slots)
        self.dim = int(dim)
        self.updater = updater
        self.lr = lr
        self.adagrad_init = adagrad_init
        self.salt = salt

        # Pallas gather opt-in, resolved ONCE here (the jitted pull is
        # trace-cached, so a late env toggle would be silently ignored).
        # Single-device meshes only: pallas_call has no GSPMD partitioning
        # rule, so on a sharded table it would force a full replication
        # all-gather of emb instead of the sharded XLA gather. The backend
        # check applies even to an explicit use_pallas=True — the kernel
        # uses pltpu primitives, which fail Mosaic lowering off-TPU.
        from minips_tpu.ops import pallas_kernels as _pk

        n_dev = len(np.asarray(mesh.devices).reshape(-1))
        self.use_pallas = bool(
            (use_pallas if use_pallas is not None else _pk.pallas_enabled())
            and n_dev == 1 and _pk.backend_supported())

        self._sharding = NamedSharding(mesh, P(DATA_AXIS, None))
        key = jax.random.PRNGKey(seed)
        emb = jax.random.normal(key, (self.num_slots, self.dim), dtype) * init_scale
        self.emb = jax.device_put(emb, self._sharding)
        if updater == "adagrad":
            self.accum = jax.device_put(
                jnp.full((self.num_slots, self.dim), adagrad_init, dtype),
                self._sharding,
            )
        else:
            self.accum = None

    # ------------------------------------------------------------------ hash
    def slots_of(self, keys: jnp.ndarray) -> jnp.ndarray:
        return hash_to_slots(jnp.asarray(keys), self.num_slots, self.salt)

    # ------------------------------------------------------------------ pull
    def pull(self, keys: jnp.ndarray) -> jnp.ndarray:
        """Gather embedding rows for (hashed) keys — KVClientTable::Pull for
        sparse tables (SURVEY.md §2 "KVClientTable"). [B] or [B, F] keys →
        [..., dim] rows."""
        return self._jit_pull(self.emb, jnp.asarray(keys))

    @functools.cached_property
    def _jit_pull(self):
        from minips_tpu.ops import pallas_kernels

        @jax.jit
        def pull(emb, keys):
            slots = hash_to_slots(keys, self.num_slots, self.salt)
            if (self.use_pallas
                    and pallas_kernels.gather_supported(self.dim, slots.size)):
                # opt-in hand-scheduled DMA gather; XLA native is the
                # measured default (ops/pallas_kernels.py docstring)
                rows = pallas_kernels.gather_rows(emb, slots.reshape(-1))
                return rows.reshape(*slots.shape, self.dim)
            return emb[slots]
        return pull

    # ------------------------------------------------------------------ push
    def push(self, keys: jnp.ndarray, grads: jnp.ndarray) -> None:
        """Scatter-add grads for (hashed) keys and apply the updater to the
        touched rows only — the reference's per-key server update
        (SURVEY.md §3.3 ``updater->Update(keys, grads)``)."""
        if self.updater == "sgd":
            self.emb = self._jit_push_sgd(self.emb, jnp.asarray(keys),
                                          jnp.asarray(grads))
        else:
            self.emb, self.accum = self._jit_push_adagrad(
                self.emb, self.accum, jnp.asarray(keys), jnp.asarray(grads))

    @functools.cached_property
    def _jit_push_sgd(self):
        from minips_tpu.ops.sparse_update import row_sgd

        @functools.partial(jax.jit, donate_argnums=(0,))
        def push(emb, keys, grads):
            slots = hash_to_slots(keys, self.num_slots, self.salt)
            return row_sgd(emb, slots, grads, self.lr)
        return push

    @functools.cached_property
    def _jit_push_adagrad(self):
        from minips_tpu.ops.sparse_update import row_adagrad

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def push(emb, accum, keys, grads):
            slots = hash_to_slots(keys, self.num_slots, self.salt)
            return row_adagrad(emb, accum, slots, grads, self.lr)
        return push

    # ------------------------------------------------------------- state I/O
    def state_dict(self) -> dict:
        out = {"emb": np.asarray(self.emb)}
        if self.accum is not None:
            out["accum"] = np.asarray(self.accum)
        return out

    def load_state_dict(self, state: dict) -> None:
        self.emb = jax.device_put(jnp.asarray(state["emb"]), self._sharding)
        if self.accum is not None and "accum" in state:
            self.accum = jax.device_put(jnp.asarray(state["accum"]),
                                        self._sharding)
