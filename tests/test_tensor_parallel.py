"""Tensor parallelism (Megatron-style) on the reserved ``model`` mesh axis.

Beyond parity (reference has no TP, SURVEY.md §2.2): block weights shard
column-/row-parallel, activations replicate, two psums per block. Tests
prove logits and gradients match the unsharded oracle, and that TP composes
with data parallelism on a 2D (data=4, model=2) mesh.
"""

import jax

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from minips_tpu.utils.jaxcompat import shard_map
from minips_tpu.models import transformer as tfm
from minips_tpu.parallel.mesh import make_mesh

CFG = dict(vocab=31, dim=32, heads=4, depth=2, max_len=64)
F32 = dict(compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh(4, model_size=2)


@pytest.fixture(scope="module")
def params():
    return tfm.init(jax.random.PRNGKey(0), **CFG)


def _toks(B, T, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG["vocab"], (B, T)), jnp.int32)


def test_tp_logits_match_full(mesh42, params):
    tokens = _toks(2, 16)
    want = tfm.apply(params, tokens, heads=CFG["heads"], **F32)

    specs = tfm.tp_specs(params)
    f = shard_map(
        lambda p, t: tfm.apply_tp(p, t, heads=CFG["heads"], **F32),
        mesh=mesh42, in_specs=(specs, P()), out_specs=P())
    got = f(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # fast tier keeps tp logits parity + dpxtp compose
def test_tp_grad_matches_full(mesh42, params):
    toks = _toks(2, 17, seed=1)

    def full_loss(p):
        return tfm.loss(p, {"tokens": toks}, heads=CFG["heads"], **F32)

    def tp_loss(p):
        specs = tfm.tp_specs(params)

        def shard_fn(p_, t_):
            logits = tfm.apply_tp(p_, t_[:, :-1], heads=CFG["heads"], **F32)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, t_[:, 1:, None], axis=-1)[..., 0]
            return jnp.mean(nll)

        return shard_map(shard_fn, mesh=mesh42,
                             in_specs=(specs, P()), out_specs=P())(p, toks)

    l_f, g_f = jax.value_and_grad(full_loss)(params)
    l_t, g_t = jax.value_and_grad(tp_loss)(params)
    assert abs(float(l_f) - float(l_t)) < 1e-5
    f1, _ = jax.flatten_util.ravel_pytree(g_f)
    f2, _ = jax.flatten_util.ravel_pytree(g_t)
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f1),
                               rtol=2e-4, atol=2e-4)


def test_tp_composes_with_dp(mesh42, params):
    """2D mesh: batch sharded over data (4), weights over model (2) — one
    optax SGD step matches the single-device step.

    The supported composition is value_and_grad OUTSIDE the shard_map (as
    in Megatron's conjugate f/g operators, which JAX's shard_map transpose
    implements automatically); taking raw local grads inside would miss
    the cross-rank reductions replicated params need."""
    import optax

    toks = _toks(8, 17, seed=2)
    specs = tfm.tp_specs(params)
    tx = optax.sgd(0.1)

    def tp_loss(p):
        def shard_fn(p_, t_):
            logits = tfm.apply_tp(p_, t_[:, :-1], heads=CFG["heads"], **F32)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, t_[:, 1:, None], axis=-1)[..., 0]
            return jax.lax.pmean(jnp.mean(nll), "data")
        return shard_map(shard_fn, mesh=mesh42,
                             in_specs=(specs, P("data")),
                             out_specs=P())(p, toks)

    @jax.jit
    def step_2d(p):
        loss, g = jax.value_and_grad(tp_loss)(p)
        updates, _ = tx.update(g, tx.init(p), p)
        return optax.apply_updates(p, updates), loss

    def full_step(p):
        def l(p_):
            return tfm.loss(p_, {"tokens": toks}, heads=CFG["heads"], **F32)
        loss, g = jax.value_and_grad(l)(p)
        updates, _ = tx.update(g, tx.init(p), p)
        return optax.apply_updates(p, updates), loss

    new_p, loss2d = step_2d(params)
    want_p, loss1 = full_step(params)
    assert abs(float(loss2d) - float(loss1)) < 1e-5
    f2, _ = jax.flatten_util.ravel_pytree(new_p)
    f1, _ = jax.flatten_util.ravel_pytree(want_p)
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f1),
                               rtol=2e-4, atol=2e-4)


def test_tp_heads_not_divisible_raises(mesh42, params):
    specs = tfm.tp_specs(params)
    with pytest.raises(ValueError, match="divisible"):
        shard_map(
            lambda p, t: tfm.apply_tp(p, t, heads=3),
            mesh=mesh42, in_specs=(specs, P()), out_specs=P()
        )(params, _toks(1, 8))


def test_tp_gqa_logits_match_full(mesh42):
    """GQA under TP: wq/wkv shard column-parallel at head boundaries
    (each model shard computes 2 q-heads over 1 kv head here); logits
    must match the unsharded oracle."""
    p = tfm.init(jax.random.PRNGKey(7), **{**CFG, "kv_heads": 2})
    tokens = _toks(2, 16, seed=7)
    want = tfm.apply(p, tokens, heads=CFG["heads"], **F32)
    specs = tfm.tp_specs(p)
    f = shard_map(
        lambda q, t: tfm.apply_tp(q, t, heads=CFG["heads"], **F32),
        mesh=mesh42, in_specs=(specs, P()), out_specs=P())
    got = f(p, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_tp_gqa_kv_not_divisible_raises(mesh42):
    """kv_heads=1 cannot split across model=2 shards — apply_tp must
    refuse loudly instead of computing garbage."""
    p = tfm.init(jax.random.PRNGKey(7), **{**CFG, "kv_heads": 1})
    tokens = _toks(1, 8)
    specs = tfm.tp_specs(p)
    f = shard_map(
        lambda q, t: tfm.apply_tp(q, t, heads=CFG["heads"], **F32),
        mesh=mesh42, in_specs=(specs, P()), out_specs=P())
    with pytest.raises(ValueError, match="kv_heads"):
        f(p, tokens)
