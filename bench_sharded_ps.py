"""Sharded multi-process PS throughput curve (VERDICT r2 #2).

Measures train/sharded_ps.py — the key-range-sharded multi-process server —
via apps/sharded_ps_bench.py workers: rows/sec and wire-bytes/sec of the
pull→push cycle per process, with model math stripped out so the number
isolates routing + serialization + bus + server-side updater (the
reference's Mailbox/ServerThread hot path, SURVEY.md §3.3 hot spots b+c).

The sweep:
- world size 1 (standalone, zero wire: the pure server-apply ceiling)
  then 2→4 real processes over loopback;
- zmq vs the native C++ TCP mailbox at world size 3;
- sparse key-slice path vs dense contiguous-range path at world size 3.

Everything here is HOST-CPU loopback — the sharded PS is the control-plane
topology (real pods put one process per node); these are deliberately NOT
chip rates and never feed vs_baseline. Emits ONE JSON line.

Usage: python bench_sharded_ps.py [--iters 60] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

_PORT = [6600 + (os.getpid() % 389)]


def _worker_argv(path: str, iters: int, warmup: int,
                 compute: str = "none",
                 hidden: int | None = None,
                 push_comm: str = "float32",
                 pull_wire: str = "f32",
                 overlap: bool = False,
                 overlap_legs: str = "both",
                 key_dist: str = "uniform",
                 staleness: float | None = None,
                 cache_bytes: int = 0,
                 pull_dedup: bool = True,
                 push_dedup: bool = True,
                 rows: int | None = None,
                 updater: str | None = None) -> list[str]:
    argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", path, "--iters", str(iters), "--warmup", str(warmup)]
    if compute != "none":
        argv += ["--compute", compute]
    if hidden is not None:
        argv += ["--hidden", str(hidden)]
    if push_comm != "float32":
        argv += ["--push-comm", push_comm]
    if pull_wire != "f32":
        argv += ["--pull-wire", pull_wire]
    if overlap:
        argv += ["--overlap"]
        if overlap_legs != "both":
            argv += ["--overlap-legs", overlap_legs]
    if key_dist != "uniform":
        argv += ["--key-dist", key_dist]
    if staleness is not None:
        argv += ["--staleness", str(staleness)]
    if cache_bytes:
        argv += ["--cache-bytes", str(cache_bytes)]
    if not pull_dedup:
        argv += ["--no-pull-dedup"]
    if not push_dedup:
        argv += ["--no-push-dedup"]
    if rows is not None:
        argv += ["--rows", str(rows)]
    if updater is not None:
        argv += ["--updater", updater]
    return argv


def _run(n: int, path: str, iters: int, warmup: int, bus: str,
         compute: str = "none", force_cpu: bool = False,
         hidden: int | None = None, push_comm: str = "float32",
         pull_wire: str = "f32", overlap: bool = False,
         overlap_legs: str = "both", key_dist: str = "uniform",
         staleness: float | None = None, cache_bytes: int = 0,
         pull_dedup: bool = True, push_dedup: bool = True,
         rows: int | None = None,
         updater: str | None = None) -> dict:
    """One sweep point → {rows_per_sec_per_process, aggregate, wire...}.

    ``compute="jit"`` adds a real jitted model-grad step between pull and
    push on every worker — rank 0 on the default backend (the chip when
    alive and ``force_cpu`` is False), peers on CPU — the north-star
    topology (accelerator workers against a sharded host PS) instead of
    the bare control plane. ``hidden`` sizes that step's MLP."""
    argv = _worker_argv(path, iters, warmup, compute, hidden,
                        push_comm, pull_wire, overlap, overlap_legs,
                        key_dist, staleness, cache_bytes, pull_dedup,
                        push_dedup, rows, updater)
    env_extra = {}
    if bus != "zmq":
        env_extra["MINIPS_BUS"] = bus
    if force_cpu:
        env_extra["MINIPS_FORCE_CPU"] = "1"
    if n == 1:  # standalone zero-wire baseline (no launcher, no bus)
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=240,
                              env={**os.environ, **env_extra})
        if proc.returncode != 0:
            raise RuntimeError(f"standalone worker failed: {proc.stderr}")
        res = [json.loads([ln for ln in proc.stdout.splitlines()
                           if ln.startswith("{")][-1])]
    else:
        from minips_tpu import launch

        _PORT[0] += n + 3
        res = launch.run_local_job(
            n, argv, base_port=_PORT[0],
            env_extra=env_extra or None,
            timeout=300.0)
    per = [r["rows_per_sec"] for r in res]
    wire = [r["wire_push_bytes_per_sec"] + r["wire_pull_bytes_per_sec"]
            for r in res]
    out = {
        "rows_per_sec_per_process": round(statistics.mean(per), 1),
        "aggregate_rows_per_sec": round(sum(per), 1),
        "wire_bytes_per_sec_per_process": round(statistics.mean(wire), 1),
        # 1 decimal: the sweep-point resolution the artifact history uses
        # (26.7 f32 both legs → 20.0 one int8 leg → 13.3 both)
        "wire_bytes_per_row_moved": round(statistics.mean(
            [r["wire_bytes_per_row_moved"] for r in res]), 1),
    }
    fracs = [r["timing"].get("pull_overlap_fraction")
             for r in res if r.get("timing")]
    fracs = [f for f in fracs if f is not None]
    if fracs:
        out["pull_overlap_fraction"] = round(statistics.mean(fracs), 4)
    if compute != "none":
        out["worker_compute"] = sorted({r.get("compute", "?")
                                        for r in res})
    # row-flow + cache observables (the dedup/cache sweep's evidence):
    # wire-row fraction from the per-rank timers; hit rate from the
    # caches (None — distinct from 0.0 — when the arm runs cache-off)
    reqs = sum(r["timing"].get("pull_rows_requested", 0) for r in res)
    wires = sum(r["timing"].get("pull_rows_wire", 0) for r in res)
    if reqs:
        out["pull_rows_wire_frac"] = round(wires / reqs, 4)
    caches = [r.get("cache") for r in res]
    if any(c is not None for c in caches):
        hits = sum(c["hits"] for c in caches if c)
        looks = sum(c["lookups"] for c in caches if c)
        out["cache_hit_rate"] = (round(hits / looks, 4) if looks
                                 else 0.0)
    # the workers echo their wire formats — a silent flag-plumbing
    # regression must not publish a float32 number labeled int8 (nor a
    # synchronous number labeled overlapped)
    echoed = {r.get("push_comm", "float32") for r in res}
    assert echoed == {push_comm}, (push_comm, echoed)
    echoed_pw = {r.get("pull_wire", "f32") for r in res}
    assert echoed_pw == {pull_wire}, (pull_wire, echoed_pw)
    echoed_ov = {bool(r.get("overlap")) for r in res}
    assert echoed_ov == {overlap}, (overlap, echoed_ov)
    echoed_legs = {r.get("overlap_legs") for r in res}
    assert echoed_legs == {overlap_legs if overlap else None}, (
        overlap_legs, echoed_legs)
    echoed_kd = {r.get("key_dist", "uniform") for r in res}
    assert echoed_kd == {key_dist}, (key_dist, echoed_kd)
    echoed_cb = {r.get("cache_bytes", 0) for r in res}
    assert echoed_cb == {cache_bytes}, (cache_bytes, echoed_cb)
    echoed_dd = {r.get("pull_dedup", True) for r in res}
    assert echoed_dd == {pull_dedup}, (pull_dedup, echoed_dd)
    echoed_pd = {r.get("push_dedup", True) for r in res}
    assert echoed_pd == {push_dedup}, (push_dedup, echoed_pd)
    if staleness is not None:
        echoed_s = {r.get("staleness") for r in res}
        assert echoed_s == {int(staleness)}, (staleness, echoed_s)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--quick", action="store_true",
                    help="short iters (harness validation, not numbers)")
    args = ap.parse_args()
    iters = 15 if args.quick else args.iters
    warmup = max(2, iters // 6)

    curve = {}  # world-size scaling, sparse path, zmq
    for n in (1, 2, 3, 4):
        curve[str(n)] = _run(n, "sparse", iters, warmup, "zmq")
    buses = {"zmq": curve["3"],
             "native": _run(3, "sparse", iters, warmup, "native")}
    paths = {"sparse": curve["3"],
             "dense": _run(3, "dense", iters, warmup, "zmq")}
    # the compressed push wire: same rows/sec workload, int8 codes on the
    # cross-process push leg — wire bytes/sec drops toward the codec
    # ratio while the pull leg is whatever --pull-wire says (f32 here).
    # Both wire comparisons measure their arms BACK-TO-BACK rather than
    # reusing curve["3"] from minutes earlier: shared-host drift would
    # otherwise dominate the rows/sec column (B/row is drift-immune, the
    # throughput comparison is not).
    wires = {"float32": _run(3, "sparse", iters, warmup, "zmq"),
             "int8": _run(3, "sparse", iters, warmup, "zmq",
                          push_comm="int8")}
    # the compressed PULL wire (this PR): pull REPLIES ship int8 codes +
    # per-row f32 scales instead of raw f32 rows — the other half of the
    # bytes/row story (the pull leg dominates sparse wire volume: reply
    # rows outweigh the 8B key slices going out)
    pull_wires = {"f32": _run(3, "sparse", iters, warmup, "zmq"),
                  "int8": _run(3, "sparse", iters, warmup, "zmq",
                               pull_wire="int8")}
    # overlapped pipeline, three arms: off (fully synchronous cycle) vs
    # pull (double-buffered prefetch only) vs on (prefetch + async ack-
    # windowed push) — the latency levers, orthogonal to the wire
    # codecs, measured in the north-star shape (--compute jit: real
    # model math between pull and push; CPU-forced so all arms run
    # identical backends). READ THE NUMBERS WITH THE HOST IN MIND: on a
    # host whose cores are OVERSUBSCRIBED by the world size (every CI
    # container this has run on so far), the sync arm's blocked time is
    # not idle — the scheduler hands it to the other processes — so
    # overlap has nothing to reclaim and its remaining cost shows as a
    # deficit: measured on 2 cores, pull ~TIES off (the prefetch is
    # near-free) while on trails by ~10-15% (the sender thread + ack
    # settling contend for the GIL/cores three ways). The lever the
    # arms prove regardless is pull_overlap_fraction: ~0 sync vs ~0.8+
    # overlapped — the pull RTT genuinely left the critical path, which
    # converts to rows/sec only where worker compute and PS serving
    # have their own hardware (real pods; an accelerator-backed
    # worker). The _fit point (min(3, cores)) pins the least-
    # oversubscribed topology this host can host so the crossover is
    # visible the day the measurement environment grows headroom.
    def _overlap_arms(n: int, reps: int) -> dict:
        # shared-CI hosts drift (cgroup bursts swing absolute rates 2-4x
        # within minutes), so one off-run vs one on-run can crown either
        # arm by luck. ALTERNATE the arms rep-by-rep — adjacent runs see
        # near-identical machine state — and report each arm's MEDIAN
        # rep, so a throttle window contaminates at most one rep of each
        # arm, never a whole arm.
        arms = {"off": {}, "pull": {"overlap": True, "overlap_legs": "pull"},
                "on": {"overlap": True}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(n, "sparse", iters, warmup, "zmq",
                                    compute="jit", force_cpu=True, **kw))

        def med(arm: str) -> dict:
            by_rate = sorted(runs[arm],
                             key=lambda r: r["rows_per_sec_per_process"])
            return {**by_rate[len(by_rate) // 2], "reps": reps}
        return {a: med(a) for a in arms}

    o_reps = 1 if args.quick else 3
    over = _overlap_arms(3, o_reps)
    n_fit = min(3, os.cpu_count() or 3)
    over_fit = _overlap_arms(n_fit, o_reps) if n_fit != 3 else over

    # client row cache + deduplicated pull wire: "off" is the SEED wire
    # (duplicate keys verbatim, no cache) — the before/after this PR's
    # tentpole is judged on; "on" is unique-key wire + clock-versioned
    # row cache. The grid crosses key distribution with staleness
    # because the cache's validity window IS the staleness budget: the
    # uniform arms keep the standard 64k-row table (keys essentially
    # never recur — the no-win control, dedup/locality only), the zipf
    # arms shrink the table to the HOT WORKING SET a zipf(1.1) head
    # concentrates on, so re-draws land within the staleness window.
    # Same alternating-median honesty rules as the overlap sweep.
    # Fixed knobs: sgd updater + f32 push wire (the write-through
    # regime — adagrad/adam invalidate on push, pinning hit rate to ~0
    # in a pull+push cycle; see docs/consistency.md); cache ample (no
    # LRU pressure — the byte bound has its own tests). READ THE
    # ROWS/SEC COLUMN WITH THE HOST IN MIND (the overlap sweep's
    # caveat, again): on this CPU-loopback container wire bytes are
    # memcpys — shipping 5x the rows costs almost nothing — so the
    # on-arm's saved bytes buy no wall-clock, while its bursty misses
    # (same-step fills share a stamp and expire TOGETHER) hit the
    # owner park / gate wake instead of riding an amortized stream:
    # measured medians put the zipf on-arm ~5-15% under the off-arm
    # at s>=1 (with --compute jit filling the freed time the arms tie
    # within drift). The levers this sweep PROVES are hit rate > 0
    # rising with s (the staleness budget buying locality) and
    # B/row-moved down ~84% on zipf — the currency that converts to
    # rows/sec exactly where the wire is a real network or the worker
    # has its own compute, the deployments the north star names.
    ZIPF_ROWS, CACHE_BYTES = 2048, 1 << 22

    def _cache_arms(reps: int) -> dict:
        arms = {"off": {"cache_bytes": 0, "pull_dedup": False,
                        "push_dedup": False},  # = the full seed wire
                "on": {"cache_bytes": CACHE_BYTES}}
        dists = {"uniform": None, "zipf": ZIPF_ROWS}  # dist -> rows
        runs: dict[tuple, list[dict]] = {}
        for _ in range(reps):
            for dist, rows in dists.items():
                for s in (0, 1, 2):
                    for a, kw in arms.items():
                        runs.setdefault((dist, s, a), []).append(
                            _run(3, "sparse", iters, warmup, "zmq",
                                 key_dist=dist, staleness=s,
                                 rows=rows, updater="sgd", **kw))
        grid: dict = {"zipf_rows": ZIPF_ROWS, "cache_bytes": CACHE_BYTES}
        for (dist, s, a), rs in runs.items():
            by = sorted(rs, key=lambda r: r["rows_per_sec_per_process"])
            point = {**by[len(by) // 2], "reps": reps}
            grid.setdefault(dist, {}).setdefault(f"s{s}", {})[a] = point
        return grid

    cache_grid = _cache_arms(o_reps)

    headline = curve["3"]["rows_per_sec_per_process"]
    print(json.dumps({
        "metric": "sharded-PS rows/sec/process (sparse pull+push, "
                  "3 procs, zmq, CPU loopback control plane)",
        "value": headline,
        "unit": "rows/sec/process",
        "vs_baseline": None,  # control-plane rate; not a chip number
        "device": "cpu-loopback",
        "scaling_sparse_zmq": curve,
        "bus_comparison_3proc": buses,
        "path_comparison_3proc": paths,
        "push_wire_comparison_3proc": wires,
        "pull_wire_comparison_3proc": pull_wires,
        "overlap_on_off_3proc": over,
        "overlap_on_off_fit": {"nprocs": n_fit, **over_fit},
        "cache_comparison_3proc": cache_grid,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
