"""Property-based tests of the consistency layer (SURVEY.md §4: the
consistency models are the reference's most heavily tested surface —
scripted Add/Get/Clock sequences; hypothesis generates the scripts).

Invariants under ANY interleaving of clock/admit calls:

1. Admission rule: ``admit(w)`` ⟺ ``min_clock >= clock_of(w) - staleness``
   (BSP: s=0; SSP: s; ASP: ∞ ⇒ always true).
2. Clock vector: advancing w increments only w; min/max/skew consistent.
3. ``advance`` returns the new min iff the min changed.
4. PendingBuffer: pop_ready returns exactly the items whose admission
   clock <= min, FIFO within a clock, ascending clocks; never loses items.
5. Wake-up soundness (threaded path): a parked pull is admitted as soon as
   the min reaches its threshold — checked via the controller state
   machine rather than real threads (the distributed smoke tests cover the
   threaded/process reality).
"""

from __future__ import annotations

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis "
    "(pip install -e .[test])")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from minips_tpu.consistency.controllers import ASP, BSP, SSP, make_controller
from minips_tpu.consistency.tracker import PendingBuffer, ProgressTracker

# a script is a list of (worker, op) with op in {"clock", "admit"}
scripts = st.lists(
    st.tuples(st.integers(0, 3),
              st.sampled_from(["clock", "admit"])),
    min_size=1, max_size=200)


@given(script=scripts, staleness=st.integers(0, 5))
@settings(max_examples=200, deadline=None)
def test_admission_rule_is_exactly_bounded_staleness(script, staleness):
    c = SSP(4, staleness=staleness)
    for worker, op in script:
        if op == "clock":
            c.clock(worker)
        else:
            expected = (c.tracker.min_clock
                        >= c.tracker.clock_of(worker) - staleness)
            assert c.admit(worker) == expected


@given(script=scripts)
@settings(max_examples=100, deadline=None)
def test_bsp_admits_only_at_min(script):
    c = BSP(4)
    for worker, op in script:
        if op == "clock":
            c.clock(worker)
        else:
            assert c.admit(worker) == (
                c.tracker.clock_of(worker) == c.tracker.min_clock)


@given(script=scripts)
@settings(max_examples=100, deadline=None)
def test_asp_always_admits(script):
    c = ASP(4)
    for worker, op in script:
        if op == "clock":
            c.clock(worker)
        else:
            assert c.admit(worker)


@given(advances=st.lists(st.integers(0, 3), min_size=1, max_size=300))
@settings(max_examples=200, deadline=None)
def test_tracker_vector_semantics(advances):
    t = ProgressTracker(4)
    shadow = [0, 0, 0, 0]
    for w in advances:
        old_min = min(shadow)
        changed = t.advance(w)
        shadow[w] += 1
        assert t.snapshot() == shadow
        new_min = min(shadow)
        assert changed == (new_min if new_min != old_min else None)
        assert t.min_clock == new_min
        assert t.max_clock == max(shadow)
        assert t.skew == max(shadow) - new_min


@given(
    parked=st.lists(st.tuples(st.integers(0, 10), st.integers(0, 999)),
                    max_size=50),
    pops=st.lists(st.integers(0, 12), max_size=10),
)
@settings(max_examples=200, deadline=None)
def test_pending_buffer_conservation_and_order(parked, pops):
    buf = PendingBuffer()
    shadow: list[tuple[int, int]] = []  # (clock, item), insertion order
    for clock, item in parked:
        buf.park(clock, item)
        shadow.append((clock, item))
    popped_total = []
    done = set()
    for min_clock in sorted(pops):
        got = buf.pop_ready(min_clock)
        # expected: all not-yet-popped items with clock <= min_clock,
        # ascending clock, FIFO within a clock
        expect = []
        for c in sorted({c for i, (c, _) in enumerate(shadow)
                         if c <= min_clock and i not in done}):
            for i, (ci, item) in enumerate(shadow):
                if ci == c and i not in done:
                    expect.append(item)
                    done.add(i)
        assert got == expect
        popped_total.extend(got)
    assert buf.num_parked == len(shadow) - len(done)


@given(script=scripts, staleness=st.integers(0, 3))
@settings(max_examples=100, deadline=None)
def test_skew_of_gated_execution_never_exceeds_staleness_plus_one(
        script, staleness):
    """Simulate workers that respect the gate: a worker only clocks when
    admitted (else it 'blocks' = skips its turn). The resulting clock skew
    can never exceed staleness + 1 — the system-level SSP guarantee the
    multi-process trainer also asserts (tests/test_distributed_smoke.py)."""
    c = SSP(4, staleness=staleness)
    for worker, _ in script:
        if c.admit(worker):
            c.clock(worker)
        assert c.skew <= staleness + 1


@settings(max_examples=60, deadline=None)
@given(
    # interleaved script: ("pull", clk) requests and ("min", m) advances
    st.lists(st.one_of(
        st.tuples(st.just("pull"), st.integers(0, 8)),
        st.tuples(st.just("min"), st.integers(0, 10))),
        min_size=1, max_size=40),
    st.integers(0, 3))
def test_owner_side_park_serves_each_admitted_pull_exactly_once(
        script, staleness):
    """The sharded-PS owner's PendingBuffer (reference server-side
    ``model->Get``): for ANY interleaving of pull requests and min-clock
    advances, every pull is served exactly once as soon as (and never
    before) global_min >= clk - s, and pulls whose bound is never reached
    stay parked. Serves are recorded via the reply path with bus=None
    stubbed out."""
    from minips_tpu.train.sharded_ps import ShardedTable

    t = ShardedTable("t", 8, 1, None, 0, 1, updater="sgd")
    served = []
    t._serve_pull = lambda sender, req, keys, clk=0: served.append(req)

    class Cons:
        gmin = 0

        def admit_pull(self, clk):
            return self.gmin >= clk - staleness

    cons = Cons()
    t.bind_consistency(cons)

    issued = []  # (req, clk)
    req = 0
    for op, val in script:
        if op == "pull":
            req += 1
            issued.append((req, val))
            t._on_pull(0, {"req": req, "clk": val,
                           "__blob__": np.int64(3).tobytes()})
        else:
            cons.gmin = max(cons.gmin, val)  # clocks only advance
            t.serve_parked()
    # final drain at the terminal min
    t.serve_parked()
    should_serve = sorted(r for r, c in issued
                          if cons.gmin >= c - staleness)
    assert sorted(served) == should_serve  # exactly once, all admitted
    parked_reqs = sorted(p[1] for p in t._parked)
    assert parked_reqs == sorted(r for r, c in issued
                                 if cons.gmin < c - staleness)


# --------------------------------------------------- client row cache
# a cache script interleaves inserts (stamped at/below the current
# clock, like real replies), lookups, pushes (invalidate), and ticks
cache_ops = st.lists(
    st.tuples(st.sampled_from(["insert", "lookup", "tick", "invalidate"]),
              st.integers(0, 7),      # key (small domain: collisions)
              st.integers(0, 4)),     # insert: stamp lag below clk
    min_size=1, max_size=120)


@given(ops=cache_ops, staleness=st.integers(0, 3))
@settings(max_examples=200, deadline=None)
def test_cache_served_row_never_older_than_clk_minus_staleness(
        ops, staleness):
    """The tentpole's safety property (train/sharded_ps.RowCache): for
    ANY interleaving of reply-inserts, pulls, pushes, and clock ticks
    under SSP(s), a cache-SERVED row carries a stamp >= clk − s — the
    exact owner-side admission bound — and the LRU byte bound is never
    exceeded. Row payloads encode their own stamp so the assertion
    checks delivered DATA, not bookkeeping."""
    from minips_tpu.consistency.gate import admits
    from minips_tpu.train.sharded_ps import RowCache

    cap = 5 * 8  # room for five dim-2 rows: eviction pressure is real
    cache = RowCache(dim=2, cache_bytes=cap)
    clk = 0
    for op, key, lag in ops:
        if op == "insert":
            stamp = max(clk - lag, 0)  # replies are stamped <= my clock
            cache.insert(np.array([key]),
                         np.full((1, 2), stamp, np.float32), stamp)
        elif op == "lookup":
            rows, miss = cache.lookup(np.array([key]), clk, staleness)
            if not miss[0]:
                stamp = int(rows[0, 0])
                assert admits(stamp, clk, staleness)
                assert stamp >= clk - staleness
        elif op == "tick":
            clk += 1
            cache.age(clk, staleness)
        else:
            cache.invalidate(np.array([key]))
        assert cache.nbytes <= cap


@given(ops=cache_ops)
@settings(max_examples=100, deadline=None)
def test_cache_bsp_never_serves_across_a_tick(ops):
    """BSP (s=0) degenerate case: after any tick, every earlier insert
    is un-servable — the cache can only satisfy re-reads within one
    clock frame, which is why BSP cache-on runs are bitwise identical
    to cache-off (test_cache_on_off_bitwise_equal_under_bsp)."""
    from minips_tpu.train.sharded_ps import RowCache

    cache = RowCache(dim=1, cache_bytes=1 << 12)
    clk = 0
    stamped_at = {}  # key -> clk at insert
    for op, key, _ in ops:
        if op == "insert":
            cache.insert(np.array([key]),
                         np.zeros((1, 1), np.float32), clk)
            stamped_at[key] = clk
        elif op == "lookup":
            _, miss = cache.lookup(np.array([key]), clk, 0)
            if not miss[0]:
                assert stamped_at.get(key) == clk
        elif op == "tick":
            clk += 1
            cache.age(clk, 0)
            assert len(cache) == 0  # s=0: a tick empties the cache


# The BSP bitwise cache-on/off equivalence drill lives in
# tests/test_row_cache.py (test_cache_on_off_bitwise_equal_under_bsp):
# it needs no hypothesis, and parking it here would silently skip it on
# installs without the test extra.
