"""Multi-host SPMD data plane (comm/cluster.py + apps/multihost_example).

VERDICT r2 Missing #1: the reference actually runs N processes on N nodes
(SURVEY.md §1 L7, §3.1); the rebuild's SPMD equivalent is
``jax.distributed.initialize`` + one global mesh. These tests prove that
path with REAL processes over loopback on the CPU backend — each process
contributes 4 fake devices to an 8-device global mesh, the fused
DenseTable step's collectives cross the process boundary (Gloo), batches
are fed per-process, and a globally-sharded orbax checkpoint round-trips
with every process writing only its addressable shards.

Fast tier covers the single-process degenerate paths of every cluster.py
function (the no-op contract the sandbox relies on).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from minips_tpu import launch

APP = "minips_tpu.apps.multihost_example"


# ------------------------------------------------------------ fast tier
def test_initialize_single_process_is_noop(monkeypatch):
    """No coordinator anywhere -> False, and jax.distributed is NOT
    touched (calling it twice in-process would raise)."""
    from minips_tpu.comm import cluster

    for var in ("MINIPS_COORDINATOR", "JAX_COORDINATOR_ADDRESS",
                "MINIPS_NUM_PROCS", "MINIPS_PROC_ID"):
        monkeypatch.delenv(var, raising=False)
    assert cluster.initialize() is False
    assert cluster.process_count() == 1
    assert cluster.process_index() == 0


def test_initialize_num_procs_one_is_noop(monkeypatch):
    """A coordinator with world size 1 (launcher run with --n 1) must not
    start the distributed runtime either."""
    from minips_tpu.comm import cluster

    monkeypatch.setenv("MINIPS_COORDINATOR", "127.0.0.1:1")
    monkeypatch.setenv("MINIPS_NUM_PROCS", "1")
    monkeypatch.setenv("MINIPS_PROC_ID", "0")
    assert cluster.initialize() is False


def test_initialize_jax_standard_env_passes_through(monkeypatch):
    """A pod configured the JAX-standard way (JAX_COORDINATOR_ADDRESS +
    JAX's own num/process env) must reach jax.distributed.initialize with
    num_processes/process_id left for JAX to resolve — NOT silently
    degrade to N independent single-process runs."""
    import jax

    from minips_tpu.comm import cluster

    for var in ("MINIPS_COORDINATOR", "MINIPS_NUM_PROCS",
                "MINIPS_PROC_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    calls = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.update(kw))
    assert cluster.initialize() is True
    assert calls["coordinator_address"] == "10.0.0.1:1234"
    assert calls["num_processes"] is None  # JAX resolves from its env
    assert calls["process_id"] is None


def test_barrier_single_process_returns():
    from minips_tpu.comm import cluster

    cluster.barrier("unit")  # must not hang or require a cluster


def test_global_batch_single_process(mesh8):
    """Single-process global_batch = device_put with the data sharding —
    the same call sites work on one host and on a pod."""
    import jax

    from minips_tpu.comm import cluster

    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    out = cluster.global_batch(mesh8, {"x": x})
    assert isinstance(out["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out["x"]), x)
    # sharded along data: each of the 8 devices holds 2 rows
    assert out["x"].sharding.shard_shape(out["x"].shape) == (2, 2)


def test_host_copy_addressable(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from minips_tpu.comm import cluster

    x = jax.device_put(np.arange(8, dtype=np.float32),
                       NamedSharding(mesh8, P("data")))
    np.testing.assert_array_equal(cluster.host_copy(x), np.arange(8))


# ------------------------------------------------------------ slow tier
def _run_multihost(n, extra, *, local_devices=4, timeout=240.0):
    return launch.run_local_job(
        n, [sys.executable, "-m", APP] + extra,
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1",
                   "MINIPS_MH_LOCAL_DEVICES": str(local_devices)},
        timeout=timeout)


@pytest.mark.slow
def test_two_process_global_mesh_trains_and_checkpoints(tmp_path):
    """The pod story end-to-end: 2 real processes, one 8-device global
    mesh, fused-step collectives across the process boundary, per-process
    batch feeding, coordinated globally-sharded orbax save->restore, and
    the cluster barrier. SPMD agreement: both ranks see identical losses
    and fingerprints."""
    res = _run_multihost(
        2, ["--iters", "12", "--checkpoint-dir", str(tmp_path / "ck"),
            "--save-at", "6"])
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done"
        assert r["multi"] is True
        assert r["process_count"] == 2
        assert r["global_devices"] == 8 and r["local_devices"] == 4
        assert r["loss_last"] < r["loss_first"], r
        assert r["ckpt_roundtrip_ok"] is True
    assert res[0]["losses"] == res[1]["losses"]
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_two_process_wd_sparse_tables_on_global_mesh():
    """The flagship sparse workload multi-host: DeepFM's hashed
    SparseTables + deep tower as ONE fused step over the 2-process global
    mesh — embedding gathers/scatter-adds and grad collectives cross the
    process boundary; both ranks converge identically (the 2-proc ≡
    1-proc equality itself is pinned by the LR parity test below — one
    oracle rerun in the tier is enough for the suite's time budget)."""
    res = _run_multihost(2, ["--model", "wd", "--iters", "12",
                             "--batch", "64"])
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["global_devices"] == 8
        assert r["loss_last"] < r["loss_first"], r
    assert res[0]["losses"] == res[1]["losses"]
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_two_process_ring_attention_sequence_parallel():
    """Long-context x multi-host: the LM with ring-attention SEQUENCE
    parallelism over the 2-process global mesh — each host feeds only its
    sequence slice and the K/V ring ppermutes cross the process boundary.
    Ranks agree exactly, and the whole run equals a 1-process 8-device
    oracle (the ring is the same; only the wiring under it changed)."""
    lm = ["--model", "lm", "--iters", "8", "--batch", "8",
          "--seq-len", "64", "--updater", "adam", "--lr", "0.003"]
    res = _run_multihost(2, lm)
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["global_devices"] == 8 and r["seq_local"] == 32
        assert r["loss_last"] < r["loss_first"], r
    assert res[0]["losses"] == res[1]["losses"]
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]
    proc = subprocess.run(
        [sys.executable, "-m", APP] + lm,
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "MINIPS_FORCE_CPU": "1",
             "MINIPS_MH_LOCAL_DEVICES": "8"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    solo = json.loads([ln for ln in proc.stdout.splitlines()
                       if ln.startswith("{")][-1])
    # rtol looser than the LR/WD parity tests: the grad psum-scatter's
    # cross-process reduction ORDER differs from the one-process tree,
    # and bf16 block matmuls + adam's rsqrt amplify the LSB over steps
    # (observed ~3e-5 by step 8; first 6 steps bit-identical)
    np.testing.assert_allclose(res[0]["losses"], solo["losses"],
                               rtol=5e-4)


@pytest.mark.slow
def test_multihost_kill_detect_relaunch_resume(tmp_path):
    """The recovery story on the pod path (reference §3.5 semantics,
    all-or-nothing per SURVEY §7.4.5): a peer death leaves the survivor
    BLOCKED in a collective, so the bus-heartbeat watchdog thread detects
    it (~2s, vs the coordination service's ~100s backstop), emits
    peer_failure and exits 42; recovery = relaunch + coordinated orbax
    restore, after which the trajectory continues EXACTLY where the
    uninterrupted run would be (shared-stream replay)."""
    ck = str(tmp_path / "ck")
    # leg 1: save at 6, rank 1 dies at 9 -> survivor must self-detect
    rc, events = launch.run_local_job_raw(
        2, [sys.executable, "-m", APP, "--iters", "16",
            "--checkpoint-dir", ck, "--save-at", "6",
            "--kill-at", "9", "--kill-rank", "1"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1",
                   "MINIPS_MH_LOCAL_DEVICES": "4"},
        timeout=240.0)
    assert rc != 0
    surv = [e for e in events[0] if e.get("event") == "peer_failure"]
    assert surv and 1 in surv[0]["dead"], events[0][-3:]

    # leg 2: relaunch at the same world size, restore step 6, finish
    res = _run_multihost(
        2, ["--iters", "16", "--checkpoint-dir", ck,
            "--restore-from", "6"])
    assert all(r["event"] == "done" and r["resumed_from"] == 6
               for r in res)
    assert res[0]["losses"] == res[1]["losses"]
    assert len(res[0]["losses"]) == 10  # iters 6..15
    assert res[0]["loss_last"] < res[0]["losses"][0]


@pytest.mark.slow
def test_collective_ssp_gates_xla_collectives():
    """VERDICT r3 missing #2 / SURVEY §7.4.1 as written: SSP whose sync
    is an XLA COLLECTIVE. 2 real processes, per-process local fused
    steps, a straggler on rank 1, staleness 2 with the merge every 8
    steps (period > bound, so the host-side gate — not the collective
    barrier — is what restrains the fast rank). Asserts:

    - the fast rank actually BLOCKED on the gossiped clock gate
      (gate_waits > 0) and skew stayed inside s+1;
    - sync traffic is a collective (compiled merge HLO contains
      all-reduce over the (proc, local) global mesh spanning all 8
      devices across both processes) while params/opt state stay on
      local devices (fast tier pins that);
    - post-finalize replicas are IDENTICAL across ranks;
    - per-rank loss streams equal the sequential 2-virtual-host oracle
      (the gate changes overlap, never math), which also transitively
      pins bsp/asp modes — same program, different gate constant.
    """
    res = _run_multihost(
        2, ["--mode", "ssp", "--staleness", "2", "--sync-every", "8",
            "--iters", "8", "--batch", "64", "--slow-rank", "1",
            "--slow-ms", "40"])
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["sync_hlo_has_all_reduce"] is True
        assert r["sync_plane_devices"] == 8
        assert r["max_skew_seen"] <= 3  # s + 1, same bound as the relay
        assert r["loss_last"] < r["loss_first"], r
        assert r["sync_rounds"] == 1
    fast = res[0] if res[0]["rank"] == 0 else res[1]
    assert fast["gate_waits"] > 0, fast
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]

    proc = subprocess.run(
        [sys.executable, "-m", APP, "--mode", "ssp", "--sync-every", "8",
         "--iters", "8", "--batch", "64", "--oracle-hosts", "2"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "MINIPS_FORCE_CPU": "1",
             "MINIPS_MH_LOCAL_DEVICES": "8"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    oracle = json.loads([ln for ln in proc.stdout.splitlines()
                         if ln.startswith("{")][-1])
    for r in res:
        np.testing.assert_allclose(
            r["losses"], oracle["losses_per_host"][r["rank"]], rtol=1e-6)
        np.testing.assert_allclose(
            r["param_fingerprint"], oracle["param_fingerprints"][0],
            rtol=1e-6)


@pytest.mark.slow
def test_collective_bsp_two_process_lockstep():
    """staleness=0 over the collective-sync path: lockstep (skew <= 1),
    one merge per step, identical replicas — the BSP end of the one
    staleness axis, now on the collective plane too."""
    res = _run_multihost(
        2, ["--mode", "bsp", "--iters", "6", "--batch", "64"])
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["max_skew_seen"] <= 1
        assert r["sync_rounds"] == 6
        assert r["sync_hlo_has_all_reduce"] is True
        assert r["loss_last"] < r["loss_first"], r
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_collective_ssp_beats_bsp_under_transient_stalls():
    """The SSP win measured on the COLLECTIVE-SYNC path (bench_ssp
    --collective): with random per-rank transient stalls, BSP (s=0)
    locksteps every local step and pays the union of all stalls, while
    SSP's slack window absorbs them — and on this path the gate changes
    ONLY overlap, so the loss streams must be IDENTICAL, making the
    speedup pure wall-clock. Tolerant bound (0.95) for a loaded 1-core
    host; bench_ssp publishes the real number (~1.2x at these knobs)."""
    jitter = ["--jitter-ms", "40", "--jitter-prob", "0.3",
              "--sync-every", "8", "--iters", "40", "--batch", "64"]
    last = None
    for attempt in range(2):  # RuntimeError-only shield: launch timeout
        try:                  # under tier load, same policy as the
            walls, streams, skews = {}, {}, {}   # sharded-PS smoke
            for mode, s in [("bsp", 0), ("ssp", 4)]:
                res = _run_multihost(
                    2, ["--mode", mode, "--staleness", str(s)] + jitter,
                    local_devices=2)
                walls[mode] = max(r["wall_s"] for r in res)
                streams[mode] = sorted((r["rank"], tuple(r["losses"]))
                                       for r in res)
                skews[mode] = max(r["max_skew_seen"] for r in res)
        except RuntimeError as e:  # noqa: PERF203
            last = e
            print(f"attempt {attempt}: {e}")
            continue
        assert walls["ssp"] < walls["bsp"] * 0.95, (walls, skews)
        assert streams["ssp"] == streams["bsp"]  # gate never changes math
        assert skews["ssp"] <= 5  # s + 1
        return
    raise last


@pytest.mark.slow
def test_two_process_loss_parity_with_single_process():
    """2 processes x 4 devices must train EXACTLY like 1 process x 8
    devices on the same global batch stream — the distributed data plane
    changes the wiring, never the math (the reference's N-node run
    computes the same updates as its 1-node run, SURVEY.md §2.2 DP row)."""
    res2 = _run_multihost(2, ["--iters", "8"])
    # single process, 8 local devices, no launcher: the oracle
    proc = subprocess.run(
        [sys.executable, "-m", APP, "--iters", "8"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "MINIPS_FORCE_CPU": "1",
             "MINIPS_MH_LOCAL_DEVICES": "8"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    solo = json.loads(line)
    assert solo["multi"] is False and solo["process_count"] == 1
    np.testing.assert_allclose(res2[0]["losses"], solo["losses"],
                               rtol=1e-6)
