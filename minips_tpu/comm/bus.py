"""ControlBus — the surviving sliver of the reference's ZeroMQ Mailbox.

The reference routes *all* traffic (push/pull payloads, clocks, barriers,
heartbeats) through a zmq ROUTER/DEALER mailbox (SURVEY.md §2.3). In the
rebuild the data plane is XLA collectives, so the only traffic that still
needs sockets is the control plane: SSP clock gossip and heartbeats, which
must stay nonblocking while a TPU step runs (SURVEY.md §2.3 "Control
plane"). This is a deliberately tiny pub/sub bus: every process binds one
PUB socket and subscribes to all peers; messages are small JSON dicts
``{kind, sender, payload}``.

Tested over loopback in-process (the reference tests its mailbox the same
way — threads as nodes, SURVEY.md §4).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

try:
    import zmq
    _HAS_ZMQ = True
except ImportError:  # pragma: no cover - zmq is present in the target env
    _HAS_ZMQ = False


class ControlBus:
    """PUB/SUB gossip bus: ``publish(kind, payload)`` fans out to all peers;
    handlers registered per kind run on a background receive thread."""

    def __init__(self, my_addr: str, peer_addrs: list[str],
                 my_id: int = 0):
        if not _HAS_ZMQ:
            raise RuntimeError("pyzmq not available")
        self.my_id = my_id
        self._ctx = zmq.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.bind(my_addr)
        self._sub = self._ctx.socket(zmq.SUB)
        for addr in peer_addrs:
            self._sub.connect(addr)
        self._sub.setsockopt_string(zmq.SUBSCRIBE, "")
        self._handlers: dict[str, Callable[[int, dict], None]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pub_lock = threading.Lock()

    def on(self, kind: str, handler: Callable[[int, dict], None]) -> None:
        """Register ``handler(sender_id, payload)`` for message kind."""
        self._handlers[kind] = handler

    def start(self) -> "ControlBus":
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()
        # PUB/SUB needs a beat for subscriptions to propagate (slow joiner).
        time.sleep(0.05)
        return self

    def publish(self, kind: str, payload: dict) -> None:
        msg = json.dumps({"kind": kind, "sender": self.my_id,
                          "payload": payload})
        with self._pub_lock:
            self._pub.send_string(msg)

    def _recv_loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self._sub, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=50)):
                continue
            try:
                msg = json.loads(self._sub.recv_string(zmq.NOBLOCK))
            except (zmq.ZMQError, json.JSONDecodeError):
                continue
            handler = self._handlers.get(msg.get("kind"))
            if handler is not None:
                handler(msg.get("sender", -1), msg.get("payload", {}))

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        self._pub.close(linger=0)
        self._sub.close(linger=0)

    def __enter__(self) -> "ControlBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ClockGossip:
    """SSP clock exchange over the bus (SURVEY.md §7.4): each process
    publishes its local worker clocks; the merged global view feeds the
    host-side staleness gate."""

    def __init__(self, bus: ControlBus, num_processes: int,
                 workers_per_process: int):
        self.bus = bus
        self._clocks = {p: [0] * workers_per_process
                        for p in range(num_processes)}
        self._lock = threading.Lock()
        bus.on("clock", self._on_clock)

    def _on_clock(self, sender: int, payload: dict) -> None:
        with self._lock:
            self._clocks[sender] = list(payload.get("clocks", []))

    def publish_local(self, clocks: list[int]) -> None:
        with self._lock:
            self._clocks[self.bus.my_id] = list(clocks)
        self.bus.publish("clock", {"clocks": list(clocks)})

    def global_min(self) -> int:
        with self._lock:
            return min(min(v) for v in self._clocks.values() if v)

    def snapshot(self) -> dict[int, list[int]]:
        with self._lock:
            return {k: list(v) for k, v in self._clocks.items()}
