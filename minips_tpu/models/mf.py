"""Matrix factorization — the reference's MF workload (BASELINE.json:9:
MovieLens-20M, async ASP).

Rating r_ui ≈ mu + b_u + b_i + <p_u, q_i>. User/item factors live in
SparseTables (keys = user/item ids — the PS's per-key pull/push is exactly
embedding-row traffic); biases ride in the last factor column to keep one
table per side: factor vector = [p_u (k), b_u (1)] and [q_i (k), 1].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def predict(u_rows, i_rows, mu: float = 0.0):
    """u_rows/i_rows: [B, k+1] where column k holds bias (user) / 1 (item
    handled by caller init). Prediction = mu + sum(u*i)."""
    return mu + jnp.sum(u_rows * i_rows, axis=-1)


def loss(u_rows, i_rows, ratings, mu: float = 0.0, reg: float = 0.0):
    """Squared error + L2 on the touched rows (the reference regularizes
    per-sample on pulled keys — server-side global L2 is impossible in a
    per-key PS, same here)."""
    err = predict(u_rows, i_rows, mu) - ratings
    l = jnp.mean(err * err)
    if reg > 0.0:
        l = l + reg * (jnp.mean(jnp.sum(u_rows * u_rows, -1))
                       + jnp.mean(jnp.sum(i_rows * i_rows, -1)))
    return l


def grad_fn(u_rows, i_rows, batch, mu: float = 0.0, reg: float = 0.02):
    def f(rows):
        return loss(rows[0], rows[1], batch["rating"], mu, reg)
    l, (gu, gi) = jax.value_and_grad(f)((u_rows, i_rows))
    return l, gu, gi
