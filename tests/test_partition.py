import numpy as np
import pytest

from minips_tpu.parallel.mesh import padded_size
from minips_tpu.parallel.partition import RangePartitioner


def test_padded_size():
    assert padded_size(10, 4) == 12
    assert padded_size(8, 4) == 8
    assert padded_size(1, 8) == 8
    assert padded_size(0, 4) == 4  # empty tables still get one row per shard


def test_contiguous_ranges():
    p = RangePartitioner(num_keys=10, num_shards=4)
    assert p.padded == 12 and p.shard_size == 3
    keys = np.arange(10)
    np.testing.assert_array_equal(
        p.shard_of(keys), [0, 0, 0, 1, 1, 1, 2, 2, 2, 3])


def test_split_preserves_order_and_partition():
    p = RangePartitioner(num_keys=100, num_shards=8)
    keys = np.array([5, 99, 13, 0, 64, 63, 12])
    slices = p.split(keys)
    assert len(slices) == 8
    merged = np.concatenate([s for s in slices])
    assert sorted(merged.tolist()) == sorted(keys.tolist())
    for s, sl in enumerate(slices):
        assert (p.shard_of(sl) == s).all()


def test_local_offset_roundtrip():
    p = RangePartitioner(num_keys=64, num_shards=8)
    keys = np.arange(64)
    recon = p.shard_of(keys) * p.shard_size + p.local_offset(keys)
    np.testing.assert_array_equal(recon, keys)
