"""Heat-aware shard rebalancer (minips_tpu/balance/ + the epoch-fenced
migration in train/sharded_ps.py) — this PR's tentpole.

Three layers of drill:

- pure logic: MINIPS_REBALANCE spec parsing, the greedy bin-pack
  planner's hysteresis/improvement invariants (hypothesis), and the
  decayed heat accountant;
- threads-as-nodes over real loopback buses: a forced migration moves
  parameter rows AND optimizer state intact, stale-routed pushes
  forward to the new owner, stale-routed pulls are refused with the
  new table and transparently retried, the row cache drops migrated
  blocks, checkpoints round-trip the routing epoch/overlay/block
  state (and refuse to load without the subsystem armed; elastic
  reshard restores THROUGH rebalanced checkpoints), a BSP run with the
  rebalancer ON is bitwise-equal to OFF on uniform traffic
  (hysteresis: balanced traffic never migrates), a hypothesis property
  shows pulls admitted MID-MIGRATION never read staler than the SSP
  bound, and the whole protocol composes with seeded chaos + the
  retransmit layer (migration control frames survive drops);
- the slow tier: the acceptance drill — a real 3-process SSP launcher
  run on UNPERMUTED zipf(1.1) with MINIPS_REBALANCE on performs >= 1
  migration and ends with max/mean per-shard serve load strictly below
  the static-partition arm, zero poisons.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np
import pytest

from minips_tpu.balance.heat import HeatAccountant
from minips_tpu.balance.rebalancer import RebalanceConfig, plan_assignment
from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


class _StubRB:
    """Table-level rebalancer stand-in for raw-table protocol tests —
    `is not None` gating, adopt_now(), and a note_plan that adopts
    directly (raw-table tests drive no concurrent pushes, so the
    production rule 'adopt only on the push-driving thread' is moot)."""

    def __init__(self):
        self.tables = []

    def adopt_now(self):
        pass

    def note_plan(self, name, ep, ov):
        for t in self.tables:
            if t.name == name:
                t.adopt_table(ep, ov)


def _attach(tables, spec="block=4"):
    rb = _StubRB()
    rb.tables = list(tables)
    cfg = RebalanceConfig.parse(spec)
    for t in tables:
        t.attach_rebalancer(rb, cfg)
    return cfg


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.01)


# --------------------------------------------------------- config spec
def test_rebalance_config_parses_and_rejects_garbage():
    c = RebalanceConfig.parse(
        "interval=0.5,threshold=1.25,max_blocks=4,block=16,decay=0.9,"
        "topk=8,min_heat=2")
    assert (c.interval, c.threshold, c.max_blocks, c.block,
            c.decay, c.topk, c.min_heat) == (0.5, 1.25, 4, 16, 0.9, 8, 2)
    d = RebalanceConfig.parse("1")
    assert d.threshold >= 1.0 and d.block == 0  # defaults, block auto
    with pytest.raises(ValueError, match="unknown knob"):
        RebalanceConfig.parse("explode=1")
    with pytest.raises(ValueError, match="k=v"):
        RebalanceConfig.parse("interval")
    with pytest.raises(ValueError, match="bad value"):
        RebalanceConfig.parse("interval=abc")
    with pytest.raises(ValueError, match="threshold"):
        RebalanceConfig.parse("threshold=0.5")


# ------------------------------------------------------------- planner
def test_plan_assignment_invariants():
    """Seeded randomized property sweep (hypothesis-in-spirit; the
    sweep must run even where the test extra isn't installed): for
    arbitrary loads/candidates the planner never exceeds max_blocks,
    never fires under the hysteresis threshold, never moves a block
    twice, and never increases the global max load."""
    rng = np.random.default_rng(3)
    for _case in range(150):
        n = int(rng.integers(2, 7))
        loads = rng.uniform(0.0, 1000.0, size=n)
        threshold = float(rng.uniform(1.0, 3.0))
        max_blocks = int(rng.integers(1, 9))
        candidates = {}
        for b in rng.choice(64, size=int(rng.integers(0, 17)),
                            replace=False):
            o = int(rng.integers(0, n))
            # candidates live on the shard the load says (heat <= load)
            candidates[int(b)] = (o, min(float(rng.uniform(0.01, 300.0)),
                                         float(loads[o])))
        moves = plan_assignment(loads, candidates, threshold, max_blocks)
        mean = loads.sum() / n
        if mean > 0 and loads.max() / mean < threshold:
            assert moves == []  # hysteresis: below the ratio, never
            continue
        assert len(moves) <= max_blocks
        seen = set()
        new = loads.copy()
        for b, src, dst in moves:
            assert b not in seen  # a block moves at most once per plan
            seen.add(b)
            o, h = candidates[b]
            assert o == src  # moved FROM its reported owner
            assert 0 <= dst < n
            new[src] -= h
            new[dst] += h
        if moves:
            # every move strictly improves the pair it touches, so the
            # global max can never increase — and never goes negative
            assert new.max() <= loads.max() + 1e-9
            assert new.min() >= -1e-9


def test_plan_assignment_flattens_a_hot_shard():
    loads = [90.0, 5.0, 5.0]
    cands = {0: (0, 40.0), 1: (0, 25.0), 2: (0, 15.0), 3: (1, 2.0)}
    moves = plan_assignment(loads, cands, 1.3, 8)
    assert moves  # fired
    new = np.asarray(loads)
    for b, src, dst in moves:
        h = cands[b][1]
        new[src] -= h
        new[dst] += h
    assert new.max() < 90.0  # strictly better than static


# ---------------------------------------------------------------- heat
def test_heat_accountant_touch_decay_report():
    h = HeatAccountant(8, decay=0.5)
    h.touch(np.array([0, 0, 0, 1, 7]))
    assert h.total == 5.0
    h.tick()
    np.testing.assert_allclose(h.snapshot()[[0, 1, 7]], [1.5, 0.5, 0.5])
    h.touch(np.array([99, -3]))  # out-of-range ids are dropped, not grown
    assert h.total == 2.5
    rep = h.report(np.arange(8), topk=2)
    assert rep["blocks"] == [0, 1] or rep["blocks"] == [0, 7]
    assert rep["heat"][0] == 1.5
    assert rep["total"] == 2.5
    # cold blocks are not offered as candidates
    assert all(x > 0 for x in rep["heat"])
    with pytest.raises(ValueError):
        HeatAccountant(0)


# ---------------------------------------- migration protocol, in-proc
def test_migration_moves_rows_and_optimizer_state():
    """The core move: block 0 (keys 0..3) migrates rank0 -> rank1 with
    its adagrad accumulator; post-migration pushes step EXACTLY like an
    unmigrated oracle (state moved, never perturbed), and pulls route
    to the new owner transparently."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    _attach([t0, t1])
    # oracle: a standalone 1-shard table receiving the same frames
    oracle = ShardedTable("o", 64, 2, None, 0, 1, updater="adagrad",
                          lr=0.1)
    try:
        keys = np.arange(4, dtype=np.int64)  # block 0, home = rank 0
        g1 = np.full((4, 2), 2.0, np.float32)
        t0.push(keys, g1)  # pre-migration: accumulates real opt state
        oracle.push(keys, g1)
        w_pre = t0._w[:4].copy()
        acc_pre = t0._acc[:4].copy()
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        np.testing.assert_array_equal(t1._xtra[0]["w"], w_pre)
        np.testing.assert_array_equal(t1._xtra[0]["acc"], acc_pre)
        assert t0.rb_stats["blocks_out"] == 1
        assert t1.rb_stats["blocks_in"] == 1
        # post-migration push routes to the NEW owner and steps the
        # MOVED accumulator — bitwise the oracle's trajectory
        g2 = np.full((4, 2), 1.0, np.float32)
        t0.push(keys, g2)
        oracle.push(keys, g2)
        _wait(lambda: t1.serve["push_rows"] >= 4, msg="push applied")
        np.testing.assert_array_equal(t1._xtra[0]["w"], oracle._w[:4])
        np.testing.assert_array_equal(t1._xtra[0]["acc"],
                                      oracle._acc[:4])
        # pulls (from both sides) see the migrated rows
        np.testing.assert_array_equal(t0.pull(keys), oracle._w[:4])
        np.testing.assert_array_equal(t1.pull(keys), oracle._w[:4])
        # pull_all assembles the overlay over the dead home copy
        np.testing.assert_array_equal(t0.pull_all()[:4], oracle._w[:4])
        np.testing.assert_array_equal(t1.pull_all()[:4], oracle._w[:4])
    finally:
        for b in buses:
            b.close()


def test_migration_moves_adam_moments_and_steps():
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adam",
                      lr=0.05, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adam",
                      lr=0.05, pull_timeout=10.0)
    _attach([t0, t1])
    oracle = ShardedTable("o", 64, 2, None, 0, 1, updater="adam",
                          lr=0.05)
    try:
        keys = np.arange(4, dtype=np.int64)
        for g in (2.0, -1.0):
            grads = np.full((4, 2), g, np.float32)
            t0.push(keys, grads)
            oracle.push(keys, grads)
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        g3 = np.full((4, 2), 0.5, np.float32)
        t1.push(keys, g3)  # new owner's LOCAL push hits the xtra block
        oracle.push(keys, g3)
        st_ = t1._xtra[0]
        np.testing.assert_array_equal(st_["w"], oracle._w[:4])
        np.testing.assert_array_equal(st_["m"], oracle._m[:4])
        np.testing.assert_array_equal(st_["v"], oracle._v[:4])
        np.testing.assert_array_equal(st_["steps"], oracle._steps[:4])
    finally:
        for b in buses:
            b.close()


def test_stale_push_is_forwarded_to_the_current_owner():
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 1, buses[0], 0, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 1, buses[1], 1, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    _attach([t0, t1])
    try:
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        # a STALE-ROUTED frame (epoch 0 wire stamp, old owner target):
        # the old owner must forward it, not drop or misapply it
        keys = np.arange(2, dtype=np.int64)
        grads = np.ones((2, 1), np.float32)
        buses[1].send(0, "psP:t",
                      {"n": 2, "comm": "float32", "ep": 0,
                       "ws": 2, "nr": 64, "dm": 1, "rb": 4},
                      blob=keys.tobytes() + grads.tobytes())
        _wait(lambda: t1._xtra.get(0) is not None
              and t1._xtra[0]["w"][0, 0] == -1.0, msg="forwarded apply")
        assert t0.rb_stats["forwarded_pushes"] == 1
        assert t0.frames_dropped == 0 and t1.frames_dropped == 0
    finally:
        for b in buses:
            b.close()


def test_stale_pull_is_refused_and_transparently_retried():
    """Rank 1 never hears the plan (its adoption comes via the psE
    refusal itself): its pull of a migrated block round-trips to the
    OLD owner, gets refused-with-table, re-splits to the new owner,
    and still returns the right rows — the client-visible API never
    sees the migration."""
    buses = _mk_buses(3)
    tabs = [ShardedTable("t", 96, 1, buses[i], i, 3, updater="sgd",
                         lr=1.0, pull_timeout=15.0) for i in range(3)]
    _attach(tabs)
    try:
        tabs[0]._w[:4] = 7.0  # block 0 content before migration
        tabs[0].adopt_table(1, {0: 2})  # block 0: rank0 -> rank2
        tabs[2].adopt_table(1, {0: 2})
        keys = np.arange(4, dtype=np.int64)
        rows = tabs[1].pull(keys)  # rank1 still routes by the OLD table
        np.testing.assert_array_equal(rows, np.full((4, 1), 7.0))
        assert tabs[1].router.epoch == 1  # adopted via the refusal
        assert tabs[0].rb_stats["refused_pulls"] >= 1
        _wait(lambda: all(t.rebalance_settled() for t in tabs),
              msg="fences settle")
        assert all(t.frames_dropped == 0 for t in tabs)
    finally:
        for b in buses:
            b.close()


def test_row_cache_drops_migrated_blocks_on_adoption():
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd",
                      lr=0.5, pull_timeout=10.0, cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd",
                      lr=0.5, pull_timeout=10.0)
    _attach([t0, t1], spec="block=8")  # shard_size 32 -> block 8 keys
    try:
        keys = np.arange(32, 36, dtype=np.int64)  # t1's home block 4
        t1._w[...] = 3.0
        t0.pull(keys)  # cached
        assert len(t0._cache) == 4
        # block 4 (keys 32..39) migrates t1 -> t0: the adopter drops
        # its cached copies of every moved block
        t1.adopt_table(1, {4: 0})
        t0.adopt_table(1, {4: 0})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        assert len(t0._cache) == 0
        assert t0._cache.invalidations >= 4
        np.testing.assert_array_equal(t0.pull(keys),
                                      np.full((4, 2), 3.0))
    finally:
        for b in buses:
            b.close()


def test_checkpoint_roundtrips_epoch_overlay_and_block_state(tmp_path):
    from minips_tpu.ckpt.checkpoint import _flatten, _unflatten

    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    _attach([t0, t1])
    try:
        keys = np.arange(4, dtype=np.int64)
        t0.push(keys, np.full((4, 2), 2.0, np.float32))
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        # the npz round trip (flatten -> unflatten) preserves the
        # routing epoch, the overlay, and the migrated block's state
        sd1 = _unflatten(_flatten(t1.shard_state_dict()))
        assert int(sd1["ep"]) == 1
        f0 = ShardedTable("t", 64, 2, None, 0, 2, updater="adagrad",
                          lr=0.1)
        f1 = ShardedTable("t", 64, 2, None, 1, 2, updater="adagrad",
                          lr=0.1)
        _attach([f0, f1])
        f0.load_shard_state_dict(
            _unflatten(_flatten(t0.shard_state_dict())))
        f1.load_shard_state_dict(sd1)
        assert f0.router.epoch == 1 and f1.router.epoch == 1
        assert f0.router.table()[1] == {0: 1} == f1.router.table()[1]
        np.testing.assert_array_equal(f1._xtra[0]["w"],
                                      t1._xtra[0]["w"])
        np.testing.assert_array_equal(f1._xtra[0]["acc"],
                                      t1._xtra[0]["acc"])
        # restoring a rebalanced checkpoint WITHOUT the subsystem armed
        # would serve moved blocks from the wrong shard: refuse loudly
        cold = ShardedTable("t", 64, 2, None, 1, 2, updater="adagrad")
        with pytest.raises(ValueError, match="MINIPS_REBALANCE"):
            cold.load_shard_state_dict(sd1)
    finally:
        for b in buses:
            b.close()


def test_elastic_reshard_restores_through_overlay(tmp_path):
    """The overlay-aware reshard (elastic membership): a rebalanced
    checkpoint's moved blocks live in their owner's xtra section and
    the home slab holds dead copies — the reshard must place the LIVE
    rows (and optimizer leaves) wherever the new partition puts them,
    and flatten the overlay away (no routing metadata survives)."""
    from minips_tpu.ckpt.elastic import reshard_table_state

    # 8 rows, 2 old ranks (shard 4), block=2: block 0 = keys [0, 2)
    # moved from rank 0's home range to rank 1
    d0 = tmp_path / "rank0" / "step_0000000001"
    d0.mkdir(parents=True)
    w0 = np.arange(8, dtype=np.float32).reshape(4, 2)  # rows 0-3 (dead b0)
    np.savez(d0 / "t.npz", w=w0, m=w0 + 100, lo=np.asarray(0),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]))
    d1 = tmp_path / "rank1" / "step_0000000001"
    d1.mkdir(parents=True)
    w1 = np.arange(8, 16, dtype=np.float32).reshape(4, 2)  # rows 4-7
    live_b0 = np.full((2, 2), 55.0, np.float32)  # block 0's LIVE rows
    np.savez(d1 / "t.npz", w=w1, m=w1 + 100, lo=np.asarray(4),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]),
             **{"xtra/0/w": live_b0, "xtra/0/m": live_b0 + 1})

    # reshard 2 -> 1 (whole table on one shard of 8)
    st = reshard_table_state(str(tmp_path), 1, 2, "t", 8, 0, 8)
    assert not ({"ep", "ovb", "ovo", "rb_block"} & set(st))
    np.testing.assert_array_equal(st["w"][:2], live_b0)   # overlay wins
    np.testing.assert_array_equal(st["m"][:2], live_b0 + 1)
    np.testing.assert_array_equal(st["w"][2:4], w0[2:4])  # home rows
    np.testing.assert_array_equal(st["w"][4:], w1)

    # a torn rebalanced save (overlay recorded, owner's xtra missing)
    # still refuses loudly instead of assembling dead rows
    np.savez(d1 / "t.npz", w=w1, m=w1 + 100, lo=np.asarray(4),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]))
    with pytest.raises(ValueError, match="torn"):
        reshard_table_state(str(tmp_path), 1, 2, "t", 8, 0, 8)


def test_all_blocks_home_checkpoint_stays_elastic_reshardable():
    """Once every block migrates back home the layout IS the base
    partition again: the checkpoint must not record a routing epoch
    (which would lock elastic resize out forever — epochs never
    reset), and a cold rb-off table must accept it."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 1, buses[0], 0, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 1, buses[1], 1, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    _attach([t0, t1])
    try:
        t0.adopt_table(1, {0: 1})   # away...
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="migration settle")
        t0.adopt_table(2, {})       # ...and back home
        t1.adopt_table(2, {})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="return settle")
        sd = t0.shard_state_dict()
        assert "ep" not in sd and "xtra" not in sd
        cold = ShardedTable("t", 64, 1, None, 0, 2, updater="sgd")
        cold.load_shard_state_dict(sd)  # rb off: accepted
        np.testing.assert_array_equal(cold._w, t0._w)
    finally:
        for b in buses:
            b.close()


# --------------------------------------------- trainer-level, in-proc
def _run_trainers(n, spec, body, *, staleness=1, rows=64, dim=1,
                  updater="sgd", lr=1.0, bus_kw=None, steps=12):
    """Threads-as-nodes trainer run; body(r, table, trainer, step) runs
    per rank per step. Returns (tables, trainers, finals, chaos_drops)."""
    buses = _mk_buses(n, **(bus_kw or {}))
    tables = [ShardedTable("t", rows, dim, buses[i], i, n,
                           updater=updater, lr=lr, pull_timeout=20.0)
              for i in range(n)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], n,
                                 staleness=staleness, gate_timeout=30.0,
                                 rebalance=spec) for i in range(n)]
    finals: list = [None] * n
    errs: list = []

    def worker(r):
        try:
            for i in range(steps):
                body(r, tables[r], trainers[r], i)
                trainers[r].tick()
            trainers[r].finalize(timeout=30.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            import traceback

            traceback.print_exc()
            errs.append((r, repr(e)))

    try:
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in ts), "run wedged"
        assert not errs, errs
        drops = sum(getattr(b, "chaos").snapshot()["dropped"]
                    for b in buses if getattr(b, "chaos", None))
        return tables, trainers, finals, drops
    finally:
        for b in buses:
            b.close()


HOT_SPEC = ("interval=0.05,threshold=1.05,max_blocks=4,block=4,"
            "topk=16,min_heat=1")


@pytest.mark.parametrize("staleness,seed",
                         [(0, 11), (1, 23), (1, 57), (2, 101)])
def test_pulls_mid_migration_respect_the_staleness_bound(staleness,
                                                         seed):
    """THE safety property: with sgd lr=1 and +1 gradients, a row's
    value counts applied pushes — at any pull admitted at clock c,
    every peer's pushes through c − s must already be readable, WHILE
    blocks migrate under the reader. Any interleaving of plan adoption,
    state ship, fences, refusals and forwards must keep that bound."""
    hot = np.arange(8, dtype=np.int64)  # blocks 0,1 of shard 0
    n = 2
    bad: list = []

    def body(r, table, trainer, i):
        rows = table.pull(hot)
        counts = -rows[:, 0]
        need = i + max(0, i - staleness) * (n - 1)
        if not (counts >= need - 1e-6).all():
            bad.append((r, i, counts.min(), need))
        table.push(hot, np.ones((hot.size, 1), np.float32))
        time.sleep(0.01 * (1 + (seed + r) % 3) / 2)

    tables, trainers, finals, _ = _run_trainers(
        n, HOT_SPEC, body, staleness=staleness, steps=12)
    assert not bad, f"staleness bound violated mid-migration: {bad[:4]}"
    migrated = sum(t.rb_stats["blocks_in"] for t in tables)
    assert migrated >= 1, "no migration fired — the drill proved nothing"
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
        assert tr.max_skew_seen <= staleness + 1
    np.testing.assert_array_equal(finals[0], finals[1])


def test_bsp_uniform_is_bitwise_equal_with_rebalancer_on_and_off():
    """Acceptance pin: arming the rebalancer must not perturb one bit
    of training state when nothing migrates. BSP lockstep drive (the
    deterministic harness the chaos BSP drill uses — free-running BSP
    threads may LEGALLY read fresher-than-bound rows, so only lockstep
    order is comparable bitwise), uniform traffic, rb-armed vs seed
    path: final shards must be bitwise equal."""
    def run(rb_on):
        buses = _mk_buses(2)
        tabs = [ShardedTable("t", 64, 1, buses[i], i, 2, updater="sgd",
                             lr=0.5, pull_timeout=10.0)
                for i in range(2)]
        if rb_on:
            _attach(tabs, spec="block=4")
        try:
            for i in range(6):
                for r in (0, 1):
                    rng = np.random.default_rng((7, r, i))
                    keys = rng.integers(0, 64, size=16)
                    rows = tabs[r].pull(keys)
                    tabs[r].push(keys, (0.125 * rows + 1.0))
                # FIFO barrier per link: the next frame's reads prove
                # this step's pushes applied (deterministic order)
                tabs[0].pull(np.array([32]))
                tabs[1].pull(np.array([0]))
            return [t._w.copy() for t in tabs]
        finally:
            for b in buses:
                b.close()

    w_off = run(False)
    w_on = run(True)
    for a, b in zip(w_off, w_on):
        np.testing.assert_array_equal(a, b)  # bitwise, not allclose


def test_uniform_traffic_never_trips_the_hysteresis():
    """Balanced traffic + the default threshold: the planner must stay
    idle (zero migrations) on a full trainer run — the observable half
    of the bitwise pin above."""
    def body(r, table, trainer, i):
        rng = np.random.default_rng((7, r, i))
        keys = rng.integers(0, 64, size=16)
        rows = table.pull(keys)
        table.push(keys, (0.125 * rows + 1.0))

    tables, trainers, _finals, _ = _run_trainers(
        2, "interval=0.01,block=4", body, staleness=0, steps=8, lr=0.5)
    for tr in trainers:
        s = tr.rebalance_stats()
        assert s is not None and s["blocks_in"] == 0, s
        assert tr.frames_dropped == 0


def test_migration_composes_with_chaos_and_reliable():
    """Migration control frames (rbP/rbS/rbA/rbF/psE) ride the same
    reliable layer as everything else: under seeded drop/dup the run
    completes, migrates, loses nothing unrecovered, and replicas agree."""
    def body(r, table, trainer, i):
        rows = table.pull(np.arange(8, dtype=np.int64))
        table.push(np.arange(8, dtype=np.int64),
                   (0.01 * rows + 1.0))
        time.sleep(0.01)

    tables, trainers, finals, drops = _run_trainers(
        2, HOT_SPEC, body, staleness=1, steps=15,
        bus_kw={"chaos": "2025:drop=0.03,dup=0.01", "reliable": "1"})
    assert drops > 0, "chaos never fired — the drill proved nothing"
    assert sum(t.rb_stats["blocks_in"] for t in tables) >= 1
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
    np.testing.assert_array_equal(finals[0], finals[1])


def test_serve_counters_ride_wire_record():
    from minips_tpu.utils.metrics import wire_record

    def body(r, table, trainer, i):
        keys = np.arange(4, dtype=np.int64)
        table.pull(keys)
        table.push(keys, np.ones((4, 1), np.float32))

    tables, trainers, _finals, _ = _run_trainers(
        2, None, body, staleness=1, steps=3)
    rec = wire_record(trainers[0])
    assert rec["serve"]["pull_rows"] > 0
    assert rec["serve"]["push_rows"] > 0
    assert rec["rebalance"] is None  # off = None, not zeros


# ------------------------------------------------------- multi-process
@pytest.mark.slow
def test_rebalance_3proc_unpermuted_zipf_beats_static():
    """The acceptance drill: 3-process SSP(1) on UNPERMUTED zipf(1.1)
    (the whole head in shard 0's range). With MINIPS_REBALANCE on the
    run must perform >= 1 migration and end with max/mean per-shard
    serve load STRICTLY below the static arm's, with zero poisons,
    drops, or unrecovered frames on both arms."""
    from minips_tpu import launch

    argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", "sparse", "--rows", "4096", "--batch", "1024",
            "--iters", "30", "--warmup", "4", "--key-dist", "zipf",
            "--no-zipf-permute-hot", "--staleness", "1",
            "--updater", "sgd", "--pull-timeout", "30"]
    spec = ("interval=0.2,threshold=1.2,max_blocks=16,block=8,"
            "topk=64,min_heat=100")

    def run(rebalance):
        res = launch.run_local_job(
            3, argv, base_port=None,
            env_extra={"MINIPS_REBALANCE": rebalance,
                       "JAX_PLATFORMS": "cpu"},
            timeout=240.0)
        assert all(r["event"] == "done" for r in res)
        for r in res:
            assert r["wire_frames_lost"] == 0, r
            assert r["rebalance_spec"] == (rebalance or None), r
        served = [r["serve"]["pull_rows"] + r["serve"]["push_rows"]
                  for r in res]
        imb = max(served) / (sum(served) / len(served))
        moved = sum((r.get("rebalance") or {}).get("blocks_in", 0)
                    for r in res)
        return imb, moved

    static_imb, static_moved = run("")
    rb_imb, rb_moved = run(spec)
    assert static_moved == 0
    assert rb_moved >= 1, "rebalancer never migrated under head skew"
    # the whole zipf head sits in shard 0's range: static is heavily
    # imbalanced, and the rebalancer must land strictly below it
    assert static_imb > 1.5, static_imb
    assert rb_imb < static_imb, (rb_imb, static_imb)
