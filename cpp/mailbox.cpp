// Native control-plane mailbox: TCP full-mesh pub/sub transport.
//
// The reference's Mailbox is native C++ over ZeroMQ ROUTER/DEALER sockets
// with per-thread ThreadsafeQueue inboxes and a dedicated Sender actor
// (SURVEY.md L0/L1, §2.3). In the TPU rebuild the data plane is XLA
// collectives, so what survives here is the control plane (SSP clocks,
// heartbeats, barriers, host-relayed deltas) — but that plane is still
// native C++, matching the reference's runtime layering: raw TCP sockets,
// a ThreadsafeQueue<Message> inbox, an accept/reader actor per connection
// and a Sender actor draining a BOUNDED outgoing queue: publish() is
// nonblocking until the outbox holds outbox_cap_ frames, then it applies
// producer backpressure (blocks up to 30s, after which the frame is
// counted dropped — never silently lost)
// the training thread.
//
// Wire frame (little-endian):
//   u32 magic 'MPSB' | u32 msg_len | i64 blob_len (-1 = none)
//   | msg bytes (JSON) | blob bytes
//
// C ABI only (pybind11 absent in this image); bound via ctypes from
// minips_tpu/comm/native_bus.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4253504Du;  // 'MPSB'
constexpr uint32_t kMaxMsg = 16u << 20;   // 16 MB JSON frame cap
constexpr int64_t kMaxBlob = 1ll << 30;   // 1 GB blob cap

struct Msg {
  std::string msg;
  std::vector<uint8_t> blob;
  bool has_blob = false;
  int dest = -1;  // outgoing-peer index (connect order); -1 = broadcast
};

// The reference's ThreadsafeQueue<Message>: mutex + condvar inbox.
template <typename T>
class ThreadsafeQueue {
 public:
  void push(T v) {
    {
      std::lock_guard<std::mutex> g(mu_);
      q_.push_back(std::move(v));
    }
    cv_.notify_one();
  }
  // Bounded push: BLOCKS while the queue holds >= cap items (producer
  // backpressure — an ASP worker outrunning the Sender actor must slow
  // down, not grow the queue without bound). cap 0 = unbounded. Returns
  // false (item NOT enqueued) only after timeout_ms of no space or on a
  // closed queue — the caller counts that as a dropped frame.
  bool push_bounded(T v, size_t cap, int timeout_ms) {
    std::unique_lock<std::mutex> g(mu_);
    if (cap > 0) {
      auto ok = space_cv_.wait_for(
          g, std::chrono::milliseconds(timeout_ms),
          [&] { return q_.size() < cap || closed_; });
      if (!ok || closed_) return false;
    } else if (closed_) {
      return false;
    }
    q_.push_back(std::move(v));
    g.unlock();
    cv_.notify_one();
    return true;
  }
  size_t size() {
    std::lock_guard<std::mutex> g(mu_);
    return q_.size();
  }
  // Returns false on timeout or close-with-empty-queue.
  bool pop(T* out, int timeout_ms) {
    std::unique_lock<std::mutex> g(mu_);
    auto pred = [&] { return !q_.empty() || closed_; };
    if (timeout_ms < 0) {
      cv_.wait(g, pred);
    } else if (!cv_.wait_for(g, std::chrono::milliseconds(timeout_ms),
                             pred)) {
      return false;
    }
    if (q_.empty()) return false;  // closed
    *out = std::move(q_.front());
    q_.pop_front();
    g.unlock();
    space_cv_.notify_all();  // wake bounded producers
    return true;
  }
  void close() {
    {
      std::lock_guard<std::mutex> g(mu_);
      closed_ = true;
    }
    cv_.notify_all();
    space_cv_.notify_all();
  }
  bool drain_wait(int timeout_ms) {  // wait until empty (sender flush)
    std::unique_lock<std::mutex> g(mu_);
    return drained_cv_.wait_for(g, std::chrono::milliseconds(timeout_ms),
                                [&] { return q_.empty(); });
  }
  void notify_drained() { drained_cv_.notify_all(); }
  bool empty() {
    std::lock_guard<std::mutex> g(mu_);
    return q_.empty();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable drained_cv_;
  std::condition_variable space_cv_;  // bounded-push producers wait here
  std::deque<T> q_;
  bool closed_ = false;
};

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// Blocking read of exactly n bytes, polling `stop` every 100ms.
bool read_all(int fd, void* buf, size_t n, const std::atomic<bool>& stop) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, 100);
    if (stop.load()) return false;
    if (pr == 0) continue;
    if (pr < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Mailbox {
 public:
  Mailbox() = default;

  // Bind + listen; returns false on failure. port 0 = ephemeral.
  bool Bind(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) < 0 ||
        ::listen(listen_fd_, 64) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    bound_port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread(&Mailbox::AcceptLoop, this);
    sender_thread_ = std::thread(&Mailbox::SenderLoop, this);
    return true;
  }

  int BoundPort() const { return bound_port_; }

  // Connect to a peer's listener, retrying until timeout_ms (the peer's
  // process may not have bound yet — the reference's startup has the same
  // bind-before-connect ordering problem, solved there by config-ordered
  // boot; here by retry).
  bool Connect(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return false;
    while (!stop_.load()) {
      int fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) return false;
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // Bounded sends: a wedged peer (full receive buffer, SIGSTOP)
        // must not block the Sender actor forever while it holds
        // peers_mu_ — after 5s the peer is treated as dead and dropped,
        // the same verdict the heartbeat layer would reach.
        struct timeval tv = {5, 0};
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        std::lock_guard<std::mutex> g(peers_mu_);
        peer_fds_.push_back(fd);
        return true;
      }
      ::close(fd);
      if (std::chrono::steady_clock::now() >= deadline) return false;
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
    return false;
  }

  // Enqueue for the Sender actor. Bounded: when the outbox holds
  // outbox_cap_ frames the producer BLOCKS (backpressure) up to 30s;
  // only then is the frame counted dropped — the Python layer surfaces
  // dropped_ so a send-side loss can never be silent.
  void Publish(Msg m) {
    if (!outbox_.push_bounded(std::move(m), outbox_cap_.load(), 30000))
      dropped_.fetch_add(1);
  }

  void SetOutboxCap(size_t cap) { outbox_cap_.store(cap); }

  // Wake any bounded-push producer immediately (they see closed_ and
  // return false → counted drop). Safe concurrently with Publish; used
  // by the Python close() path so teardown never waits out a 30s
  // backpressure stall.
  void InterruptOutbox() { outbox_.close(); }
  int64_t OutboxDepth() { return static_cast<int64_t>(outbox_.size()); }
  int64_t Dropped() const { return dropped_.load(); }

  bool Recv(Msg* out, int timeout_ms) { return inbox_.pop(out, timeout_ms); }

  // Flush outgoing queue (bounded), then tear everything down.
  void Close() {
    outbox_.drain_wait(1000);
    stop_.store(true);
    inbox_.close();
    outbox_.close();
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (accept_thread_.joinable()) accept_thread_.join();
    if (sender_thread_.joinable()) sender_thread_.join();
    {
      std::lock_guard<std::mutex> g(readers_mu_);
      for (int fd : reader_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& t : reader_threads_)
      if (t.joinable()) t.join();
    {
      std::lock_guard<std::mutex> g(readers_mu_);
      for (int fd : reader_fds_) ::close(fd);
      reader_fds_.clear();
    }
    {
      std::lock_guard<std::mutex> g(peers_mu_);
      for (int fd : peer_fds_)
        if (fd >= 0) ::close(fd);
      peer_fds_.clear();
    }
    if (listen_fd_ >= 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
  }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      struct pollfd pfd = {listen_fd_, POLLIN, 0};
      int pr = ::poll(&pfd, 1, 100);
      if (stop_.load()) return;
      if (pr <= 0) continue;
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) continue;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(readers_mu_);
      reader_fds_.push_back(fd);
      reader_threads_.emplace_back(&Mailbox::ReaderLoop, this, fd);
    }
  }

  void ReaderLoop(int fd) {
    while (!stop_.load()) {
      uint32_t header[2];
      int64_t blob_len;
      if (!read_all(fd, header, sizeof(header), stop_)) return;
      if (header[0] != kMagic || header[1] > kMaxMsg) return;  // poisoned
      if (!read_all(fd, &blob_len, sizeof(blob_len), stop_)) return;
      if (blob_len > kMaxBlob) return;
      Msg m;
      m.msg.resize(header[1]);
      if (header[1] && !read_all(fd, &m.msg[0], header[1], stop_)) return;
      if (blob_len >= 0) {
        m.has_blob = true;
        m.blob.resize(static_cast<size_t>(blob_len));
        if (blob_len &&
            !read_all(fd, m.blob.data(), m.blob.size(), stop_))
          return;
      }
      inbox_.push(std::move(m));
    }
  }

  // The Sender actor: drains the outbox, fanning each message out to every
  // connected peer. A peer whose socket dies is dropped (marked -1) — the
  // heartbeat layer above notices the silence and excludes it.
  void SenderLoop() {
    while (true) {
      Msg m;
      if (!outbox_.pop(&m, 200)) {  // idle beat or closed-and-empty
        outbox_.notify_drained();
        if (stop_.load()) return;
        continue;
      }
      uint32_t header[2] = {kMagic, static_cast<uint32_t>(m.msg.size())};
      int64_t blob_len = m.has_blob
                             ? static_cast<int64_t>(m.blob.size())
                             : -1;
      std::lock_guard<std::mutex> g(peers_mu_);
      // Directed frames (dest >= 0, connect-order index) hit one socket;
      // broadcasts fan out. FIFO through the shared outbox preserves the
      // per-peer ordering contract across send() and publish().
      size_t lo = 0, hi = peer_fds_.size();
      if (m.dest >= 0) {
        if (static_cast<size_t>(m.dest) >= peer_fds_.size()) continue;
        lo = static_cast<size_t>(m.dest);
        hi = lo + 1;
      }
      for (size_t i = lo; i < hi; ++i) {
        int& fd = peer_fds_[i];
        if (fd < 0) continue;
        bool ok = write_all(fd, header, sizeof(header)) &&
                  write_all(fd, &blob_len, sizeof(blob_len)) &&
                  (m.msg.empty() || write_all(fd, m.msg.data(),
                                              m.msg.size())) &&
                  (!m.has_blob || m.blob.empty() ||
                   write_all(fd, m.blob.data(), m.blob.size()));
        if (!ok) {
          ::close(fd);
          fd = -1;
        }
      }
      if (outbox_.empty()) outbox_.notify_drained();
    }
  }

  int listen_fd_ = -1;
  int bound_port_ = 0;
  std::atomic<size_t> outbox_cap_{8192};  // frames; see Publish()
  std::atomic<int64_t> dropped_{0};
  std::atomic<bool> stop_{false};
  ThreadsafeQueue<Msg> inbox_;
  ThreadsafeQueue<Msg> outbox_;
  std::mutex peers_mu_;
  std::vector<int> peer_fds_;  // outgoing fan-out sockets
  std::mutex readers_mu_;
  std::vector<int> reader_fds_;  // accepted incoming sockets
  std::vector<std::thread> reader_threads_;
  std::thread accept_thread_;
  std::thread sender_thread_;
};

}  // namespace

extern "C" {

void* mailbox_create(int listen_port) {
  auto* mb = new Mailbox();
  if (!mb->Bind(listen_port)) {
    delete mb;
    return nullptr;
  }
  return mb;
}

int mailbox_port(void* h) { return static_cast<Mailbox*>(h)->BoundPort(); }

int mailbox_connect(void* h, const char* host, int port, int timeout_ms) {
  return static_cast<Mailbox*>(h)->Connect(host, port, timeout_ms) ? 0 : -1;
}

void mailbox_publish(void* h, const char* msg, int64_t msg_len,
                     const uint8_t* blob, int64_t blob_len) {
  Msg m;
  m.msg.assign(msg, static_cast<size_t>(msg_len));
  if (blob_len >= 0) {
    m.has_blob = true;
    m.blob.assign(blob, blob + blob_len);
  }
  static_cast<Mailbox*>(h)->Publish(std::move(m));
}

// Directed variant: peer_index is the order Connect() was called in.
void mailbox_send(void* h, int peer_index, const char* msg, int64_t msg_len,
                  const uint8_t* blob, int64_t blob_len) {
  Msg m;
  m.dest = peer_index;
  m.msg.assign(msg, static_cast<size_t>(msg_len));
  if (blob_len >= 0) {
    m.has_blob = true;
    m.blob.assign(blob, blob + blob_len);
  }
  static_cast<Mailbox*>(h)->Publish(std::move(m));
}

// Returns 1 with ownership of *msg_out/*blob_out transferred (free via
// mailbox_free_buf), 0 on timeout/closed.
int mailbox_recv(void* h, int timeout_ms, char** msg_out, int64_t* msg_len,
                 uint8_t** blob_out, int64_t* blob_len) {
  Msg m;
  if (!static_cast<Mailbox*>(h)->Recv(&m, timeout_ms)) return 0;
  *msg_len = static_cast<int64_t>(m.msg.size());
  *msg_out = static_cast<char*>(::malloc(m.msg.size() + 1));
  std::memcpy(*msg_out, m.msg.data(), m.msg.size());
  (*msg_out)[m.msg.size()] = '\0';
  if (m.has_blob) {
    *blob_len = static_cast<int64_t>(m.blob.size());
    *blob_out = static_cast<uint8_t*>(::malloc(m.blob.size() ? m.blob.size()
                                                             : 1));
    std::memcpy(*blob_out, m.blob.data(), m.blob.size());
  } else {
    *blob_len = -1;
    *blob_out = nullptr;
  }
  return 1;
}

void mailbox_free_buf(void* p) { ::free(p); }

// Outgoing-queue observability: depth (frames awaiting the Sender actor),
// the producer-side drop counter (bounded-push timeouts; must stay 0 in a
// healthy job), and the cap setter (0 = unbounded).
int64_t mailbox_outbox_depth(void* h) {
  return static_cast<Mailbox*>(h)->OutboxDepth();
}

int64_t mailbox_dropped(void* h) {
  return static_cast<Mailbox*>(h)->Dropped();
}

void mailbox_set_outbox_cap(void* h, int64_t cap) {
  static_cast<Mailbox*>(h)->SetOutboxCap(
      cap < 0 ? 0 : static_cast<size_t>(cap));
}

void mailbox_interrupt(void* h) {
  static_cast<Mailbox*>(h)->InterruptOutbox();
}

void mailbox_close(void* h) {
  auto* mb = static_cast<Mailbox*>(h);
  mb->Close();
  delete mb;
}

}  // extern "C"
