"""Windowed metrics — per-interval deltas over the always-on counters
and log2 histograms, so "how is the fleet doing NOW" stops reading
"how has it done since boot".

Every latency histogram in the stack (obs/hist.py) and every load
counter (serve sheds, reliable retransmits, drops) is CUMULATIVE: cheap,
merge-able, and exactly wrong for control decisions. The autoscaler's
``up_p99_ms`` arming read the cumulative pull-latency hist, so a storm's
tail samples stayed in the p99 forever — the signal could arm but
provably never disarm (ROADMAP item 3 carry-forward (b)). This module is
the windowed layer over those same primitives:

- **Hist windows.** The log2 buckets are FIXED, so a histogram's delta
  over an interval is an elementwise subtraction, and a window quantile
  is ``summarize_counts`` over the elementwise SUM of the last K deltas
  — the identical trick the per-rank merge uses, pointed at time instead
  of space. No second recording path: the hot paths keep feeding the one
  cumulative histogram; :meth:`WindowedMetrics.roll` snapshots it once
  per interval (the trainer's clock boundary) and stores the delta in a
  bounded ring.
- **Counter windows.** Same shape, scalar: per-roll deltas of cumulative
  counters, summed over the window and divided by the window's wall span
  for a rate. A counter that went BACKWARD (layer restarted) re-baselines
  instead of booking a negative burst.
- **Gauges.** Values that are already instantaneous (oldest outstanding
  reliable gap age): the ring stores samples, the window reports
  last/max.

The layer is ALWAYS ON (``MINIPS_OBS=0`` disables it — that arm exists
for the OBS-TAX honesty measurement, not for production): the roll is
one snapshot pass per clock boundary, far off the per-frame hot path.
Off-vs-idle follows the PR5 convention — an OFF layer reports ``None``
in the done line, an armed-but-idle window reports ``{"count": 0}``.

Spec grammar (``MINIPS_OBS``): ``""``/``"1"`` = defaults on, ``"0"`` =
off, else ``window=<rolls>,ring=<rolls>`` (window = the default K
quantiles/rates read; ring = how many deltas are retained, the largest
readable window).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from minips_tpu.obs.hist import N_BUCKETS, quantile_us, summarize_counts

__all__ = ["ObsWindowConfig", "WindowedMetrics", "maybe_build"]

_DEF_WINDOW = 8
_DEF_RING = 32


class ObsWindowConfig:
    """Parsed ``MINIPS_OBS`` knobs (k=v comma list; ``"1"``/empty =
    every default)."""

    def __init__(self, *, window: int = _DEF_WINDOW,
                 ring: int = _DEF_RING):
        if window < 1:
            raise ValueError("MINIPS_OBS: window must be >= 1 roll")
        if ring < window:
            raise ValueError(
                f"MINIPS_OBS: ring {ring} must hold at least one "
                f"window ({window} rolls) — a window the ring cannot "
                "cover would silently report a shorter one")
        self.window = int(window)
        self.ring = int(ring)

    @classmethod
    def parse(cls, spec: str) -> "Optional[ObsWindowConfig]":
        """None = the layer is OFF (``"0"``); a config otherwise."""
        spec = (spec or "").strip()
        if spec == "0":
            return None
        if spec in ("", "1", "on", "true"):
            return cls()
        kw: dict = {}
        for item in filter(None, (e.strip() for e in spec.split(","))):
            if "=" not in item:
                raise ValueError(
                    f"MINIPS_OBS: expected k=v, got {item!r}")
            k, _, v = item.partition("=")
            k = k.strip()
            if k not in ("window", "ring"):
                raise ValueError(f"MINIPS_OBS: unknown knob {k!r}")
            try:
                kw[k] = int(v)
            except ValueError as e:
                raise ValueError(
                    f"MINIPS_OBS: bad value for {k}: {v!r}") from e
        return cls(**kw)


def maybe_build(spec: Optional[str] = None
                ) -> "Optional[WindowedMetrics]":
    """Build from an explicit spec or ``$MINIPS_OBS`` (explicit wins,
    the shared knob convention); None when the layer is disabled."""
    if spec is None:
        spec = os.environ.get("MINIPS_OBS", "")
    cfg = ObsWindowConfig.parse(spec)
    if cfg is None:
        return None
    return WindowedMetrics(window=cfg.window, ring=cfg.ring)


class WindowedMetrics:
    """Ring-buffered per-roll deltas over registered cumulative signals.

    One instance per trainer (or mesh plane); :meth:`roll` is called
    from the push-driving thread at each clock boundary, reads may come
    from any thread (the autoscaler's decision step, the done line, a
    flight-recorder dump) — one lock serializes, and every critical
    section is a bounded copy (K deltas of 40 ints), never a wire or
    file touch."""

    def __init__(self, *, window: int = _DEF_WINDOW,
                 ring: int = _DEF_RING,
                 clock: Callable[[], float] = time.monotonic):
        cfg = ObsWindowConfig(window=window, ring=ring)  # re-validate
        self.window = cfg.window
        self.ring = cfg.ring
        self._clock = clock
        self._lock = threading.Lock()
        self._hists: dict[str, Callable[[], list]] = {}
        self._hist_last: dict[str, list[int]] = {}
        self._hist_ring: dict[str, deque] = {}
        self._counters: dict[str, Callable[[], float]] = {}
        self._ctr_last: dict[str, float] = {}
        self._ctr_ring: dict[str, deque] = {}
        self._gauges: dict[str, Callable[[], float]] = {}
        self._gauge_ring: dict[str, deque] = {}
        # roll timestamps, one longer than the ring so a full-ring
        # window still knows when its FIRST interval began (rates need
        # the span, not just the deltas)
        self._t_ring: deque = deque([clock()], maxlen=cfg.ring + 1)
        self.rolls = 0

    # -------------------------------------------------------- registration
    def register_hist(self, name: str,
                      fn: Callable[[], list]) -> None:
        """``fn`` returns the CURRENT cumulative bucket counts (any
        monotone per-bucket source: one Log2Histogram's counts, or an
        elementwise merge across tables — sums of monotone counts are
        monotone). Primed at registration: history before this call
        never enters a window."""
        with self._lock:
            cur = list(fn())
            if len(cur) != N_BUCKETS:
                raise ValueError(
                    f"hist {name!r}: expected {N_BUCKETS} buckets, "
                    f"got {len(cur)}")
            self._hists[name] = fn
            self._hist_last[name] = cur
            self._hist_ring[name] = deque(maxlen=self.ring)

    def register_counter(self, name: str,
                         fn: Callable[[], float]) -> None:
        with self._lock:
            self._counters[name] = fn
            self._ctr_last[name] = float(fn())
            self._ctr_ring[name] = deque(maxlen=self.ring)

    def register_gauge(self, name: str,
                       fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn
            self._gauge_ring[name] = deque(maxlen=self.ring)

    # --------------------------------------------------------------- roll
    def roll(self) -> None:
        """Close the current interval: snapshot every registered signal,
        ring-buffer the delta since the previous roll. A signal whose
        cumulative value stepped BACKWARD (restarted layer) re-baselines
        with a zero delta rather than booking a negative one.

        The registered fns are called OUTSIDE the window lock: they
        acquire foreign locks (CommTimers, the reliable channel, serve
        counters), and holding this lock across those acquisitions
        would let a reader blocked on it (a flight dump's snapshot
        hook, fired from a poison path that may itself hold a table
        lock a reliable-dispatched handler wants) close a cross-thread
        lock cycle. Rolls come from ONE thread (the push-driving
        clock boundary), so the unlocked read phase never races
        another roll; only the ring/baseline mutation needs the lock
        readers share."""
        now = self._clock()
        with self._lock:
            hists = list(self._hists.items())
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
        hist_cur = [(name, list(fn())) for name, fn in hists]
        ctr_cur = [(name, float(fn())) for name, fn in counters]
        gauge_cur = [(name, float(fn())) for name, fn in gauges]
        with self._lock:
            self._t_ring.append(now)
            self.rolls += 1
            for name, cur in hist_cur:
                last = self._hist_last[name]
                delta = [max(c - p, 0) for c, p in zip(cur, last)]
                self._hist_ring[name].append(delta)
                self._hist_last[name] = cur
            for name, cur in ctr_cur:
                delta = cur - self._ctr_last[name]
                self._ctr_ring[name].append(max(delta, 0.0))
                self._ctr_last[name] = cur
            for name, cur in gauge_cur:
                self._gauge_ring[name].append(cur)

    # -------------------------------------------------------------- reads
    def _k(self, window: Optional[int]) -> int:
        k = self.window if window is None else int(window)
        if k < 1:
            raise ValueError("window must be >= 1 roll")
        return min(k, self.ring)

    def window_counts(self, name: str,
                      window: Optional[int] = None
                      ) -> Optional[list[int]]:
        """Elementwise sum of the last ``window`` hist deltas — sound
        because the buckets are fixed (the per-rank-merge argument,
        applied over time). None for an unregistered name; all-zero for
        an idle (or not-yet-rolled) window."""
        k = self._k(window)
        with self._lock:
            ring = self._hist_ring.get(name)
            if ring is None:
                return None
            out = [0] * N_BUCKETS
            for delta in list(ring)[-k:]:
                for i, c in enumerate(delta):
                    out[i] += c
        return out

    def summarize(self, name: str,
                  window: Optional[int] = None) -> Optional[dict]:
        """``summarize_counts`` over the window sum: the done-line shape
        ({"count": 0} when the window saw no samples)."""
        counts = self.window_counts(name, window)
        return None if counts is None else summarize_counts(counts)

    def quantile_ms(self, name: str, q: float,
                    window: Optional[int] = None) -> Optional[float]:
        """The windowed quantile in milliseconds — the autoscaler's
        arming signal. None when the window is empty (idle ≠ slow) or
        the name is unregistered."""
        counts = self.window_counts(name, window)
        if counts is None:
            return None
        v = quantile_us(counts, q)
        return None if v is None else round(v / 1e3, 4)

    def delta_sum(self, name: str,
                  window: Optional[int] = None) -> Optional[float]:
        """Counter events inside the window (sum of the last K deltas)."""
        k = self._k(window)
        with self._lock:
            ring = self._ctr_ring.get(name)
            if ring is None:
                return None
            return float(sum(list(ring)[-k:]))

    def rate(self, name: str,
             window: Optional[int] = None) -> Optional[float]:
        """Counter events per SECOND over the window's wall span; None
        before the first roll or for an unregistered name."""
        k = self._k(window)
        with self._lock:
            ring = self._ctr_ring.get(name)
            if ring is None:
                return None
            deltas = list(ring)[-k:]
            if not deltas:
                return None
            ts = list(self._t_ring)
            # ts has one more entry than rolls retained: ts[-1] closed
            # the newest interval, ts[-(len(deltas)+1)] opened the
            # oldest one in this window
            span = ts[-1] - ts[-(len(deltas) + 1)]
            if span <= 0:
                return None
            return sum(deltas) / span

    def gauge(self, name: str, *, agg: str = "last",
              window: Optional[int] = None) -> Optional[float]:
        k = self._k(window)
        with self._lock:
            ring = self._gauge_ring.get(name)
            if ring is None or not ring:
                return None
            vals = list(ring)[-k:]
        return max(vals) if agg == "max" else vals[-1]

    # -------------------------------------------------------------- record
    def record(self, window: Optional[int] = None) -> dict:
        """The done-line ``window`` block: per-hist window summaries
        ({"count": 0} idle), per-counter window rates, gauge last/max —
        all over the DEFAULT window unless asked otherwise. The trainer
        reports None instead of calling this when the layer is off."""
        k = self._k(window)
        out: dict = {"rolls": self.rolls, "window": k,
                     "ring": self.ring, "hist": {}, "rate_per_s": {},
                     "events": {}, "gauge": {}}
        for name in list(self._hists):
            out["hist"][name] = self.summarize(name, k)
        for name in list(self._counters):
            r = self.rate(name, k)
            d = self.delta_sum(name, k)
            out["rate_per_s"][name] = (round(r, 3)
                                       if r is not None else None)
            out["events"][name] = int(d) if d is not None else None
        for name in list(self._gauges):
            g = self.gauge(name, agg="max", window=k)
            out["gauge"][name] = (round(g, 4) if g is not None
                                  else None)
        return out
