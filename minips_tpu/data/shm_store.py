"""Shared-memory sample store — parse once per host, map everywhere.

The reference colocates server and worker threads in ONE process per node
(SURVEY.md §1), so its in-memory sample store is naturally shared by every
worker on the host. The rebuild's launcher starts one *process* per worker
(process isolation is what makes the SSP/fault drills honest), which would
multiply both parse time and resident memory by the colocation factor —
N processes each parsing the same Criteo/libsvm file into N private
copies.

``shared_load`` restores the reference's economics: the host's local
leader (``MINIPS_LOCAL_RANK`` 0) runs the loader once — typically the
native C++ parser (data/native.py) writing straight into files under
/dev/shm — and every colocated process maps the same physical pages
read-only via ``np.memmap``. One parse, one copy of the dataset in host
memory, zero-copy views for all.

Coordination is file-based (atomic rename of a JSON manifest), so it works
before the control bus exists and for bus-less apps. Segments are
namespaced by ``MINIPS_RUN_ID`` (set per launcher invocation) so a
relaunch after a crash never attaches to a stale store; the leader
unlinks its segments at exit (mapped pages survive until the last reader
exits — POSIX semantics).
"""

from __future__ import annotations

import atexit
import json
import os
import tempfile
import time
from typing import Callable, Optional

import numpy as np

_PREFIX = "minips_shm"
_CLEANUP_GRACE_S = 30.0  # max leader-exit wait for peers to attach


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def _names(tag: str, directory: str) -> tuple[str, str]:
    run = os.environ.get("MINIPS_RUN_ID", "solo")
    base = os.path.join(directory, f"{_PREFIX}_{run}_{tag}")
    return base, base + ".manifest.json"


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def _atomic_write_array(path: str, arr: np.ndarray) -> None:
    """arr.tofile streams the buffer — no tobytes() copy of a
    dataset-sized array on the very host-memory path this store exists
    to relieve."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        arr.tofile(f)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def make_tag(prefix: str, *parts) -> str:
    """Stable cross-process tag from arbitrary key parts (PYTHONHASHSEED
    makes hash() useless here). All colocated callers that pass the same
    parts share one store."""
    import hashlib

    digest = hashlib.md5("|".join(map(repr, parts)).encode()).hexdigest()
    return f"{prefix}_{digest[:12]}"


def sweep_stale_segments(directory: Optional[str] = None) -> int:
    """Delete segments whose run (MINIPS_RUN_ID = launcher pid) is dead.
    A SIGKILLed job never runs its atexit cleanup; without this, every
    crash+relaunch cycle would leave another dataset-sized copy in tmpfs.
    Called by the launcher before spawning. Returns #files removed."""
    directory = directory or _shm_dir()
    removed = 0
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    for name in entries:
        if not name.startswith(_PREFIX + "_"):
            continue
        run = name[len(_PREFIX) + 1:].split("_", 1)[0]
        if not run.isdigit():
            continue  # non-pid run id (e.g. tests): not ours to judge
        from minips_tpu.comm.shm_bus import _pid_alive
        if _pid_alive(int(run)):
            continue  # launcher still alive (portable: /proc is
            # Linux-only and this store runs wherever the bus does)
        try:
            os.unlink(os.path.join(directory, name))
            removed += 1
        except OSError:
            pass
    return removed


def shared_load(
    tag: str,
    loader: Callable[[], dict],
    *,
    local_rank: Optional[int] = None,
    local_procs: Optional[int] = None,
    directory: Optional[str] = None,
    timeout: float = 300.0,
    writable_copy: bool = False,
) -> dict:
    """Load ``loader() -> {name: ndarray}`` once per host, share via mmap.

    ``local_rank``/``local_procs`` default to the launcher's
    ``MINIPS_LOCAL_RANK``/``MINIPS_LOCAL_PROCS``; single-process (or
    unlaunched) callers just run the loader directly. The local leader
    materializes each array into a file under /dev/shm and publishes a
    manifest; peers poll for the manifest (up to ``timeout`` — parsing a
    big file takes a while) and return read-only ``np.memmap`` views of
    the same physical pages. ``writable_copy=True`` gives peers private
    copies instead (copy-on-use) when the caller must mutate batches.
    """
    if local_rank is None:
        local_rank = int(os.environ.get("MINIPS_LOCAL_RANK", "0") or 0)
    if local_procs is None:
        local_procs = int(os.environ.get("MINIPS_LOCAL_PROCS", "1") or 1)
    if local_procs <= 1:
        return loader()
    directory = directory or _shm_dir()
    base, manifest_path = _names(tag, directory)

    if local_rank == 0:
        data = loader()
        manifest = {}
        paths = [manifest_path]
        for key, arr in data.items():
            arr = np.ascontiguousarray(arr)
            path = f"{base}.{key}.bin"
            _atomic_write_array(path, arr)
            paths.append(path)
            manifest[key] = {"dtype": arr.dtype.str,
                             "shape": list(arr.shape)}
        _atomic_write(manifest_path, json.dumps(manifest).encode())

        def _cleanup(paths=paths, base=base, n_peers=local_procs - 1,
                     grace=_CLEANUP_GRACE_S):  # captured NOW: atexit runs
            # after test monkeypatches are unwound
            # A leader that finishes quickly must not unlink before slower
            # peers attach (they'd time out on a vanished manifest): wait
            # for the attach markers, bounded so dead peers can't wedge
            # leader shutdown. Mapped pages survive the unlink (POSIX).
            deadline = time.monotonic() + grace
            def attached():
                return sum(os.path.exists(f"{base}.attached.{i}")
                           for i in range(1, n_peers + 1))
            while attached() < n_peers and time.monotonic() < deadline:
                time.sleep(0.05)
            for i in range(1, n_peers + 1):
                paths.append(f"{base}.attached.{i}")
            for p in paths:  # names vanish; peers' mappings stay valid
                try:
                    os.unlink(p)
                except OSError:
                    pass
            # tombstone: a peer arriving after reclamation fails fast with
            # the true story instead of polling out its whole timeout on
            # "leader never published" (tiny file; swept with the run)
            try:
                _atomic_write(base + ".tombstone", b"1")
            except OSError:
                pass

        atexit.register(_cleanup)
        return data

    deadline = time.monotonic() + timeout
    tombstone = base + ".tombstone"
    while not os.path.exists(manifest_path):
        if os.path.exists(tombstone):
            raise RuntimeError(
                f"shared_load({tag!r}): the leader already exited and "
                "reclaimed this store — this process attached too late "
                "(raise the leader-side grace or start peers sooner)")
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"shared_load({tag!r}): leader never published "
                f"{manifest_path} within {timeout}s")
        time.sleep(0.05)
    with open(manifest_path, "rb") as f:
        manifest = json.loads(f.read())
    out = {}
    for key, meta in manifest.items():
        mm = np.memmap(f"{base}.{key}.bin", dtype=np.dtype(meta["dtype"]),
                       mode="r", shape=tuple(meta["shape"]))
        out[key] = np.array(mm) if writable_copy else mm
    # tell the leader we hold mappings — it may now unlink the names
    _atomic_write(f"{base}.attached.{local_rank}", b"1")
    return out
