from minips_tpu.models import (  # noqa: F401
    decode,
    lr,
    mf,
    mlp,
    transformer,
    wide_deep,
    word2vec,
)
