"""Pallas gather kernel — interpret-mode correctness (SURVEY.md §4: the
TPU-free test story; compiled-mode numbers live in ops/pallas_kernels.py's
docstring, measured on the real chip)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.ops import pallas_kernels as pk


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_gather_matches_xla(rng):
    S, D, N = 512, 128, 64
    emb = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    slots = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    out = pk.gather_rows(emb, slots, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(emb)[np.asarray(slots)], rtol=1e-6)


def test_gather_repeated_and_boundary_rows(rng):
    S, D = 256, 128
    emb = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    slots = jnp.asarray([0, 0, S - 1, S - 1, 3, 3, 0, S - 1], jnp.int32)
    out = pk.gather_rows(emb, slots, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(emb)[np.asarray(slots)], rtol=1e-6)


def test_unsupported_shapes_fall_back(rng):
    # D=8 (not lane-aligned) and N=7 (not chunk-aligned) take the XLA path
    emb = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
    slots = jnp.asarray(rng.integers(0, 64, 7), jnp.int32)
    assert not pk.gather_supported(8, 56)    # lane-misaligned dim
    assert not pk.gather_supported(128, 7)   # chunk-misaligned n
    out = pk.gather_rows(emb, slots)  # must not raise
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(emb)[np.asarray(slots)], rtol=1e-6)


def test_supported_shapes_fall_back_off_tpu(rng):
    # aligned shapes (D=128, N=64) with interpret=False: on this CPU test
    # session the compiled pltpu kernel can't lower, so gather_rows must
    # take the XLA path instead of crashing in Mosaic
    assert pk.gather_supported(128, 64)
    assert not pk.backend_supported()
    emb = jnp.asarray(rng.normal(size=(256, 128)), jnp.float32)
    slots = jnp.asarray(rng.integers(0, 256, 64), jnp.int32)
    out = pk.gather_rows(emb, slots)  # must not raise
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(emb)[np.asarray(slots)], rtol=1e-6)


def test_opt_in_is_off_by_default_and_off_tpu(monkeypatch):
    assert not pk.pallas_enabled()  # default: no env flag
    monkeypatch.setenv("MINIPS_PALLAS", "1")
    # CPU test session: still disabled (TPU-only switch)
    assert not pk.pallas_enabled()
