"""Elastic resume — reshard rank-local checkpoints across WORLD SIZES.

The reference's recovery is relaunch at the SAME node count + per-server
Dump/Load (SURVEY.md §3.5: "no elastic resize, same as the reference's
fixed node set"). minips_tpu keeps that fast path untouched and adds an
elastic one on top: a job checkpointed by N processes can relaunch at
M != N. Each new rank reassembles its M-way row range from the
overlapping row slices of the N old shard files — parameters AND
optimizer state are row-aligned in a ShardedTable (w/acc/m/v per-row,
steps per-row), so ONE slicing rule re-partitions everything, adam
moments included. A grown world (M > N) and a shrunk one (M < N) are the
same math.

Requirements, stated honestly:

- ``checkpoint_dir`` must be a SHARED filesystem: a new rank reads OLD
  ranks' shard files. That is the assumption the reference's HDFS-backed
  dumps already make; per-host local dirs support only same-size resume
  (the existing fast path).
- resharding is only meaningful at the rank-dir layout
  ``<checkpoint_dir>/rank<r>/step_<s>/<table>.npz`` written by
  ``apps.common.shard_checkpointing``; the step chosen is the NEWEST one
  whose holders form a complete old world (rank dirs 0..k-1 all hold
  it) — a partial holder set means that incarnation's save was torn and
  is skipped.

After an elastic restore the caller should re-publish the resharded
state at the same step under its NEW rank dir (``Checkpointer.save``),
so the next crash resumes through the ordinary same-size path.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import numpy as np


def _rank_dirs(checkpoint_dir: str) -> dict[int, str]:
    out = {}
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return out
    for d in entries:
        m = re.fullmatch(r"rank(\d+)", d)
        if m and os.path.isdir(os.path.join(checkpoint_dir, d)):
            out[int(m.group(1))] = os.path.join(checkpoint_dir, d)
    return out


def _steps_in(rank_dir: str) -> set[int]:
    out = set()
    try:
        entries = os.listdir(rank_dir)
    except OSError:
        return out
    for d in entries:
        m = re.fullmatch(r"step_(\d+)", d)
        if m and os.path.exists(os.path.join(rank_dir, d, "manifest.json")):
            out.add(int(m.group(1)))
    return out


def _fits_partition(checkpoint_dir: str, step: int, r: int, tables: dict,
                    k: int) -> bool:
    """True iff rank ``r``'s files at ``step`` were saved under a
    ``k``-process partition (lo == r*shard_size(k) and padded rows ==
    shard_size(k) for every ShardedTable)."""
    d = os.path.join(checkpoint_dir, f"rank{r}", f"step_{step:010d}")
    for name, t in tables.items():
        if not hasattr(t, "shard_lo"):
            continue
        sz = -(-t.num_rows // k)  # RangePartitioner.shard_size at k
        if _shard_layout(d, name) != (r * sz, sz):
            return False
    return True


def find_elastic_step(checkpoint_dir: str,
                      tables: dict) -> Optional[tuple[int, int]]:
    """Newest ``(step, old_n)`` such that ranks 0..old_n-1 all hold
    ``step`` saved under a CONSISTENT old_n-process partition. None if no
    complete old world exists (fresh start).

    The partition-fit check matters because one step NUMBER can carry
    mixed layouts: an earlier elastic resume re-publishes the resharded
    state at the same step under the new world's rank dirs, while ranks
    beyond the new world still hold the old world's files. Candidate
    world sizes are tried largest-first so the most complete consistent
    layout wins."""
    dirs = _rank_dirs(checkpoint_dir)
    if not dirs:
        return None
    holders: dict[int, set[int]] = {}
    for r, d in dirs.items():
        for s in _steps_in(d):
            holders.setdefault(s, set()).add(r)
    for s in sorted(holders, reverse=True):
        ranks = holders[s]
        for k in range(len(ranks), 0, -1):
            if not set(range(k)) <= ranks:
                continue
            if all(_fits_partition(checkpoint_dir, s, r, tables, k)
                   for r in range(k)):
                return s, k
    return None


def _shard_layout(step_dir: str,
                  name: str) -> Optional[tuple[int, int]]:
    """(lo, padded row count) recorded in one table's shard file, or
    None when the file is absent/unreadable — the ONE place both layout
    checks read, so the negotiation filter and the elastic scan cannot
    drift apart on what 'fits' means."""
    path = os.path.join(step_dir, f"{name}.npz")
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            return int(z["lo"]), int(z["w"].shape[0])
    except (OSError, KeyError, ValueError):
        return None


def step_matches_layout(rank_dir: str, step: int, tables: dict) -> bool:
    """True iff ``step`` in ``rank_dir`` was saved under the CALLER'S
    partition — same shard origin (``lo``) and same padded shard row
    count for every ShardedTable. A surviving rank relaunched into a
    DIFFERENT world size still holds its old-world steps; offering those
    to the resume negotiation would either crash the restore (shape/lo
    mismatch) or, worse, silently restore the wrong rows. Steps that
    fail this filter stay on disk — they are exactly what the elastic
    path reshards from."""
    d = os.path.join(rank_dir, f"step_{step:010d}")
    for name, t in tables.items():
        if not hasattr(t, "shard_lo"):
            continue
        if _shard_layout(d, name) != (t.shard_lo, t.part.shard_size):
            return False
    return True


def _load_table_npz(checkpoint_dir: str, step: int, old_rank: int,
                    name: str) -> dict[str, np.ndarray]:
    path = os.path.join(checkpoint_dir, f"rank{old_rank}",
                        f"step_{step:010d}", f"{name}.npz")
    with np.load(path) as z:
        return dict(z.items())


def reshard_table_state(checkpoint_dir: str, step: int, old_n: int,
                        name: str, num_rows: int, new_lo: int,
                        new_shard_size: int) -> dict[str, np.ndarray]:
    """Assemble the state dict for the new shard ``[new_lo, new_lo +
    new_shard_size)`` of table ``name`` from the ``old_n`` old shard
    files at ``step``.

    Slicing rule: any leaf whose leading dimension equals the OLD
    shard_size is row-aligned (w, acc, m, v, steps — shards are PADDED to
    shard_size, so only the rows inside ``num_rows`` are real); ``lo`` is
    replaced by the new shard origin; any other leaf must be identical
    across old shards (there are none today — the assert is the tripwire
    for a future leaf this rule cannot place)."""
    probe = _load_table_npz(checkpoint_dir, step, 0, name)
    if int(probe.get("ep", np.zeros(()))):
        # a rebalanced checkpoint's rows are NOT where the range map
        # says (overlay blocks live in other ranks' xtra sections, home
        # slab copies of moved-out blocks are dead) — slicing by range
        # would assemble a silently-torn table
        raise ValueError(
            f"elastic reshard: step {step} of table {name!r} was saved "
            f"with a rebalanced routing table (epoch "
            f"{int(probe['ep'])}); elastic resize cannot place overlay "
            "blocks — restore at the original world size (with "
            "MINIPS_REBALANCE armed) first")
    old_sz = -(-num_rows // old_n)  # RangePartitioner.shard_size
    new_hi = min(new_lo + new_shard_size, num_rows)
    pieces: dict[str, list[np.ndarray]] = {}
    passthrough: dict[str, np.ndarray] = {}
    if new_hi <= new_lo:
        # a grown world's last shard can lie ENTIRELY in padding
        # (shard_lo >= num_rows): there are no rows to assemble, but the
        # live table still expects every leaf at full shard shape — use
        # old rank 0's leaves as the shape/dtype template, zero-filled
        state = _load_table_npz(checkpoint_dir, step, 0, name)
        out = {"lo": np.asarray(new_lo)}
        for key, arr in state.items():
            if key == "lo":
                continue
            if arr.ndim >= 1 and arr.shape[0] == old_sz:
                out[key] = np.zeros((new_shard_size,) + arr.shape[1:],
                                    arr.dtype)
            else:
                out[key] = arr
        return out
    for o in range(old_n):
        lo_o = o * old_sz
        hi_o = min(lo_o + old_sz, num_rows)
        a, b = max(lo_o, new_lo), min(hi_o, new_hi)
        if a >= b:
            continue
        state = _load_table_npz(checkpoint_dir, step, o, name)
        for key, arr in state.items():
            if key == "lo":
                continue
            if arr.ndim >= 1 and arr.shape[0] == old_sz:
                pieces.setdefault(key, []).append(arr[a - lo_o:b - lo_o])
            else:
                prev = passthrough.get(key)
                # a hard refusal, not an assert: resharding a leaf that
                # is neither row-aligned nor shard-invariant would
                # silently pick one shard's copy — and `python -O`
                # strips asserts, so the tripwire must be a real raise
                if prev is not None and not np.array_equal(prev, arr):
                    raise ValueError(
                        f"elastic reshard: leaf {name}.{key} is neither "
                        "row-aligned nor identical across old shards")
                passthrough[key] = arr
    out: dict[str, np.ndarray] = {"lo": np.asarray(new_lo)}
    for key, parts in pieces.items():
        rows = np.concatenate(parts, axis=0)
        pad = new_shard_size - rows.shape[0]
        if pad:  # last shard: pad back up to shard_size, like __init__
            rows = np.concatenate(
                [rows, np.zeros((pad,) + rows.shape[1:], rows.dtype)],
                axis=0)
        out[key] = rows
    out.update(passthrough)
    return out


def read_saved_clock(checkpoint_dir: str, step: int,
                     name: str = "trainer") -> int:
    """The clock stamped into rank 0's trainer snapshot at ``step`` — at
    a save boundary every rank stamps the same value (save_hook runs at
    clock == i+1), so one representative suffices."""
    state = _load_table_npz(checkpoint_dir, step, 0, name)
    return int(state["clock"])
