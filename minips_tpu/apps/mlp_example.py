"""mlp_example — 3-layer MLP on MNIST-shaped data (BASELINE.json:8:
"3-layer MLP on MNIST, dense KVTable, SSP staleness=4").

Default matches the reference config: SSP staleness 4. On the SPMD path that
gate is only observable multi-host, so single-host SPMD runs BSP-fused
steps; ``--exec threaded`` runs true SSP semantics with worker threads
(each jitting its compute on the chip).

Usage: python -m minips_tpu.apps.mlp_example --num_iters 300
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from minips_tpu.apps.common import app_main
from minips_tpu.core.config import Config, TableConfig, TrainConfig
from minips_tpu.core.engine import Engine, MLTask
from minips_tpu.data.loader import BatchIterator
from minips_tpu.data import synthetic
from minips_tpu.models import mlp as mlp_model
from minips_tpu.parallel.mesh import make_mesh
from minips_tpu.tables.dense import DenseTable
from minips_tpu.train.loop import TrainLoop

DEFAULT = Config(
    table=TableConfig(name="mlp", kind="dense", consistency="ssp",
                      staleness=4, updater="adagrad", lr=0.05),
    train=TrainConfig(batch_size=256, num_iters=300),
)


def run(cfg: Config, args, metrics) -> dict:
    sizes = (784, 256, 128, 10)
    images = getattr(args, "images", None)
    labels = getattr(args, "labels", None)
    if images:  # real MNIST idx files (BASELINE.json:8)
        if not labels:
            raise SystemExit("--labels is required with --images")
        from minips_tpu.data.mnist import read_mnist
        data = read_mnist(images, labels)
    else:
        if labels:
            raise SystemExit("--labels without --images would silently "
                             "train on synthetic data; pass both")
        data = synthetic.mnist_like(8192, seed=cfg.train.seed)
    template = mlp_model.init(jax.random.PRNGKey(cfg.train.seed), sizes)

    if getattr(args, "exec_mode", "spmd") == "threaded":
        return _run_threaded(cfg, metrics, data, template)

    batches = BatchIterator(data, cfg.train.batch_size, seed=cfg.train.seed)
    mesh = make_mesh()
    table = DenseTable(template, mesh, updater=cfg.table.updater,
                       lr=cfg.table.lr)
    step = table.make_step(mlp_model.grad_fn)

    def do_step(batch):
        b = {"x": jnp.asarray(batch["x"]), "y": jnp.asarray(batch["y"])}
        return table.step_inplace(step, b)

    loop = TrainLoop(do_step, batches, metrics=metrics,
                     log_every=cfg.train.log_every,
                     batch_size=cfg.train.batch_size)
    losses = loop.run(cfg.train.num_iters)
    acc = float(mlp_model.accuracy(
        table.pull(), {"x": jnp.asarray(data["x"][:2048]),
                       "y": jnp.asarray(data["y"][:2048])}))
    metrics.log(final_loss=losses[-1], accuracy=acc)
    return {"losses": losses, "accuracy": acc,
            "samples_per_sec": loop.timer.samples_per_sec, "table": table}


def _run_threaded(cfg, metrics, data, template) -> dict:
    from minips_tpu.apps.common import threaded_train

    engine = Engine(num_workers=cfg.train.num_workers).start_everything()
    engine.create_table(
        TableConfig(name="mlp", kind="dense",
                    consistency=cfg.table.consistency,
                    staleness=cfg.table.staleness,
                    updater=cfg.table.updater, lr=cfg.table.lr),
        template=template)
    g = jax.jit(mlp_model.grad_fn)

    def step_fn(info, batch):
        tbl = info.table("mlp")
        params = tbl.pull()
        loss, grads = g(params, {"x": jnp.asarray(batch["x"]),
                                 "y": jnp.asarray(batch["y"])})
        tbl.push(jax.tree.map(lambda x: x / info.num_workers, grads))
        return loss

    mean_losses = threaded_train(engine, cfg, data, step_fn,
                                 clock_tables=["mlp"])
    skew = engine.controllers["mlp"].skew
    final_params = engine.tables["mlp"].pull()
    engine.stop_everything()
    acc = float(mlp_model.accuracy(
        final_params, {"x": jnp.asarray(data["x"][:2048]),
                       "y": jnp.asarray(data["y"][:2048])}))
    metrics.log(final_loss=mean_losses[-1], accuracy=acc, clock_skew=skew)
    return {"losses": mean_losses, "accuracy": acc, "skew": skew,
            "samples_per_sec": 0.0}


def _flags(parser):
    parser.add_argument("--images", default=None,
                        help="MNIST images idx3 file (e.g. "
                             "train-images-idx3-ubyte[.gz]); synthetic "
                             "data when omitted")
    parser.add_argument("--labels", default=None,
                        help="MNIST labels idx1 file (required with "
                             "--images)")


def main():
    return app_main("mlp_example", DEFAULT, run, extra_flags=_flags)


if __name__ == "__main__":
    main()
