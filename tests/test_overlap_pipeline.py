"""Overlapped PS pipeline (train/sharded_ps.py): async push, pull
prefetch, int8 pull wire.

Fast tier: threads-as-nodes over real loopback buses (the reference's
in-process multi-node trick, SURVEY.md §4) proving the three levers'
semantics — codec fidelity + mixed fleets on the pull wire, prefetch
consumption/admission, the async EMIT-barrier ordering the BSP/SSP
staleness proof rests on, and the dropped-ack drill (poison, never
hang). Slow tier: the sharded_ps_example smoke with --overlap under a
real SSP launcher run asserting the staleness bound and replica
agreement survive the in-flight window.
"""

import sys
import threading
import time

import numpy as np
import pytest

from minips_tpu import launch
from minips_tpu.ops.quantized_comm import (dequantize_rows_int8,
                                           quantize_rows_int8)
from minips_tpu.train.sharded_ps import ShardedTable

APP = "minips_tpu.apps.sharded_ps_example"


def _mk_buses(n):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n)


# ------------------------------------------------------------ pull wire
def test_pull_wire_nearest_codec_deterministic_and_bounded():
    """rng=None selects round-to-NEAREST — the pull-wire mode for
    weights: per-element error <= half a quantization step (half the
    stochastic wire's worst case) and bit-identical across calls, so
    every puller of an unchanged row decodes the same bytes."""
    rng = np.random.default_rng(3)
    rows = rng.normal(scale=2.0, size=(32, 16)).astype(np.float32)
    rows[5] = 0.0
    c1, s1 = quantize_rows_int8(rows)
    c2, s2 = quantize_rows_int8(rows)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(s1, s2)
    out = dequantize_rows_int8(c1, s1)
    half_step = np.abs(rows).max(axis=1, keepdims=True) / 127.0 / 2.0
    assert np.all(np.abs(out - rows) <= half_step + 1e-7)
    assert not out[5].any() and s1[5] == 0.0


def test_pull_wire_int8_and_mixed_fleet():
    """pull_wire='int8' compresses pull REPLIES (errors within one
    quantization step); frames self-describe their wire, so a MIXED
    fleet — one owner compressed, one not — decodes correctly per frame,
    and bytes_pulled counts actual (compressed) wire bytes."""
    buses = _mk_buses(3)
    # rank 1 serves int8 replies, rank 2 serves f32 — the puller (rank
    # 0, itself configured int8) must decode both per-frame
    tables = [ShardedTable("t", 96, 4, buses[i], i, 3, updater="sgd",
                           lr=1.0, pull_timeout=10.0,
                           pull_wire=("int8" if i < 2 else "f32"))
              for i in range(3)]
    try:
        vals = np.arange(96 * 4, dtype=np.float32).reshape(96, 4) / 7.0
        for t in tables:  # owners hold distinct known rows
            t._w[...] = vals[t.shard_lo:t.shard_lo + 32]
        keys = np.array([2, 40, 70])  # one row per owner
        rows = tables[0].pull(keys)
        # own shard exact; remote rows within one codec step of truth
        np.testing.assert_array_equal(rows[0], vals[2])
        for i, k in ((1, 40), (2, 70)):
            step = np.abs(vals[k]).max() / 127.0
            assert np.all(np.abs(rows[i] - vals[k]) <= step + 1e-6), k
        # wire accounting: keys out (2*8B) + int8 reply (4B scale + 4B
        # codes) + f32 reply (16B) — compressed counted compressed
        assert tables[0].bytes_pulled == 2 * 8 + (4 + 4) + 16
        # pull_all: the mixed wires assemble the same table everywhere
        full0 = tables[0].pull_all()
        full1 = tables[1].pull_all()
        step = np.abs(vals).max() / 127.0
        assert np.all(np.abs(full0 - vals) <= step + 1e-6)
        assert np.all(np.abs(full1 - vals) <= step + 1e-6)
    finally:
        for b in buses:
            b.close()


def test_pull_wire_flag_validation():
    with pytest.raises(ValueError, match="pull_wire"):
        ShardedTable("t", 8, 2, None, 0, 1, pull_wire="bf16")
    # the push-knob spelling is accepted as an alias
    t = ShardedTable("t", 8, 2, None, 0, 1, pull_wire="float32")
    assert t.pull_wire == "f32"


# ------------------------------------------------------------- prefetch
def test_prefetch_consumed_by_pull_without_second_round_trip():
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = 5.0
        keys = np.array([40, 41])
        fut = t0.prefetch_pull(keys, clock_ahead=0)
        reqs_after_prefetch = t0._req
        rows = t0.pull(keys)  # must consume fut, not issue a new pull
        assert t0._req == reqs_after_prefetch, "pull() re-issued on wire"
        np.testing.assert_allclose(rows, 5.0)
        with pytest.raises(RuntimeError, match="twice"):
            fut.wait()
        # a fresh pull (nothing prefetched) still round-trips normally
        np.testing.assert_allclose(t0.pull(keys), 5.0)
        assert t0._req == reqs_after_prefetch + 2  # group + leg id
        # cancel releases the reply slot of an unconsumed prefetch
        fut2 = t0.prefetch_pull(keys)
        fut2.cancel()
        assert not t0._replies and not t0._prefetched
    finally:
        for b in buses:
            b.close()


def test_prefetch_same_keys_twice_keeps_held_future_waitable():
    """The double-buffer pattern holds batch t's future while issuing
    batch t+1's; when consecutive batches draw byte-identical keys the
    new prefetch displaces the old registry slot but must NOT invalidate
    the handle the caller still holds (regression: cancelling it made
    ``fut.wait()`` raise RuntimeError — guaranteed crash on iteration 2
    of ``--overlap`` runs over tiny key spaces)."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = 4.0
        keys = np.array([40, 41])
        f1 = t0.prefetch_pull(keys)            # batch t
        f2 = t0.prefetch_pull(keys.copy())     # batch t+1, same bytes
        np.testing.assert_allclose(f1.wait(), 4.0)  # t consumes its own
        np.testing.assert_allclose(t0.pull(keys), 4.0)  # consumes f2
        assert f2._done and not t0._prefetched and not t0._replies
    finally:
        for b in buses:
            b.close()


def test_stale_prefetch_not_consumed_by_later_pull():
    """A dangling prefetch from an earlier step was admitted under an
    OLDER global-min view; a pull() many clocks later with byte-
    identical keys must NOT consume it (that would read past the
    staleness bound silently) — it cancels the stale future and
    round-trips fresh."""

    class Cons:
        def __init__(self):
            self.clock = 5

        def admit_pull(self, clk):
            return True  # admission open: staleness isn't the guard here

    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, pull_timeout=10.0)
    c0 = Cons()
    t0.bind_consistency(c0)
    try:
        t1._w[...] = 1.0
        keys = np.array([40, 41])
        fut = t0.prefetch_pull(keys)  # stamped clock 6
        time.sleep(0.3)               # served + replied with rows = 1.0
        t1._w[...] = 9.0              # owner state moves on...
        c0.clock = 9                  # ...and so does my clock
        rows = t0.pull(keys)          # stamp 6 < clock 9: must re-issue
        np.testing.assert_allclose(rows, 9.0)
        assert fut._done and not t0._prefetched and not t0._replies
        # a CURRENT prefetch (stamped clock+1) is still consumed
        fut2 = t0.prefetch_pull(keys)
        assert t0.pull(keys) is not None and fut2._done
    finally:
        for b in buses:
            b.close()


def test_prefetch_future_clock_parks_until_admitted():
    """A prefetch stamped one clock AHEAD is parked at the owner under
    exactly the admission rule the consuming step would face — overlap
    never weakens the staleness bound — and the LOCAL shard slice obeys
    the same rule on the requester."""

    class Cons:  # controllable admission stub (same as test_sharded_ps)
        clock = 5

        def __init__(self):
            self.ok = False

        def admit_pull(self, clk):
            return self.ok or clk <= self.clock

    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, pull_timeout=10.0)
    c0, c1 = Cons(), Cons()
    t0.bind_consistency(c0)
    t1.bind_consistency(c1)
    try:
        t1._w[...] = 3.0
        t0._w[...] = 7.0
        # keys span the remote owner AND my own shard: both legs gate
        fut = t0.prefetch_pull(np.array([40, 3]))  # stamped clock 6
        got = {}

        def waiter():
            got["rows"] = fut.wait()

        th = threading.Thread(target=waiter)
        th.start()
        deadline = time.time() + 5
        while not t1._parked and time.time() < deadline:
            time.sleep(0.02)
        assert t1._parked, "future-stamped prefetch was served early"
        assert th.is_alive()  # wait() blocked on remote + local admission
        c1.ok = True
        t1.serve_parked()
        time.sleep(0.2)
        assert th.is_alive(), "local slice read before local admission"
        c0.ok = True  # my own view catches up
        th.join(timeout=5)
        assert not th.is_alive()
        np.testing.assert_allclose(got["rows"], [[3.0, 3.0], [7.0, 7.0]])
    finally:
        for b in buses:
            b.close()


# ----------------------------------------------------------- async push
def test_async_push_applies_acks_and_hard_drains():
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, async_push=True, push_window=4)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)
    try:
        for k in range(3):
            t0.push(np.array([40 + k, k]), np.ones((2, 2), np.float32))
        t0.flush_pushes()  # hard drain: queue empty AND every ack in
        assert t0._q_pending == 0 and not t0._inflight
        assert t0.timers.push_acks == 3  # one acked frame per push
        for k in range(3):  # owner applied every frame, local leg too
            np.testing.assert_allclose(t1._w[40 + k - 32], -1.0)
            np.testing.assert_allclose(t0._w[k], -1.0)
        # callers may reuse their buffers: push() copies
        buf = np.ones((1, 2), np.float32)
        t0.push(np.array([50]), buf)
        buf[...] = 99.0
        t0.flush_pushes()
        np.testing.assert_allclose(t1._w[50 - 32], -1.0)
    finally:
        for b in buses:
            b.close()


def test_async_push_emit_barrier_orders_before_clock_frame():
    """The EMIT-barrier contract behind the BSP/SSP staleness proof:
    after flush_pushes(acks=False) — the clock-boundary drain tick()
    runs under a finite bound — a frame sent on the SAME link is
    ordered AFTER every drained push, so an owner that has seen my
    clock frame has already applied my step's pushes."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, async_push=True, push_window=8)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)
    seen = []
    # a stand-in for the clock frame, riding the same rank0->rank1 link
    buses[1].on("probe", lambda s, p: seen.append(t1._w[40 - 32].copy()))
    try:
        for _ in range(5):
            seen.clear()
            w0 = t1._w[40 - 32, 0]
            t0.push(np.array([40]), np.ones((1, 2), np.float32))
            t0.flush_pushes(acks=False)  # queue handed to the bus...
            buses[0].send(1, "probe", {})  # ...then the "clock" frame
            deadline = time.time() + 5
            while not seen and time.time() < deadline:
                time.sleep(0.01)
            assert seen, "probe frame lost"
            # FIFO per link: the probe observed the push already applied
            np.testing.assert_allclose(seen[0], w0 - 1.0)
        t0.flush_pushes()
    finally:
        for b in buses:
            b.close()


def test_async_push_dropped_ack_poisons_via_check_fatal_not_hang():
    """Fault drill (the acceptance criterion): the owner receives and
    APPLIES pushes but its acks are lost. The sender's window jams, the
    drain deadline poisons the table, and check_fatal() raises — the
    loop fails loudly instead of hanging."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=1.0, async_push=True, push_window=2)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=1.0)
    t1._ack_push = lambda sender, payload: None  # ack loss injection
    try:
        t_start = time.monotonic()
        for k in range(3):  # window 2: frame 3 queues behind lost acks
            t0.push(np.array([40 + k]), np.ones((1, 2), np.float32))
        t0.flush_pushes(timeout=1.0)  # returns (poisoned), never hangs
        with pytest.raises(RuntimeError, match="push"):
            t0.check_fatal()  # what trainer.tick() runs every step
        assert time.monotonic() - t_start < 10.0  # bounded, not a hang
        # the pushes that DID get out were applied — loss detection is
        # about the sender's knowledge, not the owner's state
        np.testing.assert_allclose(t1._w[40 - 32], -1.0)
    finally:
        for b in buses:
            b.close()


def test_async_push_backpressure_bounds_queue():
    """With no bus (standalone) pushes apply inline; with a dead owner
    the queue is bounded by push_window and surfaces a loud error."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=0.5, async_push=True, push_window=1)
    ShardedTable("t", 64, 2, buses[1], 1, 2)
    buses[1].close()  # owner gone: no acks ever
    try:
        with pytest.raises((TimeoutError, RuntimeError)):
            for k in range(8):  # window 1 jams almost immediately
                t0.push(np.array([40]), np.ones((1, 2), np.float32))
                time.sleep(0.05)
        assert t0._q_pending <= 1 + t0.push_window
    finally:
        buses[0].close()


# ------------------------------------------------------------ TrainLoop
def test_train_loop_prefetch_announces_next_batch_first():
    from minips_tpu.train.loop import TrainLoop

    events = []
    loop = TrainLoop(lambda b: events.append(("step", b)) or 0.0,
                     iter([0, 1, 2, 3]),
                     prefetch=lambda b: events.append(("prefetch", b)),
                     log_every=0, batch_size=1)
    losses = loop.run(3)
    assert len(losses) == 3
    # batch t+1 is announced before batch t steps; batch 3 was
    # prefetched but never stepped (num_iters bound) — caller cleanup
    assert events == [("prefetch", 1), ("step", 0),
                      ("prefetch", 2), ("step", 1),
                      ("prefetch", 3), ("step", 2)]

    # a finite stream ends cleanly with lookahead active
    events.clear()
    loop = TrainLoop(lambda b: events.append(("step", b)) or 0.0,
                     iter([0, 1]),
                     prefetch=lambda b: events.append(("prefetch", b)),
                     log_every=0, batch_size=1)
    assert len(loop.run(5)) == 2
    assert events == [("prefetch", 1), ("step", 0), ("step", 1)]


def test_train_loop_extra_metrics_ride_the_log_line():
    """The extra_metrics hook (wire/cache health next to loss): its dict
    is splatted into every periodic metrics record."""
    from minips_tpu.train.loop import TrainLoop
    from minips_tpu.utils.metrics import MetricsLogger

    records = []
    logger = MetricsLogger(verbose=False)
    logger.log = lambda **r: records.append(r)  # capture, don't print
    calls = [0]

    def extra():
        calls[0] += 1
        return {"cache_hit_rate": 0.5, "pull_rows_wire": 7}

    loop = TrainLoop(lambda b: 0.0, iter(range(6)), metrics=logger,
                     log_every=2, batch_size=1, extra_metrics=extra)
    loop.run(6)
    logged = [r for r in records if "loss" in r]
    assert len(logged) == 3 and calls[0] == 3
    for r in logged:
        assert r["cache_hit_rate"] == 0.5 and r["pull_rows_wire"] == 7


# ------------------------------------------------------- multi-process
@pytest.mark.slow
def test_overlap_ssp_three_processes_staleness_bound_holds():
    """The BSP/SSP consistency proof under the full pipeline: --overlap
    (async ack-windowed push + prefetch stamped one clock ahead) with a
    straggler must still honor the s+1 transient skew bound, lose no
    frames, and agree across replicas after finalize — the in-flight
    window may never widen staleness."""
    res = launch.run_local_job(
        3, [sys.executable, "-m", APP, "--iters", "40", "--model",
            "sparse", "--mode", "ssp", "--staleness", "2",
            "--slow-rank", "1", "--slow-ms", "30", "--overlap",
            "--pull-wire", "int8"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=240.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["wire_frames_lost"] == 0, r
        assert r["max_skew_seen"] <= 3, r  # s + 1 transient bound
        assert r["loss_last"] < r["loss_first"], r
        # the knob echo the sweeps assert on
        assert r["overlap"] is True and r["pull_wire"] == "int8", r
        # the pipeline actually overlapped: pull wait left the step path
        frac = r["timing"]["pull_overlap_fraction"]
        assert frac is not None and frac > 0.3, r["timing"]
    sums = [r["param_sum"] for r in res]
    assert max(sums) - min(sums) < 1e-4, sums


@pytest.mark.slow
def test_overlap_bsp_two_processes_lockstep_holds():
    """BSP + --overlap: the drain at the clock boundary keeps lockstep
    (skew <= 1) with the async window active."""
    res = launch.run_local_job(
        2, [sys.executable, "-m", APP, "--iters", "30", "--model",
            "sparse", "--mode", "bsp", "--overlap"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=240.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["wire_frames_lost"] == 0, r
        assert r["max_skew_seen"] <= 1, r  # BSP lockstep
    sums = [r["param_sum"] for r in res]
    assert max(sums) - min(sums) < 1e-4, sums
