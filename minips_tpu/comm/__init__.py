from minips_tpu.comm.bus import ControlBus  # noqa: F401
from minips_tpu.comm.heartbeat import HeartbeatMonitor  # noqa: F401
