"""Batch iteration + device prefetch — the host input pipeline.

The reference's loader threads read shards into per-worker sample stores
(SURVEY.md §2 "Data loading"); the TPU rebuild's job is keeping the chip
fed: batches are assembled on host (numpy), then double-buffered onto the
device with data-axis sharding so step N+1's H2D copy overlaps step N's
compute (SURVEY.md §7.4 item 4).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

import jax
import numpy as np


class BatchIterator:
    """Infinite shuffled minibatches over a dict of equal-length arrays.

    ``drop_last=True`` (default) yields only full batches — TPU steps need
    static shapes; ``drop_last=False`` also yields the ragged tail batch
    each epoch (useful for evaluation sweeps).
    """

    def __init__(self, data: dict, batch_size: int, *, seed: int = 0,
                 drop_last: bool = True):
        self.data = {k: np.asarray(v) for k, v in data.items()}
        lens = {len(v) for v in self.data.values()}
        if len(lens) != 1:
            raise ValueError("all arrays must share length")
        self.n = lens.pop()
        if batch_size > self.n:
            raise ValueError(f"batch_size {batch_size} > dataset size {self.n}")
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[dict]:
        return self.iter_from(0)

    def iter_from(self, start_batch: int) -> Iterator[dict]:
        """The same infinite stream, starting at batch ``start_batch`` —
        the resume fast-forward. Skipped epochs cost one RNG permutation
        draw each (O(n) ints), not ``start_batch`` full batch copies."""
        end = (self.n - self.batch_size + 1 if self.drop_last else self.n)
        starts = range(0, end, self.batch_size)
        per_epoch = len(starts)
        skip_epochs, skip_batches = divmod(start_batch, per_epoch)
        for _ in range(skip_epochs):
            self._rng.permutation(self.n)  # advance the stream's RNG only
        while True:
            perm = self._rng.permutation(self.n)
            for s in starts[skip_batches:]:
                sel = perm[s: s + self.batch_size]
                yield {k: v[sel] for k, v in self.data.items()}
            skip_batches = 0


_POISON = object()


def prefetch_to_device(it, put: Callable[[Any], Any], depth: int = 2):
    """Run ``put`` (e.g. PSTrainStep.shard_batch) on a background thread,
    keeping ``depth`` batches in flight ahead of the consumer. Producer
    errors re-raise in the consumer; early consumer exit releases the
    producer (no leaked thread parked on a full queue)."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in it:
                if stop.is_set() or not _put(("item", put(item))):
                    return
            _put((_POISON, None))
        except BaseException as e:  # re-raised consumer-side
            _put(("error", e))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            kind, item = q.get()
            if kind is _POISON:
                return
            if kind == "error":
                raise item
            yield item
    finally:
        stop.set()
