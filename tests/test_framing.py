"""Binary wire framing (comm/framing.py) — this PR's tentpole codec.

The contract under test: every control head the stack emits round-trips
BITWISE through the binary codec (encode is deterministic, decode
reproduces the exact payload object, the blob rides untouched), the
decoded object is indistinguishable from what the seed JSON codec would
have delivered (handlers must not care which codec framed the wire),
and a mixed fleet — one rank still on ``MINIPS_WIRE_FMT=json`` —
decodes per frame via the magic-byte sniff.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from minips_tpu.comm import framing as F


def _cfg_header() -> dict:
    return {"ws": 3, "nr": 65536, "dm": 8, "rb": "block=16,topk=64"}


def _frame_corpus(rng: np.random.Generator) -> list[tuple[str, dict,
                                                          bytes]]:
    """One representative (kind, payload, blob) per frame kind the stack
    emits — the shapes mirror the real send sites in train/sharded_ps.py,
    serve/plane.py, comm/reliable.py, and comm/bus.py. Blobs cover the
    empty case, dtype variety (i64 keys, f32 rows, int8 codes + f32
    scales, f64), and a max-size row block."""
    dim = 8
    n = int(rng.integers(1, 64))
    keys = rng.integers(0, 65536, size=n).astype(np.int64)
    rows = rng.standard_normal((n, dim)).astype(np.float32)
    codes = rng.integers(-128, 128, size=(n, dim)).astype(np.int8)
    scales = rng.random(n).astype(np.float32)
    maxrows = np.ones((4096, dim), np.float32)  # a full-block grant
    ints = lambda k: [int(x) for x in rng.integers(0, 1 << 20, size=k)]
    corpus = [
        # push / pull / ack / epoch-nack (psh family)
        ("psP:t", {"n": n, "comm": "int8", "seq": 17, "ep": 2,
                   **_cfg_header()},
         keys.tobytes() + scales.tobytes() + codes.tobytes()),
        ("psR:t", {"lo": 0, "hi": n, "comm": "float32", "ep": 2,
                   **_cfg_header()}, rows.tobytes()),
        ("psG:t", {"req": 912, "clk": 5, "ep": 2, "rt": 1,
                   **_cfg_header()}, keys.tobytes()),
        ("psA:t", {"req": 913, "clk": 5, **_cfg_header()}, b""),
        ("psr:t", {"req": 912, "wire": "int8", "n": n, "stamp": 4,
                   "acks": ints(32)},
         scales.tobytes() + codes.tobytes()),
        ("psK:t", {"seqs": ints(48)}, b""),
        ("psE:t", {"req": 912, "ep": 3, "ovb": ints(6), "ovo": ints(6)},
         b""),
        ("psQ:t", {}, b""),
        ("psFlush", {"clock": 41}, b""),
        ("psFlushAck", {}, b""),
        ("psBye", {}, b""),
        ("clock", {"clocks": ints(4)}, b""),
        # rebalancer (rb family)
        ("rbS:t", {"b": 7, "ep": 4, "lo": 112, "n": n, "u": "adam",
                   **_cfg_header()},
         rows.tobytes() + rows.astype(np.float64).tobytes()),
        ("rbA:t", {"ep": 4}, b""),
        ("rbF:t", {"b": 7, "ep": 4}, b""),
        # serving plane (sv family)
        ("svU:t", {"stamp": 9, "lease": 2.0, "ep": 3, "wire": "f32",
                   "bs": ints(16), "fl": [0] * 16, "ns": [n] * 16,
                   "renew": 1, **_cfg_header()},
         keys.tobytes() + maxrows.tobytes()),
        ("svR:t", {"bs": ints(5), "ep": 3}, b""),
        ("svM:t", {"bs": ints(8), "hs": [ints(2) for _ in range(8)],
                   "ep": 3}, b""),
        ("svN:t", {"req": 912, "why": "stale"}, b""),
        ("svS:t", {"req": 912, "h": ints(2), "bs": ints(3)}, b""),
        ("svB:t", {"req": 912, "ms": 2.0}, b""),
        ("svP:t", {"req": 912, "clk": 5, **_cfg_header()},
         keys.tobytes()),
        # reliable-delivery control plane
        ("__rl_nack", {"s": "d", "seqs": ints(256)}, b""),
        ("__rl_gone", {"s": "b", "seqs": ints(3)}, b""),
        ("__rl_top", {"b": 512, "d": {"0": 31, "2": 7}}, b""),
        ("__rt", {"m2": F.encode_head_bin(
            {"kind": "psK:t", "sender": 1,
             "payload": {"seqs": ints(4)}, "ds": 9})}, rows.tobytes()),
        ("__rt", {"m": json.dumps({"kind": "psK:t", "sender": 1,
                                   "payload": {"seqs": ints(4)},
                                   "ds": 9})}, b""),
        # bus-level exchange + handshake
        ("blobx", {"round": 3, "tag": "union", "dtype": "int64"},
         keys.tobytes()),
        ("blobx_req", {"round": 3, "tag": "union"}, b""),
        ("__hello", {}, b""),
        ("__ready", {}, b""),
    ]
    return corpus


def _stamp(head: dict, i: int, rng: np.random.Generator) -> dict:
    kind = head["kind"]
    if kind.startswith("__"):
        return head  # handshake/control: unstamped, like the backends
    if rng.random() < 0.5:
        head["bs"] = i
    else:
        head["ds"] = i
    return head


def test_every_frame_kind_roundtrips_bitwise():
    """Seeded sweep over the full frame corpus: binary decode must
    reproduce the head EXACTLY (and agree with what the JSON codec
    delivers, where JSON can express it), re-encode must be
    byte-identical (deterministic canonical encoding — what makes the
    zmq-vs-shm lockstep drill meaningful), and the blob must pass
    through untouched."""
    rng = np.random.default_rng(20260803)
    for rep in range(8):
        for i, (kind, payload, blob) in enumerate(_frame_corpus(rng)):
            head = _stamp({"kind": kind, "sender": int(rng.integers(3)),
                           "payload": payload}, i, rng)
            wire = F.encode_head_bin(head)
            dec = F.decode_head(wire)
            assert dec == head, kind
            assert F.encode_head_bin(dec) == wire, kind  # bitwise stable
            # JSON parity wherever JSON can express the payload
            try:
                jwire = json.dumps(head).encode()
            except TypeError:
                jwire = None  # bytes-bearing payload (__rt m2): bin-only
            if jwire is not None:
                assert F.decode_head(jwire) == dec, kind
            # the blob slot never passes through the codec at all, but
            # pin the bytes anyway: the transport contract is bitwise
            assert bytes(blob) == blob
            assert F.decode_head(F.encode_head(head, "bin")) == dec


def test_empty_and_maximal_payloads():
    empty = {"kind": "psQ:t", "sender": 0, "payload": {}}
    assert F.decode_head(F.encode_head_bin(empty)) == empty
    big = {"kind": "psr:t", "sender": 2,
           "payload": {"acks": list(range(100_000))}, "ds": 1}
    wire = F.encode_head_bin(big)
    assert F.decode_head(wire) == big
    # int64 range edges + arbitrary precision beyond them
    edges = {"kind": "x", "sender": 0,
             "payload": {"a": 2**63 - 1, "b": -(2**63), "c": 2**80,
                         "d": -(2**80)}}
    assert F.decode_head(F.encode_head_bin(edges)) == edges


def test_decoded_payload_matches_json_semantics():
    """Handlers must not see codec-dependent shapes: tuples decode as
    lists, non-str dict keys coerce the way json.dumps coerces them,
    bools survive inside int lists (the int64 fast path must not
    swallow them), floats stay floats."""
    head = {"kind": "x", "sender": 1, "payload": {
        "tup": (1, 2, 3), "mixed": [1, True, 2.5, "s", None],
        "nested": {"a": [{"b": []}]}, "f": 1.0, "i": 1,
    }, "bs": 7}
    dec = F.decode_head(F.encode_head_bin(head))
    jdec = json.loads(json.dumps(head))
    assert dec == jdec
    assert isinstance(dec["payload"]["f"], float)
    assert isinstance(dec["payload"]["i"], int)
    assert dec["payload"]["mixed"][1] is True
    ik = {"kind": "x", "sender": 1, "payload": {1: "a", True: "b"}}
    assert F.decode_head(F.encode_head_bin(ik)) \
        == json.loads(json.dumps(ik))


def test_malformed_binary_frames_decode_to_none_not_raise():
    good = F.encode_head_bin({"kind": "psr:t", "sender": 1,
                              "payload": {"req": 3, "acks": [1, 2]},
                              "ds": 5})
    assert F.decode_head(good) is not None
    for bad in (b"", b"\x00", bytes([F.MAGIC]), good[:-3], good[:7],
                bytes([F.MAGIC ^ 1]) + good[1:],
                good + b"trailing", b"not json at all", b"[1, 2]",
                b"{torn json"):
        assert F.decode_head(bad) is None, bad[:16]
    # a truncated length field inside the TLV must not over-read
    assert F.decode_head(good[: len(good) // 2]) is None


def test_mixed_fleet_sniffs_per_frame():
    """A json-fmt rank and a bin-fmt rank interoperate: the receive
    path sniffs the first byte, so both decode to the same dict."""
    head = {"kind": "clock", "sender": 0,
            "payload": {"clocks": [3, 4]}, "bs": 12}
    assert F.decode_head(F.encode_head(head, "json")) \
        == F.decode_head(F.encode_head(head, "bin")) == head
    assert F.encode_head(head, "json")[:1] == b"{"
    assert F.encode_head(head, "bin")[0] == F.MAGIC


def test_wire_fmt_env_resolution(monkeypatch):
    monkeypatch.delenv("MINIPS_WIRE_FMT", raising=False)
    assert F.wire_fmt_from_env() == "bin"
    monkeypatch.setenv("MINIPS_WIRE_FMT", "json")
    assert F.wire_fmt_from_env() == "json"
    monkeypatch.setenv("MINIPS_WIRE_FMT", "base64")
    with pytest.raises(ValueError, match="MINIPS_WIRE_FMT"):
        F.wire_fmt_from_env()


def test_dup_msg_is_deep_and_codec_agnostic():
    """The chaos injector's duplicate op (satellite): the copy must be
    independent at every nesting level (handlers mutate payloads in
    place) and must carry values JSON cannot (bytes in a retransmit
    wrapper) — the seed's json.loads(json.dumps(...)) raised there."""
    msg = {"kind": "__rt", "sender": 1,
           "payload": {"m2": b"\x00\xb6raw", "seqs": [1, 2],
                       "nest": {"a": [1, {"b": 2}]}, "t": (1, 2)}}
    dup = F.dup_msg(msg)
    assert dup["payload"]["m2"] == b"\x00\xb6raw"
    assert dup["payload"]["t"] == [1, 2]  # JSON parity: tuples -> lists
    dup["payload"]["nest"]["a"][1]["b"] = 99
    dup["payload"]["seqs"].append(3)
    assert msg["payload"]["nest"]["a"][1]["b"] == 2
    assert msg["payload"]["seqs"] == [1, 2]


def test_reliable_retransmit_carries_binary_heads():
    """The __rt wrapper round-trip at the codec level: a journaled
    binary head re-ships as raw bytes ("m2") and decodes back to the
    exact original frame — the reliable channel's recovery path under
    MINIPS_WIRE_FMT=bin."""
    inner = {"kind": "psP:t", "sender": 0,
             "payload": {"n": 4, "comm": "int8", **_cfg_header()},
             "ds": 41}
    journaled = F.encode_head_bin(inner)
    wrap = {"kind": "__rt", "sender": 0, "payload": {"m2": journaled}}
    wire = F.encode_head_bin(wrap)
    got = F.decode_head(wire)
    assert F.decode_head(got["payload"]["m2"]) == inner
