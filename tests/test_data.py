import numpy as np
import pytest

from minips_tpu.data import synthetic
from minips_tpu.data.libsvm import densify, read_libsvm, write_libsvm
from minips_tpu.data.loader import BatchIterator, prefetch_to_device


def test_libsvm_roundtrip(tmp_path):
    d = synthetic.classification_sparse(50, dim=1000, nnz_per_row=5, seed=0)
    path = str(tmp_path / "x.libsvm")
    write_libsvm(path, d["y"], d["idx"], d["val"], d["mask"])
    back = read_libsvm(path, use_native=False)
    np.testing.assert_array_equal(back["y"], d["y"])
    # same nonzeros row-by-row (order preserved)
    np.testing.assert_array_equal(back["idx"] * back["mask"].astype(int),
                                  d["idx"] * d["mask"].astype(int))
    np.testing.assert_allclose(back["val"] * back["mask"],
                               d["val"] * d["mask"], rtol=1e-4)


def test_densify_oracle():
    data = {"idx": np.array([[0, 2], [1, 1]], np.int32),
            "val": np.array([[1.0, 2.0], [3.0, 4.0]], np.float32),
            "mask": np.array([[1, 1], [1, 1]], np.float32),
            "y": np.array([1.0, 0.0], np.float32)}
    out = densify(data, dim=3)
    np.testing.assert_allclose(out["x"],
                               [[1.0, 0.0, 2.0], [0.0, 7.0, 0.0]])


def test_batch_iterator_shapes_and_coverage():
    data = {"x": np.arange(100).reshape(100, 1), "y": np.arange(100)}
    it = iter(BatchIterator(data, 32, seed=0))
    seen = set()
    for _ in range(6):  # two epochs worth
        b = next(it)
        assert b["x"].shape == (32, 1)
        seen.update(b["y"].tolist())
    assert len(seen) > 90  # near-full coverage over 2 epochs


def test_batch_iterator_rejects_mismatch():
    with pytest.raises(ValueError):
        BatchIterator({"x": np.zeros(10), "y": np.zeros(9)}, 2)
    with pytest.raises(ValueError):
        BatchIterator({"x": np.zeros(10)}, 20)


def test_prefetch_preserves_order_and_transform():
    src = ({"i": np.array([i])} for i in range(10))
    out = list(prefetch_to_device(src, lambda b: b["i"][0] * 2, depth=3))
    assert out == [i * 2 for i in range(10)]


def test_criteo_like_schema():
    d = synthetic.criteo_like(100, seed=0)
    assert d["dense"].shape == (100, 13)
    assert d["cat"].shape == (100, 26)
    assert set(np.unique(d["y"])) <= {0.0, 1.0}
    # per-field id spaces are disjoint
    assert (d["cat"].min(axis=0) >= np.arange(26) * 100_000).all()


def test_skipgram_pairs():
    tokens = np.arange(50, dtype=np.int32)
    c, x = synthetic.skipgram_pairs(tokens, window=2, seed=0)
    assert len(c) == len(x) > 0
    assert (np.abs(c - x) <= 2).all() and (c != x).all()


def test_batch_iterator_drop_last_false_covers_tail():
    data = {"x": np.arange(10)}
    it = iter(BatchIterator(data, 4, seed=0, drop_last=False))
    sizes = [len(next(it)["x"]) for _ in range(3)]
    assert sorted(sizes) == [2, 4, 4]  # tail batch of 2 included


def test_prefetch_propagates_producer_error():
    def bad(b):
        raise RuntimeError("put exploded")
    src = ({"i": np.array([i])} for i in range(5))
    gen = prefetch_to_device(src, bad, depth=2)
    with pytest.raises(RuntimeError, match="put exploded"):
        next(gen)


def test_prefetch_early_exit_releases_producer():
    import threading
    n_before = threading.active_count()
    src = ({"i": np.array([i])} for i in range(1000))
    gen = prefetch_to_device(src, lambda b: b, depth=1)
    next(gen)
    gen.close()  # consumer walks away with the queue full
    import time
    time.sleep(0.5)
    assert threading.active_count() <= n_before + 1


def test_native_reader_matches_python(tmp_path):
    from minips_tpu.data.native import read_libsvm_native
    d = synthetic.classification_sparse(200, dim=5000, nnz_per_row=7, seed=3)
    path = str(tmp_path / "n.libsvm")
    write_libsvm(path, d["y"], d["idx"], d["val"], d["mask"])
    nat = read_libsvm_native(path)
    if nat is None:
        pytest.skip("native lib unavailable (no compiler)")
    py = read_libsvm(path, use_native=False)
    np.testing.assert_array_equal(nat["y"], py["y"])
    np.testing.assert_array_equal(nat["idx"], py["idx"])
    np.testing.assert_allclose(nat["val"], py["val"], rtol=1e-6)
    np.testing.assert_array_equal(nat["mask"], py["mask"])


def test_native_reader_width_cap(tmp_path):
    from minips_tpu.data.native import read_libsvm_native
    with open(tmp_path / "w.libsvm", "w") as f:
        f.write("1 1:1.0 2:2.0 3:3.0\n-1 5:5.0\n")
    nat = read_libsvm_native(str(tmp_path / "w.libsvm"), max_features=2)
    if nat is None:
        pytest.skip("native lib unavailable")
    assert nat["idx"].shape == (2, 2)
    np.testing.assert_array_equal(nat["y"], [1.0, 0.0])  # {-1,1}->{0,1}
    np.testing.assert_array_equal(nat["idx"][0], [1, 2])  # truncated at 2
    np.testing.assert_array_equal(nat["mask"][1], [1.0, 0.0])
