"""Row-wise sparse updates — the per-key server update, jit-safe.

The reference server applies ``updater->Update(keys, grads)`` touching only
the pushed keys (SURVEY.md §3.3). On TPU that becomes scatter-add (SGD) or a
dedup + row-wise accumulator step (Adagrad), with static shapes throughout:
duplicates are merged with a sorted-segment sum (O(B log B)) so the
accumulator sees each touched row exactly once per push — matching the
reference's "sum duplicate Adds, then update" semantics.

Shared by SparseTable.push and the fused GSPMD training steps so both paths
have identical numerics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dedup_segment_sum(slots: jnp.ndarray, grads: jnp.ndarray):
    """Merge duplicate slots. Returns (rep_slots [B], summed [B, D], valid
    [B]) where only the first k entries (k = number of unique slots) are
    valid; invalid entries have summed == 0 so scatter-ADDs are no-ops.
    Shapes are static (B) for jit."""
    slots = slots.reshape(-1)
    grads = grads.reshape(slots.shape[0], -1)
    order = jnp.argsort(slots)
    s_sorted = slots[order]
    g_sorted = grads[order]
    first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s_sorted[1:] != s_sorted[:-1]])
    seg_id = jnp.cumsum(first) - 1
    n = s_sorted.shape[0]
    g_sum = jnp.zeros_like(g_sorted).at[seg_id].add(g_sorted)
    rep = jnp.zeros(n, slots.dtype).at[seg_id].max(s_sorted)
    valid = jnp.arange(n) <= seg_id[-1]
    g_sum = jnp.where(valid[:, None], g_sum, 0)
    rep = jnp.where(valid, rep, 0)
    return rep, g_sum, valid


def row_sgd(emb: jnp.ndarray, slots: jnp.ndarray, grads: jnp.ndarray,
            lr: float) -> jnp.ndarray:
    """SGD scatter: duplicates accumulate natively under scatter-add."""
    return emb.at[slots.reshape(-1)].add(
        -lr * grads.reshape(slots.size, -1).astype(emb.dtype))


# Above this table size (elements), the dense-accumulate adagrad path's
# extra table-shaped scratch buffer (256 MB of f32 at the threshold) stops
# being worth it and the sort-dedup path takes over.
DENSE_ACCUM_MAX_ELEMS = 1 << 26


def row_adagrad(emb: jnp.ndarray, accum: jnp.ndarray, slots: jnp.ndarray,
                grads: jnp.ndarray, lr: float, eps: float = 1e-10,
                prefer_dense: bool | None = None):
    """Row-wise Adagrad on the touched rows only.

    Two numerically identical strategies, chosen by (static) table size:

    - **dense-accumulate** (default for tables <= DENSE_ACCUM_MAX_ELEMS):
      scatter-add the batch gradients into a table-shaped buffer, then a
      whole-table update. Streams O(S·D) but avoids any sort — measured
      on the real chip with chained donated state at the Criteo bench
      shapes (S=2^18, D=8, 426k keys/push): ~1ms vs ~20ms per push,
      because TPU sorts are slow and the scatter dominates either way.
    - **sort-dedup** (large tables): argsort + segment-sum so cost stays
      O(B log B + B·D), independent of table size, and no table-shaped
      scratch is allocated.
    """
    if eps <= 0:
        raise ValueError(f"eps must be > 0, got {eps}")  # dense path divides
    if prefer_dense is None:
        prefer_dense = emb.size <= DENSE_ACCUM_MAX_ELEMS
    if prefer_dense:
        return _row_adagrad_dense(emb, accum, slots, grads, lr, eps)
    return _row_adagrad_sorted(emb, accum, slots, grads, lr, eps)


def _row_adagrad_dense(emb, accum, slots, grads, lr, eps):
    # Untouched rows need no masking: their scattered g is exactly 0, so
    # g2 = 0 leaves accum bitwise unchanged (accum >= 0, no -0.0 case) and
    # step = 0/(sqrt(accum)+eps) = 0 as long as eps > 0.
    flat = slots.reshape(-1)
    g = (jnp.zeros_like(emb)
         .at[flat].add(grads.reshape(flat.shape[0], -1).astype(emb.dtype)))
    new_accum = accum + g * g
    return emb - lr * g / (jnp.sqrt(new_accum) + eps), new_accum


def _row_adagrad_sorted(emb, accum, slots, grads, lr, eps):
    rep, g_sum, _ = dedup_segment_sum(slots, grads.astype(emb.dtype))
    g2 = g_sum * g_sum
    acc_rows = accum[rep] + g2
    accum = accum.at[rep].add(g2)
    step = -lr * g_sum / (jnp.sqrt(acc_rows) + eps)
    emb = emb.at[rep].add(step)
    return emb, accum


def row_adam(emb: jnp.ndarray, m: jnp.ndarray, v: jnp.ndarray,
             steps: jnp.ndarray, slots: jnp.ndarray, grads: jnp.ndarray,
             lr: float, b1: float = 0.9, b2: float = 0.999,
             eps: float = 1e-8, prefer_dense: bool | None = None):
    """Row-wise LAZY Adam: touched rows get one full Adam step (moments,
    per-row bias correction via a per-row step counter) and untouched rows
    are left completely alone — no moment decay, the standard lazy-Adam
    semantics sparse/CTR systems use, and the sparse analog of the
    reference's per-key server update. Same two strategies as
    :func:`row_adagrad`, auto-picked by static table size."""
    if eps <= 0:
        raise ValueError(f"eps must be > 0, got {eps}")
    if prefer_dense is None:
        # Adam's dense path streams m and v whole-table and materializes
        # two extra table-shaped temporaries (~4x adagrad's scratch
        # traffic), so its crossover to sort-dedup sits 4x lower.
        prefer_dense = emb.size <= DENSE_ACCUM_MAX_ELEMS // 4
    if prefer_dense:
        return _row_adam_dense(emb, m, v, steps, slots, grads, lr, b1, b2,
                               eps)
    return _row_adam_sorted(emb, m, v, steps, slots, grads, lr, b1, b2,
                            eps)


def _row_adam_dense(emb, m, v, steps, slots, grads, lr, b1, b2, eps):
    flat = slots.reshape(-1)
    g = (jnp.zeros_like(emb)
         .at[flat].add(grads.reshape(flat.shape[0], -1).astype(emb.dtype)))
    touched = jnp.zeros((emb.shape[0],), jnp.bool_).at[flat].set(True)
    tcol = touched[:, None]
    steps_new = steps + touched.astype(steps.dtype)
    m_new = jnp.where(tcol, b1 * m + (1 - b1) * g, m)
    v_new = jnp.where(tcol, b2 * v + (1 - b2) * g * g, v)
    tf = steps_new.astype(emb.dtype)
    bc1 = jnp.where(touched, 1 - b1 ** tf, 1.0)[:, None]
    bc2 = jnp.where(touched, 1 - b2 ** tf, 1.0)[:, None]
    update = lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    return (emb - jnp.where(tcol, update, 0.0), m_new, v_new, steps_new)


def _row_adam_sorted(emb, m, v, steps, slots, grads, lr, b1, b2, eps):
    rep, g_sum, valid = dedup_segment_sum(slots, grads.astype(emb.dtype))
    vcol = valid[:, None]
    m_rows, v_rows = m[rep], v[rep]
    s_new = steps[rep] + valid.astype(steps.dtype)
    m_n = b1 * m_rows + (1 - b1) * g_sum
    v_n = b2 * v_rows + (1 - b2) * g_sum * g_sum
    tf = s_new.astype(emb.dtype)
    bc1 = jnp.where(valid, 1 - b1 ** tf, 1.0)[:, None]
    bc2 = jnp.where(valid, 1 - b2 ** tf, 1.0)[:, None]
    update = lr * (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
    # masked DELTA scatter-adds: invalid entries contribute exactly zero,
    # so the duplicate rep=0 rows of the invalid tail are harmless
    emb = emb.at[rep].add(jnp.where(vcol, -update, 0.0))
    m = m.at[rep].add(jnp.where(vcol, m_n - m_rows, 0.0))
    v = v.at[rep].add(jnp.where(vcol, v_n - v_rows, 0.0))
    steps = steps.at[rep].add(valid.astype(steps.dtype))
    return emb, m, v, steps
