"""Sharded-embedding collective traffic scales with touched rows, not table
size (VERDICT round-1 task 6; SURVEY.md §7.4.2 hard part 2).

The reference ships per-batch key/val slices through its Mailbox, never the
table (SURVEY.md §3.3) — so a TPU rebuild whose sharded gather degraded to
"all-gather the table" would be an asymptotic regression hiding behind
GSPMD. These tests pin the compiled behavior: we lower the REAL
SparseTable pull/push on the 8-device mesh, parse the partitioned HLO, and
assert the collective payload is independent of table capacity and linear
in the batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from minips_tpu.tables.sparse import SparseTable
from minips_tpu.utils.comm_analysis import (collective_bytes, collective_ops,
                                            traffic_report)

DIM = 32
BATCH = 512


def _sharded_keys(mesh, batch):
    return jax.device_put(
        jnp.arange(batch, dtype=jnp.int32),
        NamedSharding(mesh, P("data")))


def _pull_bytes(mesh, slots, batch):
    t = SparseTable(slots, DIM, mesh, updater="sgd")
    comp = t._jit_pull.lower(t.emb, _sharded_keys(mesh, batch)).compile()
    return collective_bytes(comp)


def _push_bytes(mesh, slots, batch):
    t = SparseTable(slots, DIM, mesh, updater="adagrad")
    grads = jax.device_put(
        jnp.ones((batch, DIM)), NamedSharding(mesh, P("data", None)))
    comp = t._jit_push.lower(
        t.emb, t.opt_state(), _sharded_keys(mesh, batch), grads).compile()
    return collective_bytes(comp)


def test_pull_traffic_independent_of_table_size(mesh8):
    small = _pull_bytes(mesh8, 1 << 12, BATCH)
    large = _pull_bytes(mesh8, 1 << 18, BATCH)  # 64x the capacity
    assert small == large, (
        f"pull collectives grew with table size: {small} -> {large}")
    # and the traffic is batch-sized, nowhere near one table shard
    table_shard_bytes = (1 << 18) * DIM * 4 // 8
    assert large < table_shard_bytes / 8


def test_push_traffic_independent_of_table_size(mesh8):
    small = _push_bytes(mesh8, 1 << 12, BATCH)
    large = _push_bytes(mesh8, 1 << 18, BATCH)
    assert small == large, (
        f"push collectives grew with table size: {small} -> {large}")


def test_traffic_linear_in_batch(mesh8):
    b1 = _pull_bytes(mesh8, 1 << 14, BATCH)
    b4 = _pull_bytes(mesh8, 1 << 14, 4 * BATCH)
    # linear within fuzz (key all-gather adds a small constant-ish term)
    assert b1 * 3 < b4 <= b1 * 4 + 1024


def test_no_table_sized_collective_op(mesh8):
    """No single collective touches anything with the table's row count —
    the literal 'did GSPMD all-gather the table' check."""
    slots = 1 << 16
    t = SparseTable(slots, DIM, mesh8)
    comp = t._jit_pull.lower(t.emb, _sharded_keys(mesh8, BATCH)).compile()
    for op in collective_ops(comp.as_text()):
        # integer dim comparison, not substring (16384 inside f32[163840])
        assert not op.has_dim(slots) and not op.has_dim(slots // 8), (
            f"table-sized collective scheduled: {op}")


def test_traffic_report_shape(mesh8):
    t = SparseTable(1 << 12, DIM, mesh8)
    comp = t._jit_pull.lower(t.emb, _sharded_keys(mesh8, BATCH)).compile()
    rep = traffic_report(comp)
    assert rep["total_bytes"] == sum(o["bytes"] for o in rep["ops"])
    assert all(o["kind"] in ("all-gather", "all-reduce", "all-to-all",
                             "reduce-scatter", "collective-permute")
               for o in rep["ops"])
    assert rep["total_bytes"] > 0  # a sharded gather must communicate


def test_collective_parser_on_known_hlo():
    """Parser unit-check against hand-written HLO lines: sync variadic
    tuples sum, async start/done pairs count once, and a -start tuple
    (operand alias + output) counts only the output — the real-TPU shape
    of all-gather-start, where summing would ~double the payload."""
    hlo = "\n".join([
        "%ar = f32[128,64]{1,0} all-reduce(%x), replica_groups={}",
        "%ag = (s32[8]{0}, s32[8]{0}) all-gather(%y)",
        "%st = f32[256]{0} collective-permute-start(%z)",
        "%dn = f32[256]{0} collective-permute-done(%st)",
        "%ags = (f32[512,32]{1,0}, f32[4096,32]{1,0}) all-gather-start(%w)",
        "%agd = f32[4096,32]{1,0} all-gather-done(%ags)",
        "%not_a_collective = f32[999]{0} add(%a, %b)",
    ])
    ops = collective_ops(hlo)
    kinds = sorted(o.kind for o in ops)
    assert kinds == ["all-gather", "all-gather", "all-reduce",
                     "collective-permute"]
    total = sum(o.bytes for o in ops)
    assert total == (128 * 64 * 4      # all-reduce
                     + 2 * 8 * 4       # variadic sync all-gather: sums
                     + 256 * 4         # permute start counted once
                     + 4096 * 32 * 4)  # async start: output only


def test_collective_parser_fp8_and_unknown_dtypes():
    """ADVICE r2: fp8/u4 HLO names must parse (full-name tokenization, not
    the trailing 'fn'), and an unknown primitive type degrades to a warned
    conservative estimate instead of a KeyError crash."""
    import warnings

    ops = collective_ops(
        "%q = f8e4m3fn[1024,64]{1,0} all-reduce(%x)\n"
        "%u = u4[256]{0} all-gather(%y)")
    assert [o.bytes for o in ops] == [1024 * 64 * 1, 256 * 1]
    assert ops[0].shape == "f8e4m3fn[1024,64]"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ops = collective_ops("%z = zz9[100]{0} all-reduce(%x)")
    assert ops[0].bytes == 100 * 16  # conservative: >= widest known type
    assert any("unknown HLO primitive" in str(x.message) for x in w)


def test_collective_op_has_dim_is_integer_exact():
    """16384 as a dim must not match f32[163840] (the substring trap)."""
    ops = collective_ops("%a = f32[163840]{0} all-reduce(%x)")
    assert not ops[0].has_dim(16384)
    assert ops[0].has_dim(163840)
