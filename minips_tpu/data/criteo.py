"""Criteo display-advertising TSV reader/writer.

The reference family's flagship sparse workload is Wide&Deep / DeepFM on
Criteo-1TB (SURVEY.md §2 "Data loading"; BASELINE.json:10). Line format:

    label \\t I1..I13 (decimal ints, may be empty or negative)
          \\t C1..C26 (8-hex-digit categorical hashes, may be empty)

``read_criteo`` returns the same batch schema the apps and the synthetic
generator use (minips_tpu/data/synthetic.py ``criteo_like``):

- ``y``          [N]      float32 click labels
- ``dense``      [N, 13]  float32 numeric features (missing → 0)
- ``dense_mask`` [N, 13]  float32 presence mask
- ``cat``        [N, 26]  int64 categorical ids, offset ``field << 32`` so
  every column keeps a distinct id space (per-column vocabularies); missing
  values map to the field-offset 0 token. Downstream, SparseTable hashes
  these unbounded ids onto slots (tables/sparse.py ``hash_to_slots``).

A native C++ parser (cpp/criteo_reader.cpp, SURVEY.md §2.1 item 6) is used
transparently when buildable; the pure-Python path is the fallback and the
correctness oracle for it.
"""

from __future__ import annotations

import numpy as np

NUM_DENSE = 13
NUM_CAT = 26


def write_criteo(path: str, y: np.ndarray, dense: np.ndarray,
                 cat: np.ndarray, dense_mask: np.ndarray | None = None) -> None:
    """Write rows in Criteo TSV form (used by tests/synthetic dumps). ``cat``
    entries are written as 8-hex of their low 32 bits; a masked-out dense
    cell (or NaN) is written as an empty field."""
    y = np.asarray(y)
    dense = np.asarray(dense)
    cat = np.asarray(cat)
    with open(path, "w") as f:
        for r in range(len(y)):
            fields = [str(int(y[r]))]
            for j in range(dense.shape[1]):
                v = dense[r, j]
                present = not np.isnan(v) if dense_mask is None \
                    else bool(dense_mask[r, j])
                fields.append(str(int(v)) if present else "")
            for j in range(cat.shape[1]):
                fields.append(format(int(cat[r, j]) & 0xFFFFFFFF, "08x"))
            f.write("\t".join(fields) + "\n")


def _read_python(path: str) -> dict:
    ys, denses, masks, cats = [], [], [], []
    field_offset = np.arange(NUM_CAT, dtype=np.int64) << 32
    with open(path) as f:
        for line in f:
            line = line.rstrip("\r\n")
            if not line:
                continue
            parts = line.split("\t")
            # pad short lines so slicing below is uniform
            parts += [""] * (1 + NUM_DENSE + NUM_CAT - len(parts))
            # strict int label (same contract as the native parser's rc=3)
            ys.append(float(int(parts[0])) if parts[0] else 0.0)
            d = np.zeros(NUM_DENSE, np.float32)
            m = np.zeros(NUM_DENSE, np.float32)
            for j, tok in enumerate(parts[1:1 + NUM_DENSE]):
                if tok:
                    d[j] = float(int(tok))
                    m[j] = 1.0
            cat_toks = parts[1 + NUM_DENSE:1 + NUM_DENSE + NUM_CAT]
            if any(len(tok) > 8 for tok in cat_toks):
                # >8 hex digits would exceed the 32-bit per-field id space
                # (the native parser rejects these too — rc=3)
                raise ValueError(f"categorical token over 8 hex digits in "
                                 f"{path!r}")
            c = np.array([int(tok, 16) if tok else 0 for tok in cat_toks],
                         np.int64) | field_offset
            denses.append(d)
            masks.append(m)
            cats.append(c)
    n = len(ys)
    return {
        "y": np.asarray(ys, np.float32),
        "dense": (np.stack(denses) if n else
                  np.zeros((0, NUM_DENSE), np.float32)),
        "dense_mask": (np.stack(masks) if n else
                       np.zeros((0, NUM_DENSE), np.float32)),
        "cat": (np.stack(cats) if n else np.zeros((0, NUM_CAT), np.int64)),
    }


def read_criteo(path: str, use_native: bool = True,
                shared: bool = False) -> dict:
    """Returns dict(y, dense, dense_mask, cat) — see module docstring.
    ``shared=True``: under the launcher, only the host's local leader
    parses; colocated processes mmap the same copy (data/shm_store.py)."""
    if shared:
        from minips_tpu.data.shm_store import make_tag, shared_load

        tag = make_tag("criteo", path)
        return shared_load(tag, lambda: read_criteo(
            path, use_native=use_native, shared=False))
    if use_native:
        try:
            from minips_tpu.data.native import read_criteo_native

            out = read_criteo_native(path)
            if out is not None:
                return out
        except ImportError:
            pass
    return _read_python(path)


def log_transform(dense: np.ndarray,
                  mask: np.ndarray | None = None) -> np.ndarray:
    """Standard Criteo numeric preprocessing: ``log1p(max(x, 0))``, with
    masked-out (missing) cells staying 0. Negative raw values (I2 can be
    −1..−3) clamp to 0 before the log."""
    out = np.log1p(np.maximum(np.asarray(dense, np.float32), 0.0))
    if mask is not None:
        out = out * np.asarray(mask, np.float32)
    return out
