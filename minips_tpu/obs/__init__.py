"""Observability layer for the sharded PS (this PR's tentpole).

Four pieces, all reading the same per-rank event stream:

- :mod:`minips_tpu.obs.tracer` — the env-gated (``MINIPS_TRACE``)
  bounded ring buffer of typed wire events, dumped as Chrome-trace JSON
  per rank;
- :mod:`minips_tpu.obs.hist` — fixed-bucket log2 latency histograms
  (always on, independent of the tracer) feeding p50/p95/p99 into the
  done lines next to the means;
- :mod:`minips_tpu.obs.merge` — the cross-rank merger: clock alignment
  from heartbeat exchange, flow arrows linking client pull legs to
  owner serves, optional XLA device-trace interleave;
- :mod:`minips_tpu.obs.report` — blocked-time attribution over a merged
  trace (per-rank: fraction blocked on which owner / gate peer /
  fence).

Everything here is import-light on purpose: the tracer module is
imported by every hot-path module (bus, tables, gate) and must cost one
attribute lookup + one branch when the layer is off.
"""
