"""Push-visible-at-replica freshness tracking — the metric a recsys
fleet is paid on, measured with the stamps the serving plane already
ships.

Freshness is the wall time between a push landing at its OWNER and that
value being servable at a REPLICA. Every drill before this layer was
about read latency or staleness BOUNDS (the gate's ``admits`` proof);
none measured the lag itself. The plumbing is one head field: the owner
stamps each refresh frame with ``fts`` — the monotonic time of the
OLDEST push contained in that batch (per granted block, ``note_push``
records first-dirty time; the refresh pops it with the dirty set) — and
the replica records ``now - fts`` on delta apply. Grant snapshots stamp
``fts`` with the owner's state-read time, so their lag is pure
ship+install delay.

Honest limits, stated here because the number is only as good as they
are:

- **Refresh-interval-quantized.** A push becomes visible when the NEXT
  owner refresh ships, so observed lag ~= U(0, interval) + wire + apply.
  A p99 near the serve ``interval`` knob is the floor, not a problem.
- **Cross-process clocks.** ``fts`` is the owner's ``time.monotonic()``
  compared against the replica's. On one Linux host CLOCK_MONOTONIC is
  system-wide, so the loopback benches measure real lag (ms-scale
  scheduler noise). Across hosts the raw difference absorbs the boot
  offset — multi-host numbers need the flight-recorder offset alignment
  (obs/flight.py) applied first, and this layer does not pretend
  otherwise.
- **Renew-only frames carry no ``fts``.** A lease renewal with no dirty
  rows contains no push, so there is nothing to be fresh about; those
  frames are counted (``unstamped_frames``) but record no lag.

One tracker per (table, rank) — i.e. per tenant when tenancy is on,
since tenants are tables (tenant/registry.py). The done-line block
follows the PR5 convention: serving plane OFF -> the ``freshness``
block is ``None``; armed with no replica traffic -> ``{"count": 0}``
summaries and zero counters.
"""

from __future__ import annotations

import threading

from minips_tpu.obs.hist import Log2Histogram, merge_counts, \
    summarize_counts

__all__ = ["FreshnessTracker", "merge_freshness"]


class FreshnessTracker:
    """Per-table freshness state: the replica-side visibility-lag
    histogram plus owner/replica engagement counters. Lives on the
    table's serve state (serve/plane.py) so it appears and disappears
    with the plane."""

    __slots__ = ("hist", "counters", "_lock")

    def __init__(self) -> None:
        self.hist = Log2Histogram()
        self._lock = threading.Lock()
        self.counters = {
            # owner side: refresh/grant frames shipped WITH an fts stamp
            "stamped_frames": 0,
            # owner side: frames shipped without one (renew-only)
            "unstamped_frames": 0,
            # replica side: lag samples recorded (one per stamped frame
            # applied, not per row — the lag is a frame property)
            "lag_samples": 0,
            # replica side: stamped frames whose lag came out negative
            # (cross-host clock skew) — clamped to 0 but counted, so a
            # multi-host run cannot silently report rosy lags
            "clock_skew_clamped": 0,
        }

    # ------------------------------------------------------------ owner
    def note_shipped(self, stamped: bool) -> None:
        with self._lock:
            if stamped:
                self.counters["stamped_frames"] += 1
            else:
                self.counters["unstamped_frames"] += 1

    # ---------------------------------------------------------- replica
    def note_lag(self, lag_s: float) -> None:
        """Record one push-visible-at-replica lag sample (seconds)."""
        with self._lock:
            self.counters["lag_samples"] += 1
            if lag_s < 0.0:
                self.counters["clock_skew_clamped"] += 1
                lag_s = 0.0
        self.hist.record_s(lag_s)

    # ------------------------------------------------------------ reads
    def snapshot_counts(self) -> list:
        return self.hist.snapshot()

    def record(self) -> dict:
        """Done-line shape for ONE table: lag summary + counters."""
        with self._lock:
            ctr = dict(self.counters)
        return {"lag": summarize_counts(self.hist.snapshot()), **ctr}


def merge_freshness(trackers: "list[FreshnessTracker]") -> dict:
    """Fleet view over several tables' trackers: elementwise hist merge
    (fixed buckets) + counter sums — ``{"count": 0}`` lag when armed but
    idle, matching ``summarize_counts``."""
    if not trackers:
        return {"lag": {"count": 0}, "stamped_frames": 0,
                "unstamped_frames": 0, "lag_samples": 0,
                "clock_skew_clamped": 0}
    counts = merge_counts([t.snapshot_counts() for t in trackers])
    out: dict = {"lag": summarize_counts(counts)}
    for k in trackers[0].counters:
        out[k] = sum(t.counters[k] for t in trackers)
    return out
