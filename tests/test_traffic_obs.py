"""Freshness observability + SLO burn accounting + the open-loop
traffic driver (obs/freshness.py, obs/slo.py, apps/traffic_driver.py)
— this PR's tentpole.

Three layers of drill, the house shape:

- pure logic: the MINIPS_TRAFFIC grammar (parse/refuse table, the
  crowd token, and the seeded 250-spec fuzzer), the deterministic
  rate curve and arrival schedule, ``frac_over_target``'s log2
  interpolation, and the MINIPS_SLO grammar;
- unit protocol: the driver replays its schedule against a fake pull
  (counts, key bounds, error survival) and proves the
  coordinated-omission point at unit scale (a slow backend shows up in
  scheduled-arrival latency, not in service time); FreshnessTracker
  clamps cross-host skew loudly; SloTracker burns on a real windowed
  layer, edges once per transition, flexes the boost, and falls back
  to fleet signals for untagged tenants;
- armed-idle drills: a rate=0 armed driver against the BSP lockstep is
  bitwise-equal to off with zero requests scheduled (TRAFFIC-IDLE at
  test scale), and an armed-but-idle serve+slo trainer reports the
  zeros the off-vs-idle convention promises in ``wire_record`` (the
  None side is pinned in test_obs_trace.py's schema test).
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from minips_tpu.apps.traffic_driver import (TrafficConfig,
                                            TrafficDriver)
from minips_tpu.apps.traffic_driver import maybe_config as maybe_traffic
from minips_tpu.obs.freshness import FreshnessTracker, merge_freshness
from minips_tpu.obs.slo import (SloConfig, SloTracker,
                                frac_over_target)
from minips_tpu.obs.slo import maybe_config as maybe_slo
from minips_tpu.obs.window import WindowedMetrics


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


# ---------------------------------------------- MINIPS_TRAFFIC grammar
def test_traffic_config_parses_and_refuses():
    c = TrafficConfig.parse(
        "rate=500,users=250000,alpha=1.3,batch=16,conc=8,ramp=2,"
        "period=20,crowd=4+2x8,seed=7")
    assert (c.rate, c.users, c.alpha, c.batch, c.conc) == (
        500.0, 250000, 1.3, 16, 8)
    assert (c.ramp, c.period) == (2.0, 20.0)
    assert (c.crowd_at, c.crowd_for, c.crowd_x) == (4.0, 2.0, 8.0)
    assert c.seed == 7
    # off spellings vs armed defaults
    assert TrafficConfig.parse("") is None
    assert TrafficConfig.parse("0") is None
    assert TrafficConfig.parse("1").rate == 200.0
    # rate=0 parses ARMED (the idle drill's whole point)
    assert TrafficConfig.parse("rate=0").rate == 0.0
    for bad, frag in [
        ("rate", "expected k=v"),
        ("rate=abc", "bad value for rate"),
        ("rate=-1", "rate must be"),
        ("users=0", "users must be"),
        ("alpha=1.0", "alpha must be"),
        ("batch=0", "batch must be"),
        ("conc=0", "conc must be"),
        ("ramp=0.5", "ramp is a peak multiplier"),
        ("period=0", "period must be"),
        ("crowd=4+2", "crowd wants"),
        ("crowd=x", "crowd wants"),
        ("crowd=a+bxc", "bad crowd value"),
        ("crowd=4+2x0.5", "crowd multiplier"),
        ("crowd=-1+2x8", "crowd at/duration"),
        ("turbo=1", "unknown knob"),
    ]:
        with pytest.raises(ValueError, match=frag):
            TrafficConfig.parse(bad)


def test_traffic_knob_fuzzer_parse_or_refuse_loudly():
    """Seeded MINIPS_TRAFFIC fuzz (the MINIPS_TENANT fuzzer
    convention): every random spec either parses — twice, to the same
    signature — or refuses with ValueError naming MINIPS_TRAFFIC; any
    other exception is a parser bug."""
    rng = np.random.default_rng(20260807)
    knobs = ["rate", "users", "alpha", "batch", "conc", "ramp",
             "period", "seed", "crowd", "zz", ""]
    vals = ["500", "0", "1", "1.5", "-1", "abc", "inf", "nan", "",
            "4+2x8", "4+2", "x", "1e6"]
    checked = 0
    for _ in range(250):
        n = int(rng.integers(0, 5))
        spec = ",".join(
            f"{knobs[int(rng.integers(len(knobs)))]}"
            f"={vals[int(rng.integers(len(vals)))]}"
            for _k in range(n))
        outcomes = []
        for _twice in range(2):
            try:
                c = maybe_traffic(spec)
                outcomes.append(
                    ("ok", None if c is None else c.signature()))
            except ValueError as e:
                assert "MINIPS_TRAFFIC" in str(e), spec
                outcomes.append(("refused", str(e)))
            except Exception as e:  # noqa: BLE001 - the fuzzer's point
                pytest.fail(f"spec {spec!r} raised {e!r} "
                            f"(not ValueError)")
        assert outcomes[0] == outcomes[1], spec
        checked += 1
    assert checked == 250


def test_rate_curve_is_deterministic_and_shaped():
    flat = TrafficConfig.parse("rate=100")
    assert flat.rate_at(0.0) == flat.rate_at(7.3) == 100.0
    # raised-cosine ramp: troughs at 0 and period, peak ramp*base at
    # period/2 — and the curve is a pure function of t
    ramp = TrafficConfig.parse("rate=100,ramp=3,period=10")
    assert ramp.rate_at(0.0) == pytest.approx(100.0)
    assert ramp.rate_at(5.0) == pytest.approx(300.0)
    assert ramp.rate_at(10.0) == pytest.approx(100.0)
    assert ramp.rate_at(2.5) == ramp.rate_at(2.5)
    # crowd window is half-open [at, at+dur)
    crowd = TrafficConfig.parse("rate=100,crowd=4+2x8")
    assert crowd.rate_at(3.999) == 100.0
    assert crowd.rate_at(4.0) == 800.0
    assert crowd.rate_at(5.999) == 800.0
    assert crowd.rate_at(6.0) == 100.0


# --------------------------------------------------- driver: schedule
def test_schedule_deterministic_and_rate_faithful():
    """Same spec -> bit-identical arrivals AND user draws (two runs of
    one spec offer identical load); the arrival count integrates the
    rate curve (rate*duration within one inter-arrival gap)."""
    mk = lambda: TrafficDriver(TrafficConfig.parse(
        "rate=200,users=1000,alpha=1.2,seed=3,crowd=1+1x4"),
        lambda keys: None, rows=64, duration_s=4.0)
    a, b = mk(), mk()
    np.testing.assert_array_equal(a.arrivals, b.arrivals)
    np.testing.assert_array_equal(a._users, b._users)
    # 3s at 200/s + 1s crowd at 800/s = ~1400 arrivals
    assert abs(len(a.arrivals) - 1400) <= 2
    assert np.all(np.diff(a.arrivals) > 0)
    assert a.arrivals[-1] < 4.0
    # user draws live on the configured population
    assert a._users.min() >= 0 and a._users.max() < 1000


def test_rate_zero_is_armed_idle_and_guard_refuses_blowup():
    idle = TrafficDriver(TrafficConfig.parse("rate=0"),
                         lambda keys: None, rows=8, duration_s=60.0)
    assert len(idle.arrivals) == 0
    rec = idle.record()
    assert rec["scheduled"] == 0 and rec["requests"] == 0
    assert rec["sched_ms"] == {"count": 0}
    # the schedule memory guard names the fix
    with pytest.raises(ValueError, match="lower rate/duration"):
        TrafficDriver(TrafficConfig.parse("rate=1e6"),
                      lambda keys: None, rows=8, duration_s=10.0)
    with pytest.raises(ValueError, match="rows"):
        TrafficDriver(TrafficConfig.parse("1"), lambda keys: None,
                      rows=0, duration_s=1.0)


def test_keys_are_bounded_and_user_pinned():
    d = TrafficDriver(TrafficConfig.parse("rate=100,users=50,batch=4"),
                      lambda keys: None, rows=37, duration_s=1.0)
    for i in range(len(d.arrivals)):
        keys = d._keys_for(i)
        assert keys.shape == (4,)
        assert keys.min() >= 0 and keys.max() < 37
    # the fan-out is a function of the user alone: hot users pin hot
    # row sets across their every request
    same = [i for i in range(len(d.arrivals))
            if d._users[i] == d._users[0]]
    for i in same[1:]:
        np.testing.assert_array_equal(d._keys_for(i), d._keys_for(0))


# --------------------------------------------------- driver: dispatch
def test_driver_replays_schedule_and_survives_errors():
    calls: list = []

    def pull(keys):
        calls.append(np.asarray(keys).copy())

    d = TrafficDriver(TrafficConfig.parse(
        "rate=400,users=100,batch=3,conc=2,seed=5"),
        pull, rows=64, duration_s=0.5)
    d.start()
    time.sleep(0.9)
    d.stop()
    rec = d.record()
    assert rec["requests"] == rec["scheduled"] == len(calls) > 0
    assert rec["unissued"] == 0 and rec["errors"] == 0
    assert rec["rows"] == 3 * rec["requests"]
    assert rec["sched_ms"]["count"] == rec["requests"]
    assert rec["first_error"] is None
    # a failing backend is counted and quoted, never raises into the
    # dispatcher (the driver outlives the fleet it measures)
    boom = TrafficDriver(TrafficConfig.parse("rate=400,conc=2"),
                         lambda k: 1 / 0, rows=8, duration_s=0.25)
    boom.start()
    time.sleep(0.5)
    boom.stop()
    rec = boom.record()
    assert rec["errors"] > 0 and rec["requests"] == 0
    assert "ZeroDivisionError" in rec["first_error"]


def test_open_loop_records_queueing_a_closed_loop_would_omit():
    """The coordinated-omission point at unit scale: a backend that
    serves in ~1ms but admits one request at a time under a 10x
    oversubscribed schedule must show scheduled-arrival p50 far above
    service p50 — the queueing a closed loop's think-after-completion
    accounting silently absorbs."""
    gate = threading.Lock()

    def slow_pull(keys):
        with gate:  # serialized backend: capacity ~1/svc
            time.sleep(0.004)

    d = TrafficDriver(TrafficConfig.parse("rate=1000,conc=4,seed=2"),
                      slow_pull, rows=8, duration_s=0.4)
    d.start()
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        with d._lock:
            if d._next >= len(d.arrivals):
                break
        time.sleep(0.05)
    time.sleep(0.1)
    d.stop()
    rec = d.record()
    assert rec["requests"] > 50
    assert rec["late_issues"] > 0, "schedule never outpaced service"
    # service sits near 4ms; scheduled-arrival latency carries the
    # backlog (>= several service times by mid-schedule)
    assert rec["sched_ms"]["p50_ms"] > 3 * rec["svc_ms"]["p50_ms"], rec


# -------------------------------------------------- freshness tracker
def test_freshness_tracker_records_and_clamps_skew_loudly():
    ft = FreshnessTracker()
    ft.note_shipped(True)
    ft.note_shipped(True)
    ft.note_shipped(False)  # renew-only: counted, no lag
    ft.note_lag(0.010)
    ft.note_lag(0.020)
    ft.note_lag(-0.005)  # cross-host skew: clamped to 0, counted
    rec = ft.record()
    assert rec["stamped_frames"] == 2 and rec["unstamped_frames"] == 1
    assert rec["lag_samples"] == 3
    assert rec["clock_skew_clamped"] == 1
    assert rec["lag"]["count"] == 3
    assert 5.0 <= rec["lag"]["p50_ms"] <= 35.0


def test_merge_freshness_fleet_view_and_armed_idle_zeros():
    assert merge_freshness([]) == {
        "lag": {"count": 0}, "stamped_frames": 0,
        "unstamped_frames": 0, "lag_samples": 0,
        "clock_skew_clamped": 0}
    a, b = FreshnessTracker(), FreshnessTracker()
    a.note_shipped(True)
    a.note_lag(0.001)
    b.note_shipped(True)
    b.note_lag(0.1)
    m = merge_freshness([a, b])
    assert m["lag"]["count"] == 2 and m["stamped_frames"] == 2
    # idle trackers merge to the same zeros as the empty list
    idle = merge_freshness([FreshnessTracker()])
    assert idle["lag"] == {"count": 0} and idle["lag_samples"] == 0


# -------------------------------------------------------- MINIPS_SLO
def test_frac_over_target_log2_interpolation():
    from minips_tpu.obs.hist import Log2Histogram

    assert frac_over_target([0] * 40, 100.0) == 0.0
    h = Log2Histogram()
    for us in (3, 3, 3, 3):  # bucket [2,4)us
        h.record_us(us)
    counts = h.snapshot()
    assert frac_over_target(counts, 1.0) == 1.0   # all above
    assert frac_over_target(counts, 8.0) == 0.0   # all below
    # target mid-bucket: linear fraction of the straddler
    assert frac_over_target(counts, 3.0) == pytest.approx(0.5)
    # mixed: one bucket fully over, the straddler contributes its part
    h.record_us(100)
    assert frac_over_target(h.snapshot(), 3.0) == pytest.approx(
        (4 * 0.5 + 1) / 5)


def test_slo_config_parses_and_refuses():
    c = SloConfig.parse("fresh_ms=50,read_ms=20,shed_rate=5,fast=3,"
                        "slow=9,burn=2,q=0.95,boost=2,pressure=0")
    assert c.signature() == (50.0, 20.0, 5.0, 3, 9, 2.0, 0.95, 2, 0)
    assert SloConfig.parse("") is None and SloConfig.parse("0") is None
    d = SloConfig.parse("1")  # armed-idle: no targets monitored
    assert (d.fresh_ms, d.read_ms, d.shed_rate) == (0.0, 0.0, 0.0)
    assert maybe_slo("") is None
    for bad, frag in [
        ("read_ms=-1", "targets must be"),
        ("fast=0", "fast window"),
        ("fast=4,slow=2", "inverts the blip filter"),
        ("burn=0", "burn threshold"),
        ("q=1", "q must be"),
        ("boost=-1", "boost must be"),
        ("pressure=2", "pressure must be"),
        ("read_ms", "expected k=v"),
        ("zz=1", "unknown knob"),
        ("read_ms=abc", "bad value for read_ms"),
    ]:
        with pytest.raises(ValueError, match=frag):
            SloConfig.parse(bad)
        assert "MINIPS_SLO" in str(pytest.raises(
            ValueError, SloConfig.parse, bad).value)


class _FleetSim:
    """A windowed layer fed by hand: one read-latency hist + one shed
    counter per tenant, with an injected clock so rates are exact."""

    def __init__(self, tenants=("a", "b")):
        self.t = [0.0]
        self.ow = WindowedMetrics(window=4, ring=16,
                                  clock=lambda: self.t[0])
        from minips_tpu.obs.hist import Log2Histogram

        self.hists = {n: Log2Histogram() for n in tenants}
        self.sheds = {n: [0] for n in tenants}
        for n in tenants:
            h, s = self.hists[n], self.sheds[n]
            self.ow.register_hist(f"pull_latency:{n}",
                                  (lambda hh=h: hh.counts))
            self.ow.register_counter(f"shed:{n}",
                                     (lambda ss=s: ss[0]))

    def roll(self, dt=1.0):
        self.t[0] += dt
        self.ow.roll()


def test_slo_tracker_burns_edges_and_boosts():
    sim = _FleetSim()
    cfg = SloConfig.parse("read_ms=1,fast=2,slow=4,boost=2")
    sl = SloTracker(cfg, sim.ow, ["a", "b"])
    # tenant a violates (10ms reads vs 1ms target); b is clean (100us)
    for _ in range(4):
        for _s in range(20):
            sim.hists["a"].record_us(10_000)
            sim.hists["b"].record_us(100)
        sim.roll()
        sl.on_roll()
    assert sl.burning("a") and not sl.burning("b")
    assert sl.burning_tenants() == ["a"]
    assert sl.counters["burns"] == 1  # ONE rising edge, not per roll
    assert sl.replica_boost("a") == 2 and sl.replica_boost("b") == 0
    assert sl.pressure_quanta() == 1
    sl.note_budget("a", 3)
    sl.note_budget("a", 2)  # max wins
    rec = sl.record()
    assert rec["burning"] == ["a/read"]
    assert rec["tenants"]["a"]["max_budget"] == 3
    assert rec["tenants"]["a"]["read_burn"][0] >= cfg.burn
    assert rec["tenants"]["b"]["burning"] == []
    # recovery: clean windows long enough for BOTH windows -> one clear
    for _ in range(5):
        for _s in range(20):
            sim.hists["a"].record_us(100)
        sim.roll()
        sl.on_roll()
    assert not sl.burning("a")
    assert sl.counters["clears"] == 1
    assert sl.pressure_quanta() == 0


def test_slo_tracker_shed_rate_pressure_knob_and_fallbacks():
    sim = _FleetSim(tenants=("a",))
    cfg = SloConfig.parse("shed_rate=5,fast=2,slow=2,pressure=0")
    sl = SloTracker(cfg, sim.ow, ["a"])
    for _ in range(3):
        sim.sheds["a"][0] += 50  # 50 sheds/s vs target 5/s
        sim.roll(dt=1.0)
        sl.on_roll()
    assert sl.burning("a")
    assert sl.pressure_quanta() == 0  # the knob gates the autoscaler
    # an unregistered per-tenant signal falls back to the FLEET signal
    fleet = _FleetSim(tenants=())
    shed = [0]
    fleet.ow.register_counter("shed", lambda: shed[0])
    sl2 = SloTracker(SloConfig.parse("shed_rate=5,fast=2,slow=2"),
                     fleet.ow, ["ghost"])
    for _ in range(3):
        shed[0] += 50
        fleet.roll(dt=1.0)
        sl2.on_roll()
    assert sl2.burning("ghost"), "fleet fallback never engaged"
    # and the windowed layer is mandatory, loudly
    with pytest.raises(ValueError, match="MINIPS_OBS=0"):
        SloTracker(SloConfig.parse("1"), None, [])


# --------------------------------------------------------- armed idle
def test_traffic_armed_idle_lockstep_bitwise_equal_to_off():
    """TRAFFIC-IDLE at test scale: a rate=0 armed driver against the
    BSP lockstep schedules nothing, issues nothing, and the final
    weights are bitwise-identical to the traffic-off run."""
    from tests.test_chaos_reliable import run_bsp_lockstep

    base, lost0 = run_bsp_lockstep()
    st: dict = {}
    armed, lost1 = run_bsp_lockstep(
        traffic="rate=0,users=1000000", stats=st)
    assert lost0 == [0, 0] and lost1 == [0, 0]
    for w0, w1 in zip(base, armed):
        np.testing.assert_array_equal(w0, w1)
    assert st["traffic_scheduled"] == 0
    assert st["traffic_requests"] == 0


def test_wire_record_armed_idle_zeros_for_freshness_and_slo():
    """The off-vs-idle convention's armed side (the None side lives in
    test_obs_trace.py's schema test): serve+slo armed with zero
    serving traffic reports zero-count freshness and an empty burning
    set — scrapers can tell 'armed but quiet' from 'off'."""
    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)
    from minips_tpu.utils.metrics import wire_record

    buses = _mk_buses(2)
    errs: list = []
    recs: list = [None, None]
    try:
        tables = [ShardedTable("t", 64, 2, buses[i], i, 2,
                               updater="sgd", pull_timeout=20.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer(
            {"t": tables[i]}, buses[i], 2, staleness=1,
            gate_timeout=30.0,
            serve="replicas=1,hot=4,interval=0.05",
            slo="read_ms=20,fast=2,slow=4") for i in range(2)]

        def worker(r):
            try:
                rng = np.random.default_rng(r)
                for _ in range(6):
                    keys = rng.integers(0, 64, size=8)
                    tables[r].pull(keys)
                    tables[r].push(keys, np.ones((8, 2),
                                                 dtype=np.float32))
                    trainers[r].tick()
                    time.sleep(0.01)
                trainers[r].finalize(timeout=30.0)
                recs[r] = wire_record(trainers[r])
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append((r, repr(e)))

        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60.0)
        assert not errs, errs
        for rec in recs:
            fr = rec["freshness"]
            assert fr is not None, "armed plane must not report off"
            assert fr["fleet"]["lag"] == {"count": 0}
            assert fr["fleet"]["lag_samples"] == 0
            sl = rec["slo"]
            assert sl is not None
            assert sl["burning"] == [] and sl["burns"] == 0
            assert sl["checks"] > 0, "armed tracker never evaluated"
            assert sl["targets"]["read_ms"] == 20.0
    finally:
        for b in buses:
            b.close()


# ------------------------------------------- storm accounting (bench)
def test_storm_off_done_line_carries_none_latency_keys():
    """The pull_storm_3proc schema fix (coordinated omission): storm
    OFF pins the read_intended_ms/read_svc_ms keys to None — present
    in every done line, so artifact diffs see the schema, not a
    KeyError."""
    import os as _os
    import pathlib

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    proc = subprocess.run(
        [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
         "--path", "sparse", "--iters", "6", "--warmup", "2",
         "--rows", "1024", "--batch", "64"],
        capture_output=True, text=True, timeout=120, cwd=repo,
        env={**_os.environ, "MINIPS_FORCE_CPU": "1",
             "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert "read_intended_ms" in out and "read_svc_ms" in out
    assert out["read_intended_ms"] is None
    assert out["read_svc_ms"] is None


@pytest.mark.slow
def test_storm_records_intended_arrival_latency_next_to_service():
    """Armed side of the storm fix: a 2-proc storm run must summarize
    BOTH clocks, with intended-arrival latency >= service latency
    (the schedule debt a closed loop would have hidden)."""
    from minips_tpu import launch

    res = launch.run_local_job(
        2, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", "sparse", "--iters", "12", "--warmup", "3",
            "--rows", "2048", "--batch", "128",
            "--storm", "2", "--storm-batch", "8",
            "--storm-think-ms", "5"],
        base_port=None, timeout=240.0,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"})
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done"
        iv, sv = r["read_intended_ms"], r["read_svc_ms"]
        assert iv["count"] == sv["count"] > 0
        # intended includes the wait-for-schedule leg: never below
        # service at the median (log2-quantized, so >= not >)
        assert iv["p50_ms"] >= sv["p50_ms"]
