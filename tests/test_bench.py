"""bench.py harness contract: one JSON line, FLOP-accounted fields, and
the off-TPU vs_baseline refusal (VERDICT r1 weak #7 / next-round #2)."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = str(pathlib.Path(__file__).resolve().parents[1])


@pytest.mark.slow
def test_bench_cpu_emits_accounted_json():
    proc = subprocess.run(
        [sys.executable, "bench.py", "--cpu", "--suite", "lrmlp",
         "--batch", "512", "--chain", "2", "--reps", "2"],
        capture_output=True, text=True, timeout=420, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    # a CPU run must never publish a TPU-comparable ratio
    assert out["vs_baseline"] is None
    s = out["suites"]["lrmlp"]
    assert s["tflops_per_chip"] > 0
    assert "mfu_vs_bf16_peak" in s and s["mfu_vs_bf16_peak"] is None
    assert "warning" not in s
