"""Heartbeat / failure detection — rebuild of the reference's liveness pings.

The reference's lineage runs periodic heartbeats through the mailbox with a
master that detects dead nodes and triggers restart-from-checkpoint
(SURVEY.md §2 "Heartbeat / failure detection", §5.3). Here heartbeats ride
the control bus; a monitor flags peers whose last beat is older than
``timeout``; the recovery action (reload latest checkpoint and relaunch —
restart semantics are all-or-nothing per JAX job, SURVEY.md §7.4.5) is the
caller's, delivered via the ``on_failure`` callback.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from minips_tpu.comm.bus import ControlBus
from minips_tpu.obs import tracer as _trc


def liveness_knobs(interval: float,
                   timeout: float) -> tuple[float, float]:
    """Resolve the heartbeat liveness knobs against
    ``$MINIPS_HEARTBEAT`` — ``"interval=0.1,timeout=0.8"``, either knob
    optional, empty string (or unset, or ``"1"``) meaning the caller's
    defaults — the same explicit-empty convention as ``MINIPS_BUS`` /
    ``MINIPS_SHM_RING``. Exists so the death drills can run CI-fast
    detection timeouts (and production can run lazier ones) without
    patching every app's hardcoded monitor numbers."""
    spec = os.environ.get("MINIPS_HEARTBEAT", "").strip()
    if not spec or spec in ("1", "on", "true"):
        return interval, timeout
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        if "=" not in entry:
            raise ValueError(
                f"MINIPS_HEARTBEAT: expected k=v, got {entry!r}")
        k, _, v = entry.partition("=")
        k = k.strip()
        if k not in ("interval", "timeout"):
            raise ValueError(f"MINIPS_HEARTBEAT: unknown knob {k!r}")
        try:
            val = float(v)
        except ValueError as e:
            raise ValueError(
                f"MINIPS_HEARTBEAT: bad value for {k}: {v!r}") from e
        if val <= 0:
            raise ValueError(f"MINIPS_HEARTBEAT: {k} must be > 0")
        if k == "interval":
            interval = val
        else:
            timeout = val
    if timeout <= interval:
        raise ValueError(
            f"MINIPS_HEARTBEAT: timeout {timeout} must exceed the "
            f"interval {interval} (a beat must be able to land)")
    return interval, timeout


class HeartbeatMonitor:
    def __init__(self, bus: ControlBus, peer_ids: list[int],
                 interval: float = 1.0, timeout: float = 5.0,
                 on_failure: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        # env knobs override the caller's numbers (liveness_knobs):
        # drills tune detection latency fleet-wide via the launcher's
        # env inheritance instead of per-app flag plumbing
        interval, timeout = liveness_knobs(interval, timeout)
        self.bus = bus
        self.interval = interval
        self.timeout = timeout
        self.on_failure = on_failure
        self._clock = clock
        now = clock()
        self._last_seen = {p: now for p in peer_ids if p != bus.my_id}
        self._dead: set[int] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        bus.on("heartbeat", self._on_beat)

    def _on_beat(self, sender: int, payload: dict) -> None:
        tr = _trc.TRACER
        if tr is not None and "t" in payload:
            # the cross-rank clock-alignment sample obs/merge.py feeds
            # on: my receive timestamp (the event ts) paired with the
            # sender's send timestamp, both monotonic — min-filtered
            # NTP-style across both directions, the one-way delays
            # cancel and the per-rank clock offsets fall out
            tr.instant("hb", "hb", {"from": sender,
                                    "t_sent": float(payload["t"])})
        with self._lock:
            if sender in self._last_seen:
                self._last_seen[sender] = self._clock()

    def check(self) -> set[int]:
        """Sweep for newly-dead peers; fires on_failure once per peer."""
        newly_dead = []
        with self._lock:
            now = self._clock()
            for p, seen in self._last_seen.items():
                if p not in self._dead and now - seen > self.timeout:
                    self._dead.add(p)
                    newly_dead.append(p)
        for p in newly_dead:
            if self.on_failure is not None:
                self.on_failure(p)
        return set(self._dead)

    def start(self) -> "HeartbeatMonitor":
        def loop() -> None:
            while not self._stop.wait(self.interval):
                self.bus.publish("heartbeat", {"t": self._clock()})
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    @property
    def dead(self) -> set[int]:
        with self._lock:
            return set(self._dead)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
