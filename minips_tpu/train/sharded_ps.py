"""Key-range-sharded multi-process parameter server.

This is the reference's *actual* server topology (SURVEY.md §1 L2, §2
SimpleRangeManager/ServerThread/KVTable rows): every process hosts a server
shard owning a contiguous row range of each table, and worker pushes/pulls
route **per-owner key slices** over the bus — point-to-point directed
frames, not full-model broadcasts. This replaces the replicated delta relay
(train/ssp_trainer.py) for workloads whose tables don't fit one host:

- per-process table memory is ``~1/N`` of the table (plus optimizer state,
  sharded identically — PS state *is* optimizer state);
- wire traffic per push is the touched rows, split by owner (the sparse
  Criteo/W&D case ships only the batch's embedding rows, SURVEY.md §7.4.2);
- the server applies the updater (SGD/Adagrad/lazy-Adam, reference
  ``updater->Update(keys, grads)`` semantics with duplicate keys summed
  first) on receipt, exactly the reference's server-side optimizer;
- consistency is the same StalenessGate + ClockGossip as the delta relay —
  BSP/SSP/ASP admission is unchanged (consistency/gate.py).

Why the SSP contract holds — admission happens AT THE OWNER, like the
reference's server-side ``model->Get`` (SURVEY.md §3.3): every pull request
carries the requester's clock ``c``; the owner serves it only once *its
own* view of the global min clock reaches ``c − s``, otherwise the request
is **parked** (the reference's PendingBuffer) and re-checked on every clock
message. Every bus backend preserves per-(sender → receiver) frame order,
and a worker pushes its step-``k`` slices *before* publishing clock ``k`` —
so when the owner's view says peer P reached ``c − s``, P's pushes through
``c − s`` have already been applied to the owner's shard. An admitted pull
therefore reads state containing every peer's updates up to ``c − s``, the
SSP contract, enforced per-owner (client-side gating alone could not
promise this: the pusher→owner link and the pusher→reader clock broadcast
are different links).

Numerics: the server-side numpy updaters match ops/sparse_update.py's
row_sgd/row_adagrad/row_adam (sum-duplicates-then-update; lazy moments for
adam) bit-for-bit at f32 — the parity tests in tests/test_sharded_ps.py
assert it against those oracles.

The OVERLAPPED pipeline (this PR's tentpole): the synchronous loop pays
full round-trip latency on every leg, so the hot path grows three
independently-gated levers —

- **async push** (``async_push=True``): ``push()``/``push_dense()``
  enqueue and return; a per-table sender thread routes/encodes/sends,
  and every cross-process frame carries a sequence number the owner
  ACKS after applying. Under a FINITE staleness bound (BSP/SSP) every
  ``tick()`` drains the queue to the EMIT barrier before the clock
  frame goes out — all step-``k`` push frames precede the clock-``k``
  frame on the same ordered per-link stream, so the FIFO staleness
  argument above holds unchanged (bound preserved at send cost, no
  per-step ack round trip). Under ASP (``staleness=inf``) admission
  always passes — there is no bound for a drain to protect — so the
  clock frame goes out without waiting and the sender drains behind
  the next step's compute. Acks are pure loss detection and cost
  ~zero frames in steady state: owners BATCH ack seqs and piggyback
  them on their next pull reply to the pusher (one per PS cycle),
  with dedicated psK frames only on the batch threshold, clock events
  (``serve_parked``), or a drain's psQ solicitation. ``push_window``
  bounds both the unacked-frame window and the unsent queue depth
  (backpressure), and ``finalize()`` runs the HARD drain — queue
  empty AND every ack in, soliciting stragglers. A lost ack cannot
  hang the loop: a jammed window or drain deadline poisons the table
  and ``check_fatal()`` raises at the next tick.
- **pull prefetch** (``prefetch_pull(keys)``): issue batch ``t+1``'s
  pull while batch ``t`` computes. The request is stamped with a FUTURE
  clock (``clock_ahead``, default 1 — the clock the consuming step will
  run at), so the owner parks it under exactly the admission rule a
  synchronous pull at that step would face; the reply rides back while
  the worker computes/pushes/ticks, and ``wait()`` (or a later
  ``pull()`` with the same keys, which consumes the registered
  prefetch) picks it up, re-checking LOCAL admission before reading the
  local shard slice.
- **int8 pull wire** (``pull_wire="int8"``): pull replies ship per-row
  absmax int8 codes + f32 scales (round-to-nearest — deterministic, so
  identical bytes decode identically everywhere) instead of raw f32
  rows, mirroring the push codec in ops/quantized_comm.py. Frames
  self-describe their wire (mixed fleets decode per frame), and workers
  echo the negotiated format so the bench can assert it.

The DEDUPLICATED PULL WIRE + CLOCK-VERSIONED ROW CACHE (this PR's
tentpole — the reference ``KVClientTable``'s process-level parameter
cache, rebuilt with the SSP rule as its validity predicate):

- ``pull()`` requests ship UNIQUE keys only (``np.unique`` client-side,
  scatter by ``return_inverse`` on reply) — a zipfian batch no longer
  pays full row traffic per occurrence of the same hot row. The owner
  is oblivious: it serves whatever keys arrive. ``pull_dedup=False``
  restores the verbatim wire (the bench's A/B lever; refused when the
  cache is on).
- ``cache_bytes > 0`` enables the worker-side row cache: every pull
  reply is STAMPED by its owner with ``min_excluding(requester)`` — the
  owner's view of every OTHER worker's applied clock (its own
  included; the requester's excluded because per-link FIFO already
  certifies its pushes, see comm/bus.py). A later pull at clock ``c``
  is served from cache for rows whose stamp satisfies
  ``consistency.gate.admits(stamp, c, s)`` — the EXACT owner-side
  admission predicate — so a hit is provably no staler than a
  synchronous pull admitted under the same min-view. Misses (and only
  misses) go to the wire, deduplicated. Local pushes WRITE THROUGH the
  cached rows they touch (sgd + float32 push wire: the delta is exact
  and additive, bitwise the server's op) or INVALIDATE them (stateful
  updaters / quantized pushes: the client cannot reproduce the
  server's step), so read-your-own-writes holds either way.
  ``tick()`` ages out rows that can never be admitted again, an LRU
  byte bound evicts beyond ``cache_bytes``, ``finalize()`` clears (the
  post-finalize agreement guarantee is exact, not staleness-bounded),
  and prefetches populate/consult the same cache under the same stamp
  rule (a fully-cached prefetch never touches the wire).

Per-leg timing (issue→reply latency, blocked time, overlap fraction,
ack latency) runs through ``utils/timing.CommTimers`` — which now also
carries rows-requested vs rows-over-wire and cache hit/lookup counts
into the done lines; wire bytes both directions count ACTUAL bytes on
the wire (compressed when compressed).

WIRE LOSS (this PR): everything above assumes frames arrive, and one
dropped frame anywhere — a pull reply, a push ack, a clock broadcast —
used to cost a deadline poison or a gate stall misread as death. With
``MINIPS_RELIABLE=1`` the bus installs the retransmission protocol
(comm/reliable.py): per-link send journals, receiver gap detection
soliciting NACK/retransmit with backoff under a retry budget, and
deliver-once in-order sequencing — so a lost pull reply or push ack
retransmits (milliseconds) long before the deadline poison fires, a
duplicated/retransmitted push frame is never applied twice (the summed
rows land exactly once — the row cache's write-through depends on it),
and clock gossip stays monotone (ClockGossip max-merges besides). Retry
exhaustion and heartbeat-confirmed death still poison through every
path below, unchanged: loss degrades to latency, never to silence.
Drills are seeded + deterministic via ``MINIPS_CHAOS`` (comm/chaos.py);
the whole ladder: docs/fault_tolerance.md.

HEAT-AWARE SHARD REBALANCING (this PR): the static range partition
above puts a zipf head's whole hot range on ONE owner — that shard
becomes the system's straggler, and nothing here could fix it short of
relaunching. With ``MINIPS_REBALANCE`` set (off by default):

- every owner keeps decayed per-key-block heat on its serve path
  (balance/heat.py) plus always-on per-owner request/row serve
  counters (in ``wire_record``/done lines even with the rebalancer
  off — imbalance is observable before it is fixed);
- the coordinator (rank 0) collects heat, bin-packs hot blocks away
  from the hottest shard past a hysteresis threshold
  (balance/rebalancer.py), and broadcasts the new block→owner overlay
  stamped with the next ROUTING EPOCH;
- each rank adopts the table at its own clock boundary (``tick``) —
  the epoch-fenced migration: the old owner snapshots the block's
  rows AND optimizer state under its state lock, ships them (``rbS``),
  and afterwards FORWARDS stale-routed pushes to the current owner;
  stale-routed pulls are REFUSED with the new table (``psE``) and the
  client retries the leg against the right owner; frames stamped with
  a FUTURE epoch park until the local table catches up;
- the SSP bound holds across the move: the new owner serves NO pull
  of a migrated block until the fence releases (``rbF``), and the
  fence releases only after every live rank's adoption ack (``rbA``)
  arrived at the old owner — each rbA rides the same per-link stream
  as that rank's pushes, so every stale push precedes it, and the rbF
  rides the old→new link AFTER every forwarded push. A pull admitted
  mid-migration therefore still reads state containing every peer's
  updates up to ``clk − s`` (property-tested in
  tests/test_rebalance.py), and read-your-own-writes survives the
  two-hop window for the same per-link-FIFO reason.

Checkpoints record the routing epoch + overlay + migrated block state
so a restored fleet agrees with itself; protocol walkthrough:
docs/architecture.md "Heat-aware shard rebalancer".

THE READ-MOSTLY SERVING PLANE (this PR, ``minips_tpu/serve/``): all of
the above measures a fixed training gang; the north star serves
parameter reads at user scale, where the workload is MANY read-only
clients against few pushers and a hot key range saturates one owner's
receive thread. With ``MINIPS_SERVE`` set (off by default):

- owners promote their hottest blocks (the same heat accounting the
  rebalancer reads) to REPLICA ranks — a full-block snapshot grant,
  then stamped delta frames each refresh interval carrying only the
  rows pushes dirtied (rows ride the configured pull wire, int8 when
  configured);
- every grant/delta is stamped with the owner's gossip ``global_min``
  read BEFORE the state read, and a replica serves a pull at requester
  clock ``c`` only when ``admits(stamp, c, s)`` — the same predicate
  the owner-side park and the row cache run — so a replica hit is
  provably no staler than an owner pull (the owner stamp
  ``min_excluding(requester)`` is ≥ this one) and the RowCache ingests
  replica replies unchanged;
- replica grants are LEASES: owners revoke them at the ``adopt_table``
  epoch-fence point when a granted block migrates away, and expiry
  (renewed by every refresh) turns a mute owner's replicas dark; a
  replica that cannot serve refuses (``svN``) and the client re-issues
  the leg against the owner — serving composes with online migration
  instead of fighting it;
- per-owner token-bucket ADMISSION on the wire pull path sheds
  overload to replicas (``svS`` redirect) or refuses with explicit
  backpressure (``svB`` + delayed retry); retried legs are
  force-admitted, so every path is bounded and nothing times out to
  a silent poison;
- clients fan hot-block pull legs across ``{owner} ∪ holders``
  round-robin, which is what converts replication into read
  throughput (the ``pull_storm`` bench arm's lever).

Protocol, knobs, and the staleness argument: docs/serving.md.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from minips_tpu.comm.bus import ClockGossip
from minips_tpu.consistency.gate import (RETIRED_CLOCK, PeerFailureError,
                                         StalenessGate, admits)
from minips_tpu.obs import flight as _fl
from minips_tpu.obs import tracer as _trc
from minips_tpu.obs import window as _ow
from minips_tpu.obs.hist import Log2Histogram, merge_counts, \
    summarize_counts
from minips_tpu.ops.quantized_comm import (HOST_BLOCK,
                                           blockwise_stream_bytes,
                                           decode_key_deltas,
                                           delta_stream_bytes,
                                           dequantize_blockwise,
                                           dequantize_rows_int8,
                                           encode_key_deltas,
                                           quantize_blockwise,
                                           quantize_rows_int8, topk_rows)
from minips_tpu.parallel.partition import BlockRouter, RangePartitioner
from minips_tpu.utils.timing import CommTimers

__all__ = ["ShardedTable", "ShardedPSTrainer", "PeerFailureError",
           "PullFuture", "RowCache", "ResidualStore", "table_state_bytes",
           "tables_hist_stats", "quantize_rows_int8",
           "dequantize_rows_int8", "sum_duplicate_keys"]

VALID_PUSH_COMM = ("float32", "int8", "topk8", "topk4")


def _as_blob(arr: np.ndarray) -> memoryview:
    """Zero-copy byte view of an array for the bus's blob slot — every
    backend accepts bytes-likes (PR7's framing ships blobs as raw
    views), so the ``tobytes()`` this replaces was a full payload copy
    per frame on the hot path. ONLY sound for arrays this process owns
    and never mutates after the send (fresh fancy-index/copy results):
    the reliable journal and the chaos injector retain the blob past
    the call, so an aliased caller buffer would retransmit whatever the
    caller wrote next."""
    return memoryview(np.ascontiguousarray(arr)).cast("B")


def _cat_blob(*parts) -> bytearray:
    """Single-allocation multi-part blob assembly: each part (array or
    bytes-like) is copied ONCE into the result — vs the seed pattern
    ``a.tobytes() + b.tobytes()`` which paid one copy per part plus the
    concatenation. The bytearray is freshly owned, so journal retention
    is alias-safe."""
    views = [memoryview(np.ascontiguousarray(p)).cast("B")
             if isinstance(p, np.ndarray) else memoryview(p)
             for p in parts]
    out = bytearray(sum(v.nbytes for v in views))
    off = 0
    for v in views:
        out[off:off + v.nbytes] = v
        off += v.nbytes
    return out


def sum_duplicate_keys(keys: np.ndarray, grads: np.ndarray,
                       dim: int) -> tuple[np.ndarray, np.ndarray, bool]:
    """THE client-side duplicate-key coalesce kernel: sum each key's
    occurrences via per-dim f64 bincount, rounded ONCE to f32 — at
    least as accurate as a sequential f32 sum, ~3x faster than
    np.add.at on the hot path. Shared by the wire plane's
    ``_coalesce_for_wire`` and the mesh plane's deposit
    (train/mesh_plane.py) deliberately: the BSP bitwise-parity drill
    depends on both planes summing duplicates identically, so the
    kernel exists exactly once. Returns ``(uniq, summed, had_dups)``
    — the ORIGINAL pairing when there is nothing to coalesce (uniq
    would be sorted; re-pairing grads against it scrambles rows)."""
    uniq, inv = np.unique(keys, return_inverse=True)
    if uniq.size == keys.size:
        return keys, grads, False
    summed = np.empty((uniq.size, dim), np.float32)
    for d in range(dim):
        summed[:, d] = np.bincount(inv, weights=grads[:, d],
                                   minlength=uniq.size)
    return uniq, summed, True


class RowCache:
    """Clock-versioned LRU cache of REMOTE rows — the reference
    KVClientTable's process-level parameter cache, with the SSP rule as
    its validity predicate instead of a freshness heuristic.

    Storage is a SLAB: one preallocated ``[cap_rows, dim]`` f32 buffer
    plus a parallel stamp vector, with an insertion-ordered ``key →
    slot`` map for LRU. Every float op (gather on lookup, scatter on
    insert, the write-through add) is a single vectorized numpy call —
    a per-key Python loop here costs more than the loopback wire it
    saves, which is exactly the per-row-overhead failure mode the
    motivation cites. Python-level work per op is one cheap
    ``dict.get`` pass over the keys.

    ``stamp`` is the freshness certificate the owning shard put on the
    pull reply that delivered the row (its min-view over every other
    worker's applied clock at serve time). ``lookup`` at clock ``c``
    under staleness ``s`` serves exactly the rows
    ``consistency.gate.admits`` would admit — the one predicate the
    owner-side park uses — so a hit can never read past the staleness
    bound a synchronous pull enforces.

    The byte bound counts row payload (``4*dim`` per entry, the slab's
    real allocation); eviction is LRU — hits and re-inserts refresh
    recency. Thread-safe: pushes from the training thread race replies
    consumed in ``wait()``.
    """

    def __init__(self, dim: int, cache_bytes: int):
        self.dim = int(dim)
        self.row_bytes = 4 * self.dim
        self.cap = int(cache_bytes)
        self.cap_rows = max(int(cache_bytes) // self.row_bytes, 1)
        self._buf = np.empty((self.cap_rows, self.dim), np.float32)
        self._stamp = np.zeros(self.cap_rows, np.int64)
        self._slot: OrderedDict[int, int] = OrderedDict()  # key -> slot
        self._free: list[int] = list(range(self.cap_rows - 1, -1, -1))
        self._lock = threading.Lock()
        self.hits = 0
        self.lookups = 0
        self.evictions = 0
        self.invalidations = 0
        self.write_throughs = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._slot)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return len(self._slot) * self.row_bytes

    def lookup(self, keys: np.ndarray, clk: int,
               staleness: float) -> tuple[np.ndarray, np.ndarray]:
        """Serve what the admission rule allows: returns
        ``(rows [n, dim], miss bool [n])`` — ``rows[i]`` is valid where
        ``miss[i]`` is False, i.e. the cached stamp admits clock
        ``clk`` under ``staleness``."""
        with self._lock:
            self.lookups += keys.size
            get = self._slot.get
            slots = np.fromiter((get(k, -1) for k in keys.tolist()),
                                np.int64, count=keys.size)
            held = slots >= 0
            hit = held.copy()
            if staleness != float("inf"):
                # vectorized admits(): stamp >= clk - s, slot-wise
                hit[held] = (self._stamp[slots[held]]
                             >= clk - int(staleness))
            out = np.empty((keys.size, self.dim), np.float32)
            hs = slots[hit]
            out[hit] = self._buf[hs]          # one gather, no row loop
            for k in keys[hit].tolist():      # LRU refresh: dict ops only
                self._slot.move_to_end(k)
            self.hits += int(hit.sum())
        return out, ~hit

    def _take_slot_locked(self, key: int) -> int:
        slot = self._slot.get(key)
        if slot is not None:
            self._slot.move_to_end(key)
            return slot
        if not self._free:  # full: evict the LRU entry, reuse its slot
            _, slot = self._slot.popitem(last=False)
            self.evictions += 1
        else:
            slot = self._free.pop()
        self._slot[key] = slot
        return slot

    def insert(self, keys: np.ndarray, rows: np.ndarray,
               stamp: int) -> None:
        """Fill from a pull reply stamped ``stamp`` by its owner; evicts
        LRU entries beyond the byte bound (slab capacity)."""
        with self._lock:
            slots = np.fromiter(
                (self._take_slot_locked(k) for k in keys.tolist()),
                np.int64, count=keys.size)
            self._buf[slots] = rows           # one scatter
            self._stamp[slots] = stamp

    def write_through(self, keys: np.ndarray, deltas: np.ndarray) -> None:
        """Apply ``row += delta`` to cached rows (missing keys are
        no-ops). Only sound when the delta is exactly what the server
        applies (sgd over a float32 push wire): additivity keeps the
        entry equal to 'stamped state + my subsequent updates', a legal
        read wherever the stamp is."""
        with self._lock:
            get = self._slot.get
            slots = np.fromiter((get(k, -1) for k in keys.tolist()),
                                np.int64, count=keys.size)
            held = slots >= 0
            # keys are unique (push dedup upstream): plain indexed add
            self._buf[slots[held]] += deltas[held]
            self.write_throughs += int(held.sum())

    def invalidate(self, keys: np.ndarray) -> None:
        """Drop cached rows a push touched — read-your-own-writes when
        the client cannot reproduce the server's update."""
        with self._lock:
            for k in keys.tolist():
                slot = self._slot.pop(k, None)
                if slot is not None:
                    self._free.append(slot)
                    self.invalidations += 1

    def age(self, clk: int, staleness: float) -> None:
        """Drop rows that can never be admitted again — clocks only
        advance, so ``not admits(stamp, clk, s)`` is terminal. Called
        from ``tick()``; keeps BSP's cache near-empty instead of
        carrying a table of dead stamps to the LRU bound."""
        if staleness == float("inf"):
            return
        with self._lock:
            dead = [k for k, s in self._slot.items()
                    if self._stamp[s] < clk - int(staleness)]
            for k in dead:
                self._free.append(self._slot.pop(k))

    def clear(self) -> None:
        with self._lock:
            self._slot.clear()
            self._free = list(range(self.cap_rows - 1, -1, -1))

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "lookups": self.lookups,
                "hit_rate": (round(self.hits / self.lookups, 4)
                             if self.lookups else None),
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "write_throughs": self.write_throughs,
                "rows": len(self._slot),
                "bytes": len(self._slot) * self.row_bytes,
            }


class ResidualStore:
    """Error-feedback residuals for the compressed push wire (the
    SparCML rule: what the codec did not send is KEPT, not dropped).

    Every ``topk8``/``topk4`` push retains two kinds of unsent mass per
    key: the full gradient row of every row the top-k selection left
    out, and the quantization error ``g - decode(encode(g))`` of every
    row it shipped. The NEXT push touching the same key FOLDS the
    residual into its gradient before selection, so hot rows
    self-repair within a step; cold rows are bounded by the staleness
    accounting instead — every entry carries a BIRTH clock (the oldest
    clock whose mass it holds; folding preserves the minimum, so age
    can never reset by re-touching), and the trainer's clock boundary
    flushes entries older than the staleness bound ``s`` as plain f32
    pushes — the RowCache stamp rule run in reverse: a cached read may
    be up to ``s`` behind, and symmetrically a withheld write may trail
    at most ``s`` clock boundaries before it is forced onto the wire.
    Epoch fences (rebalance adoption, membership transitions) and
    ``finalize()`` flush the WHOLE store, so migration, drains, and the
    exact post-finalize agreement never strand mass.

    Storage is a slab like the RowCache: a preallocated ``[cap, dim]``
    f32 buffer + parallel birth/key vectors with a dict for key lookup
    — all float work vectorized. A full slab cannot drop mass: retain
    overflow is returned to the caller, which ships it dense
    immediately (counted; the byte win shrinks, correctness does not).
    Thread-safe: the async-push sender thread retains while the
    training thread age-flushes at the boundary."""

    INF = np.iinfo(np.int64).max

    def __init__(self, dim: int, cap_bytes: int = 1 << 24):
        self.dim = int(dim)
        # byte-bounded, with a row-count ceiling: the parallel birth /
        # key vectors cost 16 B/row whatever the dim, so a dim-1 table
        # must not turn the 16 MiB byte budget into 4M preallocated
        # slots (overflow past the cap ships dense — graceful, counted)
        self.cap_rows = min(max(int(cap_bytes) // (4 * self.dim), 1024),
                            1 << 18)
        self._buf = np.zeros((self.cap_rows, self.dim), np.float32)
        self._birth = np.zeros(self.cap_rows, np.int64)
        self._key = np.full(self.cap_rows, -1, np.int64)
        self._slot: dict[int, int] = {}
        self._free: list[int] = list(range(self.cap_rows - 1, -1, -1))
        self._lock = threading.Lock()
        self.folded_rows = 0
        self.retained_rows = 0
        self.flushed_age = 0
        self.flushed_fence = 0
        self.flushed_overflow = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._slot)

    def fold(self, keys: np.ndarray, grads: np.ndarray) -> np.ndarray:
        """Add stored residuals into ``grads`` (in place) for every key
        present, release those entries, and return each key's former
        birth clock (``INF`` where nothing was stored) — the caller
        re-retains unsent mass under ``min(birth, current clock)`` so
        residual age survives the fold."""
        births = np.full(keys.size, self.INF, np.int64)
        with self._lock:
            if not self._slot:
                return births
            get = self._slot.get
            slots = np.fromiter((get(k, -1) for k in keys.tolist()),
                                np.int64, count=keys.size)
            held = slots >= 0
            if not held.any():
                return births
            hs = slots[held]
            grads[held] += self._buf[hs]
            births[held] = self._birth[hs]
            self._key[hs] = -1
            for k in keys[held].tolist():
                self._free.append(self._slot.pop(k))
            self.folded_rows += int(held.sum())
        return births

    def retain(self, keys: np.ndarray, rows: np.ndarray,
               births: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Store unsent mass (all-zero rows are skipped — nothing to
        repay). Returns the ``(keys, rows)`` OVERFLOW the slab had no
        room for; the caller must ship it dense — mass is conserved
        whatever the slab pressure."""
        live = rows.any(axis=1)
        if not live.all():
            keys, rows, births = keys[live], rows[live], births[live]
        if not keys.size:
            return keys, rows
        ov_from = keys.size
        with self._lock:
            get = self._slot.get
            for i, k in enumerate(keys.tolist()):
                slot = get(k)
                if slot is not None:  # belt-and-braces: fold removed it
                    self._buf[slot] += rows[i]
                    self._birth[slot] = min(self._birth[slot],
                                            int(births[i]))
                    continue
                if not self._free:
                    ov_from = i
                    break
                slot = self._free.pop()
                self._slot[k] = slot
                self._key[slot] = k
                self._buf[slot] = rows[i]
                self._birth[slot] = int(births[i])
            stored = min(ov_from, keys.size)
            self.retained_rows += stored
            if ov_from < keys.size:
                self.flushed_overflow += keys.size - ov_from
        return keys[ov_from:], rows[ov_from:]

    def take(self, up_to_birth: Optional[int] = None
             ) -> tuple[np.ndarray, np.ndarray]:
        """Pop every entry with ``birth <= up_to_birth`` (None = all),
        sorted by key (deterministic flush frames)."""
        with self._lock:
            used = self._key >= 0
            if up_to_birth is not None:
                used &= self._birth <= up_to_birth
            slots = np.nonzero(used)[0]
            if not slots.size:
                return (np.empty(0, np.int64),
                        np.empty((0, self.dim), np.float32))
            keys = self._key[slots].copy()
            rows = self._buf[slots].copy()
            self._key[slots] = -1
            for k in keys.tolist():
                self._free.append(self._slot.pop(k))
        order = np.argsort(keys, kind="stable")
        return keys[order], rows[order]

    def note_flushed(self, n: int, reason: str) -> None:
        with self._lock:
            if reason == "age":
                self.flushed_age += n
            else:
                self.flushed_fence += n

    def stats(self) -> dict:
        with self._lock:
            return {
                "folded_rows": self.folded_rows,
                "retained_rows": self.retained_rows,
                "flushed_age": self.flushed_age,
                "flushed_fence": self.flushed_fence,
                "flushed_overflow": self.flushed_overflow,
                "resident_rows": len(self._slot),
                "resident_bytes": len(self._slot) * 4 * self.dim,
            }


def table_state_bytes(num_rows: int, dim: int, updater: str) -> int:
    """Whole-table bytes of weights + optimizer state for one table — the
    accounting twin of ``ShardedTable.local_bytes`` summed over all shards
    (modulo partition padding). The apps' smoke protocol compares
    ``local_bytes * N <= table_bytes`` against this ONE formula so a state-
    layout change can't leave stale copies behind."""
    mult = {"sgd": 1, "adagrad": 2, "adam": 3}[updater]
    n = num_rows * dim * 4 * mult
    if updater == "adam":  # per-row lazy step counters (int32)
        n += num_rows * 4
    return n


class _ReissuePullAll(Exception):
    """A shard-assembly (psA) leg was addressed to a now-dead rank and
    the death plan has re-homed its blocks: the whole pull_all must
    re-issue at the new epoch (a psA leg asks one rank for ITS shard —
    there is no per-leg re-route that can recover the corpse's half).
    Internal to this module: pull_all catches it and retries."""


class PullFuture:
    """Handle for an in-flight (possibly prefetched) pull: the requests
    are already on the wire (unique MISS keys only — dupes scatter by
    inverse, cache hits were filled at issue time); ``wait()`` blocks
    only for whatever has not yet arrived, reads the LOCAL shard slice
    after re-checking admission for the stamped clock, assembles the
    unique-row matrix, inserts fetched rows into the row cache with
    their owner stamps, and scatters back to request order.
    Single-consumer: ``wait()`` may be called once."""

    def __init__(self, table: "ShardedTable", req: int, keys: np.ndarray,
                 uniq: np.ndarray, inv: Optional[np.ndarray],
                 out_u: np.ndarray, remote: list, local_idx, clk: int):
        self._table = table
        self._req = req
        self._keys = keys
        self._uniq = uniq              # unique keys (== keys if no dedup)
        self._inv = inv                # scatter map uniq -> keys order
        self._out_u = out_u            # [uniq.size, dim]; hits pre-filled
        self._remote = remote          # [(owner, idx-into-uniq)] wire legs
        self._local_idx = local_idx    # idx-into-uniq my shard owns
        self.clk = clk
        self._t_issue = time.monotonic()
        self._done = False
        self._pf_key: Optional[bytes] = None  # prefetch-registry slot
        self._issue_epoch = 0  # cache push-log position at issue time

    def _deregister(self) -> None:
        if self._pf_key is None:
            return
        t = self._table
        with t._prefetch_lock:
            if t._prefetched.get(self._pf_key) is self:
                del t._prefetched[self._pf_key]

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if self._done:
            raise RuntimeError("PullFuture.wait() called twice")
        self._done = True
        self._deregister()
        t = self._table
        t_block0 = time.monotonic()
        out_u = self._out_u
        extra_local: list = []
        try:
            if self._remote:
                got = t._await_replies(self._req, timeout=timeout)
                # the FINAL leg map: the psE re-router may have re-split
                # legs (and turned some local) since issue
                legs, extra_local = t._take_group(self._req)
                for rid, (o, idx) in legs.items():
                    rows, stamp = got[rid][0], got[rid][1]
                    out_u[idx] = rows
                    if t._sv is not None:
                        # the SERVE-STALE observable: every consumed
                        # reply (owner- OR replica-served) must satisfy
                        # the admission rule its serve claimed
                        t._sv.check_reply_stamp(int(stamp), self.clk)
                    if t._cache is not None:
                        # the prefetch path populates the same cache
                        # under the same stamp rule — this is the one
                        # fill point; keys pushed since issue are
                        # DROPPED from the insert (the reply may sit on
                        # either side of the push — read-your-own-
                        # writes over the in-flight window, see
                        # _cache_insert_guarded)
                        t._cache_insert_guarded(self, self._uniq[idx],
                                                rows, stamp)
            else:
                with t._reply_cond:
                    t._replies.pop(self._req, None)
        finally:
            # even on timeout/peer-failure: a leaked registration would
            # pin the push-journal floor forever and churn the cache
            # through the overflow valve on every later push
            if t._cache is not None:
                t._cache_close_issue(self)
        with t._reply_cond:
            t_arrived = t._reply_t.pop(self._req, t_block0)
        local_parts = ([self._local_idx]
                       if self._local_idx is not None else [])
        local_parts += [ix for ix in extra_local if ix.size]
        if local_parts:
            # the local slice obeys the SAME admission rule the remote
            # owners applied: read only once my view admits the stamped
            # clock (matters for prefetches stamped clock_ahead > 0 —
            # a synchronous pull passes instantly, its own gate already
            # waited for this); _read_local additionally honors the
            # migration fences a remote owner would have parked under
            t._wait_local_admission(self.clk, timeout)
            idxs = (local_parts[0] if len(local_parts) == 1
                    else np.concatenate(local_parts))
            out_u[idxs] = t._read_local(self._uniq[idxs], self.clk,
                                        timeout)
        now = time.monotonic()
        # latency is issue -> reply PROCESSED (t_arrived), not wait() —
        # a fully-prefetched pull whose reply landed mid-compute must
        # report the real RTT, not the compute window it hid under
        t.timers.record_pull(latency_s=t_arrived - self._t_issue,
                             blocked_s=now - t_block0)
        tr = _trc.TRACER
        if tr is not None and self._remote:
            tr.complete("pull", "pull_wait", t_block0,
                        {"owners": sorted({int(o)
                                           for o, _i in self._remote}),
                         "clk": self.clk}, t1=now)
        return out_u[self._inv] if self._inv is not None else out_u

    def cancel(self) -> None:
        """Abandon an un-waited prefetch (e.g. past the last batch):
        releases the reply slot so late replies don't accumulate."""
        if self._done:
            return
        self._done = True
        self._deregister()
        if self._table._cache is not None:
            self._table._cache_close_issue(self)
        with self._table._reply_cond:
            self._table._cleanup_group_locked(self._req)


class ShardedTable:
    """One table: my server shard (owned contiguous row range) + the client
    router splitting pulls/pushes by owner (reference KVClientTable +
    ServerThread + RangeManager collapsed into one object per process).

    ``dim=1`` rows model the reference's dense ``VectorStorage`` (each key a
    scalar parameter); larger ``dim`` is the embedding-table case
    (``MapStorage`` → fixed rows). Dense whole-vector traffic uses the
    range fast path (``pull_all``/``push_range``) with no key lists on the
    wire.
    """

    def __init__(
        self,
        name: str,
        num_rows: int,
        dim: int,
        bus,
        rank: int,
        num_processes: int,
        *,
        updater: str = "sgd",
        lr: float = 0.05,
        adagrad_init: float = 0.1,
        eps: Optional[float] = None,
        beta1: float = 0.9,
        beta2: float = 0.999,
        init_scale: float = 0.0,
        seed: int = 0,
        pull_timeout: float = 30.0,
        monitor=None,
        push_comm: Optional[str] = None,
        pull_wire: str = "f32",
        async_push: bool = False,
        push_window: int = 32,
        cache_bytes: int = 0,
        pull_dedup: bool = True,
        push_dedup: bool = True,
        topk_mass: float = 0.9,
        topk_cap: float = 0.5,
        topk_block: int = HOST_BLOCK,
    ):
        if updater not in ("sgd", "adagrad", "adam"):
            raise ValueError(
                "sharded-PS updater must be 'sgd', 'adagrad' or 'adam'")
        if push_comm is None:
            # the env spelling of the wire ladder (explicit-empty =
            # default, every MINIPS_* knob's convention); an explicit
            # constructor/flag value always wins — the bench pins ""
            # so an armed environment can't leak into baseline arms
            push_comm = os.environ.get("MINIPS_PUSH_COMM",
                                       "").strip() or "float32"
        if push_comm not in VALID_PUSH_COMM:
            raise ValueError(
                f"push_comm must be one of {VALID_PUSH_COMM}")
        if pull_wire == "float32":  # accept the push-knob spelling too
            pull_wire = "f32"
        if pull_wire not in ("f32", "int8"):
            raise ValueError("pull_wire must be 'f32' or 'int8'")
        if push_window < 1:
            raise ValueError("push_window must be >= 1")
        if cache_bytes < 0:
            raise ValueError("cache_bytes must be >= 0 (0 = cache off)")
        if cache_bytes and not pull_dedup:
            # a cache keyed on unique rows over a duplicate wire would
            # double-count hits and mis-stamp scattered fills
            raise ValueError("cache_bytes > 0 requires pull_dedup=True")
        if push_comm in ("topk8", "topk4") and not push_dedup:
            # error feedback is keyed per unique row: a per-occurrence
            # wire would fold one key's residual into whichever
            # occurrence happened first — dedup is the codec's contract
            raise ValueError(
                f"push_comm={push_comm!r} requires push_dedup=True "
                "(error-feedback residuals are keyed per unique row)")
        if not 0.0 < topk_mass <= 1.0:
            raise ValueError("topk_mass must be in (0, 1]")
        if not 0.0 < topk_cap <= 1.0:
            raise ValueError("topk_cap must be in (0, 1]")
        if topk_block < 1:
            raise ValueError("topk_block must be >= 1")
        self.name = name
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.bus = bus
        self.rank = rank
        self.num_processes = num_processes
        self.updater = updater
        self.lr = lr
        # defaults match the jax oracles (ops/sparse_update.py): adagrad
        # divides by sqrt(accum)+1e-10, adam by sqrt(v_hat)+1e-8
        self.eps = (1e-8 if updater == "adam" else 1e-10) \
            if eps is None else eps
        self.beta1 = beta1
        self.beta2 = beta2
        self.pull_timeout = pull_timeout
        self.monitor = monitor
        self.push_comm = push_comm
        self.topk_mass = float(topk_mass)
        self.topk_cap = float(topk_cap)
        self.topk_block = int(topk_block)
        # the error-feedback residual store (module class docstring):
        # only the compressed-push tiers carry unsent mass to repay
        self._ef = (ResidualStore(dim)
                    if push_comm in ("topk8", "topk4") else None)
        self.pull_wire = pull_wire
        self.async_push = bool(async_push)
        self.push_window = int(push_window)
        self.pull_dedup = bool(pull_dedup)
        self.push_dedup = bool(push_dedup)
        self.cache_bytes = int(cache_bytes)
        # the clock-versioned client row cache (module docstring): holds
        # REMOTE rows only — my own shard is always read directly
        self._cache = RowCache(dim, cache_bytes) if cache_bytes else None
        # read-your-own-writes for IN-FLIGHT pulls: a reply served
        # before my push reached the owner would be inserted into the
        # cache AFTER push() ran its write-through/invalidation — a
        # no-op for the not-yet-cached row — storing a pre-own-push row
        # every later hit would silently serve. And the converse is
        # just as possible: a PARKED pull is served after the push
        # applied, so the reply already contains the delta — the client
        # cannot tell which side of the push the serve landed on. So
        # pushes are journaled in a LOG while pulls are outstanding,
        # and an insert DROPS the keys any entry newer than its pull's
        # issue point touched: ambiguous rows are simply not cached
        # (the future's RESULT is untouched; the next pull of such a
        # key round-trips once). Single-writer in practice (all ops on
        # the training thread); the lock is belt-and-braces.
        self._cache_epoch = 0           # cache-maintenance ops so far
        self._cache_log: list[tuple] = []     # (epoch, sorted keys)
        self._cache_open: dict[int, int] = {}  # id(fut) -> issue epoch
        self._cache_broken_floor = -1   # valve: pre-floor issues skip
        self._cache_log_lock = threading.Lock()
        if self._cache is not None and self.async_push:
            # an async push frame can reach the owner AFTER a
            # later-issued pull was served, with no client-side event
            # marking the window — read-your-own-writes would need the
            # ack plumbing to certify arrival. Refuse loudly; the
            # cache composes with the prefetch leg (--overlap-legs
            # pull), which is the overlap lever that pays anyway.
            raise ValueError(
                "cache_bytes > 0 is not supported with async_push "
                "(use overlap_legs='pull'): an unacked push frame can "
                "trail a later pull, and a cached reply could then "
                "silently miss this worker's own update")
        self.timers = CommTimers()
        # quantization noise stream: per-(seed, rank) so reruns are
        # deterministic and ranks draw independent rounding noise
        self._q_rng = np.random.default_rng((seed, rank, 0x9e37))
        self._seed = int(seed)  # hier leader lane derives its own rng
        self.part = RangePartitioner(self.num_rows, num_processes)
        self.shard_lo = rank * self.part.shard_size
        # ---- heat-aware rebalancing (balance/; OFF unless a Rebalancer
        # attaches): the epoch-versioned block router overlays hot-block
        # reassignments on the base range map. With no rebalancer bound
        # every path below falls through to the seed behavior exactly.
        self.router = BlockRouter(self.part)
        self._rb = None            # balance.rebalancer.Rebalancer
        self._mb = None            # balance.membership.Membership
        self._heat = None          # balance.heat.HeatAccountant
        self._sv = None            # serve.plane.TableServeState
        self._mig_cond = threading.Condition()  # guards the maps below
        self._xtra: dict[int, dict] = {}        # migrated-in block state
        # fenced/pending carry the COUNTERPART rank (the old owner whose
        # rbF releases the fence / whose rbS is in transit): the elastic
        # membership plane resolves entries stuck on a corpse by source
        # instead of guessing
        self._fenced: dict[int, int] = {}        # block -> old owner
        self._pending_state: dict[int, int] = {}  # block -> shipper
        self._early_state: dict[int, dict] = {}  # rbS beat my adoption
        self._early_release: set[tuple] = set()  # rbF beat my adoption
        self._parked_pushes: list[tuple] = []    # future-epoch / pending
        self._adopt_acks: dict[int, set[int]] = {}  # ep -> acked ranks
        self._await_acks: dict[int, list] = {}   # ep -> [(block, dst)]
        # rbF releases awaiting the gainer's rbG confirmation:
        # (block, dst) -> (epoch, last-send monotonic). Fire-and-forget
        # releases are fine for a STAYING old owner (the reliable plane
        # retransmits for live senders), but a LEAVER whose last rbF is
        # eaten by a partition would strand the gainer's fence forever —
        # leave() re-sends until this map drains (releases_confirmed)
        self._release_unacked: dict[tuple[int, int], tuple] = {}
        self.rb_stats = {"blocks_in": 0, "blocks_out": 0,
                         "forwarded_pushes": 0, "refused_pulls": 0,
                         "parked_frames": 0, "migrated_rows": 0,
                         "blocks_restored": 0, "pushes_lost_to_dead": 0,
                         # max bytes of outbound state staged at once on
                         # the ship path — measured on BOTH the planned
                         # and the point-to-point path, it is the
                         # RESHARD-MEM observable (the p2p arm's proof
                         # that whole-plan staging exceeds the cap)
                         "peak_stage_bytes": 0}
        # ---- planned collective redistribution (balance/redistribute;
        # OFF unless attach_reshard): slice-granular migration shipping
        # in cap-bounded rounds. Inbound slice progress rides NEXT TO
        # _pending_state (block granularity is still the fence unit);
        # _early_prog mirrors _early_state for slices that beat my plan
        # adoption.
        self._reshard = None       # balance.redistribute.ReshardConfig
        self._slice_prog: dict[int, dict] = {}  # block -> {got, seen}
        self._early_prog: dict[int, dict] = {}  # pre-adoption twin
        self.rs_stats = {"plans": 0, "rounds": 0, "slices": 0,
                         "dup_slices": 0, "aborts": 0,
                         "peak_stage_bytes": 0}
        # ---- per-owner serve counters (ALWAYS on — the observability
        # half of heat accounting): requests/rows this shard served
        # (wire) and rows read/applied on this shard's storage (wire +
        # local) — utils/metrics.wire_record "serve", done lines
        self._serve_lock = threading.Lock()
        self.serve = {"pull_requests": 0, "pull_rows": 0,
                      "push_frames": 0, "push_rows": 0}
        # ---- tenancy (tenant/registry.py; OFF unless the trainer
        # binds a TenantRegistry): this table's tenant spec and its
        # 1-based tenant id — stamped on every frame head ("tb", next
        # to ws/nr/dm/rb) — plus the per-tenant SLO counters the serve
        # plane's deny paths bump when tenancy is armed. tid 0 = off:
        # no stamp, no counters (the armed-idle drill pins the bare
        # default tenant bitwise-equal to off with these at zero).
        self._tenant = None            # tenant.registry.TenantSpec
        self._tenant_tid = 0
        self.tenant_counters = {"shed": 0, "throttle": 0,
                                "stale_reads": 0, "hedge_denied": 0}
        # ---- observability (obs/): always-on server-side latency
        # histograms (serve duration, park duration — the tail half of
        # the serve counters above — and rebalance-fence duration: a
        # fence that keeps aging is a migration losing, feed for the
        # windowed layer), the env-gated wire tracer, and the always-on
        # flight recorder. ``_trc.maybe_init`` arms the process tracer
        # from MINIPS_TRACE on first construction and is a no-op (one
        # env read) when off; ``_leg_t0`` is ALWAYS stamped since the
        # fail-slow plane (one dict insert/pop per wire leg): the hedge
        # timer needs each leg's issue time and the SlownessMonitor
        # needs the per-peer round trip a reply closes, tracer or not.
        # ``_fence_t0`` is likewise always stamped (the fence hist).
        self.hist_serve = Log2Histogram()
        self.hist_park = Log2Histogram()
        self.hist_fence = Log2Histogram()
        _trc.maybe_init(rank)
        _fl.maybe_init(rank)
        self._leg_t0: dict[int, tuple] = {}   # rid -> (t0, target)
        # ---- fail-slow plane (serve/hedge.py + obs/slowness.py; OFF
        # unless the trainer attaches them): hedged pull legs against
        # replica holders, and the per-peer latency feed for the
        # SlownessMonitor. _hedges_live bounds outstanding hedges
        # (budget); counters follow the serve-plane convention.
        self._hedge = None           # serve.hedge.HedgeConfig
        self._slowness = None        # obs.slowness.SlownessMonitor
        self._hedges_live: set[int] = set()
        # legs whose group completed WITHOUT their reply (a hedge won,
        # or the pull timed out): rid -> (t0, target), bounded. The
        # late reply is precisely the tail evidence that indicts a
        # slow rank — dropping it with the group would blind the
        # detector exactly when the mitigation works (measured: with
        # hedging on, every slow-owner sample went late). Insertion-
        # ordered; oldest evicted past the cap.
        self._late_t0: dict[int, tuple] = {}
        self.hedge_counters = {k: 0 for k in
                               ("fired", "won", "lost", "no_holder",
                                "denied")}
        self._fence_t0: dict[int, float] = {}  # block -> fence start
        # ---- hierarchical push tree (balance/hier.py; OFF unless the
        # trainer attaches a HierConfig). Member side: the elected
        # leader, the unacked retained window (re-pushed on fallback),
        # and the direct-mode latch. Leader side: per-owner buckets of
        # member contributions plus per-member boundary floors — the
        # flush trigger is the GROUP-MIN floor advancing, so whichever
        # boundary frame completes a step (training thread or recv
        # thread) ships exactly one aggregated frame per owner. Owner
        # side: per-contributor floors folded into pull admission.
        # _hier_lock guards all of it; _hier_flush_lock serializes the
        # flush critical section (snapshot + sends) so a later flush's
        # floor claim can never overtake an earlier flush's mass.
        self._hier = None                    # balance.hier.HierConfig
        self._hier_lock = threading.Lock()
        self._hier_flush_lock = threading.Lock()
        self._hier_floor: dict[int, int] = {}         # owner side
        self._hier_leader: Optional[int] = None       # member side
        self._hier_retained: list[tuple] = []  # (step, owner, keys, g)
        self._hier_direct = False            # fallback latch
        self._hier_buckets: dict[int, list] = {}      # leader side
        self._hier_member_floor: dict[int, int] = {}
        self._hier_own_floor = 0
        self._hier_flushed_floor = 0
        self._hier_members: list[int] = []
        self._hier_cross: list[int] = []
        self._hier_group: list[int] = []
        self._hier_shunned: Optional[int] = None  # leader I fell back from
        self._hier_expelled: set[int] = set()     # members gone direct
        self._hier_claimed: dict[int, int] = {}   # floors I flushed
        self._hier_xa: Optional[int] = None       # expel-ack floor
        self._hier_host_of = None
        self._hier_elect_fn = None
        # leader-lane EF: a DEDICATED store + rng — flushes can fire
        # from the recv thread (a member boundary completes the step),
        # and sharing _ef/_q_rng with the training thread's flat-path
        # encodes would race both the slab and the rng stream
        self._hier_ef = None
        self._hier_rng = None
        # agg=mesh: the leader's device-reduce backend (lazy — only a
        # LEADER that actually flushes pays the mesh build), plus the
        # whole-host failure-domain latch (sticky: a mesh host demotes
        # as ONE unit and never re-enters this incarnation)
        self._hier_mesh = None
        self._hier_mesh_failed = False
        self._hier_domain_down = False
        self.hier_counters = {k: 0 for k in (
            "l1_tx_bytes", "l1_frames", "l2_tx_bytes", "l2_frames",
            "agg_frames", "agg_rows", "floor_frames", "contribs",
            "elections", "fallbacks", "repushed_steps", "repush_drops",
            "stale_leader_drops", "mesh_reduces", "mesh_agg_fallbacks",
            "domain_demotions")}
        self.hist_hier = Log2Histogram()     # leader flush latency
        # ---- server shard: ONLY my row range lives here (the 1/N memory
        # claim, materialization included — a multi-GB Criteo table must
        # never exist whole on any host); per-(seed, rank) stream keeps
        # init deterministic, and no other process ever materializes these
        # rows (single-owner), so cross-replica init equality is moot
        self._w = (np.zeros((self.part.shard_size, self.dim), np.float32)
                   if not init_scale else
                   np.random.default_rng((seed, rank)).normal(
                       scale=init_scale,
                       size=(self.part.shard_size, self.dim)
                   ).astype(np.float32))
        self._acc = (np.full((self.part.shard_size, self.dim),
                             adagrad_init, np.float32)
                     if updater == "adagrad" else None)
        # lazy adam: moments + a per-row step counter for bias correction
        # (the server-side numpy twin of ops/sparse_update.row_adam —
        # untouched rows decay nothing, the standard sparse/CTR semantics)
        if updater == "adam":
            self._m = np.zeros((self.part.shard_size, self.dim), np.float32)
            self._v = np.zeros((self.part.shard_size, self.dim), np.float32)
            self._steps = np.zeros(self.part.shard_size, np.int32)
        else:
            self._m = self._v = self._steps = None
        self._state_lock = threading.Lock()
        # dropped-frame accounting (VERDICT r2 weak #2): a dropped push is
        # a silently-lost gradient, so every early return below is counted,
        # exposed through the trainer's metrics, and asserted zero by the
        # multiproc smokes. A config mismatch (relaunch at a different
        # world size / table shape) additionally poisons the table — the
        # next client op raises instead of training garbage.
        self.drops = {"malformed": 0, "misrouted": 0, "config": 0}
        self._fatal: Optional[str] = None
        # ---- server-side admission (bound by ShardedPSTrainer): parked
        # pull requests waiting for the staleness rule — the reference's
        # PendingBuffer (SURVEY.md §2 ProgressTracker/PendingBuffer row)
        self._cons = None  # object with admit_pull(clk) + clock
        # parked pulls: (sender, req, keys|None, clk, ep, t_parked) —
        # the timestamp feeds the park-duration histogram (and the
        # tracer's 'parked' spans) when the entry is finally served
        self._parked: list[tuple] = []
        self._park_lock = threading.Lock()
        # ---- client plumbing
        self._req = 0
        self._req_lock = threading.Lock()
        # Pull bookkeeping is LEG-keyed: every per-owner slice of a pull
        # gets its own wire request id (rid), grouped under a group id
        # (gid) the PullFuture holds. The server is oblivious (it serves
        # whatever "req" it was sent) — what legs buy is RE-ROUTING: an
        # epoch-refused leg (psE, mid-migration) is re-split by the new
        # table and re-sent without disturbing the group's other legs.
        self._replies: dict[int, dict[int, tuple]] = {}  # gid->rid->reply
        self._reply_t: dict[int, float] = {}  # gid -> last-reply arrival
        self._rid_gid: dict[int, int] = {}    # live leg rid -> gid
        self._groups: dict[int, dict] = {}    # gid -> legs/clk/uniq
        self._reply_cond = threading.Condition()
        self._prefetched: dict[bytes, PullFuture] = {}
        self._prefetch_lock = threading.Lock()
        self.bytes_pushed = 0
        self.bytes_pulled = 0
        self.rows_pushed = 0
        # ---- async-push pipeline: a bounded-window sender thread + an
        # in-flight ledger. Every cross-process frame carries a seq the
        # owner acks after handling (applied OR counted-dropped — a
        # withheld ack would stack a window stall on top of an already-
        # loud drop). Acks are BATCHED at the owner and mostly ride
        # PIGGYBACKED on pull replies (the PS cycle sends one per owner
        # per step anyway) — a dedicated psK frame goes out only on the
        # batch threshold, a clock event, or a drain's solicitation, so
        # steady state pays ~zero extra frames for loss detection (a
        # per-frame ack wire measurably LOST the overlap_on_off sweep
        # on CPU-bound hosts: +1 frame per push frame).
        # ``_inflight`` maps seq -> (send time, owner); its size is
        # the unacked window ``push_window`` bounds, and a seq that
        # never leaves it is exactly what the hard drain's deadline
        # turns into a poisoned table.
        self._push_seq = 0
        self._inflight: dict[int, tuple[float, int]] = {}
        self._dead_ranks: set[int] = set()  # membership deaths (sticky)
        self._ack_pending: dict[int, list[int]] = {}  # sender -> seqs
        self._ack_lock = threading.Lock()
        self._push_cond = threading.Condition()
        self._q_pending = 0            # queued items not yet fully sent
        self._push_q: Optional[queue.Queue] = None
        if self.async_push:
            self._push_q = queue.Queue()
            threading.Thread(target=self._push_loop, daemon=True,
                             name=f"ps-push:{name}").start()
        if bus is not None:
            bus.on(f"psP:{name}", self._on_push)
            bus.on(f"psR:{name}", self._on_push_range)
            bus.on(f"psG:{name}", self._on_pull)
            bus.on(f"psA:{name}", self._on_pull_all)
            bus.on(f"psr:{name}", self._on_pull_reply)
            bus.on(f"psK:{name}", self._on_push_ack)
            bus.on(f"psQ:{name}", self._on_ack_solicit)

    # --------------------------------------------------------- server side
    def _base_state(self) -> dict:
        """The base-slab state arrays as the dict shape block updates
        operate on — migrated-in blocks (``_xtra``) carry the identical
        shape, so the updater math below has exactly one implementation
        wherever a row lives."""
        return {"w": self._w, "acc": self._acc, "m": self._m,
                "v": self._v, "steps": self._steps}

    def _update_block(self, st: dict, uniq: np.ndarray,
                      g: np.ndarray) -> None:
        """One updater step on deduped rows of ONE storage (base slab or
        a migrated block) — caller holds the state lock, ``uniq`` are
        row indices into ``st``'s arrays."""
        if self.updater == "sgd":
            st["w"][uniq] -= self.lr * g
        elif self.updater == "adagrad":
            # accum += g², step by rsqrt of NEW accum
            st["acc"][uniq] += g * g
            st["w"][uniq] -= self.lr * g / (
                np.sqrt(st["acc"][uniq]) + self.eps)
        else:
            self._adam_rows(st, uniq, g)

    def _apply_rows(self, offs: np.ndarray, grads: np.ndarray) -> None:
        """Reference ``updater->Update``: sum duplicate keys, then one
        update per touched row (ops/sparse_update.py semantics)."""
        grads = grads.reshape(offs.size, self.dim)
        self._count_serve(push_rows=offs.size)
        if self._heat is not None:
            # the serve plane's promotion signal on the seed (rb-off)
            # path — the rb path's _ingest_push already touches
            self._heat.touch(self.router.blocks_of(offs + self.shard_lo))
        with self._state_lock:
            uniq, inv = np.unique(offs, return_inverse=True)
            g = np.zeros((uniq.size, self.dim), np.float32)
            np.add.at(g, inv, grads)
            self._update_block(self._base_state(), uniq, g)
        if self._sv is not None:
            # dirty-row tracking for replica delta refresh: noted in the
            # same handler call as the apply, so per-link FIFO keeps
            # 'covered by a refresh stamp' implying 'noted or shipped'
            self._sv.note_push(offs + self.shard_lo)

    def _adam_rows(self, st: dict, uniq: np.ndarray,
                   g: np.ndarray) -> None:
        """Lazy adam on the (deduped) touched rows — one full Adam step per
        row with per-row bias correction, matching row_adam's f32 math
        (caller holds the state lock)."""
        b1, b2 = np.float32(self.beta1), np.float32(self.beta2)
        t_new = st["steps"][uniq] + 1
        m_new = b1 * st["m"][uniq] + (np.float32(1) - b1) * g
        v_new = b2 * st["v"][uniq] + (np.float32(1) - b2) * g * g
        tf = t_new.astype(np.float32)[:, None]
        bc1 = np.float32(1) - b1 ** tf
        bc2 = np.float32(1) - b2 ** tf
        st["w"][uniq] -= np.float32(self.lr) * (m_new / bc1) / (
            np.sqrt(v_new / bc2) + np.float32(self.eps))
        st["m"][uniq] = m_new
        st["v"][uniq] = v_new
        st["steps"][uniq] = t_new

    def _apply_range(self, lo_local: int, grads: np.ndarray) -> None:
        grads = grads.reshape(-1, self.dim)
        self._count_serve(push_rows=grads.shape[0])
        if self._sv is not None:
            self._sv.note_push_range(
                self.shard_lo + lo_local,
                self.shard_lo + lo_local + grads.shape[0])
        sl = slice(lo_local, lo_local + grads.shape[0])
        with self._state_lock:
            if self.updater == "sgd":
                self._w[sl] -= self.lr * grads
            elif self.updater == "adagrad":
                self._acc[sl] += grads * grads
                self._w[sl] -= self.lr * grads / (
                    np.sqrt(self._acc[sl]) + self.eps)
            else:  # every row in the range is touched: plain lazy-adam rows
                self._adam_rows(self._base_state(),
                                np.arange(sl.start, sl.stop), grads)

    def _count_serve(self, pull_requests: int = 0, pull_rows: int = 0,
                     push_frames: int = 0, push_rows: int = 0) -> None:
        """Per-owner serve-load counters (always on): ``*_rows`` count
        rows read from / applied to THIS shard's storage, local or
        wire; ``pull_requests``/``push_frames`` count served wire
        frames. Done lines and ``wire_record`` carry them so partition
        imbalance is observable with the rebalancer off."""
        with self._serve_lock:
            s = self.serve
            s["pull_requests"] += pull_requests
            s["pull_rows"] += pull_rows
            s["push_frames"] += push_frames
            s["push_rows"] += push_rows

    # ------------------------------------------- heat-aware rebalancing
    def attach_rebalancer(self, rb, cfg) -> None:
        """Bind the migration machinery (balance/rebalancer.Rebalancer):
        rebuilds the router at the configured block granularity, arms
        heat accounting, and registers the migration control frames.
        Must happen before any traffic (the trainer's constructor does,
        which precedes the bus handshake in every app)."""
        from minips_tpu.balance.heat import HeatAccountant

        self._rb = rb
        # a tenant may spec its own rebalance block granularity (its
        # rows may be much wider/narrower than the fleet default's
        # sweet spot); the per-frame rb stamp is per-table, so ranks
        # still cross-check — the registry's deterministic assignment
        # keeps them agreeing
        blk = cfg.block
        if self._tenant is not None and self._tenant.block is not None:
            blk = self._tenant.block
        self.router = BlockRouter(self.part, blk)
        self._heat = HeatAccountant(self.router.num_blocks, cfg.decay,
                                    table_id=self._tenant_tid)
        if self.bus is not None:
            self.bus.on(f"rbS:{self.name}", self._on_migrate_state)
            self.bus.on(f"rbA:{self.name}", self._on_adopt_ack)
            self.bus.on(f"rbF:{self.name}", self._on_fence_release)
            self.bus.on(f"rbG:{self.name}", self._on_release_ack)
            self.bus.on(f"psE:{self.name}", self._on_epoch_nack)

    def attach_reshard(self, cfg) -> None:
        """Arm planned collective redistribution (balance/redistribute,
        MINIPS_RESHARD): migration state ships as cap-bounded slice
        ROUNDS computed identically at every rank from the overlay diff
        instead of whole-block point-to-point snapshots. Requires the
        rebalancer machinery (the plan's input IS the epoch-fenced
        overlay diff; there is nothing to schedule without it)."""
        if self._rb is None:
            raise ValueError(
                "MINIPS_RESHARD schedules the epoch-fenced migration's "
                "state rounds — arm MINIPS_REBALANCE or MINIPS_ELASTIC "
                "too (attach_rebalancer first)")
        self._reshard = cfg

    def attach_tenant(self, spec) -> None:
        """Bind this table's tenant (tenant/registry.TenantSpec): the
        1-based tenant id joins the per-frame config stamp next to
        ws/nr/dm/rb — a fleet half-armed, or armed with divergent
        tenant order, poisons the table instead of silently crossing
        tenants' wires — and the spec's staleness/admission/hedge
        budgets override the fleet-wide ones wherever the serve plane
        and the consistency gates consult them. The trainer binds
        tenancy right after consistency and BEFORE any balance/serve
        layer arms, so attach_rebalancer/attach_serve_plane/
        attach_hedge can read the overrides."""
        self._tenant = spec
        self._tenant_tid = int(spec.tid)

    def attach_serve_plane(self, plane, cfg) -> None:
        """Bind the read-mostly serving plane (serve/plane.py): arms
        heat accounting when the rebalancer hasn't already, and
        registers the serve control/data frames. Must run AFTER
        ``attach_rebalancer`` when both are armed (the rebalancer
        rebuilds the router and heat at its own block granularity —
        the trainer constructs them in that order) and before any
        traffic, like the rebalancer."""
        from minips_tpu.balance.heat import HeatAccountant
        from minips_tpu.serve.plane import TableServeState

        self._sv = TableServeState(self, plane, cfg)
        if self._heat is None:
            self._heat = HeatAccountant(self.router.num_blocks,
                                        cfg.decay)
        if self.bus is not None:
            for kind, fn in self._sv.handlers():
                self.bus.on(f"{kind}:{self.name}", fn)

    def attach_hedge(self, cfg) -> None:
        """Arm hedged pull legs (serve/hedge.py): a leg outstanding
        past the hedge delay — or aimed at a slow-verdict owner — is
        re-issued to a replica holder under the identical admission
        stamp, first admissible reply wins. Pure client-side state; a
        table with no serving plane attached simply never finds a
        holder (counted ``no_holder``, the documented honest limit)."""
        if cfg is not None and self._tenant is not None \
                and self._tenant.hedge is not None:
            # per-tenant hedge budget: a shallow copy so one tenant's
            # budget never moves another's valve; hedge=0 keeps the
            # plane armed but always sheds at the valve (counted
            # ``denied`` + the tenant's ``hedge_denied``)
            import copy

            cfg = copy.copy(cfg)
            cfg.budget = int(self._tenant.hedge)
        self._hedge = cfg

    def attach_hier(self, cfg) -> None:
        """Arm the two-level push tree (balance/hier.py, MINIPS_HIER).
        Refusals here mirror the ctor's validation ladder: the retained
        window is the fallback's replay source, so any path that lets
        pushes leave the table without passing ``_push_now`` — the
        async push window re-frames sends on the flush thread, and the
        RowCache turns pulls into local reads the floor wait cannot
        see — would break the zero-lost-steps contract. With
        ``group=1`` (armed-idle) no pair is ever in hier mode and the
        push path is bitwise the flat wire."""
        from minips_tpu.balance.hier import elect, group_ranks, host_of
        if cfg is None:
            return
        if self.async_push:
            raise ValueError(
                "MINIPS_HIER is incompatible with async_push/"
                "MINIPS_PUSH_WINDOW: the hier retained window replays "
                "exact member contributions on leader fallback, and "
                "the async window re-frames sends outside that "
                "bookkeeping — pick one push discipline")
        if self._cache is not None:
            raise ValueError(
                "MINIPS_HIER is incompatible with the client RowCache "
                "(MINIPS_CACHE_BYTES): cached reads bypass the owner's "
                "per-contributor floor wait, so a cache hit could "
                "observe a staleness bound the hier floors have not "
                "certified yet")
        self._hier = cfg
        self._hier_host_of = lambda r: host_of(r, cfg.group)
        self._hier_elect_fn = elect
        g, n = cfg.group, self.num_processes
        self._hier_group = group_ranks(self.rank, g, n)
        self._hier_members = [r for r in self._hier_group
                              if r != self.rank]
        self._hier_cross = [r for r in range(n)
                            if host_of(r, g) != host_of(self.rank, g)]
        if cfg.agg and g > 1:
            # owner side: pre-register a floor of 0 for every cross-
            # group contributor from a multi-rank group BEFORE any
            # frame flows — an empty floor dict must mean "no hier
            # contributors", never "none heard from yet", or the
            # admission gate would ignore them at startup
            self._hier_floor = {
                r: 0 for r in self._hier_cross
                if len(group_ranks(r, g, n)) >= 2}
            self._hier_member_floor = {r: 0 for r in self._hier_members}
            if self.push_comm in ("topk8", "topk4"):
                self._hier_ef = ResidualStore(self.dim)
            self._hier_rng = np.random.default_rng(
                (self._seed, self.rank, 0x48e5))
            if self.bus is not None:
                self.bus.on(f"psH:{self.name}", self._on_hier)
        self._hier_leader = self._hier_elect()

    def bind_slowness(self, sm) -> None:
        """Feed the fail-slow detector (obs/slowness.py): pull-leg
        round trips and push-ack lags recorded at the call sites that
        already hold the timestamps — no second measurement path."""
        self._slowness = sm

    def attach_membership(self, mb) -> None:
        """Bind the elastic membership plane (balance/membership.py).
        Requires the rebalancer machinery (membership transitions ARE
        epoch-fenced migrations); arms the death-survival paths below:
        a heartbeat-dead peer whose transition the plane owns unjams
        waits and re-routes legs instead of poisoning the run."""
        if self._rb is None:
            raise RuntimeError(
                "attach_membership requires the rebalancer machinery "
                "(attach_rebalancer first): membership transitions ride "
                "the epoch-fenced migration protocol")
        self._mb = mb

    def _fatal_dead(self, dead) -> set[int]:
        """The subset of heartbeat-dead peers that must still POISON a
        wait: everything, until the elastic membership plane is armed —
        then only deaths it cannot own (no checkpoint to restore from,
        a dead coordinator, verdict timeout). A survivable death keeps
        the wait alive until the membership plan re-homes the corpse's
        blocks and the wait's own re-check path unblocks it."""
        dead = set(dead)
        if not dead or self._mb is None:
            return dead
        return self._mb.fatal_dead(dead)

    def on_ranks_dead(self, dead: set[int]) -> None:
        """Detection-time unjam (membership death path, called the
        moment the monitor's verdict lands — BEFORE any plan): unacked
        push frames addressed to the corpse will never ack, so drop
        them from the window (counted — a lost push is a lost gradient,
        never silent) and wake every waiter so the re-check paths see
        the new world. The dead set is STICKY: frames the sender thread
        registers after this sweep (already-queued async pushes, or
        pushes the pre-plan table still routes to the corpse) are
        dropped by the wait loops' re-sweep and skipped at send time —
        a one-shot sweep would let a later-registered seq jam the
        window to its deadline."""
        with self._push_cond:
            self._dead_ranks |= set(dead)
            self._drop_dead_inflight_locked()
            self._push_cond.notify_all()
        with self._reply_cond:
            self._reply_cond.notify_all()
        with self._mig_cond:
            self._mig_cond.notify_all()

    def _drop_dead_inflight_locked(self) -> None:
        gone = [s for s, (_t, o) in self._inflight.items()
                if o in self._dead_ranks]
        for s in gone:
            del self._inflight[s]
        if gone:
            self.rb_stats["pushes_lost_to_dead"] += len(gone)

    def _reroute_dead_legs(self, gid: int, dead: set[int]) -> None:
        """Re-issue a pull group's legs addressed to dead ranks by the
        CURRENT routing table — the elastic twin of the psE re-router.
        Only legs whose keys no longer route to a corpse move (the
        membership plan must land first; until then the caller keeps
        waiting, bounded by its own deadline)."""
        with self._reply_cond:
            grp = self._groups.get(gid)
            assembly = grp is not None and grp.get("uniq") is None
            miss = dict(self._missing_legs_locked(gid))
        owner_map = self.router.owner_of_blocks()
        if assembly:
            if any(o in dead and not (owner_map == o).any()
                   for o in miss.values()):
                # a psA leg asks one rank for ITS shard — nothing to
                # re-route leg-wise once that rank is a corpse. The
                # death plan has re-homed its blocks (owner_map check),
                # so the whole assembly re-issues at the new epoch.
                with self._reply_cond:
                    self._cleanup_group_locked(gid)
                raise _ReissuePullAll()
            return
        for rid, o in miss.items():
            if o not in dead or (owner_map == o).any():
                continue  # alive, or the plan hasn't re-homed it yet

            def _plan(keys: np.ndarray):
                owners = self._owners_of(keys)
                return [(int(t), "psG", {}, owners == t)
                        for t in np.unique(owners)]
            self._resend_leg(rid, _plan)

    def _owners_of(self, keys: np.ndarray) -> np.ndarray:
        return (self.router.shard_of(keys) if self._rb is not None
                else self.part.shard_of(keys))

    def _ep_header(self) -> dict:
        return {"ep": self.router.epoch} if self._rb is not None else {}

    def _excluded_ranks(self) -> set[int]:
        g = getattr(self._cons, "gossip", None)
        return set(g.excluded) if g is not None else set()

    def adopt_table(self, ep: int, overlay: dict, *,
                    dead: frozenset = frozenset(),
                    restore=None) -> bool:
        """Adopt routing epoch ``ep`` — THE epoch fence point. Only ever
        run from the PUSH-DRIVING thread (trainer tick / finalize /
        pull_all / the pull-wait poll): the adoption ack's promise is
        'every stale-routed push of mine precedes this ack per link',
        which a bus-thread adoption racing a mid-flight send could
        break. Everything the fence's safety argument needs happens
        here, in order:

        1. (async push only) drain the send queue to the bus, so every
           stale-routed push of mine is on its per-link wire BEFORE my
           adoption ack;
        2. atomically with the serve-path verdicts (one lock): swap the
           routing table, SNAPSHOT outbound blocks' rows + optimizer
           state out of storage, and fence inbound blocks
           (state-pending until their ``rbS`` lands, pull-fenced until
           the old owner's ``rbF``);
        3. ship outbound state (``rbS``) and send my adoption ack
           (``rbA``) DIRECTED to every source owner — the same per-link
           stream my stale pushes rode, which is what lets the source
           conclude 'no more stale pushes from this rank' on receipt;
        4. drop row-cache entries of moved blocks and re-evaluate
           everything parked.

        DEATH plans (elastic membership, balance/membership.py) ride the
        same fence point with two extra arguments: blocks whose source
        is in ``dead`` cannot ship an rbS or release an rbF — the new
        owner instead installs ``restore(block)`` (the coordinator-chosen
        elastic-checkpoint state, ckpt/elastic.load_block_state) and
        serves immediately, un-fenced: no stale push can ever be
        forwarded from a corpse, so the fence would protect against
        nothing, and the restored content IS the recovery semantics
        (loss of a rank rolls exactly its ranges back to the last
        checkpoint, nothing else). Blocks stuck mid-migration ON the
        corpse (pending rbS / fenced on its rbF from an earlier epoch)
        resolve the same way.
        """
        if ep <= self.router.epoch:  # cheap duplicate cut (benign race;
            return False             # the locked apply re-checks)
        t_adopt0 = time.monotonic()
        if self.async_push:
            try:
                self.flush_pushes(acks=False)
            except Exception as e:  # noqa: BLE001 - poison, don't hide
                if self._fatal is None:
                    self._fatal = (f"table {self.name}: adoption drain "
                                   f"failed: {e!r}")
        # error-feedback residuals flush BEFORE the router swap: the
        # dense frames route by the OLD table and precede my rbA on
        # every per-link stream, so the fence's promise ('no more stale
        # pushes from this rank') covers withheld mass too — migration
        # and elastic transitions can never strand a residual
        try:
            self.residual_flush(reason="fence")
        except Exception as e:  # noqa: BLE001 - poison, don't hide
            if self._fatal is None:
                self._fatal = (f"table {self.name}: residual fence "
                               f"flush failed: {e!r}")
        ships: list[tuple[int, int, dict]] = []
        moved: list[tuple[int, int, int]] = []

        def _restore_locked(b: int) -> None:
            try:
                st = restore(b) if restore is not None else None
            except Exception as e:  # noqa: BLE001 - poison, don't hide
                st = None
                if self._fatal is None:
                    self._fatal = (f"table {self.name}: elastic restore "
                                   f"of block {b} failed: {e!r}")
            if st is None:
                if self._fatal is None:
                    self._fatal = (
                        f"table {self.name}: block {b} owned by a dead "
                        "rank has no restorable checkpoint state")
                return
            self._install_block_locked(b, st)
            self.rb_stats["blocks_restored"] += 1

        planned = self._reshard is not None
        out_blocks: list[tuple[int, int]] = []
        with self._mig_cond:
            prev = self.router.apply(ep, overlay)
            if prev is None:
                return False
            home = self.router.home_of
            for b in set(prev) | set(overlay):
                o_old = prev.get(b, home(b))
                o_new = overlay.get(b, home(b))
                if o_old != o_new:
                    moved.append((int(b), int(o_old), int(o_new)))
            with self._state_lock:
                for b, src, dst in moved:
                    if src == self.rank:
                        if planned:
                            # planned mode defers the snapshot: the
                            # block is quiescent the moment the router
                            # swapped (pushes forward, residuals
                            # flushed pre-swap), so each ROUND stages
                            # only its cap-bounded slice set later —
                            # the whole point of the schedule
                            out_blocks.append((b, dst))
                        else:
                            ships.append((b, dst,
                                          self._take_block_locked(b)))
                    if dst == self.rank:
                        if src in dead:
                            # no rbS/rbF will ever come from the corpse:
                            # restore from the elastic checkpoint and
                            # serve un-fenced (docstring above)
                            self._early_state.pop(b, None)
                            self._abort_slices_locked(b, "early")
                            _restore_locked(b)
                            continue
                        early = self._early_state.pop(b, None)
                        if early is not None \
                                and b not in self._early_prog:
                            self._install_block_locked(b, early)
                            self.rb_stats["blocks_in"] += 1
                        elif early is not None:
                            # a PARTIAL slice set beat my adoption: the
                            # buffer becomes the destination storage,
                            # remaining slices land via the pending path
                            self._install_block_locked(b, early)
                            self._slice_prog[b] = self._early_prog.pop(b)
                            self._pending_state[b] = src
                        else:
                            self._pending_state[b] = src
                        if (b, ep) in self._early_release:
                            self._early_release.discard((b, ep))
                        else:
                            self._fenced[b] = src
                            self._fence_t0[b] = time.monotonic()
                if dead:
                    # blocks stuck MID-MIGRATION on the corpse from an
                    # earlier epoch: a pending rbS that will never
                    # arrive restores from checkpoint; a fence whose
                    # rbF died with its old owner releases (no source
                    # left to forward a stale push)
                    for b in [b for b, s in self._pending_state.items()
                              if s in dead]:
                        del self._pending_state[b]
                        self._abort_slices_locked(b, "pending")
                        _restore_locked(b)
                    for b in [b for b, s in self._fenced.items()
                              if s in dead]:
                        del self._fenced[b]
                        self._fence_t0.pop(b, None)
            if ships:
                self._await_acks[ep] = [(b, dst) for b, dst, _ in ships]
            if out_blocks:
                self._await_acks[ep] = list(out_blocks)
            self._adopt_acks.setdefault(ep, set()).add(self.rank)
            # prune ack bookkeeping for long-released epochs
            for stale in [e for e in self._adopt_acks
                          if e < ep - 4 and e not in self._await_acks]:
                del self._adopt_acks[stale]
            self._mig_cond.notify_all()
        tr = _trc.TRACER
        if out_blocks:
            self._ship_planned(ep, moved, dead)
        if ships:
            # point-to-point path: EVERY outbound block's full state is
            # staged at once (the list above) — record it honestly, it
            # is the baseline the RESHARD-MEM gate compares against
            staged = sum(sum(int(a.nbytes) for a in st.values())
                         for _b, _dst, st in ships)
            self.rb_stats["peak_stage_bytes"] = max(
                self.rb_stats["peak_stage_bytes"], staged)
        for b, dst, st in ships:
            head, blob = self._encode_block_state(b, ep, st)
            self.bus.send(dst, f"rbS:{self.name}", head, blob=blob)
            self.rb_stats["blocks_out"] += 1
            self.rb_stats["migrated_rows"] += int(head["n"])
            if tr is not None:
                tr.instant("rebalance", "rb_ship",
                           {"b": int(b), "dst": int(dst),
                            "rows": int(head["n"]), "ep": ep})
        for src in sorted({s for _b, s, _d in moved
                           if s != self.rank and s not in dead}):
            self.bus.send(src, f"rbA:{self.name}", {"ep": ep})
        if self._sv is not None and moved:
            # lease/epoch invalidation: every replica lease I granted on
            # a block that just migrated away dies AT the fence point —
            # serving composes with online migration (docs/serving.md)
            self._sv.on_blocks_moved(moved)
        if self._cache is not None:
            for b, _src, _dst in moved:
                lo, ln = self.router.block_span(b)
                self._cache.invalidate(np.arange(lo, lo + ln, dtype=np.int64))
        self._maybe_release_fences(ep)
        self._drain_parked_pushes()
        self.serve_parked()
        if tr is not None:
            tr.complete("rebalance", "rb_adopt", t_adopt0,
                        {"ep": ep, "out": len(ships),
                         "moved": len(moved)})
        return True

    def _take_block_locked(self, b: int) -> dict:
        """Snapshot-and-remove block ``b``'s live state (caller holds
        the state lock): a home block's slab rows are copied out (the
        slab copy is dead until the block migrates back), a migrated-in
        block's arrays leave ``_xtra`` wholesale."""
        if self.router.home_of(b) == self.rank:
            lo, ln = self.router.block_span(b)
            sl = slice(lo - self.shard_lo, lo - self.shard_lo + ln)
            st = {"w": self._w[sl].copy()}
            if self._acc is not None:
                st["acc"] = self._acc[sl].copy()
            if self._m is not None:
                st["m"] = self._m[sl].copy()
                st["v"] = self._v[sl].copy()
                st["steps"] = self._steps[sl].copy()
            return st
        return self._xtra.pop(b)

    def _install_block_locked(self, b: int, st: dict) -> None:
        if self.router.home_of(b) == self.rank:
            lo, ln = self.router.block_span(b)
            sl = slice(lo - self.shard_lo, lo - self.shard_lo + ln)
            self._w[sl] = st["w"]
            if self._acc is not None:
                self._acc[sl] = st["acc"]
            if self._m is not None:
                self._m[sl] = st["m"]
                self._v[sl] = st["v"]
                self._steps[sl] = st["steps"]
        else:
            self._xtra[b] = st

    # ---------------- planned collective redistribution (MINIPS_RESHARD)
    def _ship_planned(self, ep: int, moved: list,
                      dead: frozenset) -> None:
        """Planned-mode shipper: compile the GLOBAL round schedule from
        the overlay diff — every rank derives the identical ``moved``
        set from prev/overlay at the shared epoch, so the plan needs no
        coordination wire — then stage and ship only MY slices, one
        cap-bounded round at a time. Runs on the push-driving thread
        right after the fence swap: every outbound block is quiescent
        from that moment (pushes forward under the new table, residuals
        flushed pre-swap), so per-round lazy snapshots are consistent
        by construction. Rounds are journaled in the frame head (``rd``
        next to ws/nr/dm/rb) and as ``reshard_round`` flight events;
        redelivered slices resume idempotently at the receiver
        (``reshard_resume``), a death mid-plan aborts the affected
        blocks back to checkpoint state (``reshard_abort``)."""
        from minips_tpu.balance import redistribute as _rd

        cfg = self._reshard
        rbytes = _rd.state_row_bytes(self.dim, self.updater)
        live_moves = [(b, s, d) for b, s, d in moved if s not in dead]
        rounds = _rd.plan_rounds(
            live_moves, lambda b: self.router.block_span(b)[1], rbytes,
            cap=cfg.cap, fanout=cfg.fanout)
        self.rs_stats["plans"] += 1
        tr = _trc.TRACER
        total = {b: self.router.block_span(b)[1]
                 for b, s, _d in live_moves if s == self.rank}
        shipped = dict.fromkeys(total, 0)
        nrd = len(rounds)
        for rd, exchanges in enumerate(rounds):
            mine = [ex for ex in exchanges if ex.src == self.rank]
            if not mine:
                continue
            staged = []
            with self._state_lock:
                for ex in mine:
                    staged.append((ex, self._take_slice_locked(ex)))
                    shipped[ex.block] += ex.rows
                    if shipped[ex.block] >= total[ex.block]:
                        # the block's last slice just staged: a
                        # migrated-in block's arrays leave _xtra now —
                        # the planned twin of _take_block_locked's pop
                        self._xtra.pop(ex.block, None)
                        self.rb_stats["blocks_out"] += 1
            round_bytes = sum(sum(int(a.nbytes) for a in st.values())
                              for _ex, st in staged)
            self.rs_stats["peak_stage_bytes"] = max(
                self.rs_stats["peak_stage_bytes"], round_bytes)
            self.rb_stats["peak_stage_bytes"] = max(
                self.rb_stats["peak_stage_bytes"], round_bytes)
            for ex, st in staged:
                head, blob = self._encode_block_state(ex.block, ep, st)
                head.update({"rd": int(rd), "nrd": int(nrd),
                             "sl": int(ex.lo),
                             "bn": int(total[ex.block])})
                self.bus.send(ex.dst, f"rbS:{self.name}", head,
                              blob=blob)
                self.rs_stats["slices"] += 1
                self.rb_stats["migrated_rows"] += int(ex.rows)
                if tr is not None:
                    tr.instant("rebalance", "rb_ship",
                               {"b": int(ex.block), "dst": int(ex.dst),
                                "rows": int(ex.rows), "ep": ep,
                                "rd": int(rd), "sl": int(ex.lo)})
            self.rs_stats["rounds"] += 1
            _fl.record("reshard_round",
                       {"table": self.name, "ep": int(ep),
                        "rd": int(rd), "nrd": int(nrd),
                        "ships": len(mine), "bytes": int(round_bytes)})

    def _take_slice_locked(self, ex) -> dict:
        """Copy rows ``[lo, lo+rows)`` of block ``ex.block``'s live
        state WITHOUT removing it (caller holds the state lock): the
        block stays readable for later rounds' slices; removal happens
        once its last slice is staged (_ship_planned)."""
        b, lo, n = ex.block, ex.lo, ex.rows
        if self.router.home_of(b) == self.rank:
            blo, _ln = self.router.block_span(b)
            s = blo - self.shard_lo + lo
            sl = slice(s, s + n)
            st = {"w": self._w[sl].copy()}
            if self._acc is not None:
                st["acc"] = self._acc[sl].copy()
            if self._m is not None:
                st["m"] = self._m[sl].copy()
                st["v"] = self._v[sl].copy()
                st["steps"] = self._steps[sl].copy()
            return st
        src = self._xtra[b]
        return {k: v[lo:lo + n].copy() for k, v in src.items()}

    def _zero_block_state(self, n: int) -> dict:
        """A zero-filled full-block state dict in the rbS layout — the
        destination allocation slice writes land in (it IS the block's
        final storage for a non-home gainer, not extra staging)."""
        st = {"w": np.zeros((n, self.dim), np.float32)}
        if self._acc is not None:
            st["acc"] = np.zeros((n, self.dim), np.float32)
        if self._m is not None:
            st["m"] = np.zeros((n, self.dim), np.float32)
            st["v"] = np.zeros((n, self.dim), np.float32)
            st["steps"] = np.zeros(n, np.int32)
        return st

    def _write_slice_locked(self, b: int, lo: int, st: dict,
                            bn: int) -> None:
        """Install one slice's rows straight into destination storage
        (caller holds the state lock): the block is fenced + state-
        pending for the whole plan, so nothing reads or writes these
        rows until completion flips the pending bit — receiver staging
        stays one in-flight frame, never a buffered block."""
        n = st["w"].shape[0]
        if self.router.home_of(b) == self.rank:
            blo, _ln = self.router.block_span(b)
            s = blo - self.shard_lo + lo
            sl = slice(s, s + n)
            self._w[sl] = st["w"]
            if self._acc is not None:
                self._acc[sl] = st["acc"]
            if self._m is not None:
                self._m[sl] = st["m"]
                self._v[sl] = st["v"]
                self._steps[sl] = st["steps"]
            return
        dst = self._xtra.get(b)
        if dst is None:
            dst = self._zero_block_state(bn)
            self._xtra[b] = dst
        for k, arr in st.items():
            dst[k][lo:lo + n] = arr

    def _abort_slices_locked(self, b: int, where: str) -> None:
        """Discard partial slice progress for block ``b`` (its source
        died mid-plan): the checkpoint restore that follows IS the
        abort-to-known-state contract — partially landed slices are
        overwritten wholesale, never mixed with restored rows."""
        prog = self._slice_prog.pop(b, None)
        eprog = self._early_prog.pop(b, None)
        got = (prog or eprog or {}).get("got", 0)
        if prog is not None or eprog is not None:
            self.rs_stats["aborts"] += 1
            _fl.record("reshard_abort",
                       {"table": self.name, "b": int(b),
                        "rows_got": int(got), "where": where})

    def _ingest_slice(self, sender: int, payload: dict,
                      st: dict) -> None:
        """Receiver half of the planned shipper: one slice frame lands
        in destination storage exactly-once. The journal is the per-
        block ``seen`` offset set — a redelivered slice (partition
        heal, reliable-channel retransmit) is counted and dropped
        (``reshard_resume``), never double-applied; completion routes
        through the same install bookkeeping as a whole-block rbS."""
        b = int(payload.get("b", -1))
        lo = int(payload.get("sl", 0))
        bn = int(payload.get("bn", 0))
        rd = int(payload.get("rd", 0))
        n = st["w"].shape[0]
        done = dup = False
        with self._mig_cond:
            with self._state_lock:
                if b in self._pending_state:
                    prog = self._slice_prog.setdefault(
                        b, {"got": 0, "seen": set()})
                    if lo in prog["seen"]:
                        dup = True
                    else:
                        self._write_slice_locked(b, lo, st, bn)
                        prog["seen"].add(lo)
                        prog["got"] += n
                        if prog["got"] >= bn:
                            del self._slice_prog[b]
                            self._pending_state.pop(b, None)
                            self.rb_stats["blocks_in"] += 1
                            done = True
                elif int(self.router.owner_of_blocks()[b]) == self.rank:
                    # slice of an already-installed block (full replay
                    # after a heal): a re-write would roll back updates
                    # applied since — drop it, count it
                    dup = True
                else:
                    # slices beat my plan adoption: accumulate into a
                    # full-block buffer exactly like _early_state (the
                    # reorder window is bounded; adoption installs a
                    # complete buffer, or carries a partial one into
                    # the pending path with its progress journal)
                    prog = self._early_prog.setdefault(
                        b, {"got": 0, "seen": set()})
                    if lo in prog["seen"]:
                        dup = True
                    else:
                        buf = self._early_state.get(b)
                        if buf is None:
                            buf = self._zero_block_state(bn)
                            self._early_state[b] = buf
                        for k, arr in st.items():
                            buf[k][lo:lo + n] = arr
                        prog["seen"].add(lo)
                        prog["got"] += n
                        if prog["got"] >= bn:
                            del self._early_prog[b]
            self._mig_cond.notify_all()
        if dup:
            self.rs_stats["dup_slices"] += 1
            _fl.record("reshard_resume",
                       {"table": self.name, "b": int(b), "sl": int(lo),
                        "rd": int(rd), "from": int(sender)})
        if done:
            tr = _trc.TRACER
            if tr is not None:
                tr.instant("rebalance", "rb_install", {"b": b})
            self._drain_parked_pushes()
            self.serve_parked()

    def reshard_table_stats(self) -> Optional[dict]:
        """Planned-redistribution counters — None when MINIPS_RESHARD
        is off (off vs armed-idle, the PR5 convention)."""
        if self._reshard is None:
            return None
        with self._mig_cond:
            inflight = len(self._slice_prog) + len(self._early_prog)
        return {**self.rs_stats, "blocks_inflight": inflight,
                "cap": self._reshard.cap,
                "fanout": self._reshard.fanout}

    def _encode_block_state(self, b: int, ep: int, st: dict) -> tuple:
        """rbS wire format: rows AND optimizer state AND the shipper's
        min-clock view at snapshot time (stamp metadata — recorded so
        drills can audit that a migrated block's content was at least
        as fresh as the bound requires)."""
        n = st["w"].shape[0]
        parts = [np.ascontiguousarray(st["w"], np.float32)]
        for k in ("acc", "m", "v"):
            if st.get(k) is not None:
                parts.append(np.ascontiguousarray(st[k], np.float32))
        if st.get("steps") is not None:
            parts.append(np.ascontiguousarray(st["steps"], np.int32))
        g = getattr(self._cons, "gossip", None)
        stamp = int(g.global_min()) if g is not None else 0
        head = {"b": int(b), "ep": int(ep), "n": int(n), "stamp": stamp,
                "u": self.updater, **self._cfg_header()}
        return head, _cat_blob(*parts)

    def _decode_block_state(self, payload: dict) -> Optional[dict]:
        n = int(payload.get("n", 0))
        blob = payload.get("__blob__") or b""
        row = n * self.dim * 4
        need = row * {"sgd": 1, "adagrad": 2, "adam": 3}[self.updater] \
            + (n * 4 if self.updater == "adam" else 0)
        if payload.get("u") != self.updater or len(blob) != need:
            return None
        st = {"w": np.frombuffer(blob[:row], np.float32
                                 ).reshape(n, self.dim).copy()}
        off = row
        if self.updater == "adagrad":
            st["acc"] = np.frombuffer(blob[off:off + row], np.float32
                                      ).reshape(n, self.dim).copy()
        elif self.updater == "adam":
            st["m"] = np.frombuffer(blob[off:off + row], np.float32
                                    ).reshape(n, self.dim).copy()
            st["v"] = np.frombuffer(blob[off + row:off + 2 * row],
                                    np.float32).reshape(n, self.dim).copy()
            st["steps"] = np.frombuffer(blob[off + 2 * row:],
                                        np.int32).copy()
        return st

    def _on_migrate_state(self, sender: int, payload: dict) -> None:
        b = int(payload.get("b", -1))
        if not self._check_peer_config(sender, payload):
            return
        st = self._decode_block_state(payload)
        if st is None:
            self._drop("malformed", sender, "bad rbS block state")
            return
        if "sl" in payload:  # planned-mode slice frame (MINIPS_RESHARD)
            self._ingest_slice(sender, payload, st)
            return
        tr = _trc.TRACER
        with self._mig_cond:
            with self._state_lock:
                if b in self._pending_state:
                    self._install_block_locked(b, st)
                    self._pending_state.pop(b, None)
                    self.rb_stats["blocks_in"] += 1
                    if tr is not None:
                        tr.instant("rebalance", "rb_install", {"b": b})
                elif int(self.router.owner_of_blocks()[b]) == self.rank:
                    pass  # duplicate of an installed block: a re-install
                    # would roll back updates applied since — drop it
                else:
                    # rbS beat my plan adoption: stash until it arrives
                    # (a whole-block frame supersedes any partial slice
                    # accumulation — drop its progress journal too)
                    self._early_prog.pop(b, None)
                    self._early_state[b] = st
            self._mig_cond.notify_all()
        self._drain_parked_pushes()
        self.serve_parked()

    def _on_adopt_ack(self, sender: int, payload: dict) -> None:
        ep = int(payload.get("ep", 0))
        with self._mig_cond:
            self._adopt_acks.setdefault(ep, set()).add(sender)
        self._maybe_release_fences(ep)

    def _maybe_release_fences(self, ep: int) -> None:
        """Old-owner side: once every LIVE rank acked adoption of ``ep``,
        no more stale-routed pushes can arrive here (each rbA trails
        that rank's last stale push on its per-link stream) — so the
        fence release (rbF) sent NOW on the old→new link is ordered
        after every forwarded push. Re-checked on exclusions too, so a
        dead rank can't hold fences forever."""
        with self._mig_cond:
            out = self._await_acks.get(ep)
            if out is None:
                return
            live = set(range(self.num_processes)) - self._excluded_ranks()
            if not live <= self._adopt_acks.get(ep, set()):
                return
            del self._await_acks[ep]
            now = time.monotonic()
            for b, dst in out:
                self._release_unacked[(int(b), int(dst))] = (int(ep),
                                                             now)
        for b, dst in out:
            self.bus.send(dst, f"rbF:{self.name}",
                          {"b": int(b), "ep": int(ep)})

    def _on_fence_release(self, sender: int, payload: dict) -> None:
        b, ep = int(payload.get("b", -1)), int(payload.get("ep", 0))
        released = False
        with self._mig_cond:
            if b in self._fenced and self.router.epoch >= ep:
                self._fenced.pop(b, None)
                released = True
            else:  # rbF beat my plan adoption (reordered control plane)
                self._early_release.add((b, ep))
            self._mig_cond.notify_all()
        # confirm receipt (idempotent — a re-sent rbF for an already-
        # released fence still acks): the old owner's leave() gate
        # re-sends rbF until this lands, so a release eaten by a
        # partition cannot strand the fence after the sender exits
        self.bus.send(sender, f"rbG:{self.name}",
                      {"b": b, "ep": ep})
        if released:
            t0 = self._fence_t0.pop(b, None)
            if t0 is not None:
                # always-on fence-duration hist (the windowed layer's
                # rebalance signal); the tracer span rides when armed
                self.hist_fence.record_s(time.monotonic() - t0)
                tr = _trc.TRACER
                if tr is not None:
                    tr.complete("rebalance", "rb_fence", t0,
                                {"b": b, "ep": ep})
        self.serve_parked()

    def _on_release_ack(self, sender: int, payload: dict) -> None:
        b = int(payload.get("b", -1))
        with self._mig_cond:
            self._release_unacked.pop((b, int(sender)), None)
            self._mig_cond.notify_all()

    def releases_confirmed(self) -> bool:
        """Every rbF this rank sent has been confirmed (rbG) by a
        still-live gainer — the leave() exit gate. Entries addressed to
        ranks excluded since (died mid-handshake) are pruned: their
        fences resolve through the death plan's dead-source path, not
        through a confirmation that can never come."""
        with self._mig_cond:
            if self._release_unacked:
                gone = self._excluded_ranks()
                for key in [k for k in self._release_unacked
                            if k[1] in gone]:
                    del self._release_unacked[key]
            return not self._release_unacked

    def resend_stale_releases(self, age_s: float = 0.25) -> None:
        """Re-send unconfirmed fence releases older than ``age_s`` —
        called from the leave() wait loop so a partition that ate the
        first rbF heals into a released fence instead of a permanently
        wedged gainer (the sender is about to exit; nobody else can
        ever release that fence)."""
        now = time.monotonic()
        with self._mig_cond:
            stale = [(b, dst, ep)
                     for (b, dst), (ep, t0) in
                     self._release_unacked.items()
                     if now - t0 > age_s]
            for b, dst, ep in stale:
                self._release_unacked[(b, dst)] = (ep, now)
        for b, dst, ep in stale:
            self.bus.send(dst, f"rbF:{self.name}",
                          {"b": int(b), "ep": int(ep)})

    def rebalance_settled(self) -> bool:
        """No migration in flight at this rank: nothing fenced, no state
        pending, no acks awaited, nothing parked — the coordinator only
        plans over a fleet that reports settled at one epoch."""
        with self._mig_cond:
            return not (self._fenced or self._pending_state
                        or self._await_acks or self._parked_pushes
                        or self._early_state)

    def _wait_settled(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while True:
            # adopt pending plans while waiting: a plan landing in this
            # window stashes rbS state as early_state here (unsettled),
            # and only THIS thread can adopt it — blocking without
            # adopting would wedge until the deadline
            if self._rb is not None:
                self._rb.adopt_now()
            if self._mb is not None:
                # membership poll too (the gate poll_hook rule): a
                # partitioned ex-coordinator can sit HERE awaiting acks
                # for a plan the survivors FENCED — acks that will
                # never come from peers it cannot convict. Its own
                # death verdict (FencedOutError) must be able to
                # resolve the wait instead of the settle deadline
                # mislabeling it a gate_timeout.
                self._mb.poll()
            with self._mig_cond:
                if not (self._fenced or self._pending_state
                        or self._await_acks or self._parked_pushes
                        or self._early_state):
                    return
                if time.monotonic() > deadline:
                    # flight dump OUTSIDE the lock below (file I/O +
                    # the windowed snapshot hook must never run under
                    # a table lock a reliable-dispatched handler may
                    # want — the outside-the-lock rule every poison
                    # site in this file follows)
                    fenced = sorted(self._fenced)
                    pending = sorted(self._pending_state)
                    break
                self._mig_cond.wait(timeout=0.2)
        _fl.poison("settle_deadline",
                   {"table": self.name, "fenced": fenced,
                    "pending": pending})
        raise TimeoutError(
            f"table {self.name}: migration never settled "
            f"(fenced={fenced}, pending={pending})")

    def rebalance_table_stats(self) -> dict:
        with self._mig_cond:
            extra = {"fenced": len(self._fenced),
                     "pending_state": len(self._pending_state),
                     "xtra_blocks": len(self._xtra)}
        return {"epoch": self.router.epoch, **self.rb_stats, **extra}

    # ---- serve-path classification (rebalancer on)
    def _pull_verdict(self, keys: np.ndarray, ep: int,
                      owners: Optional[np.ndarray] = None) -> str:
        """'serve' | 'park' | 'refuse' for a pull slice under MY current
        table: keys not mine → the sender's table is stale (refuse with
        mine) unless the FRAME's is newer (park until my adoption
        catches up); keys mine but fenced/state-pending → park.
        ``owners`` lets a caller that already routed the keys skip the
        recompute (the hot serve path routes once per frame)."""
        if owners is None:
            owners = self.router.shard_of(keys)
        if (owners != self.rank).any():
            return "park" if ep > self.router.epoch else "refuse"
        with self._mig_cond:
            if self._fenced or self._pending_state:
                blocks = {int(x)
                          for x in np.unique(self.router.blocks_of(keys))}
                if blocks & (self._fenced.keys()
                             | self._pending_state.keys()):
                    return "park"
        return "serve"

    def _pull_all_verdict(self, ep: int = 0) -> str:
        """'serve' | 'park' for a shard-assembly request stamped with
        the REQUESTER's routing epoch ``ep``: park while a migrated
        block is in transit here, and park requests from a NEWER epoch
        until my adoption catches up — a pre-adoption reply would omit
        every block the new table assigns to me (a death plan's
        restored blocks have no other live holder, so the assembler
        would read uninitialized rows for their span)."""
        if ep > self.router.epoch:
            return "park"
        with self._mig_cond:
            return "park" if (self._fenced or self._pending_state) \
                else "serve"

    def _send_epoch_nack(self, sender: int, req: int) -> None:
        ep, ov = self.router.table()
        self.rb_stats["refused_pulls"] += 1
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("serve", "pull_refused",
                       {"from": sender, "rid": req, "ep": ep})
        self.bus.send(sender, f"psE:{self.name}",
                      {"req": int(req), "ep": ep,
                       "ovb": [int(b) for b in ov],
                       "ovo": [int(o) for o in ov.values()]})

    # ---- push ingest (rebalancer on): classify → apply/forward/park
    def _ingest_push(self, keys: np.ndarray, grads: np.ndarray,
                     ep: int) -> None:
        forwards: list[tuple[int, np.ndarray, np.ndarray]] = []
        with self._mig_cond:
            owners = self.router.shard_of(keys)
            bad = (owners < 0) | (owners >= self.num_processes)
            if bad.any():  # garbage keys from a stale run
                self._drop("misrouted", -1, "push keys outside key space")
                keys, grads, owners = (keys[~bad], grads[~bad],
                                       owners[~bad])
            mine = owners == self.rank
            if not mine.all():
                if ep > self.router.epoch:
                    # the sender runs a NEWER table than me: park the
                    # whole frame until my adoption catches up
                    self._parked_pushes.append((keys, grads, ep))
                    self.rb_stats["parked_frames"] += 1
                    return
                for o in np.unique(owners[~mine]):
                    m = owners == o
                    forwards.append((int(o), keys[m], grads[m]))
                keys, grads = keys[mine], grads[mine]
            if keys.size:
                pend = self._pending_state
                if pend:
                    blocks = self.router.blocks_of(keys)
                    pm = np.isin(blocks,
                                 np.fromiter(pend, np.int64, len(pend)))
                    if pm.any():  # inbound block, state still in transit
                        self._parked_pushes.append(
                            (keys[pm], grads[pm], ep))
                        self.rb_stats["parked_frames"] += 1
                        keys, grads = keys[~pm], grads[~pm]
            if keys.size:
                self._heat.touch(self.router.blocks_of(keys))
                self._apply_keys_locked(keys, grads)
        tr = _trc.TRACER
        for o, k, g in forwards:
            # forwarded slice: decoded f32 rows, no seq (the ORIGINAL
            # frame was acked by this hop; the reliable layer covers
            # the second hop like any other frame)
            self.rb_stats["forwarded_pushes"] += 1
            if tr is not None:
                tr.instant("push", "push_forward",
                           {"to": int(o), "n": int(k.size)})
            blob = _cat_blob(k, np.ascontiguousarray(g, np.float32))
            self.bus.send(o, f"psP:{self.name}",
                          {"n": int(k.size), "comm": "float32",
                           "ep": self.router.epoch, **self._cfg_header()},
                          blob=blob)

    def _apply_keys_locked(self, keys: np.ndarray,
                           grads: np.ndarray) -> None:
        """Global-key twin of :meth:`_apply_rows` (caller holds the mig
        lock; takes the state lock): dedup-sum over the WHOLE frame
        first — identical math to the seed path — then split the unique
        rows between the base slab and migrated-in blocks."""
        grads = grads.reshape(keys.size, self.dim)
        self._count_serve(push_rows=keys.size)
        with self._state_lock:
            uniq, inv = np.unique(keys, return_inverse=True)
            g = np.zeros((uniq.size, self.dim), np.float32)
            np.add.at(g, inv, grads)
            base = (uniq >= self.shard_lo) \
                & (uniq < self.shard_lo + self.part.shard_size)
            if base.any():
                self._update_block(self._base_state(),
                                   uniq[base] - self.shard_lo, g[base])
            if (~base).any():
                rk, rg = uniq[~base], g[~base]
                blocks = self.router.blocks_of(rk)
                for b in np.unique(blocks):
                    m = blocks == b
                    st = self._xtra.get(int(b))
                    if st is None:  # protocol hole — loud, not silent
                        raise RuntimeError(
                            f"table {self.name}: no state for migrated "
                            f"block {int(b)} (keys routed here without "
                            "an installed rbS)")
                    lo, _ln = self.router.block_span(int(b))
                    self._update_block(st, rk[m] - lo, rg[m])
        if self._sv is not None:
            self._sv.note_push(keys)  # replica delta dirty tracking

    def _drain_parked_pushes(self) -> None:
        with self._mig_cond:
            take, self._parked_pushes = self._parked_pushes, []
        for keys, grads, ep in take:
            self._ingest_push(keys, grads, ep)

    def _read_rows_locked(self, keys: np.ndarray) -> np.ndarray:
        """Gather rows for keys THIS shard currently owns, wherever they
        live (base slab or migrated-in blocks); caller holds the state
        lock and has already classified ownership."""
        out = np.empty((keys.size, self.dim), np.float32)
        base = (keys >= self.shard_lo) \
            & (keys < self.shard_lo + self.part.shard_size)
        if base.any():
            out[base] = self._w[keys[base] - self.shard_lo]
        if (~base).any():
            rk = keys[~base]
            ri = np.nonzero(~base)[0]
            blocks = self.router.blocks_of(rk)
            for b in np.unique(blocks):
                m = blocks == b
                st = self._xtra.get(int(b))
                if st is None:
                    raise RuntimeError(
                        f"table {self.name}: no state for migrated "
                        f"block {int(b)} on pull")
                lo, _ln = self.router.block_span(int(b))
                out[ri[m]] = st["w"][rk[m] - lo]
        return out

    def _drop(self, reason: str, sender: int, detail: str) -> None:
        """Count a dropped frame; config mismatches (a peer launched at a
        different world size or table shape would route keys wrong forever)
        also poison the table so the next client op raises loudly."""
        self.drops[reason] += 1
        if reason == "config" and self._fatal is None:
            self._fatal = (f"table {self.name}: dropped frame from peer "
                           f"{sender}: {detail}")

    def _rb_cfg(self) -> int:
        """The rebalance config a frame stamps: the key-block size when
        the subsystem is armed, 0 when off. Divergence is a config
        mismatch like a wrong world size — an rb-off peer would
        silently drop overlay-routed pushes as misrouted and hang its
        refused pulls to timeout, and a different block granularity
        makes every overlay block id mean a different key range."""
        return self.router.block_size if self._rb is not None else 0

    def _check_peer_config(self, sender: int, payload: dict) -> bool:
        ws = int(payload.get("ws", self.num_processes))
        nr = int(payload.get("nr", self.num_rows))
        dm = int(payload.get("dm", self.dim))
        rb = int(payload.get("rb", 0))
        tb = int(payload.get("tb", 0))
        if ws != self.num_processes or nr != self.num_rows \
                or dm != self.dim or rb != self._rb_cfg() \
                or tb != self._tenant_tid:
            self._drop("config", sender,
                       f"peer sees world_size={ws} num_rows={nr} dim={dm}"
                       f" rebalance_block={rb} tenant={tb}, mine are "
                       f"{self.num_processes}/{self.num_rows}/"
                       f"{self.dim}/{self._rb_cfg()}/{self._tenant_tid}")
            return False
        return True

    def _cfg_header(self) -> dict:
        """Per-frame config stamp: a peer relaunched at a different world
        size / table shape — or with a divergent rebalance or tenant
        config — must poison the receiver (loud failure), never
        silently train garbage. ``tb`` is the 1-based tenant id
        (tenant/registry.py): absent/0 = tenancy off, so an off fleet's
        frames are byte-identical to before tenancy existed, and a
        half-armed fleet (or one whose ranks disagree on tenant order)
        fails the stamp check both directions."""
        hd = {"ws": self.num_processes, "nr": self.num_rows,
              "dm": self.dim, "rb": self._rb_cfg()}
        if self._tenant_tid:
            hd["tb"] = self._tenant_tid
        return hd

    def _on_push(self, sender: int, payload: dict) -> None:
        try:
            self._handle_push(sender, payload)
        finally:
            self._ack_push(sender, payload)

    def _on_push_range(self, sender: int, payload: dict) -> None:
        try:
            self._handle_push_range(sender, payload)
        finally:
            self._ack_push(sender, payload)

    def _ack_push(self, sender: int, payload: dict) -> None:
        """Ack EVERY seq-stamped frame, applied or dropped: a dropped
        frame is already loud at this end (drop counters; config drops
        poison my table), and withholding the ack would stall the
        pusher's window on top of it — one fault, one failure path.

        Acks are BATCHED, not per-frame: the seq lands in a per-sender
        pending list and rides out piggybacked on my next pull reply to
        that sender (one per PS cycle in steady state — zero extra
        frames), or in a dedicated psK frame when the batch threshold
        trips, a clock event lands (serve_parked), or the sender's
        drain solicits (psQ)."""
        seq = payload.get("seq")
        if seq is None or self.bus is None:
            return
        with self._ack_lock:
            pend = self._ack_pending.setdefault(sender, [])
            pend.append(int(seq))
            if len(pend) < max(1, self.push_window // 4):
                return
            seqs, self._ack_pending[sender] = pend, []
        self.bus.send(sender, f"psK:{self.name}", {"seqs": seqs})

    def _drain_acks_for(self, sender: int) -> list[int]:
        with self._ack_lock:
            return self._ack_pending.pop(sender, None) or []

    def _flush_acks(self, sender: Optional[int] = None) -> None:
        """Send out pending ack batches — for one sender (drain
        solicitation) or all (clock events): liveness when no pull
        reply is flowing to piggyback on."""
        with self._ack_lock:
            if sender is None:
                out = [(s, q) for s, q in self._ack_pending.items() if q]
                self._ack_pending.clear()
            else:
                q = self._ack_pending.pop(sender, None)
                out = [(sender, q)] if q else []
        for s, seqs in out:
            self.bus.send(s, f"psK:{self.name}", {"seqs": seqs})

    def _on_ack_solicit(self, sender: int, payload: dict) -> None:
        # per-link FIFO: the solicit was sent after the frames it wants
        # acked, so their seqs are already in my pending list
        self._flush_acks(sender)

    def _handle_push(self, sender: int, payload: dict) -> None:
        t_apply0 = time.monotonic()
        blob = payload.get("__blob__")
        n = int(payload.get("n", 0))
        comm = payload.get("comm", "float32")
        tr = _trc.TRACER
        if not self._check_peer_config(sender, payload):
            return
        if self._hier is not None and self._hier_floor:
            # stale-leader fence: an aggregated frame (it carries hfr
            # floor claims) from a sender the quorum has since convicted
            # must be dropped WHOLE — its members re-push that mass on
            # fallback, so applying the zombie copy would double-apply
            if "hfr" in payload and sender in (
                    self._excluded_ranks() | self._dead_ranks):
                self.hier_counters["stale_leader_drops"] += 1
                return
            # fallback re-push dedup: the step tag rides the exact f32
            # frame; tags below the floor the (now dead) leader already
            # delivered were applied via its last flush — exactly-once
            # across the handoff
            hst = payload.get("hst")
            if hst is not None and int(hst) < self._hier_floor.get(
                    sender, 0):
                self.hier_counters["repush_drops"] += 1
                return
        # frames self-describe their wire format, so a mixed fleet (one
        # pusher compressed, another not) decodes correctly per frame
        if comm in ("topk8", "topk4"):
            # the sparse top-k index+code stream: int32/int64 indices,
            # blockwise f32 scales, then 8- or 4-bit codes — decoded
            # into plain f32 rows here, so the updaters below (and the
            # rebalancer's forward/park classification) never know the
            # wire was compressed (ops/sparse_update.py semantics
            # already match sparse index-value application)
            bits = 8 if comm == "topk8" else 4
            blk = int(payload.get("blk", HOST_BLOCK))
            code_b, scale_b = blockwise_stream_bytes(n, self.dim, bits,
                                                     blk)
            if "dw" in payload:
                # sorted-run delta key stream (i64 base + narrow gaps —
                # ops/quantized_comm codec); frames self-describe, so a
                # plain-width pusher interoperates
                dw = int(payload["dw"])
                key_b = delta_stream_bytes(n, dw)
                if blob is None or dw not in (1, 2, 4, 8) or blk < 1 \
                        or len(blob) != key_b + scale_b + code_b:
                    self._drop("malformed", sender, "bad topk push blob")
                    return
                keys = decode_key_deltas(blob[:key_b], n, dw)
            else:
                kw = int(payload.get("kw", 8))
                key_b = n * kw
                if blob is None or kw not in (2, 4, 8) or blk < 1 \
                        or len(blob) != key_b + scale_b + code_b:
                    self._drop("malformed", sender, "bad topk push blob")
                    return
                kdt = {2: np.uint16, 4: np.int32, 8: np.int64}[kw]
                keys = np.frombuffer(blob[:key_b], kdt).astype(np.int64)
            scales = np.frombuffer(blob[key_b: key_b + scale_b],
                                   np.float32)
            grads = dequantize_blockwise(
                blob[key_b + scale_b:], scales, n, self.dim, bits,
                block=blk)
            self._count_serve(push_frames=1)
        else:
            row_bytes = (4 + self.dim) if comm == "int8" \
                else 4 * self.dim
            if blob is None or len(blob) != n * (8 + row_bytes):
                self._drop("malformed", sender, "bad push blob size")
                return  # malformed frame from a stale run
            keys = np.frombuffer(blob[: 8 * n], np.int64)
            self._count_serve(push_frames=1)
            if comm == "int8":
                scale = np.frombuffer(blob[8 * n: 12 * n], np.float32)
                codes = np.frombuffer(blob[12 * n:], np.int8
                                      ).reshape(n, self.dim)
                grads = dequantize_rows_int8(codes, scale)
            else:
                grads = np.frombuffer(blob[8 * n:], np.float32)
        if self._rb is not None:
            # classify under the CURRENT table: apply what is mine,
            # forward what migrated away, park what outruns my epoch
            self._ingest_push(keys, grads.reshape(n, self.dim),
                              int(payload.get("ep", 0)))
        else:
            offs = keys - self.shard_lo
            if n and (offs.min() < 0
                      or offs.max() >= self.part.shard_size):
                self._drop("misrouted", sender,
                           "push keys outside my range")
                return
            self._apply_rows(offs, grads)  # read-only view: never written
        if "hfr" in payload and self._hier is not None:
            # floor claims ride the SAME frame as the aggregated mass
            # (per-link FIFO: mass applied above before the claim is
            # honored here), then parked pulls re-check admission
            self._hier_merge_floors(payload)
            self.serve_parked()
        if tr is not None:
            # flow finish AFTER validation, next to the apply span: a
            # dropped (misrouted/config/malformed) frame must not draw
            # a completed cross-rank arrow for a discarded gradient
            if payload.get("seq") is not None:
                tr.flow("f", _trc.flow_id(f"push:{self.name}", sender,
                                          int(payload["seq"])), "push")
            tr.complete("push", "push_apply", t_apply0,
                        {"from": sender, "n": n})

    def _handle_push_range(self, sender: int, payload: dict) -> None:
        blob = payload.get("__blob__")
        lo = int(payload.get("lo", -1))
        comm = payload.get("comm", "float32")
        if not self._check_peer_config(sender, payload):
            return
        if blob is None:
            self._drop("malformed", sender, "range push without blob")
            return
        if comm == "int8":
            row_bytes = 4 + self.dim  # f32 scale + int8 codes per row
            if len(blob) % row_bytes:
                self._drop("malformed", sender,
                           "range blob not row-aligned")
                return
            k = len(blob) // row_bytes
            scale = np.frombuffer(blob[: 4 * k], np.float32)
            codes = np.frombuffer(blob[4 * k:], np.int8).reshape(k,
                                                                 self.dim)
            grads = dequantize_rows_int8(codes, scale)
        else:
            # validate BEFORE decoding: a torn frame must land in the
            # malformed-drop accounting, not escape as a raised ValueError
            if len(blob) % (4 * self.dim):
                self._drop("malformed", sender,
                           "range blob not row-aligned")
                return
            grads = np.frombuffer(blob, np.float32)
            k = grads.size // self.dim
        lo_local = lo - self.shard_lo
        if lo_local < 0 or lo_local + k > self.part.shard_size:
            self._drop("misrouted", sender, "range outside my shard")
            return
        self._count_serve(push_frames=1)
        if self._rb is not None and (self.router._overlay
                                     or not self.rebalance_settled()):
            # some of this home range may live elsewhere now: fall back
            # to the keyed ingest (forwards the migrated rows) — range
            # pushes are rare in rebalanced (sparse-hot) workloads, so
            # the key materialization is paid only when it must be
            self._ingest_push(np.arange(lo, lo + k, dtype=np.int64),
                              grads.reshape(k, self.dim),
                              int(payload.get("ep", 0)))
            return
        self._apply_range(lo_local, grads)

    def _on_pull(self, sender: int, payload: dict) -> None:
        blob = payload.get("__blob__")
        req = int(payload.get("req", -1))
        if not self._check_peer_config(sender, payload):
            return  # requester times out loudly; my next tick raises
        if blob is None:
            self._drop("malformed", sender, "pull without key blob")
            return
        keys = np.frombuffer(blob, np.int64)
        clk = int(payload.get("clk", 0))
        ep = int(payload.get("ep", 0))
        if self._sv is not None and not self._sv.admit_request(
                sender, req, keys, payload):
            return  # shed to a replica (svS) or refused loudly (svB)
        if self._rb is not None:
            owners = self.router.shard_of(keys)
            if keys.size and ((owners < 0)
                              | (owners >= self.num_processes)).any():
                self._drop("misrouted", sender,
                           "pull keys outside key space")
                return
            v = self._pull_verdict(keys, ep, owners=owners)
            if v == "refuse":
                self._send_epoch_nack(sender, req)
                return
            admitted = self._admit_clk(clk)
            if v == "park" or not admitted:
                tr = _trc.TRACER
                if tr is not None:
                    tr.instant("serve", "pull_park",
                               {"from": sender, "rid": req, "clk": clk,
                                "why": v if v == "park" else "admission"})
                with self._park_lock:
                    self._parked.append((sender, req, keys, clk, ep,
                                         time.monotonic()))
                # re-check (park/drain race, same as the seed path):
                # adoption/unfence/clock between verdict and append
                # would have drained an empty buffer and never retried
                if self._pull_verdict(keys, ep) == "serve" \
                        and self._admit_clk(clk):
                    self.serve_parked()
                return
            self._serve_pull(sender, req, keys, clk)
            return
        offs = keys - self.shard_lo
        if keys.size and (offs.min() < 0
                          or offs.max() >= self.part.shard_size):
            self._drop("misrouted", sender, "pull keys outside my range")
            return
        if not self._admit_clk(clk):
            tr = _trc.TRACER
            if tr is not None:
                tr.instant("serve", "pull_park",
                           {"from": sender, "rid": req, "clk": clk,
                            "why": "admission"})
            with self._park_lock:  # reference PendingBuffer: park the Get
                self._parked.append((sender, req, keys, clk, 0,
                                     time.monotonic()))
            # re-check: a clock change between the admission test and the
            # append would have drained an empty buffer and never retried
            if self._admit_clk(clk):
                self.serve_parked()
            return
        self._serve_pull(sender, req, keys, clk)

    def _serve_stamp(self, sender: int, clk: int) -> int:
        """The freshness certificate stamped on every pull reply: my view
        of every OTHER worker's applied clock (gossip min excluding the
        requester — its own pushes are certified by per-link FIFO, see
        ClockGossip.min_excluding). The requester's row cache admits the
        delivered rows at a later clock ``c`` iff ``admits(stamp, c, s)``
        — exactly the admission this serve just passed, re-evaluated at
        read time. Falls back to the request clock when no trainer is
        bound (raw-table tests): admission was vacuous there too."""
        sc = getattr(self._cons, "serving_clock", None)
        stamp = int(sc(sender)) if callable(sc) else int(clk)
        fm = self._hier_floor_min()
        if fm is not None:
            # hier contributors' pushes ride two links (member ->
            # leader -> owner), so min_excluding's FIFO self-exemption
            # no longer covers them — the certificate folds the floors,
            # SENDER INCLUDED: its own cross-host mass rides its leader
            stamp = min(stamp, int(fm))
        return stamp

    def _reply_head_blob(self, req: int, rows: np.ndarray) -> tuple:
        """Encode a pull reply on MY configured pull wire. Frames
        self-describe the format (like push frames), so a mixed fleet —
        one owner compressed, another not — decodes correctly per frame;
        the done-line echo + bench assert catch flag-plumbing drift."""
        if self.pull_wire == "int8":
            codes, scale = quantize_rows_int8(rows)  # nearest: no rng
            return ({"req": req, "wire": "int8", "n": rows.shape[0]},
                    _cat_blob(scale, codes))
        # zero-copy: `rows` is always freshly materialized by the serve
        # path (fancy index / .copy()), so the view is alias-safe
        return {"req": req, "wire": "f32"}, _as_blob(
            np.asarray(rows, np.float32))

    def _serve_pull(self, sender: int, req: int, keys: np.ndarray,
                    clk: int = 0) -> None:
        t_serve0 = time.monotonic()
        # stamp BEFORE reading state: the certificate must be a lower
        # bound on what the rows contain, and clocks only advance
        stamp = self._serve_stamp(sender, clk)
        if self._rb is not None:
            # re-verify ownership/fences ATOMICALLY with the read: a
            # concurrent adoption between the caller's verdict and here
            # may have shipped a block away (its xtra gone, or a home
            # block's slab copy now dead) — serving would be stale or
            # crash. A failed re-check re-parks; the parked path
            # re-evaluates (including refusal) on the next event.
            with self._mig_cond:
                owners = self.router.shard_of(keys)
                ok = bool((owners == self.rank).all())
                if ok and (self._fenced or self._pending_state):
                    blocks = {int(x) for x in
                              np.unique(self.router.blocks_of(keys))}
                    ok = not (blocks & (self._fenced.keys()
                                        | self._pending_state.keys()))
                if ok:
                    with self._state_lock:
                        rows = self._read_rows_locked(keys)
            if not ok:
                with self._park_lock:
                    self._parked.append((sender, req, keys, clk, 0,
                                         time.monotonic()))
                self.serve_parked()
                return
            self._heat.touch(self.router.blocks_of(keys))
        else:
            offs = keys - self.shard_lo
            with self._state_lock:
                rows = self._w[offs]  # fancy indexing: a fresh array
            if self._heat is not None:  # serve plane armed, rb off
                self._heat.touch(self.router.blocks_of(keys))
        self._count_serve(pull_requests=1, pull_rows=keys.size)
        head, blob = self._reply_head_blob(req, rows)
        head["stamp"] = stamp
        acks = self._drain_acks_for(sender)
        if acks:
            head["acks"] = acks  # piggyback: the free ack ride home
        self.bus.send(sender, f"psr:{self.name}", head, blob=blob)
        self.hist_serve.record_s(time.monotonic() - t_serve0)
        tr = _trc.TRACER
        if tr is not None:
            # the flow finish pairs with the requester's 's' event —
            # both derive the id from (requester rank, wire rid)
            tr.flow("f", _trc.flow_id(f"pull:{self.name}", sender, req),
                    "pull")
            tr.complete("serve", "serve_pull", t_serve0,
                        {"from": sender, "rid": req,
                         "rows": int(keys.size), "stamp": stamp})

    def _on_pull_all(self, sender: int, payload: dict) -> None:
        req = int(payload.get("req", -1))
        if not self._check_peer_config(sender, payload):
            return  # requester times out loudly; my next tick raises
        clk = int(payload.get("clk", 0))
        ep = int(payload.get("ep", 0))
        admitted = self._admit_clk(clk)
        parked = not admitted or (
            self._rb is not None
            and self._pull_all_verdict(ep) == "park")
        if parked:
            # a shard assembly must not ship while a migrated block is
            # in transit: the live copy would be on neither side
            with self._park_lock:
                self._parked.append((sender, req, None, clk, ep,
                                     time.monotonic()))
            if self._admit_clk(clk) and (
                    self._rb is None
                    or self._pull_all_verdict(ep) == "serve"):
                self.serve_parked()  # park/drain race, as above
            return
        self._serve_pull_all(sender, req, clk)

    def _serve_pull_all(self, sender: int, req: int,
                        clk: int = 0) -> None:
        t_serve0 = time.monotonic()
        stamp = self._serve_stamp(sender, clk)
        xb: list[int] = []
        xl: list[int] = []
        if self._rb is not None:
            # settled-check ATOMIC with the read (same race as
            # _serve_pull): a block shipping away between the caller's
            # verdict and this copy would vanish from every reply
            with self._mig_cond:
                ok = not (self._fenced or self._pending_state)
                if ok:
                    with self._state_lock:
                        rows = self._w.copy()
                        if self._xtra:
                            # migrated-in blocks ride along after the
                            # base shard; the assembler overlays them
                            # over every (stale) home copy in pass 2
                            parts = [rows]
                            for b in sorted(self._xtra):
                                arr = self._xtra[b]["w"]
                                xb.append(int(b))
                                xl.append(int(arr.shape[0]))
                                parts.append(arr.copy())
                            rows = np.concatenate(parts)
            if not ok:
                with self._park_lock:
                    self._parked.append((sender, req, None, clk, 0,
                                         time.monotonic()))
                self.serve_parked()
                return
        else:
            with self._state_lock:
                rows = self._w.copy()  # full shard: copy out of the lock
        self._count_serve(pull_requests=1, pull_rows=rows.shape[0])
        head, blob = self._reply_head_blob(req, rows)
        head["lo"] = self.shard_lo
        head["nb"] = int(self.part.shard_size)
        if xb:
            head["xb"] = xb
            head["xl"] = xl
        head["stamp"] = stamp
        acks = self._drain_acks_for(sender)
        if acks:
            head["acks"] = acks
        self.bus.send(sender, f"psr:{self.name}", head, blob=blob)
        self.hist_serve.record_s(time.monotonic() - t_serve0)
        tr = _trc.TRACER
        if tr is not None:
            tr.flow("f", _trc.flow_id(f"pull:{self.name}", sender, req),
                    "pull")
            tr.complete("serve", "serve_pull_all", t_serve0,
                        {"from": sender, "rid": req,
                         "rows": int(rows.shape[0])})

    def serve_parked(self) -> None:
        """Re-check parked pulls against the admission rule — called by the
        trainer on every clock/exclusion change (the PendingBuffer drain,
        reference ``Clock → may unpark others' Gets``, SURVEY.md §3.3).
        Also the opportunistic ack-drain point: flush my pending ack
        batches (liveness when no pull reply is flowing to piggyback
        on) and wake any window/drain waiter so in-flight accounting is
        re-read at every clock event, not only when an ack frame
        lands."""
        if self.bus is not None:
            self._flush_acks()
        with self._push_cond:
            self._push_cond.notify_all()
        self._maybe_release_fences(self.router.epoch)  # exclusions advance
        if self._cons is None and self._rb is None \
                and not self._hier_floor:
            return
        # admission is evaluated ONCE per entry: global_min advances
        # concurrently, and a flip between two evaluations must not let an
        # entry fall between "not ready" and "not kept". With the
        # rebalancer on, an entry additionally waits for its blocks'
        # fences — and a parked slice whose keys MOVED AWAY while it
        # waited is refused with the new table instead of served wrong.
        with self._park_lock:
            ready, still, refuse = [], [], []
            for p in self._parked:
                admitted = self._admit_clk(p[3])
                if self._rb is not None:
                    v = (self._pull_all_verdict(p[4]) if p[2] is None
                         else self._pull_verdict(p[2], p[4]))
                    if v == "refuse":
                        refuse.append(p)
                        continue
                    if v == "park" or not admitted:
                        still.append(p)
                        continue
                elif not admitted:
                    still.append(p)
                    continue
                ready.append(p)
            self._parked = still
        # park-duration accounting happens at UNPARK (serve or refuse):
        # a parked request's cost is the time it sat, however it left
        now = time.monotonic()
        tr = _trc.TRACER
        for sender, req, _keys, _clk, _ep, t_park in refuse:
            self.hist_park.record_s(now - t_park)
            if tr is not None:
                tr.complete("serve", "parked", t_park,
                            {"from": sender, "rid": req,
                             "why": "refused"}, t1=now)
            self._send_epoch_nack(sender, req)
        for sender, req, keys, clk, _ep, t_park in ready:
            self.hist_park.record_s(now - t_park)
            if tr is not None:
                tr.complete("serve", "parked", t_park,
                            {"from": sender, "rid": req,
                             "why": "served"}, t1=now)
            if keys is None:
                self._serve_pull_all(sender, req, clk)
            else:
                self._serve_pull(sender, req, keys, clk)

    def _on_pull_reply(self, sender: int, payload: dict) -> None:
        acks = payload.get("acks")
        if acks:  # piggybacked push acks: settle before anything else
            self._settle_acks(acks)
        blob = payload.get("__blob__")
        rid = int(payload.get("req", -1))
        if blob is None:
            self._drop("malformed", sender, "pull reply without blob")
            return
        wire = payload.get("wire", "f32")
        if wire == "int8":
            n = int(payload.get("n", 0))
            if len(blob) != n * (4 + self.dim):
                self._drop("malformed", sender, "bad int8 reply size")
                return
            scale = np.frombuffer(blob[: 4 * n], np.float32)
            codes = np.frombuffer(blob[4 * n:], np.int8).reshape(n,
                                                                 self.dim)
            rows = dequantize_rows_int8(codes, scale)
        else:
            if len(blob) % (4 * self.dim):
                self._drop("malformed", sender, "bad f32 reply size")
                return
            rows = np.frombuffer(blob, np.float32).reshape(-1, self.dim)
        leg = None
        hedge_role = None  # "won" (hedge beat the owner) | "lost"
        with self._reply_cond:
            gid = self._rid_gid.get(rid)
            if gid is None or gid not in self._replies:
                # straggler past its group's death: the stashed issue
                # stamp (if any) turns it into the slowness sample it
                # is — the slow owner's true round trip, which the
                # hedge that out-raced it must not erase
                leg = self._late_t0.pop(rid, None)
            if gid is not None and gid in self._replies:
                # wire accounting counts ACTUAL bytes received
                # (compressed when compressed) — the pull leg's half of
                # bytes/row-moved. Under the lock (the issue side bumps
                # the same counter from the training thread) and only
                # for live requests: a late reply to a cancelled
                # prefetch must not inflate the counter. A loopback
                # reply (self-shed svP, sender == me) crossed no wire.
                # A hedged pair's LOSER still crossed the wire — both
                # replies' bytes count; that duplication IS the cost
                # hedging pays and the B/row accounting must show it.
                if sender != self.rank:
                    self.bytes_pulled += len(blob)
                # hedged legs: the hedge rid maps back to its PRIMARY
                # leg — the reply (whichever wing it rode) satisfies
                # the primary slot. First-ADMISSIBLE-reply-wins is
                # first-reply-wins here: owners park and replicas
                # refuse until `gate.admits` holds, so any reply that
                # exists is admissible; the second one is the loser,
                # discarded by its rid.
                grp = self._groups.get(gid)
                hmap = grp.get("hedges") if grp is not None else None
                prim = hmap.get(rid, rid) if hmap else rid
                leg = self._leg_t0.pop(rid, None)
                if prim in self._replies[gid]:
                    self._rid_gid.pop(rid, None)
                    self._hedges_live.discard(rid)
                    if leg is not None and hmap \
                            and (rid in hmap
                                 or prim in (grp.get("hedged") or ())):
                        # the hedged pair's second wing — discarded by
                        # rid, counted AT MOST ONCE per pair: `leg`
                        # non-None means this is the wing's FIRST
                        # arrival (the t0 stamp pops exactly once), so
                        # a chaos-DUPLICATED reply of either wing can
                        # never inflate `lost` past `fired`.
                        self.hedge_counters["lost"] += 1
                        hedge_role = "lost"
                else:
                    self._replies[gid][prim] = (
                        rows, int(payload.get("stamp", 0)), payload)
                    self._reply_t[gid] = time.monotonic()
                    if prim != rid:
                        self._hedges_live.discard(rid)
                        self.hedge_counters["won"] += 1
                        hedge_role = "won"
                    self._reply_cond.notify_all()
        if leg is not None:
            if self._slowness is not None and sender != self.rank:
                # the per-peer service-latency feed: issue -> reply,
                # attributed to the rank that actually replied (a
                # hedged pair feeds BOTH wings — the slow owner's
                # eventual reply records the true tail that indicts it)
                self._slowness.note(sender, time.monotonic() - leg[0])
            tr = _trc.TRACER
            if tr is not None:
                tr.complete("pull", "pull_leg", leg[0],
                            {"owner": leg[1], "rid": rid,
                             "bytes": len(blob),
                             **({"hedge": hedge_role}
                                if hedge_role else {})})

    def _on_epoch_nack(self, sender: int, payload: dict) -> None:
        """Client side of the pull-leg epoch fence: the owner I routed a
        slice to no longer owns some of its keys — it refused the WHOLE
        leg and sent its routing table. The leg re-routes IMMEDIATELY
        using the refusal's table (progress must not wait for my next
        tick), but table ADOPTION itself is deferred to the training
        thread (tick / finalize / the pull-wait poll): adoption sends
        the rbA whose per-link ordering promises 'no more stale pushes
        from me', and this handler runs on the bus receive thread —
        concurrent with a possibly mid-flight old-table push send, so
        an ack from HERE could overtake that push and release a fence
        early. Keys the new table makes LOCAL join the group's
        extra-local set and are read at wait() time, under the same
        fence rules."""
        rid = int(payload.get("req", -1))
        ep = int(payload.get("ep", 0))
        ov = {int(b): int(o) for b, o in
              zip(payload.get("ovb", ()), payload.get("ovo", ()))}
        if self._rb is not None and ep > self.router.epoch:
            note = getattr(self._rb, "note_plan", None)
            if note is not None:
                note(self.name, ep, ov)  # training thread adopts it
        sends: list[tuple[int, int, int, np.ndarray]] = []
        tr = _trc.TRACER
        with self._reply_cond:
            gid = self._rid_gid.pop(rid, None)
            self._leg_t0.pop(rid, None)  # refused leg: span abandoned
            self._hedges_live.discard(rid)  # a refused hedge twin's
            #                                 budget slot frees here
            grp = self._groups.get(gid) if gid is not None else None
            if grp is None:
                return  # finished/cancelled group: nothing to re-route
            leg = grp["legs"].pop(rid, None)
            if leg is None:
                return
            _old_owner, idx = leg
            keys = grp["uniq"][idx]
            if ep >= self.router.epoch:  # route by the fresher table
                owners = self.router.shard_of_with(keys, ov)
            else:
                owners = self._owners_of(keys)
            for o in np.unique(owners):
                m = owners == o
                if o == self.rank:
                    grp["extra_local"].append(idx[m])
                    continue
                rid2 = self._next_req()
                grp["legs"][rid2] = (int(o), idx[m])
                self._rid_gid[rid2] = gid
                self.bytes_pulled += keys[m].nbytes
                self._leg_t0[rid2] = (time.monotonic(), int(o))
                sends.append((int(o), rid2, grp["clk"], keys[m]))
            self._reply_cond.notify_all()
        if tr is not None:
            tr.instant("serve", "pull_releg",
                       {"rid": rid, "ep": ep, "relegs": len(sends)})
        for o, rid2, clk, kslice in sends:
            if tr is not None:
                tr.flow("s",
                        _trc.flow_id(f"pull:{self.name}",
                                     self.rank, rid2),
                        "pull", {"owner": o, "rid": rid2})
            self.bus.send(o, f"psG:{self.name}",
                          {"req": rid2, "clk": clk, **self._ep_header(),
                           **self._cfg_header()}, blob=_as_blob(kslice))

    def _resend_leg(self, rid: int, plan) -> None:
        """Detach live wire leg ``rid`` (no reply yet) and re-issue its
        keys as fresh legs — the serving plane's fallback/redirect
        primitive (the epoch-nack re-router above is the hand-rolled
        sibling). ``plan(keys) -> [(target, kind, extra_head, mask)]``
        with boolean masks partitioning the leg's keys; a target equal
        to this rank joins the group's extra-local set and is read at
        ``wait()``. A leg already answered/cancelled is a no-op (late
        svB timers, crossed refusals)."""
        sends: list[tuple] = []
        tr = _trc.TRACER
        with self._reply_cond:
            gid = self._rid_gid.pop(rid, None)
            self._leg_t0.pop(rid, None)
            self._hedges_live.discard(rid)  # an svN-refused hedge twin
            #                                 dies here (leg is None
            #                                 below — primary still out)
            grp = self._groups.get(gid) if gid is not None else None
            if grp is None:
                return
            leg = grp["legs"].pop(rid, None)
            if leg is None:
                return
            _old, idx = leg
            keys = grp["uniq"][idx]
            for target, kind, extra, mask in plan(keys):
                if not mask.any():
                    continue
                if target == self.rank and kind == "psG":
                    # owner reads of my own shard never need a frame;
                    # a non-psG self target (the serve plane's svP
                    # self-shed) is a REAL leg riding the transport's
                    # in-process loopback lane — the plan only names
                    # it on a loopback-capable bus
                    grp["extra_local"].append(idx[mask])
                    continue
                rid2 = self._next_req()
                grp["legs"][rid2] = (int(target), idx[mask])
                self._rid_gid[rid2] = gid
                if target != self.rank:  # loopback legs cross no wire
                    self.bytes_pulled += keys[mask].nbytes
                self._leg_t0[rid2] = (time.monotonic(), int(target))
                sends.append((int(target), kind, rid2, grp["clk"],
                              keys[mask], extra))
            self._reply_cond.notify_all()
        for target, kind, rid2, clk, kslice, extra in sends:
            if tr is not None:
                tr.flow("s", _trc.flow_id(f"pull:{self.name}",
                                          self.rank, rid2),
                        "pull", {"owner": target, "rid": rid2})
            self.bus.send(target, f"{kind}:{self.name}",
                          {"req": rid2, "clk": clk, **extra,
                           **self._ep_header(), **self._cfg_header()},
                          blob=_as_blob(kslice))

    # --------------------------------------------------------- client side
    def bind_consistency(self, cons) -> None:
        """Attach the trainer's admission rule (server-side SSP gate)."""
        self._cons = cons

    @property
    def frames_dropped(self) -> int:
        return sum(self.drops.values())

    def check_fatal(self) -> None:
        """Raise if a config-mismatched peer frame poisoned this table —
        called from the trainer's tick so a bad relaunch fails within one
        step instead of silently discarding that peer's gradients.
        Flight: RECORD-only (no dump) — this runs under _push_cond in
        the enqueue backpressure loop, and the raise propagates to a
        path that dumps lock-free (finalize's dump_now, atexit)."""
        if self._fatal is not None:
            _fl.record("table_fatal",
                       {"table": self.name, "why": self._fatal[:200]})
            raise RuntimeError(self._fatal)

    def _my_clk(self) -> int:
        return self._cons.clock if self._cons is not None else 0

    def _cache_staleness(self) -> float:
        """The staleness bound the cache's validity predicate runs under
        — the TENANT's own ``s`` when one is spec'd (every per-table
        consumer routes through here: cache validity, replica serve
        admission, reply-stamp staleness accounting), else the
        trainer's; 0 (BSP, the strictest) when none is bound."""
        if self._tenant is not None and self._tenant.s is not None:
            return self._tenant.s
        return getattr(self._cons, "staleness", 0) \
            if self._cons is not None else 0

    def cache_age(self) -> None:
        """Drop cache rows that can never be admitted again (tick)."""
        if self._cache is not None:
            self._cache.age(self._my_clk(), self._cache_staleness())

    def cache_clear(self) -> None:
        """Drop the whole cache (finalize: post-finalize agreement is
        exact, not staleness-bounded — a cached row must not outlive
        the quiesce)."""
        if self._cache is not None:
            self._cache.clear()

    def cache_stats(self) -> Optional[dict]:
        return self._cache.stats() if self._cache is not None else None

    def _cache_on_push(self, keys: np.ndarray, deltas: np.ndarray,
                       sorted_keys: np.ndarray) -> None:
        """Keep read-your-own-writes across the cache, ON THE PUSHING
        THREAD (before an async enqueue — a pull issued right after
        push() must already see the maintenance). ``keys``/``deltas``
        are the aligned unique pairs ``push()`` computed (summed when
        the batch had duplicates, the original pairing when it did
        not); ``sorted_keys`` is the same key set sorted, for the
        journal. sgd over a float32 DEDUPED push wire write-throughs
        the exact additive delta the server will apply (the SAME
        summed rows ride the wire, so cache and server move in bitwise
        lock-step); stateful updaters, quantized pushes, and the
        per-occurrence wire (``push_dedup=False`` — the server re-sums
        in f32 there, last-ulp different from our f64 bincount)
        invalidate instead — the client cannot reproduce the server's
        step bit-for-bit. Every op is journaled in the push log so
        in-flight pulls' inserts can drop the keys it touched (see
        __init__)."""
        if self.updater == "sgd" and self.push_comm == "float32" \
                and self.push_dedup:
            self._cache.write_through(keys, -self.lr * deltas)
        else:
            self._cache.invalidate(keys)
        with self._cache_log_lock:
            if self._cache_open:  # journal only while pulls in flight
                self._cache_log.append((self._cache_epoch, sorted_keys))
                if len(self._cache_log) > 1024:
                    # leaked futures (never waited/cancelled) would pin
                    # the log forever; drop it and poison pre-floor
                    # inserts instead (they skip — safe, just cold)
                    self._cache_log.clear()
                    self._cache_broken_floor = self._cache_epoch
            self._cache_epoch += 1

    def _cache_note_issue(self, fut: "PullFuture") -> None:
        with self._cache_log_lock:
            fut._issue_epoch = self._cache_epoch
            self._cache_open[id(fut)] = self._cache_epoch

    def _cache_close_issue(self, fut: "PullFuture") -> None:
        with self._cache_log_lock:
            self._cache_open.pop(id(fut), None)
            floor = min(self._cache_open.values(),
                        default=self._cache_epoch)
            self._cache_log = [e for e in self._cache_log
                               if e[0] >= floor]

    def _cache_insert_guarded(self, fut: "PullFuture", keys: np.ndarray,
                              rows: np.ndarray, stamp: int) -> None:
        """Insert freshly-fetched rows, DROPPING any key a push touched
        between the pull's issue and now: the reply may predate the
        push at the owner (immediate serve) or already include it
        (parked serve after the push applied) — the client cannot tell
        which, so the ambiguous row is not cached at all. The future's
        RESULT is untouched (a pull returns whatever the owner served);
        only the cache refuses rows it cannot certify."""
        with self._cache_log_lock:
            if fut._issue_epoch <= self._cache_broken_floor:
                return  # log overflowed past this pull: no safe insert
            entries = [e for e in self._cache_log
                       if e[0] >= fut._issue_epoch]
        if entries:
            keep = np.ones(keys.size, bool)
            for _, ek in entries:  # ek sorted unique (np.unique)
                pos = np.clip(np.searchsorted(ek, keys), 0, ek.size - 1)
                keep &= ek[pos] != keys
            if not keep.any():
                return
            if not keep.all():
                keys, rows = keys[keep], rows[keep]
        self._cache.insert(keys, rows, stamp)
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("pull", "cache_insert",
                       {"n": int(keys.size), "stamp": int(stamp)})

    def _next_req(self) -> int:
        with self._req_lock:
            self._req += 1
            return self._req

    def _missing_legs_locked(self, gid: int) -> dict[int, int]:
        """Outstanding legs of a pull group: ``rid -> target`` for
        every leg without a reply. Own-shard reads never REGISTER a leg
        (they ride ``extra_local``), so a registered self-rank leg here
        is a loopback leg (the serve plane's svP self-shed) and is
        awaited like any other. Caller holds the reply cond."""
        grp = self._groups.get(gid)
        if grp is None:
            return {}
        got = self._replies.get(gid, {})
        return {rid: o for rid, (o, _i) in grp["legs"].items()
                if rid not in got}

    def _release_hedges_locked(self, grp: dict) -> None:
        """Drop a dying/completed group's hedge twins: a hedge whose
        reply never came must release its budget slot and its rid
        mapping (a late reply then drops at the gid lookup, the same
        path as any post-cleanup straggler). Caller holds the cond."""
        for hrid in grp.get("hedges") or ():
            self._rid_gid.pop(hrid, None)
            self._stash_late_locked(hrid)
            self._leg_t0.pop(hrid, None)
            self._hedges_live.discard(hrid)

    def _stash_late_locked(self, rid: int) -> None:
        """Keep an unanswered leg's issue stamp past its group's death
        so the LATE reply still feeds the slowness monitor (the slow
        owner's true round trip — see ``_late_t0``). Bounded: oldest
        evicted; only armed when a detector is bound."""
        if self._slowness is None:
            return
        t0 = self._leg_t0.get(rid)
        if t0 is None:
            return
        if len(self._late_t0) >= 512:
            self._late_t0.pop(next(iter(self._late_t0)))
        self._late_t0[rid] = t0

    def _cleanup_group_locked(self, gid: int) -> None:
        self._replies.pop(gid, None)
        self._reply_t.pop(gid, None)
        grp = self._groups.pop(gid, None)
        if grp is not None:
            for rid in grp["legs"]:
                self._rid_gid.pop(rid, None)
                self._stash_late_locked(rid)
                self._leg_t0.pop(rid, None)
            self._release_hedges_locked(grp)

    def _take_group(self, gid: int) -> tuple[dict, list]:
        """Detach a completed group's final leg map + extra-local idx
        lists (the psE re-router may have reshaped both since issue)."""
        with self._reply_cond:
            grp = self._groups.pop(gid, None)
            if grp is None:
                return {}, []
            for rid in grp["legs"]:
                self._rid_gid.pop(rid, None)
                # a leg whose slot was satisfied by its hedge twin has
                # NOT replied itself — keep its stamp for the late
                # reply (still in _leg_t0 iff unanswered)
                self._stash_late_locked(rid)
                self._leg_t0.pop(rid, None)
            self._release_hedges_locked(grp)
            return grp["legs"], grp["extra_local"]

    # ------------------------------------------------------- hedged legs
    def _hedge_delay_s(self) -> float:
        """The hedge delay: a fixed ``delay_ms`` when pinned, else the
        p99-derived delay — ``factor`` x the WINDOWED pull-latency p99
        (obs/window.py via the bound trainer), floored at ``min_ms``.
        The floor is what keeps armed-idle runs hedge-free: loopback
        legs answer orders of magnitude under it (SLOW-IDLE)."""
        cfg = self._hedge
        if cfg.delay_ms > 0:
            return cfg.delay_ms / 1e3
        p99 = None
        ow = getattr(self._cons, "obs_window", None)
        if ow is not None:
            p99 = ow.quantile_ms("pull_latency", 0.99)
        if p99 is None:
            return cfg.min_ms / 1e3
        return max(cfg.min_ms, cfg.factor * p99) / 1e3

    def _slow_verdicts(self) -> set[int]:
        """Current fleet slow verdicts (quorum-corroborated, membership
        plane) — a leg aimed at one hedges at the ``min_ms`` FLOOR
        instead of the p99-derived delay (which the sick rank's own
        tail has inflated). Not at zero: a hedge fired the instant of
        issue races the holder's refresh stamp and buys a guaranteed
        svN refusal + fallback (measured — the verdicted arm's p99
        went BACK to the unmitigated tail). Empty without the
        membership plane."""
        mb = self._mb
        if mb is None:
            return set()
        view = getattr(mb, "slow_view", None)
        return view() if view is not None else set()

    def _hedge_due(self, t0: float, target: int, delay: float,
                   slow: set) -> float:
        if target in slow:
            return t0 + min(delay, self._hedge.min_ms / 1e3)
        return t0 + delay

    def _maybe_hedge(self, gid: int) -> None:
        """Fire hedges for this group's overdue legs. Runs ONLY from
        the pull-wait loop (training/reader thread) — never the bus
        receive thread. One hedge per leg, ``budget`` outstanding per
        table; a leg with no replica holder covering its blocks stays
        unhedged (counted — the honest no-replica limit). With NO
        serve plane attached every overdue leg takes the no_holder
        path — marked, counted, never re-probed — so the wait loop
        cannot busy-wake at the 1ms floor forever."""
        sv = self._sv
        cfg = self._hedge
        now = time.monotonic()
        delay = self._hedge_delay_s()
        slow = self._slow_verdicts()
        sends: list[tuple] = []
        tr = _trc.TRACER
        with self._reply_cond:
            grp = self._groups.get(gid)
            if grp is None or grp.get("uniq") is None:
                return  # gone, or a pull_all group (no key space)
            hedged = grp.setdefault("hedged", set())
            hmap = grp.setdefault("hedges", {})
            got = self._replies.get(gid, {})
            for rid, (target, idx) in list(grp["legs"].items()):
                if rid in got or rid in hedged or rid in hmap:
                    continue  # answered, already hedged, or IS a hedge
                t0 = self._leg_t0.get(rid)
                if t0 is None:
                    continue
                due = self._hedge_due(t0[0], target, delay, slow)
                if now < due:
                    continue
                if len(self._hedges_live) >= cfg.budget:
                    # the budget valve is a LOAD SHED, not a queue:
                    # the denied leg is marked hedged (counted once,
                    # never re-probed) — leaving it eligible would
                    # busy-wake the wait loop at the 1ms floor and
                    # inflate `denied` into a wake count
                    self.hedge_counters["denied"] += 1
                    if self._tenant_tid:
                        with self._serve_lock:
                            self.tenant_counters["hedge_denied"] += 1
                    hedged.add(rid)
                    continue
                keys = grp["uniq"][idx]
                holder = (sv.hedge_holder(
                    keys, exclude={int(target), self.rank})
                    if sv is not None else None)
                if holder is None:
                    self.hedge_counters["no_holder"] += 1
                    hedged.add(rid)  # don't re-probe every wake
                    continue
                rid2 = self._next_req()
                hmap[rid2] = rid
                hedged.add(rid)
                self._rid_gid[rid2] = gid
                self._hedges_live.add(rid2)
                self._leg_t0[rid2] = (now, int(holder))
                self.bytes_pulled += keys.nbytes
                self.hedge_counters["fired"] += 1
                sends.append((int(holder), rid2, grp["clk"], keys,
                              int(target)))
        for holder, rid2, clk, kslice, slow_tgt in sends:
            # the hedge rides the svP wire under the SAME clk stamp as
            # the primary — the holder's `admits(stamp, clk, s)` is the
            # identical predicate the owner's park runs, so whichever
            # reply wins satisfies the same staleness bound
            _fl_rec = _fl.FLIGHT
            if _fl_rec is not None:
                fired = self.hedge_counters["fired"]
                if fired <= 8 or fired % 32 == 0:
                    # sampled like sv_shed: a long drill's hedges must
                    # not rotate the post-mortem ring, the cumulative
                    # count in each entry carries the true volume
                    _fl_rec.ev("hedge_fired",
                               {"table": self.name, "owner": slow_tgt,
                                "holder": holder, "rid": rid2,
                                "fired_total": fired})
            if tr is not None:
                tr.instant("pull", "hedge_fired",
                           {"owner": slow_tgt, "holder": holder,
                            "rid": rid2})
                tr.flow("s", _trc.flow_id(f"pull:{self.name}",
                                          self.rank, rid2),
                        "pull", {"owner": holder, "rid": rid2})
            self.bus.send(holder, f"svP:{self.name}",
                          {"req": rid2, "clk": clk,
                           **self._ep_header(), **self._cfg_header()},
                          blob=_as_blob(kslice))

    def _hedge_wait_s(self, gid: int) -> float:
        """Time until the EARLIEST unhedged leg of ``gid`` comes due —
        the wait-loop's timeout so a hedge fires on schedule instead
        of at the next 0.5 s poll. Caller holds the reply cond."""
        grp = self._groups.get(gid)
        if grp is None or grp.get("uniq") is None:
            return 0.5
        delay = self._hedge_delay_s()
        slow = self._slow_verdicts()
        got = self._replies.get(gid, {})
        hedged = grp.get("hedged") or ()
        hmap = grp.get("hedges") or {}
        now = time.monotonic()
        best = 0.5
        for rid, (target, _idx) in grp["legs"].items():
            if rid in got or rid in hedged or rid in hmap:
                continue
            t0 = self._leg_t0.get(rid)
            if t0 is None:
                continue
            due = self._hedge_due(t0[0], target, delay, slow)
            best = min(best, max(due - now, 0.001))
        return best

    def _await_replies(self, gid: int,
                       timeout: Optional[float] = None) -> dict:
        deadline = time.monotonic() + (self.pull_timeout
                                       if timeout is None else timeout)
        while True:
            with self._reply_cond:
                if not self._missing_legs_locked(gid):
                    return self._replies.pop(gid)
                self._reply_cond.wait(
                    timeout=(self._hedge_wait_s(gid)
                             if self._hedge is not None else 0.5))
                miss = self._missing_legs_locked(gid)
                if not miss:
                    return self._replies.pop(gid)
                owners = set(miss.values())
            if self._hedge is not None:
                # hedge overdue legs BEFORE the adoption/death checks:
                # this thread is the pull waiter (training or storm
                # reader), never the bus receive thread — the send-from-
                # recv-thread deadlock class stays impossible here
                self._maybe_hedge(gid)
            # ---- lock released: adoption / monitor / deadline. This
            # runs on the TRAINING thread — the one context where table
            # adoption is race-free against the push path — and a
            # refused leg re-routed mid-migration may be PARKED at its
            # new owner waiting for exactly this rank's adoption ack,
            # so the wait loop must adopt pending plans to make
            # progress (not only tick())
            if self._rb is not None:
                self._rb.adopt_now()
            if self._mb is not None:
                self._mb.poll()  # coordinator: issue a blocking death
            self._hier_poll()  # leader death mid-pull: fall back here
            dead = (set(self.monitor.check())
                    if self.monitor is not None else set())
            dead_owned = dead & owners
            if dead_owned:
                fatal = self._fatal_dead(dead_owned)
                if fatal:
                    with self._reply_cond:
                        self._cleanup_group_locked(gid)
                    _fl.poison("pull_peer_failure",
                               {"table": self.name,
                                "dead": sorted(fatal)})
                    raise PeerFailureError(fatal)
                # survivable death (elastic membership): once the death
                # plan re-homed the corpse's keys, its legs re-issue by
                # the current table; until then keep waiting (bounded
                # by this wait's own deadline)
                self._reroute_dead_legs(gid, dead_owned)
            if time.monotonic() > deadline:
                with self._reply_cond:
                    self._cleanup_group_locked(gid)
                _fl.poison("pull_deadline",
                           {"table": self.name,
                            "owners": sorted(int(o) for o in owners)})
                raise TimeoutError(
                    f"pull({self.name}): owners {sorted(owners)} "
                    "never replied")

    def _read_local(self, gkeys: np.ndarray, clk: int,
                    timeout: Optional[float] = None) -> np.ndarray:
        """Read rows of ``gkeys`` from the LOCAL shard (PullFuture's
        local leg). Seed path: a direct slab gather. With the
        rebalancer on this must honor the same rules a remote owner
        would: blocks fenced or state-pending WAIT (a fenced serve
        could be staler than the bound), and keys that migrated AWAY
        since issue round-trip to their current owner."""
        if self._rb is None:
            self._count_serve(pull_rows=gkeys.size)
            with self._state_lock:
                return self._w[gkeys - self.shard_lo]
        deadline = time.monotonic() + (self.pull_timeout
                                       if timeout is None else timeout)
        t_fence0: Optional[float] = None  # first time the read blocked

        def _trace_fence_wait() -> None:
            tr = _trc.TRACER
            if tr is not None and t_fence0 is not None:
                tr.complete("pull", "fence_wait", t_fence0,
                            {"n": int(gkeys.size)})
        while True:
            # adopt pending plans BEFORE re-evaluating fences (outside
            # the cond — adopt_table takes it): a fence whose releaser
            # died opens only at the death plan's adoption, and that
            # adoption happens on this thread; both calls are no-ops
            # off the driving thread / with nothing pending
            if self._rb is not None:
                self._rb.adopt_now()
            if self._mb is not None:
                self._mb.poll()
            with self._mig_cond:
                owners = self.router.shard_of(gkeys)
                mine = owners == self.rank
                blocked = False
                if mine.any() and (self._fenced or self._pending_state):
                    bl = {int(x) for x in
                          np.unique(self.router.blocks_of(gkeys[mine]))}
                    blocked = bool(bl & (self._fenced.keys()
                                         | self._pending_state.keys()))
                if blocked:
                    if t_fence0 is None:
                        t_fence0 = time.monotonic()
                    if time.monotonic() > deadline:
                        _trace_fence_wait()
                        break  # poison + raise BELOW, outside the lock
                    self._mig_cond.wait(timeout=0.1)
                    continue
                if mine.all():
                    _trace_fence_wait()
                    self._count_serve(pull_rows=gkeys.size)
                    self._heat.touch(self.router.blocks_of(gkeys))
                    with self._state_lock:
                        return self._read_rows_locked(gkeys)
            _trace_fence_wait()
            t_fence0 = None
            # some keys are not mine under MY CURRENT table. Two very
            # different cases hide here:
            #
            # (a) my table is BEHIND — a psE refusal re-routed these
            #     keys into the local set under a PENDING newer table
            #     this rank has not adopted yet. Re-issuing now would
            #     route by the stale table, be refused straight back
            #     into this local set, and recurse without bound (the
            #     wait->_read_local->wait mutual recursion blew the
            #     stack under the serving plane's replica-miss
            #     traffic, which hits this window constantly). Adopt
            #     the pending plan first (push-driving thread), or
            #     WAIT for the driving thread's adoption (reader
            #     threads — adopt_now is thread-guarded), then
            #     re-evaluate ownership.
            # (b) the keys genuinely migrated away since issue and my
            #     table is current: round-trip to the real owner.
            if self._rb is not None:
                self._rb.adopt_now()  # no-op off the driving thread
                pend = getattr(self._rb, "has_pending", None)
                if pend is not None and pend(self.name):
                    if time.monotonic() > deadline:
                        _fl.poison("adopt_deadline",
                                   {"table": self.name})
                        raise TimeoutError(
                            f"pull({self.name}): routing table "
                            "adoption never caught up mid-migration")
                    time.sleep(0.005)
                    continue
                with self._mig_cond:
                    if not np.array_equal(
                            self.router.shard_of(gkeys), owners):
                        continue  # adoption changed routing: re-check
            out = np.empty((gkeys.size, self.dim), np.float32)
            out[~mine] = self._issue_pull(gkeys[~mine], clk).wait(
                timeout=max(deadline - time.monotonic(), 0.1))
            out[mine] = self._read_local(gkeys[mine], clk,
                                         max(deadline - time.monotonic(),
                                             0.1))
            return out
        # only the fence-deadline break reaches here — the flight dump
        # (file I/O + the windowed snapshot hook) must not run under
        # _mig_cond: a reliable-dispatched handler may be waiting on it
        _fl.poison("fence_deadline", {"table": self.name})
        raise TimeoutError(
            f"pull({self.name}): local rows fenced mid-migration and "
            "never released")

    def _wait_local_admission(self, clk: int,
                              timeout: Optional[float] = None) -> None:
        """Block until MY admission view serves clock ``clk`` — the local
        shard's twin of the owner-side park. Synchronous pulls pass
        instantly (their gate already waited); prefetches stamped ahead
        wait here only if consumed before the staleness rule catches up."""
        if self._admit_clk(clk):
            return
        wait_fn = getattr(self._cons, "wait_admit_pull", None)
        deadline = time.monotonic() + (self.pull_timeout
                                       if timeout is None else timeout)
        while not self._admit_clk(clk):
            self._hier_poll()  # a dead leader blocks floors, not clocks
            if wait_fn is not None and not (
                    self._cons is None or self._cons.admit_pull(clk)):
                wait_fn(clk, timeout=0.5)
            else:
                # the gossip min already admits — the hier floor is the
                # blocker, and floor advances land on the recv thread
                # with no condvar to wake this one: short poll
                time.sleep(0.002)
            if self._admit_clk(clk):
                return
            dead = self._fatal_dead(
                self.monitor.check()
                if self.monitor is not None else set())
            if dead:
                _fl.poison("pull_peer_failure",
                           {"table": self.name, "dead": sorted(dead),
                            "where": "local_admission"})
                raise PeerFailureError(dead)
            if time.monotonic() > deadline:
                _fl.poison("admission_deadline",
                           {"table": self.name, "clk": int(clk)})
                raise TimeoutError(
                    f"pull({self.name}): local admission for clock "
                    f"{clk} never opened")

    def _issue_pull(self, keys: np.ndarray, clk: int) -> PullFuture:
        """Send the per-owner UNIQUE-key slices for ``keys`` stamped
        ``clk`` and return the future. Duplicates never ride the wire
        (scatter by ``return_inverse`` at ``wait()``), rows the cache
        can serve under ``admits(stamp, clk, s)`` never ride it either
        — only true misses do. The local slice is read at ``wait()``
        time."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        if self.pull_dedup:
            uniq, inv = np.unique(keys, return_inverse=True)
        else:  # the verbatim seed wire (bench A/B arm; cache refused)
            uniq, inv = keys, None
        owners = self._owners_of(uniq)
        out_u = np.empty((uniq.size, self.dim), np.float32)
        need = np.ones(uniq.size, bool)  # rows still to fetch over wire
        local_idx = None
        lmask = owners == self.rank
        if lmask.any():
            local_idx = np.nonzero(lmask)[0]
            need[lmask] = False
        if self._sv is not None and need.any():
            # zero-wire replica read: keys whose block THIS rank holds
            # as a replica (live lease, stamp admits clk) serve from
            # the local snapshot — no leg, no frame (serve/plane.py)
            self._sv.serve_local(uniq, out_u, need, clk)
        hits = lookups = 0
        if self._cache is not None and need.any():
            ridx = np.nonzero(need)[0]
            rows, miss = self._cache.lookup(uniq[ridx], clk,
                                            self._cache_staleness())
            lookups = ridx.size
            hit_idx = ridx[~miss]
            hits = hit_idx.size
            if hits:
                out_u[hit_idx] = rows[~miss]
                need[hit_idx] = False
        # client-side replica fan-out (serve plane): keys in replicated
        # hot blocks may route to a replica holder instead of the owner
        # — a REPLICA leg rides the svP wire (the holder serves from
        # its snapshot or refuses and the leg falls back to the owner)
        targets, rep_mask = owners, None
        if self._sv is not None and need.any():
            targets, rep_mask = self._sv.route_targets(uniq, owners,
                                                       need)
        remote: list[tuple[int, np.ndarray]] = []
        rep_legs: set[int] = set()  # positions in `remote` riding svP
        wire_rows = 0
        for o in range(self.num_processes):
            tmask = need & (targets == o)
            if not tmask.any():
                continue
            if rep_mask is None:
                remote.append((o, np.nonzero(tmask)[0]))
                continue
            for isrep in (False, True):  # owner + replica legs split:
                m = tmask & (rep_mask == isrep)  # different wire kinds
                if m.any():
                    if isrep:
                        rep_legs.add(len(remote))
                    remote.append((o, np.nonzero(m)[0]))
        gid = 0  # a fully-local pull (own shard + cache hits) allocates
        if remote:  # no request slot and touches no wire state at all
            gid = self._next_req()
            with self._reply_cond:
                self._replies[gid] = {}
                grp = {"clk": clk, "uniq": uniq, "legs": {},
                       "extra_local": []}
                self._groups[gid] = grp
            tr = _trc.TRACER
            for li, (o, idx) in enumerate(remote):
                # one wire request id PER LEG, registered BEFORE the
                # send (a reply must never beat its bookkeeping); the
                # psE re-router re-splits a refused leg mid-flight
                rid = self._next_req()
                kslice = uniq[idx]
                with self._reply_cond:
                    grp["legs"][rid] = (o, idx)
                    self._rid_gid[rid] = gid
                    # under the reply lock: replies land on the receive
                    # thread and bump the same counter (non-atomic RMW)
                    self.bytes_pulled += kslice.nbytes
                    self._leg_t0[rid] = (time.monotonic(), o)
                if tr is not None:
                    tr.flow("s",
                            _trc.flow_id(f"pull:{self.name}",
                                         self.rank, rid),
                            "pull", {"owner": o, "rid": rid})
                kind = "svP" if li in rep_legs else "psG"
                self.bus.send(o, f"{kind}:{self.name}",
                              {"req": rid, "clk": clk,
                               **self._ep_header(), **self._cfg_header()},
                              blob=_as_blob(kslice))
                wire_rows += idx.size
        self.timers.record_pull_rows(requested=keys.size, wire=wire_rows,
                                     hits=hits, lookups=lookups)
        fut = PullFuture(self, gid, keys, uniq, inv, out_u, remote,
                         local_idx, clk)
        if self._cache is not None and remote:
            self._cache_note_issue(fut)  # push-log replay anchor
        return fut

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Gather rows for global ``keys`` from their owners —
        KVClientTable::Pull with RangeManager routing (SURVEY.md §3.3).
        A pending ``prefetch_pull`` for the SAME keys is consumed instead
        of issuing a second round trip — but only if its clock stamp is
        current: a dangling prefetch from an earlier step was admitted
        under an OLDER global-min view, and consuming it now would read
        rows staler than a synchronous pull at my present clock is
        allowed to see. A stale stamp is cancelled and the pull
        round-trips fresh — the staleness bound outranks the saved
        RTT."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        with self._prefetch_lock:
            fut = self._prefetched.pop(keys.tobytes(), None)
        if fut is not None:
            if fut.clk >= self._my_clk():
                return fut.wait()
            fut.cancel()
        return self._issue_pull(keys, self._my_clk()).wait()

    def _serving_clk(self) -> int:
        c = getattr(self._cons, "gated_clock", None)
        return int(c) if c is not None else self._my_clk()

    def pull_serving(self, keys: np.ndarray) -> np.ndarray:
        """Read-only client pull at the last GATED clock — the serving
        plane's read clock (docs/serving.md). A training pull stamps
        the IN-FLIGHT clock, which nobody fleet-wide has proven
        admissible yet: owners park it until gossip catches up and
        replicas refuse it — correct, but the read pays a wait either
        way. The last gated clock is the newest stamp whose admission
        the local gate already PROVED (``global_min >= gated − s`` held
        when its tick completed), so owners serve it immediately and
        replica snapshots refreshed at the same boundary admit it —
        the read still sees every peer's updates through
        ``gated_clock − s``, one step behind the trainer's in-flight
        step, which is exactly the SSP serving contract. Falls back to
        the training clock when no trainer is bound."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        return self._issue_pull(keys, self._serving_clk()).wait()

    def prefetch_pull(self, keys: np.ndarray, *,
                      clock_ahead: int = 1) -> PullFuture:
        """Double-buffered pull: issue the NEXT batch's pull now, stamped
        with the clock the consuming step will run at (``_my_clk() +
        clock_ahead``), so owners park it under exactly the admission
        rule a synchronous pull at that step would face — overlap never
        weakens BSP/SSP. Returns the future; a later ``pull()`` with the
        same keys consumes it (or call ``wait()`` directly). One
        registry slot per distinct key set: re-prefetching the same keys
        points the slot at the NEW future, and the displaced one stays
        WAITABLE — the double-buffer pattern holds batch t's future
        while issuing batch t+1's, so two consecutive batches drawing
        byte-identical keys must not invalidate the handle in the
        caller's hand (cancelling it here made ``fut.wait()`` raise)."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        fut = self._issue_pull(keys, self._my_clk() + int(clock_ahead))
        kb = keys.tobytes()
        fut._pf_key = kb
        with self._prefetch_lock:
            old = self._prefetched.get(kb)
            self._prefetched[kb] = fut
        if old is not None:
            old._pf_key = None  # displaced, not cancelled
        return fut

    def pull_all(self) -> np.ndarray:
        """Assemble the full table (dense pulls / finalize / eval): each
        owner ships its shard once — an all-gather over the bus. With
        the rebalancer on, every owner's reply additionally carries its
        migrated-IN blocks, and assembly runs two passes: base shards
        first, then every overlay block over its (stale) home copy —
        the overlay entry is the authoritative one by construction
        (exactly one current owner per block). A rank dying mid-
        assembly (elastic membership) re-issues the whole gather at
        the post-death epoch — survivors' replies then carry the
        restored blocks (owners park future-epoch psA requests until
        their own adoption, so no reply can predate the plan)."""
        for _attempt in range(4):
            try:
                return self._pull_all_once()
            except _ReissuePullAll:
                continue
        _fl.poison("pull_all_churn", {"table": self.name})
        raise TimeoutError(
            f"pull_all({self.name}): shard assembly kept losing owners "
            "mid-gather (membership churn outran the retry budget)")

    def _pull_all_once(self) -> np.ndarray:
        if self._rb is not None:
            self._rb.adopt_now()  # a plan landing post-last-tick still
            self._wait_settled(self.pull_timeout)  # needs my rbA; and my
            # own in-transit blocks must land before I can assemble
        # the assembly's peer set is the CURRENT ROUTING TABLE's owner
        # set, not the gossip view: every row lives at exactly one
        # block owner, so polling the owners covers the table by
        # construction — a rank my table routes nothing to contributes
        # nothing (its home range is in other owners' xtra), and a
        # rank my table DOES route to must be polled even if my gossip
        # hasn't re-included it yet (a freshly-admitted joiner's live
        # announce rides a different link than the admit plan — using
        # the exclusion set here silently dropped its range from the
        # gather in that window). The rb-off path keeps the exclusion
        # rule: no overlay exists to re-home a corpse's rows, and
        # exclusions only appear once a death already doomed the run.
        if self._rb is not None:
            peers = {int(o)
                     for o in np.unique(self.router.owner_of_blocks())
                     } - {self.rank}
        else:
            peers = (set(range(self.num_processes)) - {self.rank}
                     - self._excluded_ranks())
        gid = 0
        legs: dict[int, tuple] = {}
        if peers:
            gid = self._next_req()
            with self._reply_cond:
                self._replies[gid] = {}
                grp = {"clk": self._my_clk(), "uniq": None, "legs": {},
                       "extra_local": []}
                self._groups[gid] = grp
            for o in sorted(peers):
                rid = self._next_req()
                with self._reply_cond:
                    grp["legs"][rid] = (o, None)
                    self._rid_gid[rid] = gid
                self.bus.send(o, f"psA:{self.name}",
                              {"req": rid, "clk": self._my_clk(),
                               **self._ep_header(), **self._cfg_header()})
        out = np.empty((self.part.padded, self.dim), np.float32)
        with self._state_lock:
            out[self.shard_lo:self.shard_lo + self.part.shard_size] = self._w
        self._count_serve(pull_rows=self.part.shard_size)
        if peers:
            # wire bytes are counted at reply receipt (_on_pull_reply),
            # actual bytes — an int8 wire's replies count compressed.
            # Shards deliberately bypass the row cache: a full-table
            # assembly would evict the working set for rows finalize/
            # eval reads once.
            got = self._await_replies(gid)
            legs, _extra = self._take_group(gid)
            for rid, (o, _none) in legs.items():  # pass 1: base shards
                rows = got[rid][0]
                pl = got[rid][2]
                lo = int(pl.get("lo", o * self.part.shard_size))
                nb = int(pl.get("nb", rows.shape[0]))
                out[lo:lo + nb] = rows[:nb]
        if self._rb is not None:
            # pass 2: overlay blocks (peers' and my own) overwrite the
            # stale home-slab copies pass 1 placed
            for rid, (o, _none) in legs.items():
                rows = got[rid][0]
                pl = got[rid][2]
                off = int(pl.get("nb", rows.shape[0]))
                for b, ln in zip(pl.get("xb") or (), pl.get("xl") or ()):
                    blo, _bln = self.router.block_span(int(b))
                    out[blo:blo + int(ln)] = rows[off:off + int(ln)]
                    off += int(ln)
            with self._state_lock:
                for b, st in self._xtra.items():
                    blo, _bln = self.router.block_span(int(b))
                    out[blo:blo + st["w"].shape[0]] = st["w"]
        with self._reply_cond:
            # _await_replies popped the reply map and _take_group the
            # legs; only the arrival timestamp is left to drop
            self._reply_t.pop(gid, None)
        return out[: self.num_rows]

    # ------------------------------------------------------- push pipeline
    def _take_push_seq(self, owner: int) -> int:
        """Claim an in-flight slot (blocks while the window is full) and
        stamp the send time — the ack latency timer's zero point. A full
        window SOLICITS the owners' pending ack batches while it waits:
        batching must never convert into a stall."""
        deadline = time.monotonic() + self.pull_timeout
        poison = None  # (reason, args): dump OUTSIDE _push_cond below
        try:
            with self._push_cond:
                while len(self._inflight) >= self.push_window:
                    if self._dead_ranks:
                        self._drop_dead_inflight_locked()  # sticky
                    self._solicit_acks_locked()
                    self._push_cond.wait(timeout=0.2)
                    if len(self._inflight) < self.push_window:
                        break
                    dead = self._fatal_dead(
                        self.monitor.check()
                        if self.monitor is not None else set())
                    if dead:
                        poison = ("push_peer_failure",
                                  {"table": self.name,
                                   "dead": sorted(dead)})
                        raise PeerFailureError(dead)
                    if time.monotonic() > deadline:
                        poison = ("ack_window_deadline",
                                  {"table": self.name,
                                   "unacked": len(self._inflight)})
                        raise TimeoutError(
                            f"push({self.name}): ack window jammed "
                            f"({len(self._inflight)} unacked)")
                self._push_seq += 1
                self._inflight[self._push_seq] = (time.monotonic(),
                                                  owner)
                return self._push_seq
        except (PeerFailureError, TimeoutError):
            if poison is not None:
                _fl.poison(*poison)
            raise

    def _solicit_acks_locked(self) -> None:
        """Ask every owner holding an unacked frame of mine to flush its
        pending ack batch (caller holds ``_push_cond``). Per-link FIFO:
        the psQ frame trails the frames it wants acked, so the owner's
        pending list already contains their seqs when it lands."""
        for o in {own for _, own in self._inflight.values()}:
            self.bus.send(o, f"psQ:{self.name}", {})

    def _settle_acks(self, seqs) -> None:
        now = time.monotonic()
        settled = []  # (seq, t_sent, owner)
        with self._push_cond:
            for s in seqs:
                rec = self._inflight.pop(int(s), None)
                if rec is not None:
                    settled.append((int(s), rec[0], rec[1]))
            if settled:
                self._push_cond.notify_all()
        tr = _trc.TRACER
        for seq, t0, owner in settled:
            self.timers.record_push_ack(now - t0)
            if self._slowness is not None and owner != self.rank:
                # push-ack lag per owner: the write path's half of the
                # fail-slow service-latency feed (a sick owner acks
                # late even when its beats land on time)
                self._slowness.note(owner, now - t0)
            if tr is not None:
                tr.complete("push", "push_ack", t0,
                            {"owner": owner, "seq": seq}, t1=now)

    def _on_push_ack(self, sender: int, payload: dict) -> None:
        seqs = payload.get("seqs")
        if seqs is None:  # single-seq spelling kept for compatibility
            seq = payload.get("seq")
            seqs = [] if seq is None else [seq]
        self._settle_acks(seqs)

    def _push_loop(self) -> None:
        """Sender thread (async_push): drains the queue, doing the
        per-owner split / codec / serialize / send OFF the training
        thread. A raised send poisons the table (check_fatal at the next
        tick) rather than dying silently on a daemon thread."""
        while True:
            kind, a = self._push_q.get()
            try:
                if kind == "sparse":
                    self._push_now(a[0], a[1], a[2])
                else:
                    self._push_dense_now(a)
            except Exception as e:  # noqa: BLE001 - surfaced via fatal
                if self._fatal is None:
                    self._fatal = (f"table {self.name}: async push "
                                   f"failed: {e!r}")
            finally:
                with self._push_cond:
                    self._q_pending -= 1
                    self._push_cond.notify_all()

    def flush_pushes(self, timeout: Optional[float] = None, *,
                     acks: bool = True) -> None:
        """Drain the async-push pipeline. Two levels:

        ``acks=False`` — the CLOCK-BOUNDARY drain (trainer ``tick()``):
        wait until every enqueued push has been HANDED TO THE BUS. That
        is exactly the barrier BSP/SSP need: the clock frame is emitted
        after all of step ``k``'s push frames on the same ordered
        per-link stream, so an owner whose view says I reached ``k`` has
        already processed those pushes — the identical FIFO argument the
        synchronous path's staleness proof uses (module docstring), at
        microsecond cost instead of an ack round trip per step.

        ``acks=True`` — the HARD drain (``finalize()``, fault drills):
        additionally wait until every in-flight frame is ACKED as
        received by its owner — the loss-detection point. In between,
        ``push_window`` bounds how many frames can ever be unacked.

        A drain that cannot complete (lost ack, wedged owner) POISONS
        the table instead of hanging — the caller's ``check_fatal()``
        raises."""
        if not self.async_push:
            return
        deadline = time.monotonic() + (self.pull_timeout
                                       if timeout is None else timeout)

        def drained() -> bool:
            return not (self._q_pending
                        or (acks and self._inflight))
        poison = None  # (reason, args): dump OUTSIDE _push_cond below
        try:
            with self._push_cond:
                while not drained():
                    if self._dead_ranks:
                        self._drop_dead_inflight_locked()
                        if drained():
                            break
                    if acks and not self._q_pending:
                        # everything is on the wire; batched acks may
                        # be sitting at the owners below their flush
                        # threshold — solicit them (FIFO: the psQ
                        # trails the frames)
                        self._solicit_acks_locked()
                    self._push_cond.wait(timeout=0.2)
                    if drained():
                        break
                    dead = self._fatal_dead(
                        self.monitor.check()
                        if self.monitor is not None else set())
                    if dead:
                        poison = ("drain_peer_failure",
                                  {"table": self.name,
                                   "dead": sorted(dead)})
                        raise PeerFailureError(dead)
                    if time.monotonic() > deadline:
                        if self._fatal is None:
                            self._fatal = (
                                f"table {self.name}: push drain timed "
                                f"out ({self._q_pending} queued, "
                                f"{len(self._inflight)} unacked — "
                                "lost ack or wedged owner)")
                        poison = ("drain_deadline",
                                  {"table": self.name,
                                   "queued": self._q_pending,
                                   "unacked": len(self._inflight)})
                        break  # the caller sees the poisoned table
        except PeerFailureError:
            if poison is not None:
                _fl.poison(*poison)
            raise
        if poison is not None:  # the drain-deadline (non-raising) exit
            _fl.poison(*poison)

    def _enqueue_push(self, kind: str, arg) -> None:
        """Hand one push to the sender thread, with BACKPRESSURE: at most
        ``push_window`` steps may sit unsent in the queue (on top of the
        unacked-frame window the sender itself honors), so a wedged owner
        stalls the training thread here — bounded memory — until the
        sender's own deadline poisons the table and the fatal check below
        raises instead of hanging."""
        self.check_fatal()
        deadline = time.monotonic() + self.pull_timeout
        poison = None  # (reason, args): dump OUTSIDE _push_cond below
        try:
            with self._push_cond:
                while self._q_pending >= self.push_window:
                    self._push_cond.wait(timeout=0.2)
                    self.check_fatal()  # sender poisoned while we wait
                    if self._q_pending < self.push_window:
                        break
                    dead = self._fatal_dead(
                        self.monitor.check()
                        if self.monitor is not None else set())
                    if dead:
                        poison = ("push_peer_failure",
                                  {"table": self.name,
                                   "dead": sorted(dead),
                                   "where": "send_queue"})
                        raise PeerFailureError(dead)
                    if time.monotonic() > deadline:
                        poison = ("send_queue_deadline",
                                  {"table": self.name,
                                   "queued": self._q_pending})
                        raise TimeoutError(
                            f"push({self.name}): send queue jammed "
                            f"({self._q_pending} steps unsent)")
                self._q_pending += 1
        except (PeerFailureError, TimeoutError):
            if poison is not None:
                _fl.poison(*poison)
            raise
        self._push_q.put((kind, arg))

    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        """Route per-owner (keys, grads) slices; owners apply the updater.
        Duplicate keys in one push are summed first (reference Add).
        With ``async_push`` this enqueues (copies, so callers may reuse
        buffers) and returns; the wire work happens on the sender thread
        inside the ack window."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, self.dim)
        n_orig = keys.size
        if self.async_push:
            # cache is None here (the constructor refuses the combo),
            # so no maintenance needs this thread — the coalesce rides
            # the sender thread with the rest of the wire work, keeping
            # the step window clean (the point of async push)
            self._enqueue_push("sparse",
                               (keys.copy(), grads.copy(), n_orig))
            return
        keys, grads = self._coalesce_for_wire(keys, grads)
        self._push_now(keys, grads, n_orig, coalesced=True)

    def _coalesce_for_wire(self, keys: np.ndarray,
                           grads: np.ndarray) -> tuple:
        """Client-side dedup + cache maintenance: duplicate keys
        coalesce to ONE summed row BEFORE the codec, so int8
        quantization error is paid once per row, not once per
        occurrence — and the wire ships each row once. The summed row
        IS what the server applies (deduped frames have nothing left to
        sum), so cache write-through and server state stay bitwise in
        lock-step; vs the seed's unsummed wire the result agrees to f32
        rounding (the per-dim bincount accumulates in f64 — at least as
        accurate as the server's old sequential f32 sum, and ~3x faster
        than np.add.at on this hot path). ``push_dedup=False`` restores
        the per-occurrence seed wire (bench A/B baseline; the server
        still sums). Returns the (keys, grads) to ship."""
        n = keys.size
        if not n or not (self.push_dedup or self._cache is not None):
            return keys, grads
        # the shared coalesce kernel keeps the original (keys[i],
        # grads[i]) pairing when there are no duplicates — uniq is
        # SORTED, and re-pairing grads against it would scramble every
        # gradient-row association (regression-tested:
        # test_push_all_unique_unsorted_keys_pair_correctly)
        ckeys, cdeltas, had_dups = sum_duplicate_keys(keys, grads,
                                                      self.dim)
        if had_dups and self.push_dedup:
            keys, grads = ckeys, cdeltas
        if self._cache is not None:
            self._cache_on_push(ckeys, cdeltas,
                                ckeys if had_dups else np.unique(keys))
        return keys, grads

    def _push_now(self, keys: np.ndarray, grads: np.ndarray,
                  n_rows: Optional[int] = None,
                  coalesced: bool = False) -> None:
        self.rows_pushed += keys.size if n_rows is None else n_rows
        if not coalesced:  # async path: dedup on the sender thread
            keys, grads = self._coalesce_for_wire(keys, grads)
        self._hier_poll()  # election/fallback on the training thread
        owners = self._owners_of(keys)
        for o in range(self.num_processes):
            mask = owners == o
            if not mask.any():
                continue
            if self._mb is not None and o in self._dead_ranks:
                # pre-plan window: the old table still routes here —
                # the corpse can neither apply nor ack; counted lost
                self.rb_stats["pushes_lost_to_dead"] += 1
                continue
            if o == self.rank:
                # local rows never cross a wire — full precision always
                if self._rb is not None:
                    # the classify-under-lock ingest: a concurrent
                    # adoption may have just shipped these rows away
                    self._ingest_push(keys[mask], grads[mask],
                                      self.router.epoch)
                else:
                    self._apply_rows(keys[mask] - self.shard_lo,
                                     grads[mask])
                continue
            lead = self._hier_route(o)
            if lead is not None:
                # level 1: this (worker, owner) pair rides the tree —
                # the slice goes to my host leader (or straight into my
                # own buckets when I am it), exact f32, and the flat
                # encode below never runs for it
                self._hier_contribute(lead, o, keys[mask],
                                      np.ascontiguousarray(
                                          grads[mask], np.float32))
                continue
            overflow = None
            if self.push_comm in ("topk8", "topk4"):
                # the compressed-push pipeline: fold residuals, select
                # top-k rows by mass, blockwise-quantize, retain the
                # unsent remainder (overflow ships dense right after)
                head0, blob, overflow = self._encode_push_topk(
                    keys[mask], np.ascontiguousarray(grads[mask],
                                                     np.float32))
            elif self.push_comm == "int8":
                codes, scale = quantize_rows_int8(grads[mask], self._q_rng)
                head0 = {"n": int(mask.sum()), "comm": "int8"}
                blob = _cat_blob(keys[mask], scale, codes)
            else:
                head0 = {"n": int(mask.sum()), "comm": "float32"}
                blob = _cat_blob(keys[mask], grads[mask])
            head = {**head0, **self._ep_header(), **self._cfg_header()}
            if self.async_push:
                head["seq"] = self._take_push_seq(o)
                tr = _trc.TRACER
                if tr is not None:
                    tr.flow("s", _trc.flow_id(f"push:{self.name}", self.rank,
                                              head["seq"]), "push",
                            {"owner": o, "seq": head["seq"]})
            self.bus.send(o, f"psP:{self.name}", head, blob=blob)
            self.bytes_pushed += len(blob)
            self._hier_count_tx(o, len(blob))
            if overflow is not None and overflow[0].size:
                # residual-slab overflow: mass the store had no room
                # for ships dense NOW — the byte win shrinks under
                # pressure, correctness never does
                self._send_f32_push(o, overflow[0], overflow[1])

    def _encode_push_topk(self, keys: np.ndarray, grads: np.ndarray,
                          birth_clk: Optional[int] = None,
                          ef=None, rng=None
                          ) -> tuple[dict, bytearray, tuple]:
        """One owner slice through the compressed-push pipeline:

        1. FOLD: stored residuals of this slice's keys join the
           gradient (in place — ``grads`` is a fresh fancy-index copy),
           remembering each key's oldest birth clock;
        2. SELECT: ``topk_rows`` keeps the rows carrying ``topk_mass``
           of the squared mass (capped at ``topk_cap`` of the slice) —
           the wire pays for the mass, not the touch set;
        3. ENCODE: blockwise absmax at 8/4 bits, stochastic rounding
           (the same ``_q_rng`` stream as the int8 wire), emitted as an
           index+code stream — int32 indices when the key space fits;
        4. RETAIN: unselected rows whole, plus the selected rows'
           quantization error, under ``min(birth, clock)`` so age
           survives folding. Slab overflow is returned for an
           immediate dense send — mass is conserved unconditionally.

        Returns ``(head fields, blob, (overflow keys, overflow rows))``.

        ``birth_clk`` overrides the residual birth stamp: a hier leader
        encodes an aggregate whose oldest contributor may be BEHIND
        this rank's clock, and the retained error must age from that
        min stamp or the age-flush bound would silently relax.
        """
        clk = self._my_clk() if birth_clk is None else int(birth_clk)
        ef = self._ef if ef is None else ef
        rng = self._q_rng if rng is None else rng
        bits = 8 if self.push_comm == "topk8" else 4
        births = ef.fold(keys, grads)
        births = np.minimum(births, clk)
        sel = topk_rows(grads, mass=self.topk_mass,
                        frac_cap=self.topk_cap)
        selmask = np.zeros(keys.size, bool)
        selmask[sel] = True
        g_sel = grads[sel]
        codes, scales = quantize_blockwise(g_sel, bits,
                                           block=self.topk_block,
                                           rng=rng)
        sent = dequantize_blockwise(codes, scales, sel.size, self.dim,
                                    bits, block=self.topk_block)
        ovk = np.empty(0, np.int64)
        ovr = np.empty((0, self.dim), np.float32)
        k1, r1 = ef.retain(keys[~selmask], grads[~selmask],
                           births[~selmask])
        k2, r2 = ef.retain(keys[sel], g_sel - sent, births[sel])
        if k1.size or k2.size:
            ovk = np.concatenate([k1, k2])
            ovr = np.concatenate([r1, r2])
        khead, kstream = self._key_stream(keys[sel])
        head = {"n": int(sel.size), "comm": self.push_comm,
                "blk": self.topk_block, **khead}
        return head, _cat_blob(kstream, scales, codes), (ovk, ovr)

    def _key_dtype(self):
        """The narrowest index-stream dtype the key space fits — the
        other half of 'index+code streams' (the seed wire's int64 keys
        cost as much as an 8-bit row at dim 8): u16 under 64Ki rows,
        i32 under 2Gi, i64 beyond."""
        if self.num_rows <= 1 << 16:
            return np.uint16
        if self.num_rows <= np.iinfo(np.int32).max:
            return np.int32
        return np.int64

    def _key_stream(self, k: np.ndarray) -> tuple[dict, bytes]:
        """Index stream for SORTED unique keys, at the cheaper of two
        codecs: the sorted-run delta stream (i64 base + gaps at the
        narrowest unsigned width, ops/quantized_comm.encode_key_deltas
        — hot zipf key sets are near-contiguous, so gaps usually fit a
        byte where absolute keys need 2-8) vs the plain narrowest-width
        stream. The head self-describes (``dw`` delta width vs ``kw``
        plain width), so mixed fleets decode per frame like every other
        wire knob."""
        kw = int(np.dtype(self._key_dtype()).itemsize)
        n = int(k.size)
        if n >= 2:
            try:
                dw, stream = encode_key_deltas(k)
            except ValueError:  # not strictly increasing: plain stream
                pass
            else:
                if delta_stream_bytes(n, dw) < n * kw:
                    return {"dw": dw}, stream
        return {"kw": kw}, k.astype(self._key_dtype()).tobytes()

    def _send_f32_push(self, o: int, k: np.ndarray,
                       g: np.ndarray, *,
                       extra_head: Optional[dict] = None) -> None:
        """A plain full-precision push frame to one owner — the
        residual-flush/overflow sender (seq-stamped under async push
        like any other frame, so the drain and ack machinery cover
        it). ``extra_head`` carries hier step tags / floor claims."""
        if self._mb is not None and o in self._dead_ranks:
            self.rb_stats["pushes_lost_to_dead"] += 1
            return
        blob = _cat_blob(k, np.ascontiguousarray(g, np.float32))
        head = {"n": int(k.size), "comm": "float32",
                **self._ep_header(), **self._cfg_header(),
                **(extra_head or {})}
        if self.async_push:
            head["seq"] = self._take_push_seq(o)
        self.bus.send(o, f"psP:{self.name}", head, blob=blob)
        self.bytes_pushed += len(blob)
        self._hier_count_tx(o, len(blob))

    def residual_flush(self, *, aged_only: bool = False,
                       reason: str = "fence") -> int:
        """Ship retained error-feedback mass, routed by the CURRENT
        table (local rows apply locally, full precision).

        ``aged_only`` is the clock-boundary rule (trainer ``tick``,
        BEFORE the clock frame goes out, so flushed frames precede the
        clock on every per-link stream exactly like the async drain):
        flush entries whose birth clock is ``<= clock - s`` — a
        residual may trail its push by at most the staleness bound,
        the RowCache stamp rule mirrored onto the write path (ASP
        never age-flushes: there is no bound to protect). The aged set
        ships DENSE in keys (no top-k selection — every aged row goes)
        but compressed in value: the blockwise 4-bit stream with
        STOCHASTIC rounding, whose quantization error is dropped, not
        re-retained — exactly the int8 wire's unbiased-noise contract
        (E[decoded] = residual), so aged mass is delivered in
        expectation at ~4 bits/element instead of re-aging a
        second-order error forever (zipf tails age out every window;
        an f32 aged flush measurably cost MORE than the int8 wire it
        was supposed to beat).

        The full flush (``aged_only=False``) is EXACT f32: it runs at
        every epoch fence (``adopt_table``, before the router swap —
        flushed frames ride the old table's links AHEAD of my rbA, so
        fences release only after the mass landed), at membership
        drains, and at ``finalize()`` — post-finalize agreement and
        the migration oracle drills are bitwise, not in-expectation.
        Returns rows flushed."""
        if self._ef is None:
            return 0
        if aged_only:
            s = self._cache_staleness()
            if s == float("inf"):
                return 0
            keys, rows = self._ef.take(self._my_clk() - int(s))
        else:
            keys, rows = self._ef.take()
        if not keys.size:
            return 0
        self._ef.note_flushed(int(keys.size),
                              "age" if aged_only else reason)
        owners = self._owners_of(keys)
        for o in np.unique(owners):
            m = owners == o
            if int(o) == self.rank:
                if self._rb is not None:
                    self._ingest_push(keys[m], rows[m],
                                      self.router.epoch)
                else:
                    self._apply_rows(keys[m] - self.shard_lo, rows[m])
            elif aged_only:
                self._send_blk4_push(int(o), keys[m], rows[m])
            else:
                self._send_f32_push(int(o), keys[m], rows[m])
        return int(keys.size)

    def _send_blk4_push(self, o: int, k: np.ndarray,
                        g: np.ndarray) -> None:
        """The aged-flush frame: the same topk4 index+code stream the
        selected path emits (one wire format, the receiver cannot tell
        a flush from a fresh push), stochastic rounding, error
        dropped — see :meth:`residual_flush`."""
        if self._mb is not None and o in self._dead_ranks:
            self.rb_stats["pushes_lost_to_dead"] += 1
            return
        order = np.argsort(k, kind="stable")  # residual-store order is
        # arbitrary; the delta key codec needs sorted runs, and sorting
        # before the quantize keeps codes/keys paired
        k, g = k[order], np.ascontiguousarray(g[order])
        codes, scales = quantize_blockwise(g, 4, block=self.topk_block,
                                           rng=self._q_rng)
        khead, kstream = self._key_stream(k)
        head = {"n": int(k.size), "comm": "topk4",
                "blk": self.topk_block, **khead,
                **self._ep_header(), **self._cfg_header()}
        if self.async_push:
            head["seq"] = self._take_push_seq(o)
        blob = _cat_blob(kstream, scales, codes)
        self.bus.send(o, f"psP:{self.name}", head, blob=blob)
        self.bytes_pushed += len(blob)
        self._hier_count_tx(o, len(blob))

    def ef_stats(self) -> Optional[dict]:
        """Error-feedback residual counters — None when the compressed
        push wire is off (off vs idle, the done-line convention)."""
        return self._ef.stats() if self._ef is not None else None

    # ---- hierarchical push tree (balance/hier.py, MINIPS_HIER) ------
    #
    # Protocol, one psH wire per table:
    #   "c"  member -> leader   contribution: one owner slice, exact f32
    #   "b"  member -> leader   boundary: "my pushes < f are with you"
    #   "a"  leader -> member   ack: "your steps < f were flushed"
    #   "f"  leader -> owner    floor-only claim (no mass this boundary)
    #   "x"  member -> leader   expel me (sick-leader fallback handshake)
    #   "xa" leader -> member   expel-ack: the floor already flushed
    #   "r"  member -> owner    waive my floor (I am direct again)
    #   "m"  member -> owner    re-arm my floor at f (re-entered a tree)
    # Aggregated MASS rides the ordinary psP wire with head extras:
    # hfr/hfv (per-contributor floor claims, max-merged at the owner)
    # and hmin (min contributor stamp — the aggregate's birth clock).

    def _hier_elect(self) -> Optional[int]:
        """My group's current leader under THE deterministic rule
        (balance/hier.elect: lowest live rank) — every member computes
        it locally from the shared gossip exclusion set."""
        return self._hier_elect_fn(
            self._hier_group,
            self._excluded_ranks() | self._dead_ranks)

    def _hier_route(self, o: int) -> Optional[int]:
        """Level-1 routing for one owner: my group's leader when the
        (me, owner) pair is in hier mode, else None = flat wire.
        In-group owners, singleton groups, the accounting-only arm
        (agg=0), and the direct-fallback latch all stay flat."""
        cfg = self._hier
        if cfg is None or not cfg.agg or cfg.group < 2:
            return None
        if self._hier_direct or len(self._hier_group) < 2:
            return None
        if self._hier_host_of(o) == self._hier_host_of(self.rank):
            return None
        return self._hier_leader  # None while leaderless -> flat

    def _hier_count_tx(self, o: int, nbytes: int) -> None:
        """Per-level byte/frame classification at every push-frame
        send: in-group traffic is level 1, cross-group is level 2 (the
        HIER-WIN gate reads l2 — the leader leg). ``group=1``
        (armed-idle) counts nothing: the tree is degenerate and the
        zeros-when-idle wire_record contract holds."""
        cfg = self._hier
        if cfg is None or cfg.group < 2:
            return
        h = self.hier_counters
        if self._hier_host_of(o) == self._hier_host_of(self.rank):
            h["l1_tx_bytes"] += nbytes
            h["l1_frames"] += 1
        else:
            h["l2_tx_bytes"] += nbytes
            h["l2_frames"] += 1

    def _hier_floor_min(self) -> Optional[int]:
        """Min floor over LIVE registered hier contributors — None when
        no contributor is registered (hier off, group=1, or a fleet
        with no cross-group multi-rank pusher). Excluded/dead
        contributors stop gating: their mass either landed or is
        counted lost, exactly like the gossip min's exclusion rule."""
        fl = self._hier_floor
        if not fl:
            return None
        exc = self._excluded_ranks() | self._dead_ranks
        vals = [f for r, f in fl.items() if r not in exc]
        return min(vals) if vals else None

    def _admit_clk(self, clk: int) -> bool:
        """THE owner-side pull admission: the gossip staleness rule AND
        the per-contributor hier floors. A hier contributor's clock
        frame no longer certifies its cross-host pushes (they ride two
        links; per-link FIFO does not compose), so the same
        ``gate.admits`` predicate is re-evaluated against the floor min
        — semantics preserved, evidence source swapped. A tenant with
        its own ``s`` is judged against THAT bound (trainer
        ``admit_pull_s``); stub consistency objects without the
        per-bound entry point keep the fleet-wide rule."""
        ts = self._tenant.s if self._tenant is not None else None
        if self._cons is not None:
            if ts is not None and hasattr(self._cons, "admit_pull_s"):
                if not self._cons.admit_pull_s(clk, ts):
                    return False
            elif not self._cons.admit_pull(clk):
                return False
        fm = self._hier_floor_min()
        if fm is None:
            return True
        return admits(int(fm), int(clk), self._cache_staleness())

    def _hier_contribute(self, lead: int, o: int, k: np.ndarray,
                         g: np.ndarray) -> None:
        """Ship one owner slice up the tree (or straight into my own
        buckets when I am the leader). The slice is RETAINED until the
        leader acks its flush — the fallback's replay source, so a
        leader death costs bytes (an exact re-push), never steps."""
        step = self._my_clk()
        if lead == self.rank:
            with self._hier_lock:
                self._hier_buckets.setdefault(int(o), []).append(
                    (k, g, step, self.rank))
            return
        blob = _cat_blob(k, g)
        head = {"op": "c", "o": int(o), "n": int(k.size),
                "clk": int(step), **self._cfg_header()}
        with self._hier_lock:
            self._hier_retained.append((step, int(o), k, g))
        self.bus.send(lead, f"psH:{self.name}", head, blob=blob)
        h = self.hier_counters
        h["contribs"] += 1
        h["l1_frames"] += 1
        h["l1_tx_bytes"] += len(blob)

    def _on_hier(self, sender: int, payload: dict) -> None:
        """The psH wire handler (bus recv thread) — see the protocol
        table above. Mutates hier state under ``_hier_lock``; the only
        sends it issues are replies/flushes, never waits."""
        op = payload.get("op")
        if op == "c":
            if not self._check_peer_config(sender, payload):
                return
            if sender in self._hier_expelled:
                return  # late frame from a member that went direct
            n = int(payload.get("n", 0))
            blob = payload.get("__blob__")
            if blob is None or len(blob) != n * (8 + 4 * self.dim):
                self._drop("malformed", sender,
                           "bad hier contribution blob")
                return
            k = np.frombuffer(blob[:8 * n], np.int64)
            g = np.frombuffer(blob[8 * n:], np.float32
                              ).reshape(n, self.dim)
            with self._hier_lock:
                self._hier_buckets.setdefault(
                    int(payload.get("o", -1)), []).append(
                    (k, g, int(payload.get("clk", 0)), sender))
        elif op == "b":
            f = int(payload.get("f", 0))
            with self._hier_lock:
                if sender not in self._hier_expelled:
                    cur = self._hier_member_floor.get(sender, 0)
                    self._hier_member_floor[sender] = max(cur, f)
            # whichever boundary completes the step flushes it: the
            # group-min trigger fires exactly once per boundary in
            # every interleaving, and running it HERE (recv thread)
            # is what keeps two groups' lockstep free of deadlock
            self._hier_maybe_flush()
        elif op == "a":
            f = int(payload.get("f", 0))
            with self._hier_lock:
                self._hier_retained = [e for e in self._hier_retained
                                       if e[0] >= f]
        elif op == "f":
            if sender in (self._excluded_ranks() | self._dead_ranks):
                self.hier_counters["stale_leader_drops"] += 1
                return
            self._hier_merge_floors(payload)
            self.serve_parked()
        elif op == "x":
            with self._hier_lock:
                self._hier_expelled.add(sender)
                self._hier_member_floor.pop(sender, None)
                for o in list(self._hier_buckets):
                    self._hier_buckets[o] = [
                        e for e in self._hier_buckets[o]
                        if e[3] != sender]
                f = int(self._hier_claimed.get(sender, 0))
            self.bus.send(sender, f"psH:{self.name}",
                          {"op": "xa", "f": f})
            self._hier_maybe_flush()  # gmin may advance without them
        elif op == "xa":
            with self._hier_lock:
                self._hier_xa = int(payload.get("f", 0))
        elif op == "r":
            with self._hier_lock:
                if sender in self._hier_floor:
                    self._hier_floor[sender] = RETIRED_CLOCK
            self.serve_parked()
        elif op == "m":
            with self._hier_lock:
                if sender in self._hier_floor:
                    self._hier_floor[sender] = int(payload.get("f", 0))

    def _hier_merge_floors(self, payload: dict) -> None:
        """Max-merge a frame's hfr/hfv floor claims into the owner-side
        floors. Max, monotone: a zombie leader's stale (lower) claim
        can never roll a floor back, and the member's own ``r``/``m``
        frames are the only lowering path (same-link FIFO with its
        re-pushes, so the lowered claim is always true)."""
        hfr = payload.get("hfr") or ()
        hfv = payload.get("hfv") or ()
        with self._hier_lock:
            for r, f in zip(hfr, hfv):
                r, f = int(r), int(f)
                cur = self._hier_floor.get(r)
                if cur is not None and f > cur:
                    self._hier_floor[r] = f

    def _hier_maybe_flush(self, force: bool = False) -> None:
        """Leader flush: fires when the GROUP-MIN boundary floor
        advances past the last flush — per owner, concat + exact f64
        dedup-sum, then ONE frame on the configured push wire with the
        floor claims and the min contributor stamp. ``_hier_flush_lock``
        spans snapshot AND sends: a later flush's floor claim must
        never overtake an earlier flush's mass on an owner link."""
        cfg = self._hier
        if cfg is None or not cfg.agg:
            return
        with self._hier_flush_lock:
            with self._hier_lock:
                if self._hier_leader != self.rank or self._hier_direct:
                    return
                exc = self._excluded_ranks() | self._dead_ranks
                live = [r for r in self._hier_member_floor
                        if r not in exc]
                gmin = min([self._hier_own_floor]
                           + [self._hier_member_floor[r] for r in live])
                if gmin <= self._hier_flushed_floor and not force:
                    return
                self._hier_flushed_floor = gmin
                buckets, self._hier_buckets = self._hier_buckets, {}
                floors = {self.rank: self._hier_own_floor}
                floors.update({r: self._hier_member_floor[r]
                               for r in live})
                self._hier_claimed.update(floors)
            t0 = time.monotonic()
            extra = {"hfr": [int(r) for r in sorted(floors)],
                     "hfv": [int(floors[r]) for r in sorted(floors)]}
            agg = (self._hier_mesh_agg()
                   if cfg.agg == "mesh" else None)
            if agg is not None:
                sent_to = self._hier_mesh_flush(agg, buckets, extra)
            else:
                sent_to = set()
                for o in sorted(buckets):
                    entries = buckets[o]
                    if not entries or o < 0:
                        continue
                    ks = np.concatenate([e[0] for e in entries])
                    gs = np.concatenate([e[1] for e in entries])
                    hmin = min(int(e[2]) for e in entries)
                    k, g, _ = sum_duplicate_keys(ks, gs, self.dim)
                    self._hier_send_agg(int(o), k, g, hmin, extra)
                    sent_to.add(int(o))
            for o in self._hier_cross:
                # owners with no mass this boundary still need the
                # claim, or their admission would stall on my group
                if o in sent_to or o in self._dead_ranks:
                    continue
                self.bus.send(o, f"psH:{self.name}",
                              {"op": "f", **extra})
                self.hier_counters["floor_frames"] += 1
            for m in live:
                self.bus.send(m, f"psH:{self.name}",
                              {"op": "a", "f": int(floors[m])})
            self.hist_hier.record_s(time.monotonic() - t0)

    def _hier_send_agg(self, o: int, k: np.ndarray, g: np.ndarray,
                       hmin: int, extra: dict) -> None:
        """One aggregated frame to one owner on the configured push
        wire (the receiver cannot tell an aggregate from a flat push
        except by its head extras). Level-2 EF folds in the leader's
        DEDICATED store under the aggregate's min stamp."""
        if self._mb is not None and o in self._dead_ranks:
            self.rb_stats["pushes_lost_to_dead"] += 1
            return
        extra = {**extra, "hmin": int(hmin)}
        if self.push_comm in ("topk8", "topk4"):
            head0, blob, overflow = self._encode_push_topk(
                k, np.ascontiguousarray(g, np.float32),
                birth_clk=hmin, ef=self._hier_ef, rng=self._hier_rng)
            if overflow is not None and overflow[0].size:
                # overflow FIRST: the floor claim rides the aggregate,
                # which must be the LAST frame of this flush on the
                # owner link — a claim overtaking its own mass would
                # admit a pull that misses it
                self._send_f32_push(o, overflow[0], overflow[1])
        elif self.push_comm == "int8":
            codes, scale = quantize_rows_int8(g, self._hier_rng)
            head0 = {"n": int(k.size), "comm": "int8"}
            blob = _cat_blob(k, scale, codes)
        else:
            head0 = {"n": int(k.size), "comm": "float32"}
            blob = _cat_blob(k, np.ascontiguousarray(g, np.float32))
        head = {**head0, **self._ep_header(), **self._cfg_header(),
                **extra}
        self.bus.send(o, f"psP:{self.name}", head, blob=blob)
        self.bytes_pushed += len(blob)
        h = self.hier_counters
        h["agg_frames"] += 1
        h["agg_rows"] += int(k.size)
        self._hier_count_tx(o, len(blob))

    def _hier_mesh_agg(self):
        """The leader's lazy MeshAggregator (``agg=mesh``). A build
        failure — no jax devices, bad env — latches a STICKY fallback
        to the host f64 kernel: the tree keeps running with identical
        frames and semantics, only the reduce engine degrades
        (flight-recorded once, never retried this incarnation)."""
        cfg = self._hier
        if cfg is None or cfg.agg != "mesh" or self._hier_mesh_failed:
            return None
        if self._hier_mesh is None:
            try:
                from minips_tpu.train.mesh_plane import MeshAggregator
                comm = (os.environ.get("MINIPS_HIER_MESH_COMM",
                                       "blk8").strip() or "blk8")
                self._hier_mesh = MeshAggregator(
                    self.num_rows, self.dim,
                    slots=max(len(self._hier_group), 1), comm=comm)
            except Exception as e:  # noqa: BLE001 — degrade, not die
                self._hier_mesh_failed = True
                self.hier_counters["mesh_agg_fallbacks"] += 1
                _fl.record("hier_mesh_fallback",
                           {"table": self.name, "err": repr(e)})
                return None
        return self._hier_mesh

    def _hier_mesh_flush(self, agg, buckets: dict,
                         extra: dict) -> set:
        """The ``agg=mesh`` reduce leg of one leader flush: every
        bucket entry deposits into the host's device mesh (one slot
        per group member), ONE reduce-scatter produces the aggregate,
        and the same per-owner ``psP`` frames ship cross-host — the
        wire cannot tell which engine reduced. The device quantizer's
        residual feeds the leader-lane ResidualStore under each
        owner's min contributor stamp (topk wire: the next encode
        folds it back — the unbiased-flush contract end-to-end); exact
        wires repay it straight into the aggregate, so every flush
        ships exact sums. Caller holds ``_hier_flush_lock``."""
        sent_to: set = set()
        hmins: dict[int, int] = {}
        okeys: dict[int, np.ndarray] = {}
        slot_of = {r: i for i, r in enumerate(self._hier_group)}
        for o in sorted(buckets):
            entries = buckets[o]
            if not entries or o < 0:
                continue
            o = int(o)
            hmins[o] = min(int(e[2]) for e in entries)
            # deposit in bucket order — the exact occurrence order the
            # f64 path concatenates, so the degenerate one-device tier
            # is bitwise agg=host
            for k, g, _clk, sender in entries:
                agg.deposit(slot_of.get(int(sender), 0), k, g)
            okeys[o] = np.unique(np.concatenate(
                [e[0] for e in entries]))
        if not hmins:
            return sent_to
        keys, rows, rk, rr = agg.reduce()
        self.hier_counters["mesh_reduces"] += 1
        if rk.size:
            # stamp each residual key with ITS owner's min contributor
            # clock (per-owner bucket membership, not a router re-read:
            # a rebalance mid-flush must not re-home retained error)
            hmin_of = np.full(keys.size, self._my_clk(), np.int64)
            owner_of = np.full(keys.size, -1, np.int64)
            for o, ok in okeys.items():
                idx = np.searchsorted(keys, ok)
                hmin_of[idx] = hmins[o]
                owner_of[idx] = o
            ridx = np.searchsorted(keys, rk)
            if self._hier_ef is not None:
                ovk, ovr = self._hier_ef.retain(rk, rr, hmin_of[ridx])
                if ovk.size:
                    # slab overflow ships dense NOW, before any
                    # aggregate: mass conserved, claims still last
                    ov_owner = owner_of[np.searchsorted(keys, ovk)]
                    for o in np.unique(ov_owner):
                        m = ov_owner == o
                        self._send_f32_push(int(o), ovk[m], ovr[m])
            else:
                rows[ridx] += rr
        for o in sorted(okeys):
            ok = okeys[o]
            g = np.ascontiguousarray(
                rows[np.searchsorted(keys, ok)], np.float32)
            self._hier_send_agg(o, ok, g, hmins[o], extra)
            sent_to.add(o)
        return sent_to

    def _hier_poll(self) -> None:
        """Election/fallback state machine, driven from the training
        thread's natural poll points (push, tick boundary, pull waits):
        re-run THE deterministic election; a convicted leader triggers
        fallback (replay the retained window direct, waive my floors);
        a live-but-sick leader (retained window past ``retain``) is
        expelled via the x/xa handshake; a NEW live leader (myself
        included) re-enters the tree."""
        cfg = self._hier
        if cfg is None or not cfg.agg or cfg.group < 2:
            return
        if (cfg.agg == "mesh" and not self._hier_domain_down
                and len(self._hier_group) >= 2):
            # agg=mesh makes the host ONE failure domain: the mesh
            # plane's collectives span every member, so a single
            # convicted/dead member invalidates the whole reduce
            # group. Latch sticky, demote the group as one unit —
            # everyone (leader included) degrades to direct pushes
            # and nobody re-enters this incarnation
            exc = self._excluded_ranks() | self._dead_ranks
            gone = sorted(r for r in self._hier_group if r in exc)
            if gone:
                self._hier_domain_down = True
                self.hier_counters["domain_demotions"] += 1
                _fl.record("hier_domain_down",
                           {"table": self.name, "rank": self.rank,
                            "gone": [int(r) for r in gone],
                            "group": [int(r) for r in
                                      self._hier_group]})
                self._hier_domain_demote()
        new = self._hier_elect()
        repush = None
        with self._hier_lock:
            old = self._hier_leader
            if new != old:
                self._hier_leader = new
                self.hier_counters["elections"] += 1
                if not self._hier_direct and old is not None \
                        and old != self.rank:
                    # my leader was convicted with my window in flight
                    self._hier_direct = True
                    self._hier_shunned = old
                    repush = list(self._hier_retained)
                    self._hier_retained.clear()
                    self.hier_counters["fallbacks"] += 1
        if new != old:
            _fl.record("hier_leader_elect",
                       {"table": self.name,
                        "old": -1 if old is None else int(old),
                        "new": -1 if new is None else int(new)})
        if repush is not None:
            self._hier_replay(repush, old, "leader_dead")
        with self._hier_lock:
            sick = (not self._hier_direct
                    and self._hier_leader not in (None, self.rank)
                    and len(self._hier_retained) > cfg.retain)
        if sick:
            self._hier_expel_and_go_direct()
        with self._hier_lock:
            direct = self._hier_direct
            shunned = self._hier_shunned
            cur = self._hier_leader
        if (direct and cur is not None and cur != shunned
                and not self._hier_domain_down):
            self._hier_reenter(cur)

    def _hier_domain_demote(self) -> None:
        """Demote my whole host group after the domain latch tripped.
        A live LEADER force-flushes its buckets (its own contributions
        have no retained copy — the flush is their only exit), then
        goes direct and waives its floor; a live MEMBER runs the x/xa
        expel handshake against a live leader (exactly-once handoff)
        or, when the leader is the dead one, lets the election
        fallback replay the retained window — both paths end direct
        with floors waived, zero lost steps."""
        with self._hier_lock:
            lead = self._hier_leader
            direct = self._hier_direct
        if direct:
            return
        if lead == self.rank:
            self._hier_maybe_flush(force=True)
            with self._hier_lock:
                self._hier_direct = True
                self._hier_shunned = self.rank
                self.hier_counters["fallbacks"] += 1
            dead = self._excluded_ranks() | self._dead_ranks
            for o in self._hier_cross:
                if o not in dead:
                    self.bus.send(o, f"psH:{self.name}", {"op": "r"})
        elif lead is not None and lead not in (
                self._excluded_ranks() | self._dead_ranks):
            self._hier_expel_and_go_direct()
        # dead-leader case: _hier_poll's election fallback replays

    def _hier_replay(self, repush: list, old, why: str) -> None:
        """The fallback's second half: re-push the retained window
        DIRECT (exact f32, step-tagged so the owner's floor filter
        dedups anything the dead leader's last flush already
        delivered), then waive my floor at every owner — the ``r``
        rides AFTER the re-pushes on each owner link, so the waiver is
        true when it lands. Zero lost steps; the cost is bytes."""
        _fl.record("hier_fallback",
                   {"table": self.name,
                    "leader": -1 if old is None else int(old),
                    "why": why, "steps": len(repush)})
        h = self.hier_counters
        for step, o, k, g in repush:
            self._send_f32_push(o, k, g, extra_head={"hst": int(step)})
            h["repushed_steps"] += 1
        dead = self._excluded_ranks() | self._dead_ranks
        for o in self._hier_cross:
            if o not in dead:
                self.bus.send(o, f"psH:{self.name}", {"op": "r"})

    def _hier_expel_and_go_direct(self) -> None:
        """Sick-leader fallback against a LIVE leader: the x/xa
        handshake makes the handoff exactly-once — the leader discards
        my pending bucket mass (I will re-push it), stops claiming my
        floor, and tells me the floor it already flushed so I replay
        only the steps above it. A leader too sick to even ack within
        the grace degrades to the dead-leader replay (floor filter
        still dedups whatever it managed to flush)."""
        with self._hier_lock:
            lead = self._hier_leader
            if self._hier_direct or lead in (None, self.rank):
                return
            self._hier_xa = None
        self.bus.send(lead, f"psH:{self.name}", {"op": "x"})
        t_end = time.monotonic() + 2.0
        f = 0
        while time.monotonic() < t_end:
            with self._hier_lock:
                if self._hier_xa is not None:
                    f = int(self._hier_xa)
                    break
            if lead in (self._excluded_ranks() | self._dead_ranks):
                break
            time.sleep(0.005)
        with self._hier_lock:
            self._hier_direct = True
            self._hier_shunned = lead
            repush = [e for e in self._hier_retained if e[0] >= f]
            self._hier_retained.clear()
            self.hier_counters["fallbacks"] += 1
        self._hier_replay(repush, lead, "expelled")

    def _hier_reenter(self, lead: int) -> None:
        """Re-enter the tree under a NEW live leader (myself included:
        a surviving lowest rank starts leading its remaining members).
        The ``m`` frame re-arms my floor at the current clock — valid
        because everything below it went direct on the same owner link
        while I was fallen back."""
        f = int(self._my_clk())
        with self._hier_lock:
            self._hier_direct = False
            self._hier_shunned = None
            if lead == self.rank:
                self._hier_own_floor = max(self._hier_own_floor, f)
        dead = self._excluded_ranks() | self._dead_ranks
        for o in self._hier_cross:
            if o not in dead:
                self.bus.send(o, f"psH:{self.name}",
                              {"op": "m", "f": f})

    def hier_boundary(self) -> None:
        """The trainer-tick hook, called AFTER the step's pushes and
        residual flushes and BEFORE the clock frame goes out (the same
        per-link-FIFO slot the async drain uses): members hand the
        leader a boundary certifying this step's contributions are
        complete; the leader advances its own floor and flushes if that
        completes the group."""
        cfg = self._hier
        if cfg is None or not cfg.agg or cfg.group < 2:
            return
        self._hier_poll()
        f = int(self._my_clk()) + 1
        with self._hier_lock:
            lead = self._hier_leader
            direct = self._hier_direct
        if direct or lead is None:
            return
        if lead == self.rank:
            with self._hier_lock:
                self._hier_own_floor = max(self._hier_own_floor, f)
            self._hier_maybe_flush()
            self._hier_residual_boundary()
        else:
            self.bus.send(lead, f"psH:{self.name}",
                          {"op": "b", "f": f})
            self.hier_counters["l1_frames"] += 1

    def _hier_residual_boundary(self) -> None:
        """Leader-lane aged residual flush — the level-2 twin of
        ``residual_flush(aged_only=True)``: retained aggregate error
        older than the staleness bound ships as the blk4 stream,
        straight to its owner (leader -> owner IS the hier lane)."""
        if self._hier_ef is None:
            return
        s = self._cache_staleness()
        if s == float("inf"):
            return
        with self._hier_flush_lock:
            keys, rows = self._hier_ef.take(self._my_clk() - int(s))
            if not keys.size:
                return
            self._hier_ef.note_flushed(int(keys.size), "age")
            owners = self._owners_of(keys)
            for o in np.unique(owners):
                m = owners == o
                if int(o) == self.rank:
                    if self._rb is not None:
                        self._ingest_push(keys[m], rows[m],
                                          self.router.epoch)
                    else:
                        self._apply_rows(keys[m] - self.shard_lo,
                                         rows[m])
                else:
                    self._send_blk4_push(int(o), keys[m], rows[m])

    def hier_finalize(self, timeout: float = 20.0) -> None:
        """Quiesce the tree BEFORE the psFlush barrier: a member's
        psFlush no longer certifies its cross-host mass (it may sit in
        the leader's buckets), so the member hands the leader a RETIRED
        boundary and waits for its retained window to drain — falling
        back (bytes, not loss) if the leader dies or hangs — and the
        leader drives its floor to RETIRED, flushing as the members'
        RETIRED boundaries land, so its own psFlush rides AFTER the
        last aggregated frame on every owner link."""
        cfg = self._hier
        if cfg is None or not cfg.agg or cfg.group < 2:
            return
        deadline = time.monotonic() + timeout
        self._hier_poll()
        with self._hier_lock:
            lead = self._hier_leader
            direct = self._hier_direct
        if not direct and lead not in (None, self.rank):
            self.bus.send(lead, f"psH:{self.name}",
                          {"op": "b", "f": int(RETIRED_CLOCK)})
            while True:
                with self._hier_lock:
                    if not self._hier_retained or self._hier_direct:
                        break
                self._hier_poll()  # a death here falls back + replays
                if time.monotonic() > deadline:
                    self._hier_expel_and_go_direct()
                    break
                time.sleep(0.005)
        with self._hier_lock:
            lead = self._hier_leader
            direct = self._hier_direct
        if lead == self.rank and not direct:
            # a demoted (domain-down) leader has nothing to drive: its
            # members went direct and will never send RETIRED
            # boundaries — waiting here would just burn the timeout
            with self._hier_lock:
                self._hier_own_floor = int(RETIRED_CLOCK)
            while True:
                self._hier_maybe_flush()
                with self._hier_lock:
                    exc = (self._excluded_ranks()
                           | self._dead_ranks)
                    waiting = [
                        r for r in self._hier_member_floor
                        if r not in exc
                        and self._hier_member_floor[r] < RETIRED_CLOCK]
                if not waiting:
                    break
                if time.monotonic() > deadline:
                    _fl.record("hier_finalize_timeout",
                               {"table": self.name,
                                "waiting": sorted(waiting)})
                    break
                time.sleep(0.005)
            self._hier_maybe_flush(force=True)
            self._hier_residual_fence()

    def _hier_residual_fence(self) -> None:
        """Exact f32 fence flush of the leader-lane residual store —
        the finalize twin of ``residual_flush(reason="fence")``:
        post-finalize agreement is bitwise, so no leader-side error
        mass may outlive the run."""
        if self._hier_ef is None:
            return
        with self._hier_flush_lock:
            keys, rows = self._hier_ef.take()
            if not keys.size:
                return
            self._hier_ef.note_flushed(int(keys.size), "fence")
            owners = self._owners_of(keys)
            for o in np.unique(owners):
                m = owners == o
                if int(o) == self.rank:
                    if self._rb is not None:
                        self._ingest_push(keys[m], rows[m],
                                          self.router.epoch)
                    else:
                        self._apply_rows(keys[m] - self.shard_lo,
                                         rows[m])
                else:
                    self._send_f32_push(int(o), keys[m], rows[m])

    def hier_stats(self) -> Optional[dict]:
        """Hier counters + live tree state — None when hier is off
        (the off-vs-idle done-line convention; ``group=1`` keeps every
        byte/frame counter at zero)."""
        if self._hier is None:
            return None
        out = {k: int(v) for k, v in self.hier_counters.items()}
        with self._hier_lock:
            out["retained_steps"] = len(self._hier_retained)
            out["leader"] = (-1 if self._hier_leader is None
                             else int(self._hier_leader))
            out["direct"] = int(self._hier_direct)
        fm = self._hier_floor_min()
        out["floor_min"] = -1 if fm is None else int(fm)
        if self._hier_ef is not None:
            out["ef_rows"] = int(
                self._hier_ef.stats()["resident_rows"])
        out["domain_down"] = int(self._hier_domain_down)
        if self._hier_mesh is not None:
            out["mesh"] = self._hier_mesh.stats()
        return out

    def push_dense(self, grad: np.ndarray) -> None:
        """Whole-vector gradient push, split into per-owner contiguous
        ranges (no key lists on the wire) — the dense-table fast path.
        Async mode enqueues like :meth:`push`."""
        grad = np.asarray(grad, np.float32).reshape(-1, self.dim)
        if grad.shape[0] != self.num_rows:
            raise ValueError(
                f"push_dense expects [{self.num_rows}, {self.dim}]")
        if self._cache is not None:
            # a dense push touches every row: conservatively drop the
            # cache (dense workloads read via pull_all, which bypasses
            # it anyway) rather than write through a whole table — and
            # poison IN-FLIGHT pulls' inserts too (broken floor): their
            # replies may sit on either side of this push, and clearing
            # alone would let them re-cache pre-push rows
            self._cache.clear()
            with self._cache_log_lock:
                self._cache_broken_floor = self._cache_epoch
                self._cache_epoch += 1
                self._cache_log.clear()
        if self.async_push:
            self._enqueue_push("dense", grad.copy())
            return
        self._push_dense_now(grad)

    def _push_dense_now(self, grad: np.ndarray) -> None:
        sz = self.part.shard_size
        for o in range(self.num_processes):
            lo, hi = o * sz, min((o + 1) * sz, self.num_rows)
            if hi <= lo:
                continue
            if o == self.rank:
                if self._rb is not None and (self.router._overlay
                                             or not
                                             self.rebalance_settled()):
                    # part of my home range may live elsewhere now: the
                    # keyed ingest forwards migrated rows instead of
                    # writing them into the dead slab copy (the same
                    # fallback _handle_push_range applies on receive)
                    self._ingest_push(np.arange(lo, hi, dtype=np.int64),
                                      grad[lo:hi], self.router.epoch)
                else:
                    self._apply_range(0, grad[lo:hi])
                continue
            if self.push_comm != "float32":
                # the range fast path has no key stream to sparsify:
                # the topk tiers fall back to the per-row int8 codec
                # here (dense pushes touch every row anyway — there is
                # no top-k win, and EF residuals would just be the
                # whole table; docs/api.md wire-ladder note)
                codes, scale = quantize_rows_int8(grad[lo:hi], self._q_rng)
                gb = scale.tobytes() + codes.tobytes()
                wire_comm = "int8"
            else:
                gb = grad[lo:hi].tobytes()
                wire_comm = "float32"
            head = {"lo": lo, "comm": wire_comm,
                    **self._ep_header(), **self._cfg_header()}
            if self.async_push:
                head["seq"] = self._take_push_seq(o)
            self.bus.send(o, f"psR:{self.name}", head, blob=gb)
            self.bytes_pushed += len(gb)
        self.rows_pushed += self.num_rows

    # ------------------------------------------------------------- accounting
    def local_bytes(self) -> int:
        """Bytes of table + optimizer state THIS process holds — the ~1/N
        sharding claim the smoke test asserts (migrated-in blocks count:
        they are live state only this process holds)."""
        n = self._w.nbytes
        if self._acc is not None:
            n += self._acc.nbytes
        if self._m is not None:
            n += self._m.nbytes + self._v.nbytes + self._steps.nbytes
        with self._state_lock:
            for st in self._xtra.values():
                n += sum(a.nbytes for a in st.values() if a is not None)
        return n

    # ------------------------------------------------------------- state I/O
    def shard_state_dict(self) -> dict:
        if self._rb is not None:
            # a checkpoint must never capture a block mid-flight (the
            # old owner already shipped it, the new owner has not
            # installed it: the step would restore without that state)
            self._wait_settled(self.pull_timeout)
        with self._state_lock:
            out = {"w": self._w.copy(), "lo": np.asarray(self.shard_lo)}
            if self._acc is not None:
                out["acc"] = self._acc.copy()
            if self._m is not None:
                out["m"] = self._m.copy()
                out["v"] = self._v.copy()
                out["steps"] = self._steps.copy()
            ep, ov = self.router.table()
            if ov or self._xtra:
                # the ROUTING EPOCH + overlay + migrated-in block state
                # ride the checkpoint, so a restored fleet routes (and
                # serves) exactly like the live peers it rejoins. An
                # EMPTY overlay (every block back home) is deliberately
                # not recorded even at epoch > 0: the layout is exactly
                # the base partition again, so the checkpoint stays
                # elastic-reshardable and restores epoch-0 everywhere
                # (consistent fleet-wide — all ranks restore one step)
                out["ep"] = np.asarray(ep)
                out["rb_block"] = np.asarray(self.router.block_size)
                out["ovb"] = np.asarray(sorted(ov), np.int64)
                out["ovo"] = np.asarray([ov[b] for b in sorted(ov)],
                                        np.int64)
                out["xtra"] = {
                    str(b): {k: v.copy() for k, v in st.items()
                             if v is not None}
                    for b, st in self._xtra.items()}
        return out

    def load_shard_state_dict(self, state: dict) -> None:
        if int(state["lo"]) != self.shard_lo:
            raise ValueError(
                f"shard checkpoint lo={int(state['lo'])} belongs to a "
                f"different rank/partition (mine starts at {self.shard_lo})")
        ep = int(state["ep"]) if "ep" in state else 0
        if ep and self._rb is None:
            raise ValueError(
                "checkpoint was saved with a rebalanced (epoch "
                f"{ep}) routing table; restoring it requires "
                "MINIPS_REBALANCE so the overlay routing/serving "
                "machinery is armed")
        with self._state_lock:
            self._w[...] = state["w"]
            if self._acc is not None:
                if "acc" not in state:
                    raise ValueError("checkpoint lacks adagrad accumulator")
                self._acc[...] = state["acc"]
            if self._m is not None:
                if not {"m", "v", "steps"} <= set(state):
                    raise ValueError(
                        "checkpoint lacks adam moments/step counters")
                self._m[...] = state["m"]
                self._v[...] = state["v"]
                self._steps[...] = state["steps"]
            if ep:
                blk = int(state.get("rb_block", self.router.block_size))
                if blk != self.router.block_size:
                    # the overlay's block ids are meaningless at another
                    # granularity — rebuild the router at the saved one,
                    # and the heat accountant with it (its counters are
                    # indexed by the router's block id space)
                    from minips_tpu.balance.heat import HeatAccountant

                    self.router = BlockRouter(self.part, blk)
                    self._heat = HeatAccountant(self.router.num_blocks,
                                                self._heat.decay)
                ov = {int(b): int(o) for b, o in
                      zip(np.asarray(state["ovb"]).tolist(),
                          np.asarray(state["ovo"]).tolist())}
                if self.router.apply(ep, ov) is None and \
                        self.router.epoch != ep:
                    raise ValueError(
                        f"checkpoint routing epoch {ep} is older than "
                        f"the live table's {self.router.epoch}")
                self._xtra = {
                    int(b): {k: np.array(v) for k, v in st.items()}
                    for b, st in (state.get("xtra") or {}).items()}

    # Checkpointer-protocol aliases: each process checkpoints ITS OWN
    # shard (the reference dumps per-server KVTable state, SURVEY.md §3.5)
    # into a rank-scoped directory — recovery = relaunch at the same world
    # size, every rank reloading its range (ckpt/checkpoint.py interface).
    state_dict = shard_state_dict
    load_state_dict = load_shard_state_dict


def tables_hist_stats(tables) -> dict:
    """The done-line ``hist`` block over a set of tables: client-side
    pull latency / blocked time / push-ack latency (CommTimers) plus
    server-side serve duration / park duration, each as a log2-bucket
    p50/p95/p99 summary. Shared by the trainer and the bench worker's
    standalone (no-trainer) path so the layout cannot fork."""
    tables = list(tables)
    tsnap = CommTimers.merge_snapshots(
        [t.timers.snapshot() for t in tables])
    serve = merge_counts([t.hist_serve.snapshot() for t in tables])
    park = merge_counts([t.hist_park.snapshot() for t in tables])
    fence = merge_counts([t.hist_fence.snapshot() for t in tables])
    # replica serve durations (serve/plane.py): merge_counts([]) is all
    # zeros, so plane-off runs report {"count": 0} like every idle
    # quantity here — the serve plane's own off-vs-idle distinction
    # lives in the done line's serve.replica block (None = off)
    replica = merge_counts([t._sv.hist_replica.snapshot()
                            for t in tables if t._sv is not None])
    return {
        "pull_latency_ms": summarize_counts(
            tsnap["hists"]["pull_latency"]),
        "pull_blocked_ms": summarize_counts(
            tsnap["hists"]["pull_blocked"]),
        "push_ack_ms": summarize_counts(tsnap["hists"]["push_ack"]),
        "serve_ms": summarize_counts(serve),
        "park_ms": summarize_counts(park),
        "fence_ms": summarize_counts(fence),
        "replica_serve_ms": summarize_counts(replica),
    }


class ShardedPSTrainer:
    """Clock/gate/finalize driver over a set of ShardedTables — the Engine-
    side loop of the sharded PS (pull → compute → push → clock → gate).

    The app owns the compute (jitted model math on pulled rows); this class
    owns consistency (StalenessGate), the finalize barrier, and aggregate
    wire/memory accounting.
    """

    def __init__(self, tables: dict[str, ShardedTable], bus,
                 num_processes: int, *, staleness: float = 0,
                 gate_timeout: float = 60.0, monitor=None,
                 rebalance: Optional[str] = None,
                 serve: Optional[str] = None,
                 elastic: Optional[str] = None,
                 reshard: Optional[str] = None,
                 autoscale: Optional[str] = None,
                 hedge: Optional[str] = None,
                 slow: Optional[str] = None,
                 hier: Optional[str] = None,
                 plane: Optional[str] = None,
                 tenant: Optional[str] = None,
                 slo: Optional[str] = None):
        # data-plane selection at the same altitude as the bus backends
        # (train/mesh_plane.resolve_plane: explicit wins, else
        # $MINIPS_MESH): this bus-backed trainer IS the host-wire plane;
        # plane="mesh" names the in-mesh collective plane, which has no
        # bus or per-process tables to drive — construct it via
        # train/mesh_plane.MeshPlane (apps route on the same knob,
        # e.g. sharded_ps_bench --plane mesh)
        from minips_tpu.train.mesh_plane import resolve_plane

        self.plane = resolve_plane(plane)
        if self.plane == "mesh":
            raise ValueError(
                "plane='mesh' selects the in-mesh collective data plane "
                "(one process, device gang) — build it with "
                "minips_tpu.train.mesh_plane.MeshPlane(num_ranks, ...) "
                "instead of the bus-backed ShardedPSTrainer. Entrypoints "
                "with mesh support route on this knob themselves "
                "(sharded_ps_bench --plane mesh); one without it refuses "
                "HERE rather than silently publishing host-wire numbers "
                "under a mesh selection — unset MINIPS_MESH to run this "
                "app on the host wire")
        self.tables = tables
        self.bus = bus
        self.num_processes = num_processes
        self.staleness = staleness
        self.monitor = monitor
        self.clock = 0
        # the newest clock whose gate has PASSED — the serving plane's
        # read stamp (pull_serving): admission for it is already proven
        # fleet-wide, so serving reads never park on the in-flight step
        self.gated_clock = 0
        _trc.maybe_init(bus.my_id)  # MINIPS_TRACE: arm the wire tracer
        _fl.maybe_init(bus.my_id)   # flight recorder: ON unless =0
        self.gossip = ClockGossip(bus, num_processes, workers_per_process=1)
        self.gate = StalenessGate(self.gossip, staleness,
                                  timeout=gate_timeout, monitor=monitor)
        self._flushed: set[int] = set()
        self._acked: set[int] = set()
        self._byes: set[int] = set()
        self._fin_cond = threading.Condition()
        bus.on("psFlush", self._on_flush)
        bus.on("psFlushAck", self._on_flush_ack)
        bus.on("psBye", self._on_bye)
        # server-side admission: tables park pulls until my view of the
        # global min clock admits them; every clock/exclusion change drains
        for t in tables.values():
            t.bind_consistency(self)
        self.gossip.add_listener(self._drain_parked)
        # multi-tenant tables (tenant/registry.py): OFF by default —
        # explicit spec wins, else $MINIPS_TENANT. Bound FIRST among
        # the optional layers: the registry's per-tenant block/rate/
        # burst/replica/hedge budgets override the fleet-wide knobs
        # inside attach_rebalancer / the serve plane / attach_hedge,
        # so every table must carry its tenant id before those arm.
        # bind() assigns deterministic 1-based ids (spec order; the
        # bare-"1" default takes sorted table-name order) — every rank
        # computes the same assignment, and the per-frame "tb" stamp
        # poisons the table if one didn't.
        from minips_tpu.tenant.registry import maybe_registry as _mt

        self.tenant_registry = _mt(tenant)
        if self.tenant_registry is not None:
            self.tenant_registry.bind(tables)
            for name, t in tables.items():
                t.attach_tenant(self.tenant_registry.spec_for(name))
        # heat-aware shard rebalancing (balance/): OFF by default —
        # explicit spec wins, else $MINIPS_REBALANCE, else disabled.
        # The elastic membership plane (below) needs the migration
        # MACHINERY either way: when only MINIPS_ELASTIC is armed the
        # rebalancer is constructed with its heat planner disabled —
        # here, not later, because attach_rebalancer rebuilds the
        # router/heat that the serve plane must see final.
        spec = rebalance if rebalance is not None \
            else os.environ.get("MINIPS_REBALANCE", "")
        espec = elastic if elastic is not None \
            else os.environ.get("MINIPS_ELASTIC", "")
        if espec == "0":
            espec = ""
        self.rebalancer = None
        if (spec and spec != "0") or espec:
            from minips_tpu.balance.rebalancer import (RebalanceConfig,
                                                       Rebalancer)

            heat_on = bool(spec and spec != "0")
            self.rebalancer = Rebalancer(
                self, RebalanceConfig.parse(spec if heat_on else ""),
                plan_heat=heat_on)
        # read-mostly serving plane (serve/): OFF by default — explicit
        # spec wins, else $MINIPS_SERVE, else disabled. Constructed
        # AFTER the rebalancer: attach_rebalancer rebuilds router+heat
        # at its block granularity and the serve plane must see the
        # final ones.
        sspec = serve if serve is not None \
            else os.environ.get("MINIPS_SERVE", "")
        self.serve_plane = None
        if sspec and sspec != "0":
            from minips_tpu.serve.plane import ServeConfig, ServePlane

            self.serve_plane = ServePlane(self, ServeConfig.parse(sspec))
        # elastic membership (balance/membership.py): OFF by default —
        # ranks join/leave the live job, deaths restore from the
        # elastic checkpoint onto survivors. Constructed LAST: it rides
        # the rebalancer (armed above) and hooks the monitor/gate.
        self.membership = None
        if espec:
            from minips_tpu.balance.membership import (Membership,
                                                       MembershipConfig)

            self.membership = Membership(self,
                                         MembershipConfig.parse(espec))
            self.gate.membership = self.membership
            for t in tables.values():
                t.attach_membership(self.membership)
        # planned collective redistribution (balance/redistribute.py):
        # OFF by default — explicit spec wins, else $MINIPS_RESHARD.
        # Armed, every migration state ship (rebalance plans, demote
        # drains, membership evacuations) runs as cap-bounded slice
        # ROUNDS instead of whole-block point-to-point snapshots; the
        # plan is a pure function of the overlay diff, so arming rides
        # the migration machinery above.
        from minips_tpu.balance import redistribute as _rd

        self.reshard_cfg = _rd.maybe_config(reshard)
        if self.reshard_cfg is not None:
            if self.rebalancer is None:
                raise ValueError(
                    "MINIPS_RESHARD schedules the epoch-fenced "
                    "migration's state rounds — arm MINIPS_REBALANCE "
                    "or MINIPS_ELASTIC too (there is no migration "
                    "wire to plan without them)")
            for t in tables.values():
                t.attach_reshard(self.reshard_cfg)
        # closed-loop autoscaler (balance/autoscaler.py): OFF by
        # default — a decision loop on the coordinator lease holder
        # that watches serve-plane shed counters / SERVE-SLO p99 /
        # heat imbalance off the rbH wire and drives mbJ admits + mbDr
        # drains with hysteresis. Rides the membership plane.
        aspec = autoscale if autoscale is not None \
            else os.environ.get("MINIPS_AUTOSCALE", "")
        self.autoscaler = None
        if aspec and aspec != "0":
            if self.membership is None:
                raise ValueError(
                    "MINIPS_AUTOSCALE drives elastic membership "
                    "transitions — arm MINIPS_ELASTIC too (the "
                    "autoscaler has nothing to scale without it)")
            from minips_tpu.balance.autoscaler import (AutoscaleConfig,
                                                       Autoscaler)

            self.autoscaler = Autoscaler(
                self, self.membership, AutoscaleConfig.parse(aspec))
        # fail-slow plane (serve/hedge.py + obs/slowness.py): OFF by
        # default — explicit specs win, else $MINIPS_HEDGE /
        # $MINIPS_SLOW. Hedging is pure client-side read mitigation
        # (it needs the serve plane's replica holders to have a target
        # — armed without one it only ever counts no_holder, the
        # documented limit). The SlownessMonitor is the detection
        # rung: per-peer latency fed from the leg/ack paths, rolled at
        # every clock boundary; with the membership plane armed its
        # suspicions gossip on heartbeats (slw ballots) and convict by
        # the same strict-majority quorum as death — bind AFTER
        # membership so the hook wiring sees it.
        from minips_tpu.obs import slowness as _slw
        from minips_tpu.serve import hedge as _hg

        self.hedge_cfg = _hg.maybe_config(hedge)
        if self.hedge_cfg is not None:
            for t in tables.values():
                t.attach_hedge(self.hedge_cfg)
        # hierarchical push tree (balance/hier.py): OFF by default —
        # explicit spec wins, else $MINIPS_HIER. Armed AFTER
        # bind_consistency (the tables' _my_clk/_excluded_ranks feeds)
        # and checked against the heat rebalancer: a mid-run routing
        # overlay would re-home keys whose mass sits in a leader's
        # buckets, and the leader flushes by the MEMBER's routing —
        # elastic membership stays allowed (death plans only move a
        # corpse's keys, and a dead leader's members fall back first).
        from minips_tpu.balance import hier as _hr

        self.hier_cfg = _hr.maybe_config(hier)
        if self.hier_cfg is not None:
            if self.hier_cfg.agg and self.hier_cfg.group > 1 \
                    and self.rebalancer is not None \
                    and getattr(self.rebalancer, "plan_heat", False):
                raise ValueError(
                    "MINIPS_HIER aggregation is incompatible with the "
                    "heat rebalancer (MINIPS_REBALANCE): a routing "
                    "overlay adopted mid-boundary would re-route keys "
                    "already bucketed at a leader under the old table. "
                    "Run hier with MINIPS_ELASTIC only, or keep the "
                    "flat wire under rebalancing")
            for t in tables.values():
                t.attach_hier(self.hier_cfg)
            if (self.hier_cfg.agg == "mesh"
                    and self.hier_cfg.group > 1
                    and self.membership is not None):
                # hybrid plane: a mesh host is ONE failure domain —
                # slow verdicts demote the whole host group
                self.membership.bind_failure_domains(
                    self.hier_cfg.group)
            if self.hier_cfg.agg and self.hier_cfg.group > 1:
                _fl.record("hier_leader_elect", {
                    "table": "*", "old": -1,
                    "new": -1 if (lead := _hr.elect(
                        _hr.group_ranks(bus.my_id, self.hier_cfg.group,
                                        num_processes))) is None
                    else int(lead)})
        self.slowness = _slw.maybe_build(bus.my_id, num_processes, slow)
        if self.slowness is not None:
            for t in tables.values():
                t.bind_slowness(self.slowness)
            self.gate.on_behind = self.slowness.note_behind
            if self.membership is not None:
                self.membership.bind_slowness(self.slowness,
                                              self.slowness.cfg)
        if self.rebalancer is not None:
            # adopt plans (and, at the coordinator, issue pending death
            # transitions) while GATE-blocked too, not just while
            # pull-blocked: the gate runs on the push-driving thread at
            # the clock boundary (post-drain), so adoption here is the
            # same fence point as the next tick's — and without it a
            # rank gate-blocked on a lagging peer can deadlock against
            # that peer's epoch-parked pull (gate.py poll_hook note)
            self.gate.poll_hook = self._gate_poll
        # seeded process-death injection (comm/chaos.py,
        # $MINIPS_CHAOS_KILL): armed per-rank, checked at every tick —
        # the launcher-level kill drill every sharded app inherits
        from minips_tpu.comm.chaos import install_chaos_kill

        self._kill_check = install_chaos_kill(bus.my_id, num_processes)
        # step-windowed partition injection (comm/chaos.py part=
        # entries): the injector keys its windows on the RECEIVER's
        # clock, fed from the same tick point as the kill check — None
        # when chaos is off or carries no partition entries, so the
        # common tick pays one attribute load
        ch = getattr(bus, "chaos", None)
        self._chaos_clock = (ch.on_clock if ch is not None
                             and ch.spec.partitions else None)
        # windowed metrics layer (obs/window.py): ALWAYS ON
        # (MINIPS_OBS=0 only for the OBS-TAX honesty arm) — rolled at
        # every clock boundary, it is what turns the cumulative hists/
        # counters above into "now" signals: the autoscaler's p99
        # arming reads it (balance/rebalancer._send_heat), the done
        # line's "window" block reports it, and the flight recorder
        # snapshots it into every dump. Built LAST so registration can
        # see every armed subsystem.
        self.obs_window = _ow.maybe_build()
        if self.obs_window is not None:
            self._register_window_signals()
        # per-tenant SLO burn-rate accounting (obs/slo.py): OFF by
        # default — explicit spec wins, else $MINIPS_SLO. Built after
        # the windowed layer (both its windows read windowed counts;
        # SloTracker refuses a None window itself) and after tenancy
        # bound (tenants are the keying). Its burning set feeds the
        # serve plane's promotion budget and the autoscaler's arming
        # pressure — both read ``trainer.slo_tracker`` lazily, so
        # construction order against them does not matter.
        from minips_tpu.obs import slo as _slo

        slo_cfg = _slo.maybe_config(slo)
        self.slo_tracker = None
        if slo_cfg is not None:
            tenants = (list(self.tables)
                       if self.tenant_registry is not None else [])
            self.slo_tracker = _slo.SloTracker(
                slo_cfg, self.obs_window, tenants)
        fl = _fl.FLIGHT
        if fl is not None:
            # the black box's final windowed-metrics snapshot: every
            # dump carries the fleet's last K intervals, not the
            # since-boot aggregate (None when the window layer is off)
            fl.snapshot_hook = (self.window_stats
                                if self.obs_window is not None
                                else None)

    def _register_window_signals(self) -> None:
        """Point the windowed layer at every cumulative signal the
        stack already keeps — no second recording path anywhere: the
        hot paths keep feeding the one histogram/counter, the window
        snapshots deltas once per clock boundary. Layers that are off
        simply never register (their done-line window entries are
        absent, matching their None top-level blocks)."""
        ow = self.obs_window

        def _hist_fn(hists):
            if len(hists) == 1:
                # the common one-table shape: hand the ROLL the live
                # counts list — roll's own list(fn()) is the only copy
                # (reading int buckets under the GIL is safe; a racing
                # increment lands in the next interval's delta). The
                # roll runs once per clock boundary, but a 3-way
                # oversubscribed host still notices every extra lock
                # hop and copy in it.
                h = hists[0]
                return lambda: h.counts
            return lambda: merge_counts([h.snapshot() for h in hists])

        tables = list(self.tables.values())
        for name in ("pull_latency", "pull_blocked", "push_ack"):
            ow.register_hist(name, _hist_fn(
                [t.timers.hists[name] for t in tables]))
        ow.register_hist("serve",
                         _hist_fn([t.hist_serve for t in tables]))
        ow.register_hist("park",
                         _hist_fn([t.hist_park for t in tables]))
        ow.register_hist("fence",
                         _hist_fn([t.hist_fence for t in tables]))
        ow.register_counter("frames_dropped",
                            lambda: self.frames_dropped)
        ow.register_counter("wire_frames_lost",
                            lambda: self.wire_frames_lost)
        ow.register_counter("gate_waits",
                            lambda: self.gate.gate_waits)
        if self.serve_plane is not None:
            ow.register_hist("replica_serve", lambda: merge_counts(
                [t._sv.hist_replica.snapshot() for t in tables
                 if t._sv is not None]))

            def _sv_sig(key):
                return lambda: sum(
                    t._sv.load_signal()[key] for t in tables
                    if t._sv is not None)

            ow.register_counter("shed", _sv_sig("shed"))
            ow.register_counter("backpressure", _sv_sig("bp"))
            # push-visible-at-replica lag (obs/freshness.py): the
            # fleet's windowed freshness quantiles — per-tenant twins
            # register below with the other per-tenant signals
            ow.register_hist("freshness", lambda: merge_counts(
                [t._sv.fresh.hist.snapshot() for t in tables
                 if t._sv is not None]))
        if getattr(self, "tenant_registry", None) is not None:
            # per-tenant SLO telemetry: each tenant's own windowed
            # pull tail (the heat report's p99 reads
            # ``pull_latency:{table}`` instead of the fleet blend —
            # balance/rebalancer._send_heat) plus its attributed deny
            # counters, so "who is being shed" is a window read
            for name, t in self.tables.items():
                ow.register_hist(f"pull_latency:{name}", _hist_fn(
                    [t.timers.hists["pull_latency"]]))
                ow.register_counter(
                    f"shed:{name}",
                    lambda t=t: t.tenant_counters["shed"])
                ow.register_counter(
                    f"throttle:{name}",
                    lambda t=t: t.tenant_counters["throttle"])
                if t._sv is not None:
                    # the tenant's own freshness tail — what its SLO
                    # burn (obs/slo.py) is judged on
                    ow.register_hist(f"freshness:{name}", _hist_fn(
                        [t._sv.fresh.hist]))
        if self.hedge_cfg is not None:
            ow.register_counter(
                "hedges_fired",
                lambda: sum(t.hedge_counters["fired"]
                            for t in tables))
        if self.hier_cfg is not None:

            def _hier_sig(key):
                return lambda: sum(t.hier_counters[key]
                                   for t in tables)

            ow.register_counter("hier_l2_bytes",
                                _hier_sig("l2_tx_bytes"))
            ow.register_counter("hier_agg_frames",
                                _hier_sig("agg_frames"))
            ow.register_counter("hier_fallbacks",
                                _hier_sig("fallbacks"))
            ow.register_hist("hier_flush", _hist_fn(
                [t.hist_hier for t in tables]))
        rel = getattr(self.bus, "reliable", None)
        if rel is not None:
            ow.register_counter(
                "retransmits", lambda: rel.stats["retransmits_got"])
            ow.register_counter(
                "gave_up", lambda: rel.stats["gave_up"])
            ow.register_gauge("gap_age_s", rel.oldest_gap_age)
        if self.monitor is not None and hasattr(self.monitor,
                                                "stall_forgiven"):
            ow.register_counter(
                "hb_stall_forgiven",
                lambda: self.monitor.stall_forgiven)

    def _gate_poll(self) -> None:
        """Gate-wait poll (StalenessGate.poll_hook): the adoption and
        death-transition work the pull-wait loops already do, run from
        inside a blocked gate so a plan landing mid-wait is adopted on
        the push-driving thread instead of waiting for a tick that may
        never come."""
        if self.membership is not None:
            self.membership.poll()
        self.rebalancer.adopt_now()

    def admit_pull(self, clk: int) -> bool:
        """Reference ``model->Get`` admission: serve a pull stamped with
        requester clock ``clk`` iff global_min >= clk - staleness — the
        shared ``consistency.gate.admits`` predicate, which the client
        row cache also runs as its validity rule."""
        return admits(self.gossip.global_min(), clk, self.staleness)

    def admit_pull_s(self, clk: int, s: float) -> bool:
        """:meth:`admit_pull` under an explicit staleness bound — the
        per-tenant entry point (tenant/registry.py): a tenant with its
        own ``s`` is judged against THAT bound over the same gossip
        min, so one tenant's looser contract never loosens another's.
        ``ShardedTable._admit_clk`` probes for this method by name and
        falls back to :meth:`admit_pull` on stub consistency objects."""
        return admits(self.gossip.global_min(), clk, s)

    def serving_clock(self, requester: int) -> int:
        """The freshness certificate a table stamps on pull replies to
        ``requester``: my view of every OTHER worker's applied clock
        (``ClockGossip.min_excluding`` — per-link FIFO certifies the
        requester's own pushes separately, and the client keeps
        read-your-own-writes via push write-through/invalidation)."""
        return int(self.gossip.min_excluding(requester))

    def wait_admit_pull(self, clk: int,
                        timeout: Optional[float] = None) -> bool:
        """Condition-variable wait for :meth:`admit_pull` — the local-
        shard admission hook PullFuture.wait uses instead of polling."""
        if self.staleness == float("inf"):
            return True
        return self.gossip.wait_global_min(clk - int(self.staleness),
                                           timeout=timeout)

    def _drain_parked(self) -> None:
        for t in self.tables.values():
            t.serve_parked()

    def _on_flush(self, sender: int, payload: dict) -> None:
        # FIFO per link: every push `sender` addressed to me precedes its
        # flush broadcast, so by now my shards hold all its updates.
        with self._fin_cond:
            self._flushed.add(sender)
            self._fin_cond.notify_all()
        self.bus.send(sender, "psFlushAck", {})

    def _on_flush_ack(self, sender: int, payload: dict) -> None:
        with self._fin_cond:
            self._acked.add(sender)
            self._fin_cond.notify_all()

    def _on_bye(self, sender: int, payload: dict) -> None:
        with self._fin_cond:
            self._byes.add(sender)
            self._fin_cond.notify_all()

    # ------------------------------------------------------------------ api
    def table(self, name: str) -> ShardedTable:
        return self.tables[name]

    def tick(self) -> None:
        """Advance my clock, gossip it, and gate (BSP/SSP/ASP rule) —
        ``KVClientTable::Clock()``. With async push under a FINITE
        staleness bound the clock boundary DRAINS the send queue first:
        every step-``k`` push frame must be on the wire BEFORE my
        clock-``k`` frame so per-link FIFO keeps the staleness proof
        intact (an undrained queue would silently widen staleness past
        the bound). Under ASP (``staleness=inf``) there is no bound for
        the drain to protect — admission always passes — so the clock
        frame goes out immediately and the sender keeps draining behind
        the next step's compute: the fully-overlapped pipeline the bench
        measures. Ack settlement — pure loss detection — stays off the
        step path in both regimes: the window/queue backpressure bounds
        it and finalize() hard-drains it."""
        if self._kill_check is not None:
            # seeded death drill: SIGKILL lands HERE, before the drain
            # and before the clock frame — the corpse's last published
            # clock is the previous step's, exactly a mid-step loss
            self._kill_check(self.clock)
        if self._chaos_clock is not None:
            # partition windows advance on the same boundary currency
            # as the kill drill: "at=8" cuts from the moment this rank
            # reaches clock 8
            self._chaos_clock(self.clock)
        if self.obs_window is not None:
            # close the previous step's metrics interval BEFORE any
            # control decision below (autoscaler signals, rbH reports)
            # reads a windowed value — the roll is this boundary's one
            # snapshot pass over the cumulative hists/counters
            self.obs_window.roll()
            if self.slo_tracker is not None:
                # burn evaluation rides the roll it just closed: the
                # fast window always includes the newest interval, and
                # the burning set is settled BEFORE the autoscaler
                # below reads it as pressure (and before the serve
                # plane's post-gate promotion reads the boost)
                self.slo_tracker.on_roll()
        if self.slowness is not None:
            # the fail-slow judgment rolls on the same boundary, BEFORE
            # the membership/rebalancer decisions below read verdicts:
            # a suspicion raised here rides this boundary's heartbeat
            # ballot, and the planner's demotion bias sees the freshest
            # quorum view. Dead/left ranks leave the judged set first —
            # a corpse's tail is the death path's business.
            for p in self.gossip.excluded:
                self.slowness.exclude(p)
            self.slowness.roll()
        drain = self.staleness != float("inf")
        for t in self.tables.values():
            if drain:
                t.flush_pushes(acks=False)  # a jammed drain poisons…
            # aged error-feedback residuals ship BEFORE the clock frame
            # (same per-link ordering argument as the drain above): a
            # withheld write may trail its push by at most `staleness`
            # boundaries — the compressed wire's half of the SSP story
            t.residual_flush(aged_only=True)
            # hier boundary LAST in the per-table block and ALWAYS
            # (ASP included — floors advance even when admission is
            # vacuous): this step's contributions and residual flushes
            # are on their links, so the boundary certificate is true,
            # and it precedes my clock frame like everything above
            t.hier_boundary()
            t.check_fatal()                 # …and this raises, no hang
        if self.autoscaler is not None:
            # BEFORE the membership queues run: an admit credit granted
            # here is consumed by membership.on_tick at this same
            # boundary on the lease holder (non-holders no-op)
            self.autoscaler.on_tick()
        if self.membership is not None:
            # BEFORE the rebalancer's adoption point: a transition plan
            # issued here is adopted in this same tick at the
            # coordinator, at the next boundary everywhere else
            self.membership.on_tick()
        if self.rebalancer is not None:
            # THE clock boundary: step-k pushes are drained to the bus
            # above, the clock frame has not gone out yet — adopt any
            # pending routing table here (epoch fence point), decay +
            # gossip heat, and (coordinator) maybe plan a migration
            self.rebalancer.on_tick()
        self.clock += 1
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("clock", "tick", {"clock": self.clock})
        self.gossip.publish_local([self.clock])
        self.gate.wait(self.clock)
        self.gated_clock = self.clock
        if self.serve_plane is not None:
            # AFTER the gate on purpose: the gate just proved
            # global_min >= clock - s, so a replica refresh stamped
            # HERE is admissible at the current clock for the whole
            # upcoming step window — refreshing before the gate ships
            # stamps one step staler and replicas refuse most reads
            # (measured: the storm's replica hit rate collapses)
            self.serve_plane.on_tick()
        for t in self.tables.values():
            t.cache_age()  # rows un-admittable at the new clock die here

    def retire(self) -> None:
        """Out of data: the shared sentinel clock (gate.py RETIRED_CLOCK)
        so peers' gates (and owner-side pull admission) never wait on this
        finished worker — dynamic block assignment makes per-worker step
        counts unequal."""
        from minips_tpu.consistency.gate import publish_clock

        self._retired = True
        publish_clock(self.gossip, self.clock, True)

    def finalize(self, timeout: float = 30.0) -> None:
        """Two-sided quiesce: my pushes applied at all owners (their acks)
        AND all peers' pushes applied at my shards (their flushes). After
        this, pull/pull_all return identical rows on every live process."""
        if self.membership is not None:
            self.membership.quiesce()  # no further transitions
        if self.rebalancer is not None:
            # no further plans; a plan that landed after my last tick
            # still gets adopted + acked here so peers' fences release
            self.rebalancer.stop()
            self.rebalancer.adopt_now()
        if self.serve_plane is not None:
            # post-finalize agreement is EXACT, not staleness-bounded:
            # stop granting and stop routing my own pulls to replicas
            # (their leases go dark by expiry; no revoke frames race
            # the shutdown barrier)
            self.serve_plane.quiesce()
        for t in self.tables.values():
            # order matters (the adopt_table pattern): quiesce the hier
            # tree FIRST — a member's cross-host mass may sit in its
            # leader's buckets, and the psFlush below only certifies
            # MY links, so the tree must drain (leader flush or member
            # fallback) before the flush broadcast means anything —
            # then drain the async queue (a queued topk push encodes
            # on the sender thread and RETAINS fresh residuals, so
            # flushing before the drain would strand exactly the mass
            # the flush exists to ship), then flush the whole store
            # (post-finalize agreement is exact), then the hard ack
            # drain covers the flush frames too
            t.hier_finalize(timeout=timeout * 0.66)
            t.flush_pushes(acks=False)
            t.residual_flush(reason="fence")
            t.flush_pushes()  # async tail: drained before the flush frame
            t.check_fatal()
            t.cache_clear()   # post-finalize reads are exact, not bounded
        self.bus.publish("psFlush", {"clock": self.clock})
        from minips_tpu.consistency.gate import publish_clock

        publish_clock(self.gossip, self.clock,
                      getattr(self, "_retired", False))
        peers = set(range(self.num_processes)) - {self.bus.my_id}
        deadline = time.monotonic() + timeout
        try:
            while True:
                with self._fin_cond:
                    live = peers - self.gossip.excluded
                    if live <= self._flushed and live <= self._acked:
                        return
                    self._fin_cond.wait(timeout=0.5)
                dead = (self.monitor.check()
                        if self.monitor is not None else set())
                for p in dead:
                    self.gossip.exclude(p)
                if time.monotonic() > deadline:
                    with self._fin_cond:
                        live = peers - self.gossip.excluded
                        missing = sorted((live - self._flushed)
                                         | (live - self._acked))
                    _fl.poison("finalize_deadline",
                               {"missing": missing})
                    raise TimeoutError(
                        f"finalize: peers {missing} never quiesced")
        finally:
            # the per-rank trace AND the flight box survive the run
            # either way: a clean finalize dumps here, a poisoned one
            # dumps here AND again at atexit (idempotent) with
            # whatever events followed
            _trc.dump_now()
            _fl.dump_now()

    def shutdown_barrier(self, timeout: float = 10.0) -> None:
        """Rendezvous before closing the bus: finalize() only quiesces
        PUSHES; a peer's post-finalize pull_all still needs my server
        alive. Everyone announces 'bye' after its last pull and waits for
        all live peers' byes — then nobody's close() can strand a peer's
        in-flight pull. A timeout is tolerated (the straggler is either
        dead, which the monitor reports, or about to finish without us)."""
        self.bus.publish("psBye", {})
        peers = set(range(self.num_processes)) - {self.bus.my_id}
        deadline = time.monotonic() + timeout
        while True:
            with self._fin_cond:
                if peers - self.gossip.excluded <= self._byes:
                    return
                self._fin_cond.wait(timeout=0.25)
            dead = self.monitor.check() if self.monitor is not None else set()
            for p in dead:
                self.gossip.exclude(p)
            if time.monotonic() > deadline:
                return

    # ------------------------------------------------------------ checkpoint
    # The trainer is a "table" to ckpt.Checkpointer — PS state includes the
    # clock (SURVEY.md §5.4 "checkpointing optimizer state + clock vector").
    def state_dict(self) -> dict:
        return {"clock": np.asarray(self.clock)}

    def load_state_dict(self, state: dict) -> None:
        from minips_tpu.consistency.gate import publish_clock

        self.clock = int(state["clock"])
        self.gated_clock = self.clock  # restored state is settled state
        # publish the restored clock NOW (not at the first tick): a resumed
        # rank's first pull is stamped with this clock, and owners park it
        # until their view of every peer reaches clock - s — peers that
        # haven't announced their restored clocks still read as 0. All
        # ranks restore before stepping, so these publishes un-park each
        # other; without them resume deadlocks at the first pull.
        publish_clock(self.gossip, self.clock,
                      getattr(self, "_retired", False))

    # ------------------------------------------------------------- metrics
    @property
    def gate_waits(self) -> int:
        return self.gate.gate_waits

    @property
    def max_skew_seen(self) -> int:
        return self.gate.max_skew_seen

    @property
    def frames_dropped(self) -> int:
        return sum(t.frames_dropped for t in self.tables.values())

    @property
    def wire_frames_lost(self) -> int:
        """Bus-level frames provably lost on established streams (zmq HWM
        drops / torn link tails — comm/bus.py FrameLossTracker). Disjoint
        from frames_dropped (frames that ARRIVED but were rejected). With
        the reliable channel on (comm/reliable.py) this is UNRECOVERED
        loss only — a retransmitted frame that landed never counts."""
        return getattr(self.bus, "frames_lost", 0)

    @property
    def wire_frames_malformed(self) -> int:
        """Undecodable control frames dropped at receive — counted, not
        silently swallowed (comm/bus.py dispatch_message); nonzero means
        a stale run's tail or genuine wire corruption."""
        return getattr(self.bus, "frames_malformed", 0)

    def reliable_stats(self) -> Optional[dict]:
        """Retransmission-protocol counters (comm/reliable.py snapshot):
        None when the channel is off, so scrapers can tell 'off' from
        'clean'. nacks/retransmits > 0 with frames_lost == 0 is the
        layer working as designed — loss became latency."""
        rel = getattr(self.bus, "reliable", None)
        return rel.snapshot() if rel is not None else None

    def chaos_stats(self) -> Optional[dict]:
        """Fault-injection counters (comm/chaos.py) when a chaos drill
        is armed; None in production runs."""
        ch = getattr(self.bus, "chaos", None)
        return ch.snapshot() if ch is not None else None

    def drop_detail(self) -> dict:
        out = {"malformed": 0, "misrouted": 0, "config": 0}
        for t in self.tables.values():
            for k, v in t.drops.items():
                out[k] += v
        return out

    def comm_timing(self) -> dict:
        """Aggregate per-leg wire timing over all tables: pull issue→
        reply latency, blocked time, overlap fraction, push ack latency,
        plus rows-requested/rows-wire and cache hit counters
        (utils/timing.CommTimers.summary fields)."""
        return CommTimers.aggregate(
            [t.timers for t in self.tables.values()])

    def hist_stats(self) -> dict:
        """Log2 latency histograms over all tables, as p50/p95/p99
        summary blocks (obs/hist.py) — the done-line ``hist`` field.
        Always a dict (the layer is always on); a quantity with no
        samples yet reports ``{"count": 0}`` — idle, not off."""
        return tables_hist_stats(self.tables.values())

    def window_stats(self) -> Optional[dict]:
        """The done-line ``window`` block (obs/window.py record): per-
        signal quantiles/rates over the last K clock boundaries. None
        when the layer is OFF (``MINIPS_OBS=0``); an armed-but-idle
        window reports ``{"count": 0}`` per hist — the PR5/PR6
        off-vs-idle convention, pinned by the schema test."""
        return (self.obs_window.record()
                if self.obs_window is not None else None)

    def heartbeat_stats(self) -> Optional[dict]:
        """Liveness-layer counters (comm/heartbeat.py stats): the
        ``stall=`` forgiveness window's arming and HITS — a forgiven
        stall is detection latency the operator traded for and must be
        visible, not silent. None when no monitor is attached."""
        mon = self.monitor
        if mon is None or not hasattr(mon, "stats"):
            return None
        return mon.stats()

    def hedge_stats(self) -> Optional[dict]:
        """Hedged-pull counters summed over tables (serve/hedge.py):
        None when hedging is OFF, all-zero when armed-but-idle — the
        off-vs-idle done-line convention. ``fired``/``won``/``lost``
        prove engagement; ``no_holder`` counts the honest no-replica
        ceiling; ``denied`` the budget valve."""
        if self.hedge_cfg is None:
            return None
        out = {k: 0 for k in ("fired", "won", "lost", "no_holder",
                              "denied")}
        for t in self.tables.values():
            for k, v in t.hedge_counters.items():
                out[k] += v
        out["delay_ms"] = self.hedge_cfg.delay_ms or None
        out["budget"] = self.hedge_cfg.budget
        return out

    def hier_stats(self) -> Optional[dict]:
        """Two-level push-tree counters summed over tables
        (balance/hier.py): None when MINIPS_HIER is off, all-zero
        byte/frame counters when armed-but-idle (``group=1``) — the
        off-vs-idle done-line convention. ``l1_*``/``l2_*`` split the
        wire by level (the HIER-WIN gate reads l2, the leader leg);
        ``elections``/``fallbacks``/``repushed_steps`` tell the
        leader-death story; ``stale_leader_drops``/``repush_drops``
        count the exactly-once fences doing their job."""
        if self.hier_cfg is None:
            return None
        out: dict = {}
        for t in self.tables.values():
            for k, v in t.hier_counters.items():
                out[k] = out.get(k, 0) + int(v)
        out["group"] = self.hier_cfg.group
        out["agg"] = self.hier_cfg.agg
        out["retain"] = self.hier_cfg.retain
        for t in self.tables.values():
            # every table elects from the same gossip inputs — one
            # table's live tree state speaks for the trainer (the
            # leader-death drill reads the post-heal leader here)
            st = t.hier_stats()
            out["leader"] = st["leader"]
            out["direct"] = st["direct"]
            break
        return out

    def hybrid_stats(self) -> Optional[dict]:
        """Hybrid data plane (``agg=mesh``) block for ``wire_record``:
        None when hier is off or the host f64 backend is configured,
        ALL-ZERO when armed but idle (``group=1`` never flushes) — the
        off-vs-idle convention, and all-NUMERIC by contract so sweep
        tooling can diff any two arms field-by-field (schema test)."""
        if self.hier_cfg is None or self.hier_cfg.agg != "mesh":
            return None
        out = {"backend_mesh": 0, "mesh_reduces": 0,
               "rows_reduced": 0, "mesh_collective_bytes": 0,
               "peak_stage_bytes": 0, "mesh_agg_fallbacks": 0,
               "domain_demotions": 0, "domain_down": 0}
        for t in self.tables.values():
            out["mesh_reduces"] += int(
                t.hier_counters["mesh_reduces"])
            out["mesh_agg_fallbacks"] += int(
                t.hier_counters["mesh_agg_fallbacks"])
            out["domain_demotions"] += int(
                t.hier_counters["domain_demotions"])
            out["domain_down"] = max(out["domain_down"],
                                     int(t._hier_domain_down))
            m = t._hier_mesh
            if m is not None:
                out["backend_mesh"] = max(out["backend_mesh"],
                                          int(m.m >= 2))
                out["rows_reduced"] += int(m.rows_reduced)
                out["mesh_collective_bytes"] += int(
                    m.collective_bytes)
                out["peak_stage_bytes"] = max(
                    out["peak_stage_bytes"], int(m.peak_stage_bytes))
        return out

    def slowness_stats(self) -> Optional[dict]:
        """Fail-slow detection state (obs/slowness.py): None when
        MINIPS_SLOW is off; armed runs carry the suspect set, per-peer
        windowed p99s, streaks, and — with the membership plane armed
        — the quorum's slow-verdict view."""
        if self.slowness is None:
            return None
        out = self.slowness.stats()
        mb = self.membership
        if mb is not None and hasattr(mb, "slow_stats"):
            out.update(mb.slow_stats())
        return out

    def serve_stats(self) -> dict:
        """Per-owner serve-load counters summed over tables (always on):
        requests/rows THIS process served as an owner — the done-line
        field sweeps compute max/mean per-shard serve load from, i.e.
        the partition-imbalance observable the rebalancer acts on.
        The ``replica`` sub-block carries the serving plane's counters
        (replica-served rows, shed/backpressure, lease refusals, SLO):
        None when the plane is OFF, all-zero counters when armed but
        idle — the PR5 off-vs-idle convention."""
        out = {"pull_requests": 0, "pull_rows": 0,
               "push_frames": 0, "push_rows": 0}
        for t in self.tables.values():
            with t._serve_lock:
                for k in out:
                    out[k] += t.serve[k]
        out["replica"] = (self.serve_plane.stats_record()
                          if self.serve_plane is not None else None)
        return out

    def tenant_stats(self) -> Optional[dict]:
        """Per-tenant SLO evidence (tenant/registry.py) — None when
        tenancy is off, zero counters when armed but idle (the
        off-vs-idle convention; the TENANT-IDLE gate pins the zeros).
        One block per tenant: its id, its spec'd overrides, the deny
        counters the serve plane attributed to ITS budget (shed =
        svS redirects, throttle = svB backpressure, stale_reads =
        replies its own ``s`` refused, hedge_denied = its hedge-budget
        valve), and its own serve-load counters — the per-tenant
        split of the fleet-summed signals PR 12 couldn't separate."""
        reg = getattr(self, "tenant_registry", None)
        if reg is None:
            return None
        by: dict = {}
        for name, t in self.tables.items():
            sp = t._tenant
            if sp is None:
                continue
            with t._serve_lock:
                tc = dict(t.tenant_counters)
                sv = dict(t.serve)
            by[name] = {"tid": sp.tid, **tc,
                        "pull_rows": sv["pull_rows"],
                        "push_rows": sv["push_rows"],
                        "overrides": sp.overrides()}
        return {"shared": int(reg.shared), "tenants": by}

    def freshness_stats(self) -> Optional[dict]:
        """Push-visible-at-replica lag (obs/freshness.py) — None when
        the serving plane is OFF (no replicas, nothing to be visible
        at), ``{"count": 0}`` lag summaries + zero counters when armed
        but idle (the off-vs-idle convention). ``fleet`` merges every
        table's tracker; ``tenants`` carries the per-table split (one
        tenant per table under tenancy) so the done line shows each
        tenant's freshness p50/p99 next to its read p99."""
        if self.serve_plane is None:
            return None
        from minips_tpu.obs.freshness import merge_freshness

        trackers = {name: t._sv.fresh
                    for name, t in self.tables.items()
                    if t._sv is not None}
        return {"fleet": merge_freshness(list(trackers.values())),
                "tenants": {name: tr.record()
                            for name, tr in trackers.items()}}

    def slo_stats(self) -> Optional[dict]:
        """SLO burn-rate accounting (obs/slo.py) — None when MINIPS_SLO
        is off, zero counters and an empty burning set when armed but
        idle. Carries the fast/slow window shape, per-tenant burn
        ratios, the flight-evented burn/clear edge counts, and the
        promotion-budget proof (``boost_ticks``, per-tenant
        ``max_budget``)."""
        return (self.slo_tracker.record()
                if self.slo_tracker is not None else None)

    def rebalance_stats(self) -> Optional[dict]:
        """Rebalancer counters (balance/rebalancer.py) — None when the
        subsystem is off, so scrapers can tell 'off' from 'idle'."""
        return (self.rebalancer.stats()
                if self.rebalancer is not None else None)

    def reshard_stats(self) -> Optional[dict]:
        """Planned-redistribution counters summed over tables (peak
        staging is a MAX — the cap bounds each rank's worst round, not
        a sum) — None when MINIPS_RESHARD is off, zero counters when
        armed but idle (the off-vs-idle convention)."""
        per = [s for s in (t.reshard_table_stats()
                           for t in self.tables.values())
               if s is not None]
        if not per:
            return None
        out = {k: sum(s[k] for s in per)
               for k in ("plans", "rounds", "slices", "dup_slices",
                         "aborts", "blocks_inflight")}
        out["peak_stage_bytes"] = max(s["peak_stage_bytes"]
                                      for s in per)
        out["cap"] = per[0]["cap"]
        out["fanout"] = per[0]["fanout"]
        return out

    def membership_stats(self) -> Optional[dict]:
        """Elastic-membership counters (balance/membership.py): the
        live/standby/dead/left sets, the coordinator lease (term,
        holder, successions, fenced frames), transition counts, and
        restored blocks — None when MINIPS_ELASTIC is off (off vs
        idle)."""
        return (self.membership.stats()
                if self.membership is not None else None)

    def autoscale_stats(self) -> Optional[dict]:
        """Closed-loop autoscaler counters (balance/autoscaler.py):
        admits/drains, hot/calm tick streaks, pre/post-admit shed
        rates, p99 watermarks — None when MINIPS_AUTOSCALE is off
        (off vs idle)."""
        return (self.autoscaler.stats()
                if self.autoscaler is not None else None)

    def ef_stats(self) -> Optional[dict]:
        """Merged error-feedback residual counters over all tables —
        the done-line ``ef`` field (None when no table runs a
        compressed push wire; zero counters = armed but idle)."""
        per = [s for s in (t.ef_stats() for t in self.tables.values())
               if s is not None]
        if not per:
            return None
        return {k: sum(s[k] for s in per) for k in per[0]}

    def cache_stats(self) -> Optional[dict]:
        """Merged row-cache counters over all tables (None when every
        table runs cache-off) — the done-line 'cache' field."""
        per = [s for s in (t.cache_stats() for t in self.tables.values())
               if s is not None]
        if not per:
            return None
        out = {k: sum(s[k] for s in per)
               for k in ("hits", "lookups", "evictions", "invalidations",
                         "write_throughs", "rows", "bytes")}
        out["hit_rate"] = (round(out["hits"] / out["lookups"], 4)
                           if out["lookups"] else None)
        return out

    @property
    def bytes_pushed(self) -> int:
        return sum(t.bytes_pushed for t in self.tables.values())

    @property
    def bytes_pulled(self) -> int:
        return sum(t.bytes_pulled for t in self.tables.values())

    def local_bytes(self) -> int:
        return sum(t.local_bytes() for t in self.tables.values())
