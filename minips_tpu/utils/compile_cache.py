"""Persistent XLA compilation cache (opt-in helper).

The test suite's wall-clock is dominated by XLA compiles, not by the tests
themselves (VERDICT round-1 weak #6: the suite must fit the driver's
budget). JAX ships a content-addressed persistent cache keyed on (HLO,
jaxlib version, backend, flags); enabling it turns every warm rerun of the
suite — and of `bench.py`, whose first TPU compile is 20-40s — into cache
hits. This helper centralizes the knobs so tests, bench, and apps enable it
identically.

Cold runs are unaffected (the cache only adds a write); correctness is
unaffected (cache keys include the program, so a changed model recompiles).
Disable with ``MINIPS_NO_COMPILE_CACHE=1`` when measuring true compile
times.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent compilation cache. Returns the cache dir,
    or None when disabled via ``MINIPS_NO_COMPILE_CACHE``.

    Default location: ``$MINIPS_COMPILE_CACHE`` if set, else
    ``~/.cache/minips_tpu/xla`` — deliberately OUTSIDE the repo so driver
    checkouts/clean trees keep their warm cache.

    Multi-process jobs get a PER-RANK subdirectory: two ranks of one job
    compile the same programs at the same moment, and sharing one cache
    dir between them deadlocked the BSP lockstep smokes (a rank stalled
    >60s inside compilation while its peer waited at the consistency
    gate). No in-tree caller is ranked today (see next paragraph) —
    the branch is defensive, for any future ranked caller.

    LAUNCHER CHILDREN DO NOT CALL THIS (round-5 finding, re-attempted
    twice — do not try a third time without new evidence). Attempt 1:
    per-rank dirs, warm reads hung children intermittently with XLA
    logging ``cpu_aot_loader ... could lead to execution errors such as
    SIGILL`` (persistent ~/.cache artifacts from a different sandbox
    host's CPU). Attempt 2: host-fingerprint-scoped dirs (CPU flags +
    jaxlib hash) to rule out foreign artifacts — the wd collective
    smokes then ran 2.5x SLOWER and the bsp leg reproducibly died on
    Gloo's 30s rendezvous deadline (``GetKeyValue() timed out``): with
    min-compile-time 0 every tiny program pays a serialize+write, and
    on this 1-core box that inflates and SKEWS the two ranks' arrival
    at their first collective past the deadline. The single-process
    test runner and bench keep the cache (no rendezvous to miss); the
    multi-process smokes run cache-less and eat the compiles."""
    if os.environ.get("MINIPS_NO_COMPILE_CACHE"):
        return None
    import jax

    path = (cache_dir
            or os.environ.get("MINIPS_COMPILE_CACHE")
            or os.path.expanduser("~/.cache/minips_tpu/xla"))
    rank = os.environ.get("MINIPS_PROC_ID")
    if rank is not None:
        path = os.path.join(path, f"rank{rank}")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        # unwritable/absent HOME (read-only CI sandboxes): run without a
        # warm cache rather than aborting the caller at import time
        return None
    jax.config.update("jax_compilation_cache_dir", path)
    # default thresholds skip sub-second compiles; the suite's cost is the
    # long tail of many 1-10s CPU compiles, so cache everything
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
