"""Planned collective redistribution (balance/redistribute.py + the
planned shipper/ingester in train/sharded_ps.py + the streaming elastic
restore in ckpt/elastic.py) — this PR's tentpole.

Layers of drill:

- pure logic: the MINIPS_RESHARD spec parser (+ the shared seeded
  grammar fuzzer), and the round planner's property sweep — every
  moved block's rows land in exactly one exchange, no round stages
  more than the cap at any rank, the partner fanout holds, and the
  schedule is deterministic under input shuffling (what lets every
  rank compile the identical plan with zero coordination frames);
- threads-as-nodes over real loopback buses: a cap-bounded planned
  migration is BITWISE the p2p migration (state moved in rounds,
  never perturbed), the degenerate plan ships byte-identical rbS
  blobs, redelivered slices drop idempotently (``reshard_resume``),
  a source death mid-plan aborts partial slices back to checkpoint
  state (``reshard_abort``), round/resume/abort events land in the
  zero-pre-arming flight box, and the whole protocol composes with
  seeded chaos + the retransmit layer;
- the streaming N→M restore: ``reshard_table_state`` under a byte cap
  is bitwise the whole-array read with MEASURED peak staging under
  the cap (the RESHARD-MEM observable), through rebalance overlays;
- whole-host evacuation: one ``plan_evacuation`` call re-homes every
  block of EVERY rank in a failure domain in one deterministic plan.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from minips_tpu.balance.membership import plan_evacuation
from minips_tpu.balance.redistribute import (Exchange, ReshardConfig,
                                             maybe_config,
                                             peak_stage_bytes,
                                             plan_rounds,
                                             state_row_bytes)
from minips_tpu.balance.rebalancer import RebalanceConfig
from minips_tpu.obs import flight as fl
from minips_tpu.parallel.partition import BlockRouter, RangePartitioner
from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


class _StubRB:
    """Table-level rebalancer stand-in (test_rebalance.py's) — planned
    shipping rides the migration machinery, so arming it is the
    precondition ``attach_reshard`` enforces."""

    def __init__(self):
        self.tables = []

    def adopt_now(self):
        pass

    def note_plan(self, name, ep, ov):
        for t in self.tables:
            if t.name == name:
                t.adopt_table(ep, ov)


def _attach(tables, spec="block=4", reshard=None):
    rb = _StubRB()
    rb.tables = list(tables)
    cfg = RebalanceConfig.parse(spec)
    for t in tables:
        t.attach_rebalancer(rb, cfg)
        if reshard is not None:
            t.attach_reshard(ReshardConfig.parse(reshard))
    return cfg


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.01)


@pytest.fixture
def flight_box(tmp_path):
    """A fresh flight recorder in a tmp dir (the zero-pre-arming box
    the reshard_round/resume/abort events must land in)."""
    fl.reset_for_tests()
    rec = fl.init(0, str(tmp_path / "box"))
    yield rec
    fl.reset_for_tests()


def _flight_kinds(rec):
    rec.dump()
    doc = json.load(open(rec.out_path))
    return [e["kind"] for e in doc["events"]]


# --------------------------------------------------------- config spec
def test_reshard_config_parses_and_rejects_garbage():
    c = ReshardConfig.parse("cap=64m,fanout=4")
    assert (c.cap, c.fanout) == (64 << 20, 4)
    assert ReshardConfig.parse("cap=2k").cap == 2048
    assert ReshardConfig.parse("cap=1g").cap == 1 << 30
    assert ReshardConfig.parse("cap=512").cap == 512
    d = ReshardConfig.parse("1")
    assert (d.cap, d.fanout) == (64 << 20, 2)  # defaults
    with pytest.raises(ValueError, match="unknown knob"):
        ReshardConfig.parse("explode=1")
    with pytest.raises(ValueError, match="k=v"):
        ReshardConfig.parse("cap")
    with pytest.raises(ValueError, match="cap"):
        ReshardConfig.parse("cap=abc")
    with pytest.raises(ValueError, match="fanout"):
        ReshardConfig.parse("fanout=x")
    with pytest.raises(ValueError, match="cap"):
        ReshardConfig.parse("cap=0")
    with pytest.raises(ValueError, match="fanout"):
        ReshardConfig.parse("fanout=0")


def test_reshard_maybe_config_env_convention(monkeypatch):
    monkeypatch.delenv("MINIPS_RESHARD", raising=False)
    assert maybe_config() is None              # unset = off
    monkeypatch.setenv("MINIPS_RESHARD", "")
    assert maybe_config() is None              # empty = off
    monkeypatch.setenv("MINIPS_RESHARD", "0")
    assert maybe_config() is None              # "0" = off
    monkeypatch.setenv("MINIPS_RESHARD", "cap=1k")
    assert maybe_config().cap == 1024          # env fallback
    assert maybe_config("cap=2k").cap == 2048  # explicit spec wins
    monkeypatch.setenv("MINIPS_RESHARD", "garbage")
    with pytest.raises(ValueError, match="MINIPS_RESHARD"):
        maybe_config()


def test_reshard_knob_fuzzer_parse_or_refuse_loudly():
    """The shared MINIPS_* spec-hygiene fuzzer (PR15 convention):
    seeded random specs from the alphabet parse or raise ValueError,
    deterministically — never a half-configured planner."""
    rng = np.random.default_rng(20260807)
    vocab = ["cap", "fanout", "bogus"]
    vals = ["0", "1", "3", "64m", "2k", "1g", "-1", "abc", "", "2.5",
            "9999999999"]
    for _ in range(200):
        n = int(rng.integers(0, 5))
        spec = ",".join(
            f"{vocab[rng.integers(0, len(vocab))]}"
            f"={vals[rng.integers(0, len(vals))]}"
            for _ in range(n))
        outcomes = []
        for _rep in range(2):
            try:
                c = ReshardConfig.parse(spec)
                outcomes.append(("ok", c.cap, c.fanout))
            except ValueError as e:
                outcomes.append(("refused", str(e)))
            except Exception as e:  # noqa: BLE001 - the contract
                pytest.fail(f"reshard spec {spec!r} raised "
                            f"{type(e).__name__}: {e}")
        assert outcomes[0] == outcomes[1], spec


# ------------------------------------------------------------- planner
def test_state_row_bytes_mirrors_encode_block_state():
    """The planner's byte model must be the wire's byte model: one
    row's plan_rounds accounting == one row's _encode_block_state blob
    share, per updater — or the cap would bound the wrong quantity."""
    for updater in ("sgd", "adagrad", "adam"):
        t = ShardedTable("t", 16, 3, None, 0, 1, updater=updater)
        n = 4
        st = {"w": np.ones((n, 3), np.float32)}
        if updater == "adagrad":
            st["acc"] = np.ones((n, 3), np.float32)
        if updater == "adam":
            st["m"] = np.ones((n, 3), np.float32)
            st["v"] = np.ones((n, 3), np.float32)
            st["steps"] = np.ones(n, np.int32)
        _head, blob = t._encode_block_state(0, 0, st)
        assert len(blob) == n * state_row_bytes(3, updater), updater


def test_plan_rounds_property_sweep():
    """Seeded randomized properties: exact row coverage (every moved
    block's rows in exactly one exchange set), the per-rank staging cap
    (modulo the documented one-row honest floor), the per-round partner
    fanout, and order-insensitive determinism."""
    rng = np.random.default_rng(7)
    for _case in range(120):
        world = int(rng.integers(2, 7))
        nblocks = int(rng.integers(1, 13))
        blocks = rng.choice(64, size=nblocks, replace=False)
        moves = []
        for b in blocks:
            s = int(rng.integers(0, world))
            d = int(rng.integers(0, world - 1))
            moves.append((int(b), s, d if d < s else d + 1))
        rows = {b: int(rng.integers(1, 40)) for b, _s, _d in moves}
        row_bytes = int(rng.integers(1, 65))
        cap = int(rng.integers(1, 600))
        fanout = int(rng.integers(1, 4))
        rounds = plan_rounds(moves, rows.__getitem__, row_bytes,
                             cap=cap, fanout=fanout)
        # --- coverage: every block's rows exactly once, right endpoints
        spans: dict[int, list] = {b: [] for b in rows}
        for rnd in rounds:
            for ex in rnd:
                assert (ex.block, ex.src, ex.dst) in [
                    (b, s, d) for b, s, d in moves]
                spans[ex.block].append((ex.lo, ex.rows))
        for b, got in spans.items():
            got.sort()
            assert got[0][0] == 0
            hi = 0
            for lo, n in got:
                assert lo == hi, (b, got)  # no gap, no overlap
                hi = lo + n
            assert hi == rows[b], (b, got)
        # --- cap: honored exactly when >= one row's bytes; a smaller
        # cap degrades to one-row slices (the documented honest floor)
        assert peak_stage_bytes(rounds, row_bytes) <= max(cap, row_bytes)
        # --- fanout: distinct partners per rank per round
        for rnd in rounds:
            partners: dict[int, set] = {}
            for ex in rnd:
                partners.setdefault(ex.src, set()).add(ex.dst)
                partners.setdefault(ex.dst, set()).add(ex.src)
            assert all(len(p) <= fanout for p in partners.values())
        # --- determinism: any input order -> the identical schedule
        shuf = list(moves)
        rng.shuffle(shuf)
        assert plan_rounds(shuf, rows.__getitem__, row_bytes,
                           cap=cap, fanout=fanout) == rounds


def test_plan_rounds_degenerate_is_one_round_of_whole_blocks():
    """cap >= every block and fanout >= world: the schedule collapses
    to ONE round of whole-block exchanges — the shape whose shipped
    bytes the byte-identity test below pins against the p2p path."""
    moves = [(3, 0, 1), (7, 1, 2), (9, 2, 0)]
    rounds = plan_rounds(moves, lambda b: 8, 16, cap=1 << 30, fanout=8)
    assert len(rounds) == 1
    assert sorted(rounds[0]) == [Exchange(3, 0, 1, 0, 8),
                                 Exchange(7, 1, 2, 0, 8),
                                 Exchange(9, 2, 0, 0, 8)]


def test_plan_rounds_rejects_bad_input():
    with pytest.raises(ValueError, match="more than one move"):
        plan_rounds([(1, 0, 1), (1, 1, 2)], lambda b: 4, 8,
                    cap=64, fanout=2)
    with pytest.raises(ValueError, match="cap"):
        plan_rounds([], lambda b: 4, 8, cap=0, fanout=2)
    with pytest.raises(ValueError, match="fanout"):
        plan_rounds([], lambda b: 4, 8, cap=64, fanout=0)
    with pytest.raises(ValueError, match="row_bytes"):
        plan_rounds([], lambda b: 4, 0, cap=64, fanout=2)
    assert plan_rounds([], lambda b: 4, 8, cap=64, fanout=2) == []
    assert peak_stage_bytes([], 8) == 0


# ---------------------------------------- migration protocol, in-proc
def test_planned_migration_bitwise_equals_p2p(flight_box):
    """THE equivalence pin: a cap-forced multi-round planned migration
    moves rows AND optimizer state bitwise-identically to the p2p
    whole-block ship, with measured per-round staging <= cap, and the
    round journal in the flight box."""
    buses = _mk_buses(2)
    # adagrad dim=2: 16 B/row, block 0 = 4 rows = 64 B; cap=32 -> two
    # 2-row slices that cannot share a round
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    _attach([t0, t1], reshard="cap=32,fanout=2")
    oracle = ShardedTable("o", 64, 2, None, 0, 1, updater="adagrad",
                          lr=0.1)
    try:
        keys = np.arange(4, dtype=np.int64)
        g1 = np.full((4, 2), 2.0, np.float32)
        t0.push(keys, g1)
        oracle.push(keys, g1)
        w_pre = t0._w[:4].copy()
        acc_pre = t0._acc[:4].copy()
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="planned migration settle")
        np.testing.assert_array_equal(t1._xtra[0]["w"], w_pre)
        np.testing.assert_array_equal(t1._xtra[0]["acc"], acc_pre)
        assert t0.rb_stats["blocks_out"] == 1
        assert t1.rb_stats["blocks_in"] == 1
        # the round schedule: 2 slices over 2 rounds, staging == cap
        assert t0.rs_stats["plans"] == 1
        assert t0.rs_stats["rounds"] == 2
        assert t0.rs_stats["slices"] == 2
        assert 0 < t0.rs_stats["peak_stage_bytes"] <= 32
        assert t0.rb_stats["peak_stage_bytes"] <= 32
        assert t1.rs_stats["dup_slices"] == 0
        assert not t1._slice_prog and not t1._early_prog
        # post-migration pushes step the MOVED state — the oracle pin
        g2 = np.full((4, 2), 1.0, np.float32)
        t0.push(keys, g2)
        oracle.push(keys, g2)
        _wait(lambda: t1.serve["push_rows"] >= 4, msg="push applied")
        np.testing.assert_array_equal(t1._xtra[0]["w"], oracle._w[:4])
        np.testing.assert_array_equal(t1._xtra[0]["acc"],
                                      oracle._acc[:4])
        np.testing.assert_array_equal(t0.pull(keys), oracle._w[:4])
        assert t0.frames_dropped == 0 and t1.frames_dropped == 0
        kinds = _flight_kinds(flight_box)
        assert kinds.count("reshard_round") == 2
    finally:
        for b in buses:
            b.close()


def test_planned_migration_moves_adam_moments_and_steps():
    """The adam wire (m, v, per-row steps) slices and reassembles
    bitwise too — one-row slices, the honest floor in action."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adam",
                      lr=0.05, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adam",
                      lr=0.05, pull_timeout=10.0)
    _attach([t0, t1], reshard="cap=1,fanout=2")  # < 1 row: 1-row slices
    oracle = ShardedTable("o", 64, 2, None, 0, 1, updater="adam",
                          lr=0.05)
    try:
        keys = np.arange(4, dtype=np.int64)
        for g in (2.0, -1.0):
            grads = np.full((4, 2), g, np.float32)
            t0.push(keys, grads)
            oracle.push(keys, grads)
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="planned migration settle")
        assert t0.rs_stats["slices"] == 4  # one per row
        rb = state_row_bytes(2, "adam")
        assert t0.rs_stats["peak_stage_bytes"] == rb  # the floor
        g3 = np.full((4, 2), 0.5, np.float32)
        t1.push(keys, g3)
        oracle.push(keys, g3)
        st_ = t1._xtra[0]
        np.testing.assert_array_equal(st_["w"], oracle._w[:4])
        np.testing.assert_array_equal(st_["m"], oracle._m[:4])
        np.testing.assert_array_equal(st_["v"], oracle._v[:4])
        np.testing.assert_array_equal(st_["steps"], oracle._steps[:4])
    finally:
        for b in buses:
            b.close()


def test_degenerate_plan_ships_byte_identical_blobs():
    """The satellite pin: with cap >= the block and fanout >= world,
    the planned path ships rbS frames whose BLOB BYTES are identical to
    the p2p ship it replaces — the head differs only by the round
    journal keys (rd/nrd/sl/bn)."""
    def run(reshard):
        buses = _mk_buses(2)
        t0 = ShardedTable("t", 64, 2, buses[0], 0, 2,
                          updater="adagrad", lr=0.1, pull_timeout=10.0)
        t1 = ShardedTable("t", 64, 2, buses[1], 1, 2,
                          updater="adagrad", lr=0.1, pull_timeout=10.0)
        _attach([t0, t1], reshard=reshard)
        sent = []
        orig = buses[0].send

        def rec_send(dst, kind, head, blob=None, **kw):
            if kind == "rbS:t":
                sent.append((dst, dict(head), blob))
            return orig(dst, kind, head, blob=blob, **kw)

        buses[0].send = rec_send
        try:
            keys = np.arange(4, dtype=np.int64)
            t0.push(keys, np.full((4, 2), 2.0, np.float32))
            t0.adopt_table(1, {0: 1})
            t1.adopt_table(1, {0: 1})
            _wait(lambda: t0.rebalance_settled()
                  and t1.rebalance_settled(), msg="settle")
            return sent
        finally:
            for b in buses:
                b.close()

    p2p = run(None)
    planned = run("cap=1g,fanout=8")
    assert len(p2p) == len(planned) == 1
    (dst_a, head_a, blob_a), (dst_b, head_b, blob_b) = p2p[0], planned[0]
    assert dst_a == dst_b == 1
    assert blob_a == blob_b  # byte-identical state payload
    assert {k: v for k, v in head_b.items()
            if k not in ("rd", "nrd", "sl", "bn")} == head_a
    assert (head_b["rd"], head_b["nrd"], head_b["sl"],
            head_b["bn"]) == (0, 1, 0, 4)


def test_redelivered_slice_drops_idempotently(flight_box):
    """Exactly-once under redelivery (partition heal, retransmit): a
    replayed slice frame is counted + dropped (``reshard_resume`` in
    the flight box), never double-applied."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="adagrad",
                      lr=0.1, pull_timeout=10.0)
    _attach([t0, t1], reshard="cap=1g,fanout=2")
    sent = []
    orig = buses[0].send

    def rec_send(dst, kind, head, blob=None, **kw):
        if kind == "rbS:t":
            sent.append((dst, dict(head), blob))
        return orig(dst, kind, head, blob=blob, **kw)

    buses[0].send = rec_send
    try:
        keys = np.arange(4, dtype=np.int64)
        t0.push(keys, np.full((4, 2), 2.0, np.float32))
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t0.rebalance_settled() and t1.rebalance_settled(),
              msg="settle")
        assert len(sent) == 1
        w_post = t1._xtra[0]["w"].copy()
        acc_post = t1._xtra[0]["acc"].copy()
        dst, head, blob = sent[0]
        buses[0].send(dst, "rbS:t", head, blob=blob)  # the replay
        _wait(lambda: t1.rs_stats["dup_slices"] == 1, msg="dup counted")
        np.testing.assert_array_equal(t1._xtra[0]["w"], w_post)
        np.testing.assert_array_equal(t1._xtra[0]["acc"], acc_post)
        assert t1.rb_stats["blocks_in"] == 1  # no double install
        assert "reshard_resume" in _flight_kinds(flight_box)
    finally:
        for b in buses:
            b.close()


def test_dead_source_mid_plan_aborts_to_checkpoint_state(flight_box):
    """A source death mid-plan: the gainer holds PARTIAL slices of the
    block; the death-plan adoption must discard them (``reshard_abort``)
    and install the checkpoint restore wholesale — never a mix of
    half-landed slices and restored rows."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 1, buses[0], 0, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 1, buses[1], 1, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    _attach([t0, t1], reshard="cap=4,fanout=2")
    try:
        # hand-deliver HALF of block 0 (rows 0-1 of 4) as a slice frame
        # — the shape a mid-plan SIGKILL of the source leaves behind
        st = {"w": np.full((2, 1), 3.0, np.float32)}
        head, blob = t0._encode_block_state(0, 1, st)
        head.update({"rd": 0, "nrd": 2, "sl": 0, "bn": 4})
        buses[0].send(1, "rbS:t", head, blob=blob)
        _wait(lambda: 0 in t1._early_prog, msg="partial slice landed")
        # rank 0 is now DEAD: the death plan re-homes block 0 onto
        # rank 1 with a checkpoint restore
        restored = np.full((4, 1), 9.0, np.float32)
        t1.adopt_table(1, {0: 1}, dead=frozenset({0}),
                       restore=lambda b: {"w": restored.copy()})
        np.testing.assert_array_equal(t1._xtra[0]["w"], restored)
        assert t1.rs_stats["aborts"] == 1
        assert not t1._early_prog and not t1._slice_prog
        assert t1.rb_stats["blocks_restored"] == 1
        assert "reshard_abort" in _flight_kinds(flight_box)
    finally:
        for b in buses:
            b.close()


def test_slices_beating_adoption_carry_their_journal():
    """Reorder window: slices that arrive BEFORE the gainer adopts the
    plan accumulate in the early buffer WITH their progress journal;
    adoption carries a partial buffer into the pending path and the
    remaining slices complete it — no row lost, none double-applied."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 1, buses[0], 0, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 1, buses[1], 1, 2, updater="sgd",
                      lr=1.0, pull_timeout=10.0)
    _attach([t0, t1], reshard="cap=4,fanout=2")
    try:
        mk = t0._encode_block_state
        first = {"w": np.full((2, 1), 3.0, np.float32)}
        h1, b1 = mk(0, 1, first)
        h1.update({"rd": 0, "nrd": 2, "sl": 0, "bn": 4})
        buses[0].send(1, "rbS:t", h1, blob=b1)
        _wait(lambda: 0 in t1._early_prog, msg="early slice landed")
        t1.adopt_table(1, {0: 1})  # partial buffer -> pending path
        assert 0 in t1._slice_prog and 0 in t1._pending_state
        # the replayed first slice is a dup even across the carry
        buses[0].send(1, "rbS:t", dict(h1), blob=b1)
        _wait(lambda: t1.rs_stats["dup_slices"] == 1, msg="dup")
        second = {"w": np.full((2, 1), 5.0, np.float32)}
        h2, b2 = mk(0, 1, second)
        h2.update({"rd": 1, "nrd": 2, "sl": 2, "bn": 4})
        buses[0].send(1, "rbS:t", h2, blob=b2)
        _wait(lambda: t1.rb_stats["blocks_in"] == 1, msg="complete")
        np.testing.assert_array_equal(
            t1._xtra[0]["w"],
            np.concatenate([first["w"], second["w"]]))
        assert not t1._slice_prog and 0 not in t1._pending_state
    finally:
        for b in buses:
            b.close()


def test_fence_release_confirmation_survives_a_lost_rbF():
    """The whole-host-evacuation wedge: a gainer's fence is released by
    a single rbF — when a partition eats it and the old owner then
    LEAVES, nobody can ever release that fence (the sender is gone and
    a clean leave issues no death plan). The rbG confirmation closes
    it: the sender tracks every release until the gainer confirms,
    re-sends stale ones, and ``releases_confirmed()`` (the leave()
    exit gate) only reports True once every gainer answered."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd",
                      lr=0.1, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd",
                      lr=0.1, pull_timeout=10.0)
    _attach([t0, t1])
    real_send = buses[0].send
    eaten = []

    def send(dst, kind, payload, **kw):
        if kind == "rbF:t" and not eaten:
            eaten.append(dict(payload))  # the partition eats rbF #1
            return
        return real_send(dst, kind, payload, **kw)

    buses[0].send = send
    try:
        t0.adopt_table(1, {0: 1})
        t1.adopt_table(1, {0: 1})
        _wait(lambda: t1.rb_stats["blocks_in"] == 1, msg="state ship")
        _wait(lambda: eaten, msg="first rbF eaten")
        # the gainer's fence is stuck — and the sender KNOWS it is
        assert 0 in t1._fenced
        assert not t0.releases_confirmed()
        # nothing stale yet at a generous age: no spurious re-sends
        t0.resend_stale_releases(age_s=60.0)
        assert 0 in t1._fenced
        # the leave() loop's nudge: re-send, fence releases, rbG lands
        t0.resend_stale_releases(age_s=0.0)
        _wait(lambda: 0 not in t1._fenced, msg="fence released")
        _wait(t0.releases_confirmed, msg="release confirmed")
        # a duplicate rbF for an already-released fence still acks
        # (idempotent handshake — re-sends race the first rbG)
        buses[0].send(1, "rbF:t", dict(eaten[0]))
        time.sleep(0.05)
        assert t0.releases_confirmed() and 0 not in t1._fenced
    finally:
        for b in buses:
            b.close()


# --------------------------------------------- trainer-level, in-proc
def test_reshard_requires_the_migration_machinery():
    t = ShardedTable("t", 16, 1, None, 0, 1, updater="sgd")
    with pytest.raises(ValueError, match="MINIPS_RESHARD"):
        t.attach_reshard(ReshardConfig.parse("1"))
    buses = _mk_buses(1)
    try:
        t2 = ShardedTable("t", 16, 1, buses[0], 0, 1, updater="sgd")
        with pytest.raises(ValueError, match="MINIPS_RESHARD"):
            ShardedPSTrainer({"t": t2}, buses[0], 1, reshard="cap=1k")
    finally:
        for b in buses:
            b.close()


def _run_trainers(n, body, *, rebalance=None, reshard=None, staleness=1,
                  rows=64, dim=1, updater="sgd", lr=1.0, bus_kw=None,
                  steps=12):
    """Threads-as-nodes trainer run (test_rebalance.py's harness plus
    the reshard knob); body(r, table, trainer, step) per rank per step.
    Returns (tables, trainers, finals, chaos_drops)."""
    import threading

    buses = _mk_buses(n, **(bus_kw or {}))
    tables = [ShardedTable("t", rows, dim, buses[i], i, n,
                           updater=updater, lr=lr, pull_timeout=20.0)
              for i in range(n)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], n,
                                 staleness=staleness, gate_timeout=30.0,
                                 rebalance=rebalance, reshard=reshard)
                for i in range(n)]
    finals: list = [None] * n
    errs: list = []

    def worker(r):
        try:
            for i in range(steps):
                body(r, tables[r], trainers[r], i)
                trainers[r].tick()
            trainers[r].finalize(timeout=30.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            import traceback

            traceback.print_exc()
            errs.append((r, repr(e)))

    try:
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in ts), "run wedged"
        assert not errs, errs
        drops = sum(getattr(b, "chaos").snapshot()["dropped"]
                    for b in buses if getattr(b, "chaos", None))
        return tables, trainers, finals, drops
    finally:
        for b in buses:
            b.close()


HOT_SPEC = ("interval=0.05,threshold=1.05,max_blocks=4,block=4,"
            "topk=16,min_heat=1")


def test_planned_migration_composes_with_chaos_and_reliable():
    """The in-proc chaos drill: planned slice frames ride the same
    reliable layer as everything else — under seeded drop/dup the run
    completes, migrates in rounds, loses nothing unrecovered, measured
    staging stays under the cap, and replicas agree bitwise."""
    def body(r, table, trainer, i):
        rows = table.pull(np.arange(8, dtype=np.int64))
        table.push(np.arange(8, dtype=np.int64), (0.01 * rows + 1.0))
        time.sleep(0.01)

    tables, trainers, finals, drops = _run_trainers(
        2, body, rebalance=HOT_SPEC, reshard="cap=8,fanout=1",
        staleness=1, steps=15,
        bus_kw={"chaos": "2025:drop=0.03,dup=0.01", "reliable": "1"})
    assert drops > 0, "chaos never fired — the drill proved nothing"
    assert sum(t.rb_stats["blocks_in"] for t in tables) >= 1
    assert sum(t.rs_stats["slices"] for t in tables) >= 2
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
        s = tr.reshard_stats()
        assert s is not None and s["peak_stage_bytes"] <= 8, s
    np.testing.assert_array_equal(finals[0], finals[1])


def test_reshard_stats_ride_wire_record():
    """wire_record's ``reshard`` block contract: None when the knob is
    off; armed-but-idle = ALL-ZERO counters (plus the cap/fanout
    echo), all-numeric, so sweep tooling diffs arms field-by-field."""
    from minips_tpu.utils.metrics import wire_record

    def body(r, table, trainer, i):
        keys = np.arange(4, dtype=np.int64)
        table.pull(keys)
        table.push(keys, np.ones((4, 1), np.float32))

    _tabs, trainers, _finals, _ = _run_trainers(
        2, body, rebalance=None, reshard=None, staleness=1, steps=3)
    rec = wire_record(trainers[0])
    assert rec["reshard"] is None  # off = None, not zeros

    _tabs, trainers, _finals, _ = _run_trainers(
        2, body, rebalance="interval=60,block=4",
        reshard="cap=1k,fanout=3", staleness=1, steps=3)
    st = wire_record(trainers[0])["reshard"]
    assert st is not None
    assert set(st) == {"plans", "rounds", "slices", "dup_slices",
                       "aborts", "blocks_inflight", "peak_stage_bytes",
                       "cap", "fanout"}
    assert all(isinstance(v, int) for v in st.values()), st
    assert (st["cap"], st["fanout"]) == (1024, 3)
    assert all(st[k] == 0 for k in st if k not in ("cap", "fanout")), st


# ------------------------------------------- streaming elastic restore
def _mk_rebalanced_ckpt(tmp_path):
    """A 2-shard rebalanced checkpoint (block 0 moved rank0 -> rank1,
    live rows in rank1's xtra) — test_rebalance.py's elastic layout."""
    d0 = tmp_path / "rank0" / "step_0000000001"
    d0.mkdir(parents=True)
    w0 = np.arange(8, dtype=np.float32).reshape(4, 2)
    np.savez(d0 / "t.npz", w=w0, m=w0 + 100, lo=np.asarray(0),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]))
    d1 = tmp_path / "rank1" / "step_0000000001"
    d1.mkdir(parents=True)
    w1 = np.arange(8, 16, dtype=np.float32).reshape(4, 2)
    live_b0 = np.full((2, 2), 55.0, np.float32)
    np.savez(d1 / "t.npz", w=w1, m=w1 + 100, lo=np.asarray(4),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]),
             **{"xtra/0/w": live_b0, "xtra/0/m": live_b0 + 1})
    return w0, w1, live_b0


def test_npz_slice_reader_reads_rows_without_whole_arrays(tmp_path):
    from minips_tpu.ckpt.elastic import NpzSliceReader

    w = np.arange(40, dtype=np.float32).reshape(10, 4)
    steps = np.arange(10, dtype=np.int32)
    np.savez(tmp_path / "s.npz", w=w, steps=steps, lo=np.asarray(0))
    with NpzSliceReader(str(tmp_path / "s.npz")) as r:
        assert set(r.keys()) >= {"w", "steps", "lo"}
        assert r.shape("w") == (10, 4) and "w" in r
        np.testing.assert_array_equal(r.read_rows("w", 3, 7), w[3:7])
        np.testing.assert_array_equal(r.read_rows("steps", 0, 10),
                                      steps)
        assert r.read_rows("w", 5, 5).shape == (0, 4)
        got = r.read_rows("w", 0, 2)
        got[0, 0] = -1.0  # writable (a copy, not a buffer view)
        np.testing.assert_array_equal(r.read("w"), w)  # source intact
        np.testing.assert_array_equal(r.read("lo"), np.asarray(0))


def test_streaming_reshard_is_bitwise_with_cap_bounded_peak(tmp_path):
    """Satellite 1's pin: the cap-bounded streaming restore assembles
    BITWISE the same state as the uncapped read — through a rebalance
    overlay — with MEASURED peak staging <= cap (never block- or
    shard-bounded). 2 -> 1 and 2 -> 3 both ways."""
    from minips_tpu.ckpt.elastic import reshard_table_state

    _w0, _w1, _live = _mk_rebalanced_ckpt(tmp_path)
    for new_n in (1, 3):
        old_sz = 4
        new_sz = -(-8 // new_n)
        for nr in range(new_n):
            lo = nr * new_sz
            full = reshard_table_state(str(tmp_path), 1, 2, "t", 8,
                                       lo, new_sz)
            stats: dict = {}
            # cap = one row of w (8 B): every chunk is a single row
            capped = reshard_table_state(str(tmp_path), 1, 2, "t", 8,
                                         lo, new_sz, cap_bytes=8,
                                         stats=stats)
            assert set(full) == set(capped)
            for k in full:
                np.testing.assert_array_equal(full[k], capped[k])
            if lo < 8:  # a shard with real rows streamed in chunks
                assert 0 < stats["peak_stage_bytes"] <= 8, stats
                assert stats["chunks"] >= new_sz, stats
    # the torn-save refusal survives the streaming reader
    d1 = tmp_path / "rank1" / "step_0000000001"
    w1 = np.arange(8, 16, dtype=np.float32).reshape(4, 2)
    np.savez(d1 / "t.npz", w=w1, m=w1 + 100, lo=np.asarray(4),
             ep=np.asarray(2), rb_block=np.asarray(2),
             ovb=np.asarray([0]), ovo=np.asarray([1]))
    with pytest.raises(ValueError, match="torn"):
        reshard_table_state(str(tmp_path), 1, 2, "t", 8, 0, 8)


def test_load_block_state_slices_through_the_reader(tmp_path):
    """The death-path restore unit reads row ranges, not whole shards:
    a block's state (home-slab AND xtra-overlay cases) round-trips
    through the slice reader bitwise."""
    from minips_tpu.ckpt.elastic import load_block_state

    _w0, w1, live_b0 = _mk_rebalanced_ckpt(tmp_path)
    cache: dict = {}
    # block 0 (rows 0-2): live state is rank1's xtra section
    st = load_block_state(str(tmp_path), 1, "t", 0, 0, 2, 0, 4, 2,
                          cache=cache)
    np.testing.assert_array_equal(st["w"], live_b0)
    np.testing.assert_array_equal(st["m"], live_b0 + 1)
    # block 2 (rows 4-6): plain home-slab rows of rank 1
    st = load_block_state(str(tmp_path), 1, "t", 2, 4, 2, 1, 4, 2,
                          cache=cache)
    np.testing.assert_array_equal(st["w"], w1[:2])
    np.testing.assert_array_equal(st["m"], w1[:2] + 100)


# --------------------------------------------- whole-host evacuation
def test_plan_evacuation_drains_a_whole_failure_domain_in_one_plan():
    """Whole-host evacuation is ONE plan: every block of EVERY rank in
    the failure domain re-homes in a single deterministic overlay (one
    epoch bump, one fence), spread round-robin over the survivors."""
    r = BlockRouter(RangePartitioner(64, 4), 4)
    r.apply(1, {0: 3})  # a prior heat migration parked block 0 on 3
    ov = plan_evacuation(r, {2, 3}, [0, 1])
    r.apply(2, ov)
    owners = r.owner_of_blocks()
    assert not np.isin(owners, [2, 3]).any()
    # round-robin balance across the survivors, within +/-1
    moved = [b for b, o in enumerate(owners)
             if o != r.home_of(b) or r.home_of(b) in (2, 3)]
    counts = [sum(1 for b in moved if owners[b] == t) for t in (0, 1)]
    assert max(counts) - min(counts) <= 1
    # determinism: the same router state compiles the same plan
    r2 = BlockRouter(RangePartitioner(64, 4), 4)
    r2.apply(1, {0: 3})
    assert plan_evacuation(r2, {2, 3}, [0, 1]) == ov
