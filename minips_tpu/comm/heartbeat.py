"""Heartbeat / failure detection — rebuild of the reference's liveness pings.

The reference's lineage runs periodic heartbeats through the mailbox with a
master that detects dead nodes and triggers restart-from-checkpoint
(SURVEY.md §2 "Heartbeat / failure detection", §5.3). Here heartbeats ride
the control bus; a monitor flags peers whose last beat is older than
``timeout``; the recovery action (reload latest checkpoint and relaunch —
restart semantics are all-or-nothing per JAX job, SURVEY.md §7.4.5) is the
caller's, delivered via the ``on_failure`` callback.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from minips_tpu.comm.bus import ControlBus
from minips_tpu.obs import flight as _fl
from minips_tpu.obs import tracer as _trc


def _parse_heartbeat_spec() -> dict[str, float]:
    """``$MINIPS_HEARTBEAT`` as a knob dict — empty (or ``"1"``) means
    every caller default, unknown knobs and non-positive values refuse
    loudly (the shared env-spec hygiene)."""
    spec = os.environ.get("MINIPS_HEARTBEAT", "").strip()
    out: dict[str, float] = {}
    if not spec or spec in ("1", "on", "true"):
        return out
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        if "=" not in entry:
            raise ValueError(
                f"MINIPS_HEARTBEAT: expected k=v, got {entry!r}")
        k, _, v = entry.partition("=")
        k = k.strip()
        if k not in ("interval", "timeout", "stall"):
            raise ValueError(f"MINIPS_HEARTBEAT: unknown knob {k!r}")
        try:
            val = float(v)
        except ValueError as e:
            raise ValueError(
                f"MINIPS_HEARTBEAT: bad value for {k}: {v!r}") from e
        if val <= 0:
            raise ValueError(f"MINIPS_HEARTBEAT: {k} must be > 0")
        out[k] = val
    return out


def liveness_knobs(interval: float,
                   timeout: float) -> tuple[float, float]:
    """Resolve the heartbeat liveness knobs against
    ``$MINIPS_HEARTBEAT`` — ``"interval=0.1,timeout=0.8"``, either knob
    optional, empty string (or unset, or ``"1"``) meaning the caller's
    defaults — the same explicit-empty convention as ``MINIPS_BUS`` /
    ``MINIPS_SHM_RING``. Exists so the death drills can run CI-fast
    detection timeouts (and production can run lazier ones) without
    patching every app's hardcoded monitor numbers. The third knob,
    ``stall=`` (observer-stall forgiveness, seconds), is resolved by
    :func:`stall_knob` — it shapes the SWEEP, not the liveness pair."""
    kn = _parse_heartbeat_spec()
    interval = kn.get("interval", interval)
    timeout = kn.get("timeout", timeout)
    if timeout <= interval:
        raise ValueError(
            f"MINIPS_HEARTBEAT: timeout {timeout} must exceed the "
            f"interval {interval} (a beat must be able to land)")
    return interval, timeout


def stall_knob(default: float = 0.0) -> float:
    """The ``stall=`` knob of ``$MINIPS_HEARTBEAT`` (0 = off): the
    observer-stall forgiveness window in seconds — see
    ``HeartbeatMonitor.check``. Off by default: forgiveness trades
    detection latency after a stall for immunity to the oversubscribed-
    host false positive, and that trade is the operator's."""
    return _parse_heartbeat_spec().get("stall", default)


class HeartbeatMonitor:
    def __init__(self, bus: ControlBus, peer_ids: list[int],
                 interval: float = 1.0, timeout: float = 5.0,
                 on_failure: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        # env knobs override the caller's numbers (liveness_knobs):
        # drills tune detection latency fleet-wide via the launcher's
        # env inheritance instead of per-app flag plumbing
        interval, timeout = liveness_knobs(interval, timeout)
        self.bus = bus
        self.interval = interval
        self.timeout = timeout
        self.on_failure = on_failure
        # control-plane piggyback (balance/control_plane.py): the lease
        # stamp provider merged into every outgoing beat, and the
        # receive hook peers observe terms through — heartbeats are the
        # one channel guaranteed to keep flowing around a partition's
        # edge, which is exactly when the lease fence matters
        self.payload_extra: Optional[Callable[[], dict]] = None
        self.on_beat_extra: Optional[Callable[[int, dict], None]] = None
        # QUORUM mode (balance/control_plane.SuspicionQuorum, armed by
        # the membership plane): with on_suspect set, a peer past the
        # timeout becomes a SUSPECT — ``on_suspect(rank, True)`` — not
        # a corpse; conviction waits for :meth:`convict` once the
        # fleet's suspicion gossip reaches a majority. A beat from a
        # suspect retracts (``on_suspect(rank, False)``). With the hook
        # unset (standalone monitors, pre-quorum fleets) the timeout
        # convicts solo, exactly the old semantics.
        self.on_suspect: Optional[Callable[[int, bool], None]] = None
        # fail-slow plumbing (obs/slowness.py via balance/membership):
        # fired once per FORGIVEN sweep — a coma observer's slow
        # ballots are retracted alongside its death suspicions (its
        # latency samples are as undateable as its silences)
        self.on_stall_forgiven: Optional[Callable[[], None]] = None
        self.stall = stall_knob()
        if self.stall and self.stall <= self.interval:
            # a stall budget at or below the sweep cadence would make
            # EVERY monitor-thread sweep "forgive" and re-baseline —
            # death detection silently disabled. Refuse as loudly as
            # timeout <= interval above.
            raise ValueError(
                f"MINIPS_HEARTBEAT: stall {self.stall} must exceed the "
                f"interval {self.interval} (every sweep would forgive)")
        self._last_sweep: Optional[float] = None
        # observer-stall forgiveness hits (the PR12 stall= window):
        # WITHOUT this counter a forgiven stall is invisible — an
        # operator cannot tell forgiveness from health, and a fleet
        # whose every sweep forgives is a fleet with detection silently
        # degraded. Surfaced via stats() -> wire_record "heartbeat".
        self.stall_forgiven = 0
        self._clock = clock
        now = clock()
        self._last_seen = {p: now for p in peer_ids if p != bus.my_id}
        self._dead: set[int] = set()
        self._suspect: set[int] = set()
        # serializes suspect-state TRANSITIONS together with their
        # on_suspect hook calls (sweep thread suspects, beat thread
        # retracts): firing the hook outside any lock let a sweep's
        # deferred suspected=True land AFTER a beat's retraction,
        # leaving a permanently stale ballot for a live rank. Ordering:
        # _sus_lock is taken FIRST, the main lock (briefly) inside —
        # never the reverse; convict() uses only the main lock, so a
        # hook that reaches convict() cannot deadlock.
        self._sus_lock = threading.Lock()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        bus.on("heartbeat", self._on_beat)

    def _on_beat(self, sender: int, payload: dict) -> None:
        tr = _trc.TRACER
        if tr is not None and "t" in payload:
            # the cross-rank clock-alignment sample obs/merge.py feeds
            # on: my receive timestamp (the event ts) paired with the
            # sender's send timestamp, both monotonic — min-filtered
            # NTP-style across both directions, the one-way delays
            # cancel and the per-rank clock offsets fall out
            tr.instant("hb", "hb", {"from": sender,
                                    "t_sent": float(payload["t"])})
        fl = _fl.FLIGHT
        if fl is not None and "t" in payload:
            # the flight recorder keeps only the min-filtered delay per
            # sender (a dict op per beat, no ring traffic): enough for
            # its merge CLI to align post-mortem timelines the same
            # NTP-style way with zero pre-arming
            fl.hb_sample(sender, float(payload["t"]), time.monotonic())
        with self._lock:
            if sender in self._last_seen:
                self._last_seen[sender] = self._clock()
        sus_hook = self.on_suspect
        if sus_hook is not None:
            # the suspect spoke: retract my vote before processing the
            # payload (a returning rank's first beat must not race its
            # own conviction through a stale ballot). Transition + hook
            # under _sus_lock so it serializes against the sweep's
            # suspected=True (see __init__)
            with self._sus_lock:
                with self._lock:
                    retracted = sender in self._suspect
                    self._suspect.discard(sender)
                if retracted:
                    sus_hook(sender, False)
        hook = self.on_beat_extra
        if hook is not None:
            hook(sender, payload)

    def check(self) -> set[int]:
        """Sweep for newly-dead peers; fires on_failure once per peer.

        With ``stall=`` armed (MINIPS_HEARTBEAT): a sweep arriving more
        than ``stall`` seconds after the previous one means THIS
        process was descheduled — on an oversubscribed host (the
        1-core CI box running 4-rank failover drills) a whole idle
        process can starve for seconds while its peers' beats sit
        undrained in the receive queue. An observer that was in a coma
        cannot date anyone else's silence, so it re-baselines every
        live peer instead of convicting them (a genuinely dead peer is
        re-detected one timeout after we wake — the honest earliest
        date). Off by default: existing fleets keep exact semantics."""
        newly_dead = []
        candidates = []
        forgave = False
        sus_hook = self.on_suspect
        with self._lock:
            now = self._clock()
            last, self._last_sweep = self._last_sweep, now
            if self.stall > 0 and last is not None \
                    and now - last > self.stall:
                for p in self._last_seen:
                    if p not in self._dead:
                        self._last_seen[p] = now
                forgave = True
                self.stall_forgiven += 1
                fl = _fl.FLIGHT
                if fl is not None:
                    fl.ev("hb_stall_forgiven",
                          {"gap_s": round(now - last, 3),
                           "stall_s": self.stall})
            else:
                for p, seen in self._last_seen.items():
                    if p in self._dead or now - seen <= self.timeout:
                        continue
                    if sus_hook is not None:
                        # quorum mode: silence makes a SUSPECT, not a
                        # corpse — the verdict needs corroboration.
                        # Transition deferred below: the add and its
                        # hook must be one atom under _sus_lock, or a
                        # concurrent beat's retraction can be
                        # overwritten by our deferred suspected=True
                        candidates.append(p)
                    else:
                        self._dead.add(p)
                        newly_dead.append(p)
        if forgave and sus_hook is not None:
            # a coma observer's standing suspicions are as undateable
            # as its convictions would have been: retract them along
            # with the re-baseline
            with self._sus_lock:
                with self._lock:
                    forgiven = sorted(self._suspect)
                    self._suspect.clear()
                for p in forgiven:
                    sus_hook(p, False)
        if forgave and self.on_stall_forgiven is not None:
            # ...and so are its fail-slow ballots (obs/slowness.py):
            # the same coma inflated every latency sample it took
            self.on_stall_forgiven()
        for p in candidates:
            with self._sus_lock:
                with self._lock:
                    fresh = self._clock()
                    seen = self._last_seen.get(p, fresh)
                    # re-verify under the transition lock: a beat that
                    # landed since the sweep snapshot retracts the case
                    begin = (p not in self._dead
                             and p not in self._suspect
                             and fresh - seen > self.timeout)
                    if begin:
                        self._suspect.add(p)
                if begin:
                    sus_hook(p, True)
        for p in newly_dead:
            if self.on_failure is not None:
                self.on_failure(p)
        with self._lock:
            return set(self._dead)

    def convict(self, r: int) -> None:
        """Quorum-mode conviction (balance/membership.py, once the
        fleet's suspicion gossip reached a majority): promote the rank
        to DEAD and fire ``on_failure`` exactly once — the same verdict
        path a solo timeout takes when quorum is off."""
        with self._lock:
            if r in self._dead:
                return
            self._dead.add(r)
            self._suspect.discard(r)
        if self.on_failure is not None:
            self.on_failure(r)

    @property
    def suspects(self) -> set[int]:
        """Peers past the timeout awaiting corroboration (quorum mode;
        always empty when on_suspect is unset)."""
        with self._lock:
            return set(self._suspect)

    def start(self) -> "HeartbeatMonitor":
        def loop() -> None:
            while not self._stop.wait(self.interval):
                payload = {"t": self._clock()}
                extra = self.payload_extra
                if extra is not None:
                    payload.update(extra())
                self.bus.publish("heartbeat", payload)
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    @property
    def dead(self) -> set[int]:
        with self._lock:
            return set(self._dead)

    def stats(self) -> dict:
        """Liveness-layer counters for the done line (``wire_record``
        "heartbeat" block): the stall-forgiveness window's arming and
        hits, plus the dead set size. A forgiven stall must be VISIBLE
        — it is detection latency the operator traded for."""
        with self._lock:
            return {"interval_s": self.interval,
                    "timeout_s": self.timeout,
                    "stall_s": self.stall or None,
                    "stall_forgiven": self.stall_forgiven,
                    "dead": sorted(self._dead),
                    "suspects": sorted(self._suspect)}

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
