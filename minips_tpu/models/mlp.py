"""3-layer MLP — the reference's ``mlp_example`` (BASELINE.json:3,8: MLP on
MNIST, dense KVTable, SSP staleness=4).

Plain-dict functional model so the whole parameter pytree lives in one
DenseTable (the reference holds MLP weights in a dense KVTable the same
way). Matmuls run in bfloat16 on the MXU with float32 params/accumulation —
the TPU-idiomatic mixed precision; the reference's Eigen math was float32
CPU (SURVEY.md §2 "Worker compute").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(key, sizes=(784, 256, 128, 10)):
    """He-initialized weights, zero biases."""
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"w{i}"] = (jax.random.normal(keys[i], (fan_in, fan_out),
                                             jnp.float32)
                           * jnp.sqrt(2.0 / fan_in))
        params[f"b{i}"] = jnp.zeros((fan_out,), jnp.float32)
    return params


def apply(params, x, *, compute_dtype=jnp.bfloat16):
    h = x.astype(compute_dtype)
    n_layers = sum(1 for k in params if k.startswith("w"))
    for i in range(n_layers):
        w = params[f"w{i}"].astype(compute_dtype)
        h = h @ w + params[f"b{i}"].astype(compute_dtype)
        if i < n_layers - 1:
            h = jax.nn.relu(h)
    return h.astype(jnp.float32)


def loss(params, batch, *, compute_dtype=jnp.bfloat16):
    logits = apply(params, batch["x"], compute_dtype=compute_dtype)
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def grad_fn(params, batch):
    l, g = jax.value_and_grad(loss)(params, batch)
    return l, g


def accuracy(params, batch):
    logits = apply(params, batch["x"])
    return jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))
