"""Word2Vec skip-gram with negative sampling — the reference's w2v workload
(BASELINE.json:11: "Word2Vec skip-gram on enwiki, negative sampling, async
push").

Input ("center") and output ("context") embeddings live in two SparseTables
keyed by vocab id. A training example is (center, positive context, K
negatives); SGNS loss = log σ(u·v⁺) + Σ log σ(−u·v⁻). Negative sampling is
done host-side from a unigram^0.75 table (the reference samples host-side
too); the device sees fixed-shape [B], [B], [B, K] id arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sgns_loss(center_rows, pos_rows, neg_rows):
    """center [B, k], pos [B, k], neg [B, K, k] → scalar SGNS loss."""
    pos_score = jnp.sum(center_rows * pos_rows, axis=-1)              # [B]
    neg_score = jnp.einsum("bk,bnk->bn", center_rows, neg_rows)       # [B, K]
    pos_loss = jnp.logaddexp(0.0, -pos_score)
    neg_loss = jnp.sum(jnp.logaddexp(0.0, neg_score), axis=-1)
    return jnp.mean(pos_loss + neg_loss)


def grad_fn(center_rows, pos_rows, neg_rows):
    def f(rows):
        return sgns_loss(*rows)
    l, (gc, gp, gn) = jax.value_and_grad(f)((center_rows, pos_rows, neg_rows))
    return l, gc, gp, gn


class UnigramSampler:
    """Host-side negative sampler over unigram counts^0.75."""

    def __init__(self, counts: np.ndarray, power: float = 0.75, seed: int = 0):
        p = np.asarray(counts, np.float64) ** power
        self._p = p / p.sum()
        self._rng = np.random.default_rng(seed)

    def sample(self, shape) -> np.ndarray:
        return self._rng.choice(len(self._p), size=shape, p=self._p)
