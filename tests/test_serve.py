"""Read-mostly serving plane (minips_tpu/serve/ + the replica routing
in train/sharded_ps.py) — this PR's tentpole.

Three layers of drill, mirroring the rebalancer's test shape:

- pure logic: MINIPS_SERVE spec parsing and the token bucket's
  refill/deny arithmetic under an injected clock;
- threads-as-nodes over real loopback buses: owners promote hot blocks
  and replicas serve them (wire and zero-wire local), every
  replica-served row satisfies the admission rule (stale_reads == 0),
  shedding/backpressure complete loudly, leases die at the rebalance
  fence (revocation racing a migration) and by expiry, the BSP
  lockstep drill with serving enabled-but-idle is BITWISE equal to
  the plane-off run, the whole protocol composes with seeded chaos +
  the retransmit layer, and the done-line serve.replica block keeps
  the off-vs-idle convention;
- the slow tier: the acceptance drill — a real 3-process pull-storm
  launcher run (6 read-only clients, 1 pusher, unpermuted zipf 1.1)
  with replicas engaged serves a strict majority of its hot reads
  from replicas with zero stale-beyond-bound reads.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np
import pytest

from minips_tpu.consistency.gate import admits
from minips_tpu.serve.admission import TokenBucket
from minips_tpu.serve.plane import ServeConfig
from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


# ----------------------------------------------------------- config
def test_serve_config_parses_and_rejects_garbage():
    c = ServeConfig.parse("replicas=2,hot=16,interval=0.5,min_heat=8,"
                          "lease=3,rate=100,burst=7,retry_ms=5,"
                          "decay=0.9,topk=64,slo_p99_ms=25")
    assert (c.replicas, c.hot, c.interval, c.min_heat, c.lease,
            c.rate, c.burst, c.retry_ms, c.decay, c.topk,
            c.slo_p99_ms) == (2, 16, 0.5, 8, 3, 100, 7, 5, 0.9, 64, 25)
    d = ServeConfig.parse("1")
    assert d.replicas == 1 and d.rate == 0  # defaults: admission off
    assert ServeConfig.parse("interval=0").interval == 0  # every tick
    with pytest.raises(ValueError, match="unknown knob"):
        ServeConfig.parse("explode=1")
    with pytest.raises(ValueError, match="k=v"):
        ServeConfig.parse("replicas")
    with pytest.raises(ValueError, match="bad value"):
        ServeConfig.parse("rate=abc")
    with pytest.raises(ValueError, match="replicas"):
        ServeConfig.parse("replicas=0")


def test_token_bucket_refills_and_denies():
    now = [0.0]
    b = TokenBucket(10.0, 5, now_fn=lambda: now[0])
    assert all(b.take() for _ in range(5))  # burst drains
    assert not b.take()                     # empty: deny
    now[0] += 0.35                          # 3.5 tokens refill
    assert b.take() and b.take() and b.take()
    assert not b.take()
    now[0] += 100.0                         # refill clamps at burst
    assert sum(b.take() for _ in range(10)) == 5
    snap = b.snapshot()
    assert snap["admitted"] == 13 and snap["denied"] == 7
    # rate=0 admits everything and never denies
    free = TokenBucket(0.0, 1)
    assert all(free.take() for _ in range(100))
    with pytest.raises(ValueError):
        TokenBucket(-1.0, 5)
    with pytest.raises(ValueError):
        TokenBucket(1.0, 0)


def test_slo_check_shapes():
    from minips_tpu.obs.hist import N_BUCKETS, slo_check

    idle = slo_check([0] * N_BUCKETS, 10.0)
    assert idle["violated"] is None and idle["count"] == 0
    counts = [0] * N_BUCKETS
    counts[14] = 100  # ~8-16ms bucket
    ok = slo_check(counts, 100.0)
    assert ok["violated"] is False and ok["observed_ms"] <= 100.0
    bad = slo_check(counts, 1.0)
    assert bad["violated"] is True


# ------------------------------------------- trainer-level, in-proc
def _run_serving(n, spec, body, *, staleness=1, rows=96, dim=2,
                 steps=20, lr=1.0, bus_kw=None, rebalance=None,
                 pace=0.005):
    """Threads-as-nodes serving run; ``body(r, table, trainer, i)``
    per rank per step (default body pulls+pushes a hot range).
    Returns (tables, trainers, finals, chaos_drops)."""
    buses = _mk_buses(n, **(bus_kw or {}))
    tables = [ShardedTable("t", rows, dim, buses[i], i, n,
                           updater="sgd", lr=lr, pull_timeout=20.0)
              for i in range(n)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], n,
                                 staleness=staleness, gate_timeout=30.0,
                                 rebalance=rebalance, serve=spec)
                for i in range(n)]
    finals: list = [None] * n
    errs: list = []

    def worker(r):
        try:
            for i in range(steps):
                body(r, tables[r], trainers[r], i)
                trainers[r].tick()
                if pace:
                    time.sleep(pace)
            trainers[r].finalize(timeout=30.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            import traceback

            traceback.print_exc()
            errs.append((r, repr(e)))

    try:
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in ts), "run wedged"
        assert not errs, errs
        drops = sum(getattr(b, "chaos").snapshot()["dropped"]
                    for b in buses if getattr(b, "chaos", None))
        return tables, trainers, finals, drops
    finally:
        for b in buses:
            b.close()


def _tot(trainers, key):
    out = 0
    for tr in trainers:
        rep = tr.serve_stats()["replica"]
        out += (rep or {}).get(key) or 0
    return out


HOT_SERVE = "replicas=2,hot=8,interval=0,min_heat=2,lease=2.0"


def _hot_body(r, table, trainer, i):
    hot = np.arange(8, dtype=np.int64)
    rows = table.pull(hot)
    table.push(hot, np.ones((hot.size, table.dim), np.float32))


def test_replicas_promote_serve_and_agree():
    """The basic plane lifecycle: hot blocks promote, replicas serve
    (wire and/or zero-wire local), no read ever violates the
    admission bound, and post-finalize replicas agree bitwise."""
    tables, trainers, finals, _ = _run_serving(
        3, HOT_SERVE, _hot_body, staleness=2, steps=25)
    assert _tot(trainers, "grants") >= 1, "nothing promoted"
    served = (_tot(trainers, "replica_served_rows")
              + _tot(trainers, "replica_local_rows"))
    assert served > 0, "replicas never served a row"
    assert _tot(trainers, "stale_reads") == 0
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
    np.testing.assert_array_equal(finals[0], finals[1])
    np.testing.assert_array_equal(finals[0], finals[2])


def test_pull_serving_reads_respect_bound_value_level():
    """Value-level staleness pin for the serving read clock (sgd lr=1,
    +1 gradients: a row's value counts applied pushes): a
    ``pull_serving`` read at gated clock c must contain at least the
    pushes every peer applied through ``c − s`` — replica hits
    included."""
    n, s = 2, 1
    bad: list = []
    hot = np.arange(8, dtype=np.int64)

    def body(r, table, trainer, i):
        rows = table.pull_serving(hot)
        counts = -rows[:, 0]
        c = trainer.gated_clock
        # every worker pushes once per step before clocking: through
        # clock c − s each of the n workers applied max(0, c−s) pushes
        need = n * max(0, c - s)
        if not (counts.sum() >= need - 1e-6):
            bad.append((r, i, counts.sum(), need))
        table.push(hot, np.ones((hot.size, 1), np.float32))

    tables, trainers, finals, _ = _run_serving(
        n, HOT_SERVE, body, staleness=s, rows=64, dim=1, steps=15)
    assert not bad, f"serving read below the bound: {bad[:4]}"
    assert _tot(trainers, "stale_reads") == 0
    np.testing.assert_array_equal(finals[0], finals[1])


def test_admission_sheds_and_backpressures_loudly():
    """Throttled admission: the run COMPLETES (refusal degrades to
    svS redirects / svB retries, never a timeout poison), the shed
    counters fire, and no read violates the bound."""
    spec = HOT_SERVE + ",rate=2,burst=1"  # starved: ~every fresh leg
    tables, trainers, finals, _ = _run_serving(  # sheds or refuses
        3, spec, _hot_body, staleness=2, steps=25,
        bus_kw={"reliable": "1"})  # bare-zmq loss must not flake this
    shed = _tot(trainers, "shed_redirects") + _tot(trainers,
                                                   "backpressure")
    assert shed > 0, "admission never throttled — the drill is vacuous"
    assert _tot(trainers, "stale_reads") == 0
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
    np.testing.assert_array_equal(finals[0], finals[1])


def test_partial_shed_redirects_covered_half_only():
    """Replica-aware shed (PR6's documented headroom): when the
    admission bucket is empty and a pull leg's blocks are only
    PARTIALLY covered by a replica holder, the owner redirects the
    covered half (svS carrying ``bs``, the client peels those keys
    onto an svP leg) and backpressures only the remainder — never
    refuses the whole leg. Deterministic by construction:
    auto-promotion is disabled (min_heat astronomical; interval huge,
    so no refresh/demote tick ever runs) and the grant is issued
    through the owner's own ``_grant_blocks`` — the exact path
    ``_promote_hot`` takes — pinning the granted set to block 0
    forever, so EVERY mixed leg must split. (The heat-driven flavor of
    this drill raced the promotion tick: ``hot=1`` caps promotions per
    TICK, not in total, so the cold block joined the holder set a few
    ticks later, every later mixed leg had full coverage, and the
    partial window closed — vacuous under suite load.)"""
    buses = _mk_buses(3, reliable="1")
    try:
        tables = [ShardedTable("t", 96, 2, buses[i], i, 3,
                               updater="sgd", lr=1.0, pull_timeout=20.0)
                  for i in range(3)]
        trainers = [ShardedPSTrainer(
            {"t": tables[i]}, buses[i], 3, staleness=2,
            serve="replicas=1,hot=1,interval=1e9,min_heat=1e18,"
                  "lease=30,rate=0.001,burst=1")
            for i in range(3)]
        sv0, sv1 = tables[0]._sv, tables[1]._sv
        span = tables[0].router.block_span(0)[1]
        hot = np.arange(span, dtype=np.int64)        # block 0
        both = np.arange(2 * span, dtype=np.int64)   # blocks 0 + 1
        seed = np.arange(2 * span * 2,
                         dtype=np.float32).reshape(-1, 2)
        tables[0]._w[: 2 * span] = seed              # known owner rows
        sv0._grant_blocks([0], (1,))                 # pinned grant
        deadline = time.monotonic() + 5.0
        while sv1.held_blocks() == 0:
            assert time.monotonic() < deadline, "grant never arrived"
            time.sleep(0.02)
        # drain the one-token bucket with an admitted covered pull
        tables[2].pull(hot)
        for rep in range(1, 4):
            # every mixed leg must split: svP rides the replica for
            # block 0, the block-1 remainder re-judges (svB -> timered
            # rt=1 retry, force-admitted) — and the values must be the
            # owner's rows bit-for-bit whichever side served them
            got = tables[2].pull(both)
            np.testing.assert_array_equal(got, seed)
            assert sv0.counters["shed_partial"] == rep, sv0.counters
        assert sv0.counters["backpressure"] >= 3     # uncovered half
        assert sv1.counters["replica_served_requests"] >= 3  # covered
        assert _tot(trainers, "stale_reads") == 0
        for tr in trainers:
            assert tr.frames_dropped == 0, tr.drop_detail()
    finally:
        for b in buses:
            b.close()


def test_lease_expiry_goes_dark_then_refuses():
    """A replica whose owner stops refreshing must refuse (expired
    lease) instead of serving an ever-staler snapshot — and the
    refusal falls back to the owner transparently."""
    buses = _mk_buses(2)
    try:
        tables = [ShardedTable("t", 64, 1, buses[i], i, 2,
                               updater="sgd", lr=1.0, pull_timeout=10.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer(
            {"t": tables[i]}, buses[i], 2, staleness=float("inf"),
            serve="replicas=1,hot=4,interval=0,min_heat=1,lease=0.3")
            for i in range(2)]
        hot = np.arange(8, dtype=np.int64)
        # heat + promotion: rank 0 owns the range, rank 1 holds it
        for _ in range(6):
            tables[0].pull(hot)
            tables[0].push(hot, np.ones((8, 1), np.float32))
            trainers[0].tick()
            trainers[1].tick()
            time.sleep(0.01)
        sv1 = tables[1]._sv
        deadline = time.monotonic() + 5.0
        while sv1.held_blocks() == 0:
            assert time.monotonic() < deadline, "grant never arrived"
            time.sleep(0.02)
        # rank 1 serves its replica locally while the lease is live
        rows = tables[1].pull_serving(hot)
        assert sv1.counters["replica_local_rows"] > 0
        # owner goes mute: no more ticks -> no renewals -> lease dies
        time.sleep(0.5)
        before = sv1.counters["replica_local_rows"]
        rows2 = tables[1].pull_serving(hot)  # falls back to the wire
        assert sv1.counters["replica_local_rows"] == before, \
            "expired lease still served locally"
        np.testing.assert_array_equal(rows, rows2)  # owner idle: equal
    finally:
        for b in buses:
            b.close()


def test_revocation_rides_the_rebalance_fence():
    """Lease/epoch invalidation racing a migration (satellite): a
    granted block that migrates away is revoked AT the adoption fence
    — replicas drop it, clients fall back, and the staleness bound
    holds through the whole window (>= 1 migration of a replicated
    block mid-run)."""
    spec = "replicas=2,hot=8,interval=0,min_heat=1,lease=2.0"
    reb = ("interval=0.05,threshold=1.05,max_blocks=4,block=4,"
           "topk=16,min_heat=1")
    hot = np.arange(8, dtype=np.int64)
    bad: list = []
    n, s = 3, 1

    def body(r, table, trainer, i):
        rows = table.pull_serving(hot)
        c = trainer.gated_clock
        need = n * max(0, c - s)
        if not (-rows[:, 0].sum() >= need - 1e-6):
            bad.append((r, i))
        table.push(hot, np.ones((hot.size, 1), np.float32))
        time.sleep(0.003 * (1 + (r + i) % 3))

    tables, trainers, finals, _ = _run_serving(
        3, spec, body, staleness=s, rows=96, dim=1, steps=25,
        rebalance=reb, pace=0.01)
    migrated = sum(t.rb_stats["blocks_in"] for t in tables)
    assert migrated >= 1, "no migration — the race never happened"
    assert _tot(trainers, "grants") >= 1
    assert _tot(trainers, "revokes") >= 1, \
        "a replicated block migrated without a lease revocation"
    assert not bad, f"staleness bound violated: {bad[:4]}"
    assert _tot(trainers, "stale_reads") == 0
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
    np.testing.assert_array_equal(finals[0], finals[1])
    np.testing.assert_array_equal(finals[0], finals[2])


def test_serving_composes_with_chaos_and_reliable():
    """Seeded-chaos pull storm (satellite): MINIPS_CHAOS drop/dup +
    MINIPS_RELIABLE under the serving plane — zero unrecovered
    frames, zero stale-beyond-bound reads, replicas bitwise agree."""
    def body(r, table, trainer, i):
        table.pull_serving(np.arange(8, dtype=np.int64))
        table.push(np.arange(8, dtype=np.int64),
                   np.ones((8, 2), np.float32))

    tables, trainers, finals, drops = _run_serving(
        2, HOT_SERVE, body, staleness=1, steps=18, pace=0.01,
        bus_kw={"chaos": "2025:drop=0.03,dup=0.01", "reliable": "1"})
    assert drops > 0, "chaos never fired — the drill proved nothing"
    assert _tot(trainers, "stale_reads") == 0
    for tr in trainers:
        assert tr.frames_dropped == 0, tr.drop_detail()
        assert tr.wire_frames_lost == 0
    np.testing.assert_array_equal(finals[0], finals[1])


def test_bsp_lockstep_serving_idle_is_bitwise_equal():
    """Acceptance pin: arming the serving plane must not perturb one
    bit of training state while it stays idle (min_heat above the
    drill's traffic: nothing promotes). Deterministic lockstep drive,
    plane-armed vs plane-off: final shards bitwise equal."""
    def run(spec):
        buses = _mk_buses(2)
        try:
            tabs = [ShardedTable("t", 64, 1, buses[i], i, 2,
                                 updater="sgd", lr=0.5,
                                 pull_timeout=10.0)
                    for i in range(2)]
            trs = [ShardedPSTrainer({"t": tabs[i]}, buses[i], 2,
                                    staleness=0, serve=spec)
                   for i in range(2)]
            for i in range(6):
                for r in (0, 1):
                    rng = np.random.default_rng((7, r, i))
                    keys = rng.integers(0, 64, size=16)
                    rows = tabs[r].pull(keys)
                    tabs[r].push(keys, (0.125 * rows + 1.0))
                # FIFO barrier per link (deterministic order)
                tabs[0].pull(np.array([32]))
                tabs[1].pull(np.array([0]))
            if spec:
                for tr in trs:
                    rep = tr.serve_stats()["replica"]
                    assert rep is not None
                    assert rep["grants"] == 0, \
                        "idle drill promoted a block"
            return [t._w.copy() for t in tabs]
        finally:
            for b in buses:
                b.close()

    w_off = run(None)
    w_on = run("replicas=1,min_heat=1e9")  # armed, never promotes
    for a, b in zip(w_off, w_on):
        np.testing.assert_array_equal(a, b)  # bitwise, not allclose


def test_serve_replica_block_off_vs_idle_in_wire_record():
    """The done-line convention (satellite): serve.replica is None
    when the plane is OFF, an all-zero counter dict when armed but
    idle — and the hist block always carries replica_serve_ms."""
    from minips_tpu.utils.metrics import wire_record

    def body(r, table, trainer, i):
        keys = np.arange(4, dtype=np.int64)
        table.pull(keys)
        table.push(keys, np.ones((4, 1), np.float32))

    # plane OFF
    tables, trainers, _f, _ = _run_serving(
        2, None, body, staleness=1, rows=64, dim=1, steps=3, pace=0)
    rec = wire_record(trainers[0])
    assert rec["serve"]["replica"] is None
    assert rec["hist"]["replica_serve_ms"] == {"count": 0}
    # plane ARMED but idle (min_heat unreachable)
    tables, trainers, _f, _ = _run_serving(
        2, "replicas=1,min_heat=1e9", body, staleness=1, rows=64,
        dim=1, steps=3, pace=0)
    rec = wire_record(trainers[0])
    rep = rec["serve"]["replica"]
    assert rep is not None
    assert rep["grants"] == 0 and rep["replica_served_rows"] == 0
    assert rep["stale_reads"] == 0
    assert rep["slo"] is None  # slo_p99_ms unset: gate off


def test_slo_record_rides_serve_stats():
    def body(r, table, trainer, i):
        table.pull(np.arange(8, dtype=np.int64))
        table.push(np.arange(8, dtype=np.int64),
                   np.ones((8, 2), np.float32))

    tables, trainers, _f, _ = _run_serving(
        2, HOT_SERVE + ",slo_p99_ms=10000", body, staleness=1, steps=5,
        pace=0)
    slo = trainers[0].serve_stats()["replica"]["slo"]
    assert slo is not None and slo["target_ms"] == 10000.0
    assert slo["count"] > 0 and slo["violated"] is False


def test_replica_pull_refused_when_not_held():
    """A wire svP for blocks the replica does not hold refuses with
    svN (lease_refused) and the client's fallback still returns the
    right rows — never silence, never a hang."""
    buses = _mk_buses(2)
    try:
        tables = [ShardedTable("t", 64, 1, buses[i], i, 2,
                               updater="sgd", lr=1.0, pull_timeout=10.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer(
            {"t": tables[i]}, buses[i], 2, staleness=float("inf"),
            serve="replicas=1,min_heat=1e9") for i in range(2)]
        tables[0].push(np.arange(8, dtype=np.int64),
                       np.full((8, 1), 2.0, np.float32))
        # hand-inject a bogus map at rank 1: block 0 "held" by rank 0's
        # peer... point the client at a holder with no snapshot
        sv1 = tables[1]._sv
        b0 = int(tables[1].router.blocks_of(np.array([0]))[0])
        sv1._on_map(0, {"bs": [b0], "hs": [[1]], "ep": 0})
        # rank 1 holds nothing: route_targets skips (self in holders)
        rows = tables[1].pull_serving(np.arange(8, dtype=np.int64))
        np.testing.assert_allclose(rows[:, 0], -2.0, rtol=1e-6)
        # now point rank 0's client at rank 1 (which holds nothing)
        sv0 = tables[0]._sv
        b_peer = int(tables[0].router.blocks_of(np.array([40]))[0])
        sv0._on_map(1, {"bs": [b_peer], "hs": [[1]], "ep": 0})
        # hmm — holder == owner; use a map where rank 1 claims to hold
        # rank 1's own block but the requester is rank 0: owner == 1,
        # holder == 1, cands == [1] ... pick may be owner or holder,
        # either way the pull must complete
        rows = tables[0].pull(np.array([40], dtype=np.int64))
        assert rows.shape == (1, 1)
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------- multi-process
@pytest.mark.slow
def test_pull_storm_3proc_replicas_engage_and_stay_fresh():
    """The acceptance drill: a real 3-process pull storm (6 read-only
    clients, 1 pusher, unpermuted zipf 1.1) with the serving plane on
    completes with replicas engaged, a strict majority of replica
    traffic served locally (zero-wire), zero stale-beyond-bound
    reads, zero poisons/drops, and read throughput recorded for the
    bench tripwires."""
    from minips_tpu import launch

    argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", "sparse", "--rows", "4096", "--batch", "128",
            "--iters", "40", "--warmup", "6", "--key-dist", "zipf",
            "--no-zipf-permute-hot", "--staleness", "1",
            "--updater", "sgd", "--pull-timeout", "30",
            "--storm", "2", "--storm-pushers", "1",
            "--storm-batch", "8", "--storm-think-ms", "2",
            "--storm-step-s", "0.03",
            "--serve", "replicas=2,hot=512,interval=0,min_heat=0.5,"
                       "decay=0.9,lease=2.0"]
    res = launch.run_local_job(3, argv, base_port=None,
                               env_extra={"JAX_PLATFORMS": "cpu"},
                               timeout=240.0)
    assert all(r["event"] == "done" for r in res)
    reps = [r["serve"]["replica"] for r in res]
    assert all(rep is not None for rep in reps)
    local = sum(rep["replica_local_rows"] for rep in reps)
    assert local > 0, "no zero-wire replica reads — plane disengaged"
    assert sum(rep["stale_reads"] for rep in reps) == 0
    assert sum(rep["grants"] for rep in reps) >= 1
    for r in res:
        assert r["wire_frames_lost"] == 0, r
        assert r["frames_dropped"] == 0, r
        assert r["storm_readers"] == 2
        assert r["read_rows_per_sec"] > 0


# ---------------------------------------------- loopback self-shed
def test_admit_request_sheds_back_at_loopback_capable_requester():
    """Owner-side half of the self-shed: with NO peer holder covering
    the leg but the REQUESTER holding every touched block, a
    loopback-capable transport gets an svS naming the requester itself
    — zero-wire self-serve instead of the backpressure ladder. A
    transport without the capability keeps the seed svB behavior."""
    from minips_tpu.serve.plane import TableServeState

    sent = []

    class _Bus:
        supports_loopback = True

        def on(self, *_a):
            pass

        def send(self, dest, kind, head, blob=None):
            sent.append((dest, kind, head))

    t = ShardedTable("t", 96, 2, _Bus(), 0, 3, updater="sgd")
    sv = TableServeState(t, None, ServeConfig.parse("rate=0.001,burst=1"))
    t._sv = sv
    span = t.router.block_span(0)[1]
    keys = np.arange(span, dtype=np.int64)  # block 0
    with sv._ow_lock:
        sv._granted[0] = (1,)  # only the requester holds it
    sv.bucket.take()  # drain the one-token bucket
    assert not sv.admit_request(1, 7, keys, {})
    dest, kind, head = sent[-1]
    assert (dest, kind) == (1, "svS:t") and head["h"] == [1]
    # same situation on a loopback-less transport: svB backpressure
    _Bus.supports_loopback = False
    sent.clear()
    assert not sv.admit_request(1, 8, keys, {})
    assert sent[-1][1] == "svB:t"
    # a PEER holder always wins over the self-shed
    _Bus.supports_loopback = True
    with sv._ow_lock:
        sv._granted[0] = (1, 2)
    sent.clear()
    assert not sv.admit_request(1, 9, keys, {})
    assert sent[-1][2]["h"] == [2]


def test_self_shed_leg_serves_from_own_snapshot_over_loopback():
    """Client half, over the real shm loopback: a shed naming THIS
    rank re-issues the leg as an svP to self — served from the held
    snapshot entirely in process (grant raced the pull: per-link FIFO
    guarantees the svU precedes the svS, so the snapshot is installed
    by redirect time), no owner fallback, no wire."""
    buses = _mk_buses(2, backend="shm", settle=0.05)
    ths = [threading.Thread(target=b.handshake, args=(2,))
           for b in buses]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=15.0)
    try:
        tables = [ShardedTable("t", 64, 2, buses[i], i, 2,
                               updater="sgd", lr=1.0,
                               pull_timeout=10.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer(
            {"t": tables[i]}, buses[i], 2, staleness=2,
            serve="replicas=1,hot=1,interval=1e9,min_heat=1e18,"
                  "lease=30")
            for i in range(2)]
        del trainers
        t0, t1 = tables
        span = t0.router.block_span(0)[1]
        seed = np.arange(span * 2, dtype=np.float32).reshape(-1, 2)
        t0._w[:span] = seed
        # rank 1's leg to the owner is OUTSTANDING (the owner's pull
        # handler is parked aside to freeze the race window open)
        t0.bus._handlers.pop("psG:t")
        keys = np.arange(span, dtype=np.int64)
        fut = t1._issue_pull(keys, 0)
        t0._sv._grant_blocks([0], (1,))  # the racing grant
        deadline = time.monotonic() + 5.0
        while t1._sv.held_blocks() == 0:
            assert time.monotonic() < deadline, "grant never arrived"
            time.sleep(0.02)
        rid = next(iter(fut._remote and
                        {r for r in t1._rid_gid}))  # the live leg
        pulled0 = t1.bytes_pulled
        t1._sv._on_shed(0, {"req": int(rid), "h": [1]})
        rows = fut.wait(timeout=10.0)
        np.testing.assert_array_equal(rows, seed)
        st = t1._sv.stats()
        assert st["shed_local_legs"] == 1
        assert st["replica_served_requests"] == 1
        assert st["replica_fallbacks"] == 0  # never bounced to owner
        assert t1.bytes_pulled == pulled0  # the serve crossed no wire
        assert buses[1].loopback_frames >= 2  # svP out + psr back
    finally:
        for b in buses:
            b.close()
