"""Wide&Deep and DeepFM — the reference's Criteo CTR workloads
(BASELINE.json:10: "Wide&Deep / DeepFM on Criteo-1TB, sparse embedding PS
shards on TPU mesh").

Criteo rows: 13 dense numeric fields + 26 categorical fields. Components:

- **wide**: per-feature scalar weights from a hashed SparseTable (dim 1) —
  exactly the sparse-LR path.
- **embeddings**: [B, 26] categorical ids → hashed SparseTable rows
  [B, 26, k].
- **deep**: MLP over [dense_13 ; flattened embeddings].
- **fm** (DeepFM): second-order interactions via the sum-square trick,
  O(B·F·k) — no pairwise blowup, MXU/VPU friendly.

All pieces are pure functions of (wide_rows, emb_rows, dense_params, batch)
so the fused GSPMD step can differentiate through to both tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from minips_tpu.models import mlp as _mlp


def init_deep(key, num_fields: int = 26, emb_dim: int = 8,
              num_dense: int = 13, hidden=(256, 128)):
    """Dense-side params: the deep MLP (+ output head) as one pytree for a
    DenseTable. Input = dense features + flattened embeddings."""
    in_dim = num_dense + num_fields * emb_dim
    return _mlp.init(key, (in_dim,) + tuple(hidden) + (1,))


def fm_term(emb_rows):
    """Second-order FM interaction from field embeddings [B, F, k]:
    0.5 * sum_k ((sum_f v)^2 - sum_f v^2)."""
    s = jnp.sum(emb_rows, axis=1)
    s2 = jnp.sum(emb_rows * emb_rows, axis=1)
    return 0.5 * jnp.sum(s * s - s2, axis=-1)


def logits(wide_rows, emb_rows, deep_params, batch, *, use_fm: bool):
    """wide_rows [B, F_tot, 1]; emb_rows [B, 26, k]; batch["dense"] [B, 13].

    use_fm=False → Wide&Deep; use_fm=True → DeepFM (wide part doubles as
    FM's first-order term, per the DeepFM formulation)."""
    B = emb_rows.shape[0]
    wide = jnp.sum(wide_rows[..., 0], axis=-1)
    deep_in = jnp.concatenate(
        [batch["dense"], emb_rows.reshape(B, -1)], axis=-1)
    deep = _mlp.apply(deep_params, deep_in)[:, 0]
    out = wide + deep
    if use_fm:
        out = out + fm_term(emb_rows)
    return out


def loss(wide_rows, emb_rows, deep_params, batch, *, use_fm: bool = False):
    from minips_tpu.models.lr import bce_with_logits
    return bce_with_logits(
        logits(wide_rows, emb_rows, deep_params, batch, use_fm=use_fm),
        batch["y"])
