"""KV-cached decoding vs the incremental training-forward oracle.

The pinned property: greedy ``generate`` must pick exactly the tokens an
oracle picks by re-running the full training-time ``transformer.apply``
on the growing sequence and taking argmax of the last position — for
every layout combination (fused MHA / GQA / MQA x learned / rope). That
equivalence proves the cache write/mask logic, the grouped attention,
and the position handling all match training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.models import decode, transformer as tfm

F32 = dict(compute_dtype=jnp.float32)


def _greedy_oracle(params, prompt, steps, heads):
    seq = prompt
    out = []
    for _ in range(steps):
        logits = tfm.apply(params, seq, heads=heads, **F32)
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)
        out.append(tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
    return jnp.stack(out, axis=1)                    # [B, steps]


@pytest.mark.parametrize("kv_heads,rope", [(None, False), (2, True),
                                           (1, False), (None, True)])
def test_greedy_matches_incremental_oracle(kv_heads, rope):
    p = tfm.init(jax.random.PRNGKey(0), vocab=61, dim=32, heads=4,
                 depth=2, max_len=32, kv_heads=kv_heads, rope=rope)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 61, size=(2, 5)), jnp.int32)
    want = _greedy_oracle(p, prompt, 6, heads=4)
    got = decode.generate(p, prompt, 6, heads=4,
                          compute_dtype=jnp.float32,
                          cache_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gqa_cache_is_group_factor_smaller():
    p_full = tfm.init(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                      depth=1, max_len=16)
    p_mqa = tfm.init(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                     depth=1, max_len=16, kv_heads=1)
    c_full = decode.init_cache(p_full, 2, 16, heads=4)
    c_mqa = decode.init_cache(p_mqa, 2, 16, heads=4)
    assert c_full[0]["k"].shape == (2, 16, 4, 8)
    assert c_mqa[0]["k"].shape == (2, 16, 1, 8)      # 4x smaller


def test_learned_positions_cap_decode_length():
    p = tfm.init(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                 depth=1, max_len=8)
    with pytest.raises(ValueError, match="max_len"):
        decode.init_cache(p, 1, 9, heads=4)
    # rope: same length is fine (no table)
    pr = tfm.init(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                  depth=1, rope=True)
    decode.init_cache(pr, 1, 9, heads=4)


def test_sampling_is_keyed_and_in_range():
    p = tfm.init(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                 depth=1, rope=True)
    prompt = jnp.zeros((2, 3), jnp.int32)
    a = decode.generate(p, prompt, 5, heads=4, temperature=1.0,
                        key=jax.random.PRNGKey(7))
    b = decode.generate(p, prompt, 5, heads=4, temperature=1.0,
                        key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 5)
    assert int(jnp.min(a)) >= 0 and int(jnp.max(a)) < 31
    with pytest.raises(ValueError, match="PRNG key"):
        decode.generate(p, prompt, 2, heads=4, temperature=0.5)


def test_moe_blocks_refused():
    p = tfm.init_moe_lm(jax.random.PRNGKey(0), vocab=31, dim=32, heads=4,
                        depth=1, num_experts=2)
    with pytest.raises(ValueError, match="MoE"):
        decode.init_cache(p, 1, 8, heads=4)
