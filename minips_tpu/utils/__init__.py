from minips_tpu.utils.metrics import MetricsLogger  # noqa: F401
from minips_tpu.utils.timing import StepTimer  # noqa: F401
