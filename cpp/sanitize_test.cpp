// Native sanitizer drill (SURVEY.md §5.2): exercises the lock-heavy C++
// components — the mailbox's full mesh (accept/reader/sender actors,
// ThreadsafeQueue, concurrent publish/directed send vs close) and the
// multi-threaded libsvm parser — under -fsanitize=address / thread.
// Built and run by `make -C cpp sanitize`; any data race / leak / UB the
// sanitizers find fails the build with a report.
//
// Links the component .cpp files directly (the C ABI is declared here, the
// implementations live in the instrumented objects).

#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* mailbox_create(int listen_port);
int mailbox_port(void* h);
int mailbox_connect(void* h, const char* host, int port, int timeout_ms);
void mailbox_publish(void* h, const char* msg, int64_t msg_len,
                     const uint8_t* blob, int64_t blob_len);
void mailbox_send(void* h, int peer_index, const char* msg, int64_t msg_len,
                  const uint8_t* blob, int64_t blob_len);
int mailbox_recv(void* h, int timeout_ms, char** msg_out, int64_t* msg_len,
                 uint8_t** blob_out, int64_t* blob_len);
void mailbox_free_buf(void* p);
void mailbox_close(void* h);

int64_t mailbox_outbox_depth(void* h);
int64_t mailbox_dropped(void* h);
void mailbox_set_outbox_cap(void* h, int64_t cap);
void mailbox_interrupt(void* h);

int libsvm_count(const char* path, int64_t* n_rows, int64_t* max_width);
int libsvm_parse_mt(const char* path, int64_t n_rows, int64_t width,
                    float* y, int32_t* idx, float* val, float* mask,
                    int n_threads);
}

namespace {

int drain(void* mb, int expect, int timeout_ms = 5000) {
  // Count frames until `expect` arrived or timeout; frees every buffer.
  int got = 0;
  char* msg = nullptr;
  int64_t msg_len = 0, blob_len = 0;
  uint8_t* blob = nullptr;
  while (got < expect &&
         mailbox_recv(mb, timeout_ms, &msg, &msg_len, &blob, &blob_len)) {
    assert(msg_len > 0);
    mailbox_free_buf(msg);
    if (blob) mailbox_free_buf(blob);
    blob = nullptr;
    ++got;
  }
  return got;
}

void mailbox_drill() {
  // 3-node full mesh on ephemeral ports.
  void* mb[3];
  int port[3];
  for (int i = 0; i < 3; ++i) {
    mb[i] = mailbox_create(0);
    assert(mb[i]);
    port[i] = mailbox_port(mb[i]);
  }
  for (int i = 0; i < 3; ++i)
    for (int j = 0; j < 3; ++j)
      if (i != j)
        assert(mailbox_connect(mb[i], "127.0.0.1", port[j], 5000) == 0);

  // Concurrent publishers: every node broadcasts 200 frames (half with
  // blobs) and directs 100 frames at each peer, from 2 threads each —
  // hammering the Sender actor, the per-connection readers and the
  // ThreadsafeQueue from both sides.
  const char* payload = "{\"kind\":\"x\",\"sender\":0,\"payload\":{}}";
  const int64_t plen = static_cast<int64_t>(std::strlen(payload));
  std::vector<uint8_t> blob(4096, 7);
  std::vector<std::thread> senders;
  for (int i = 0; i < 3; ++i) {
    for (int t = 0; t < 2; ++t) {
      senders.emplace_back([&, i] {
        for (int k = 0; k < 100; ++k) {
          mailbox_publish(mb[i], payload, plen,
                          (k & 1) ? blob.data() : nullptr,
                          (k & 1) ? static_cast<int64_t>(blob.size()) : -1);
          mailbox_send(mb[i], k % 2, payload, plen, nullptr, -1);
        }
      });
    }
  }
  for (auto& t : senders) t.join();
  // Each node: 2 peers * 200 broadcasts = 400, plus directed frames.
  // Directed: each node sends 2 threads * 100 to peer_index k%2 (50/50
  // split across its two peers * 2 threads = 100 per peer link).
  for (int i = 0; i < 3; ++i) {
    int got = drain(mb[i], 400 + 200);
    assert(got == 600);
  }
  // Late publisher AFTER the drain (frames nobody will read): teardown
  // with undelivered frames in flight through the Sender must not leak
  // or race. The publisher is joined BEFORE close — the C ABI contract
  // is no-publish-after-close (native_bus.py holds a lock for this), so
  // the publish-vs-close race itself is out of contract and untested.
  std::thread late([&] {
    for (int k = 0; k < 50; ++k)
      mailbox_publish(mb[0], payload, plen, nullptr, -1);
  });
  late.join();
  for (int i = 0; i < 3; ++i) mailbox_close(mb[i]);
  std::printf("mailbox drill: ok\n");
}

void backpressure_drill() {
  // Bounded-outbox semantics under TSan: a TINY cap with concurrent
  // producers must block (never drop) while a consumer drains, racing
  // push_bounded's space_cv_ waits against pop's notifies, the atomic
  // cap setter, and the depth/drop readers from another thread.
  void* a = mailbox_create(0);
  void* b = mailbox_create(0);
  assert(a && b);
  assert(mailbox_connect(a, "127.0.0.1", mailbox_port(b), 5000) == 0);
  assert(mailbox_connect(b, "127.0.0.1", mailbox_port(a), 5000) == 0);
  mailbox_set_outbox_cap(a, 8);
  const char* payload = "{\"kind\":\"y\",\"sender\":0,\"payload\":{}}";
  const int64_t plen = static_cast<int64_t>(std::strlen(payload));
  const int kEach = 500;
  std::vector<std::thread> prods;
  for (int t = 0; t < 3; ++t) {
    prods.emplace_back([&] {
      for (int k = 0; k < kEach; ++k)
        mailbox_send(a, 0, payload, plen, nullptr, -1);
    });
  }
  std::thread watcher([&] {  // concurrent observability + cap flip
    for (int k = 0; k < 200; ++k) {
      (void)mailbox_outbox_depth(a);
      (void)mailbox_dropped(a);
      if (k == 100) mailbox_set_outbox_cap(a, 16);
    }
  });
  int got = drain(b, 3 * kEach, 20000);
  for (auto& t : prods) t.join();
  watcher.join();
  assert(got == 3 * kEach);           // blocked, never dropped
  assert(mailbox_dropped(a) == 0);
  // interrupt wakes a blocked producer: refill a cap-1 queue with the
  // consumer gone quiet, then interrupt — the producer must return
  // (frame counted dropped), not hang
  mailbox_set_outbox_cap(a, 1);
  std::thread blocked([&] {
    for (int k = 0; k < 64; ++k)
      mailbox_send(a, 0, payload, plen, nullptr, -1);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  mailbox_interrupt(a);
  blocked.join();  // without the interrupt this would block ~30s/frame
  mailbox_close(a);
  mailbox_close(b);
  std::printf("backpressure drill: ok\n");
}

void reader_drill() {
  // Multi-threaded parse vs single-scan: byte-identical, no races.
  std::string path = "/tmp/sanitize_test.libsvm";
  {
    FILE* f = std::fopen(path.c_str(), "w");
    assert(f);
    for (int r = 0; r < 5000; ++r) {
      std::fprintf(f, "%d", (r * 7 % 2) ? 1 : -1);
      for (int k = 0; k < 1 + r % 13; ++k)
        std::fprintf(f, " %d:%.3f", (r + k * 31) % 123 + 1,
                     0.01 * ((r + k) % 97));
      std::fputc('\n', f);
    }
    std::fclose(f);
  }
  int64_t n = 0, w = 0;
  assert(libsvm_count(path.c_str(), &n, &w) == 0);
  assert(n == 5000 && w == 13);
  std::vector<float> y1(n), y4(n);
  std::vector<int32_t> i1(n * w), i4(n * w);
  std::vector<float> v1(n * w), v4(n * w), m1(n * w), m4(n * w);
  assert(libsvm_parse_mt(path.c_str(), n, w, y1.data(), i1.data(),
                         v1.data(), m1.data(), 1) == 0);
  assert(libsvm_parse_mt(path.c_str(), n, w, y4.data(), i4.data(),
                         v4.data(), m4.data(), 4) == 0);
  assert(std::memcmp(y1.data(), y4.data(), sizeof(float) * n) == 0);
  assert(std::memcmp(i1.data(), i4.data(), sizeof(int32_t) * n * w) == 0);
  assert(std::memcmp(v1.data(), v4.data(), sizeof(float) * n * w) == 0);
  assert(std::memcmp(m1.data(), m4.data(), sizeof(float) * n * w) == 0);
  std::remove(path.c_str());
  std::printf("reader drill: ok\n");
}

}  // namespace

int main() {
  mailbox_drill();
  backpressure_drill();
  reader_drill();
  std::printf("sanitize_test: ALL OK\n");
  return 0;
}
