"""Test bootstrap: 8 fake CPU devices — the "threads as nodes" trick.

The reference tests multi-node behavior with in-process threads + a fake
mailbox (SURVEY.md §4); the JAX equivalent is forcing the CPU platform with
8 host devices so every mesh/sharding/collective path runs TPU-free
(SURVEY.md §4 "Rebuild mapping"). NOTE: in this sandbox the axon TPU plugin
ignores the JAX_PLATFORMS env var, so the config.update path is required
and must run before the first backend-touching call.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
    # O0 backend codegen: ~20% off the suite's compile-dominated wall clock
    # (VERDICT r1 weak #6); parity tests still compare against oracles
    # compiled the same way, so tolerances are unaffected
    + " --xla_backend_optimization_level=0"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from minips_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

# warm reruns of the suite hit the persistent XLA cache instead of
# recompiling ~600s of transformer-family programs (VERDICT r1 weak #6)
enable_compile_cache()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from minips_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) == 8, "expected 8 fake CPU devices"
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    from minips_tpu.parallel.mesh import make_mesh

    return make_mesh(4, devices=jax.devices()[:4])
