"""Server-side updaters — rebuild of the reference's SGD/Adagrad updaters.

The reference applies the optimizer **on the server, at push time**
(``model->Add -> updater->Update(keys, grads) -> storage``, SURVEY.md §3.3),
which is exactly optax applied to the owner shard of the parameters inside
the fused SPMD step (SURVEY.md §2 "Updaters"). SGD and Adagrad are the two
the reference ships (BASELINE.json:3 via SURVEY.md §2); Adam is added because
it costs nothing under optax and apps want it.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import optax

UPDATERS = ("sgd", "adagrad", "adam", "adamw")

# a float or an optax schedule (step -> lr); optax consumes either
# directly, so warmup/cosine/decay schedules work on every updater:
#   DenseTable(..., lr=optax.warmup_cosine_decay_schedule(...))
LearningRate = Union[float, Callable[[int], float]]


class MaskedDecayState(NamedTuple):
    # the mask rides IN the optimizer state (not a closure) so that
    # DenseTable's state sharding machinery shards it alongside the
    # params — inside the fused step's shard_map, updates/params/mask all
    # arrive as aligned per-shard slices
    mask: Any


def masked_weight_decay(weight_decay: float,
                        mask) -> optax.GradientTransformation:
    """Decoupled weight decay applied only where ``mask`` is 1 — the
    standard "decay matrices, not LN/bias" rule, but elementwise so it
    survives DenseTable's ravel into one flat vector (optax.masked is
    leaf-level and cannot express a per-element mask)."""
    import jax

    def init(params):
        del params
        return MaskedDecayState(mask=mask)

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("masked_weight_decay needs params")
        updates = jax.tree.map(
            lambda g, p, m: g + weight_decay * p * m, updates, params,
            state.mask)
        return updates, state

    return optax.GradientTransformation(init, update)


def make_updater(name: str, lr: LearningRate,
                 **kwargs) -> optax.GradientTransformation:
    """``clip_norm`` (any updater) prepends global-norm gradient
    clipping — over whatever params THIS transform sees: DenseTable
    intercepts the kwarg and instead clips by the cross-shard global
    norm inside its fused step (a psum), because the transform only ever
    sees one owner shard there. ``adamw`` takes ``weight_decay``
    (default 0.01) and an optional elementwise ``decay_mask``
    (DenseTable ravels+pads a params-shaped pytree mask for you)."""
    name = name.lower()
    clip = kwargs.get("clip_norm")
    chain = [optax.clip_by_global_norm(clip)] if clip else []
    if name == "sgd":
        tx = optax.sgd(lr, momentum=kwargs.get("momentum", 0.0) or None)
    elif name == "adagrad":
        # Reference Adagrad accumulates squared grads per key; optax matches.
        tx = optax.adagrad(lr, initial_accumulator_value=kwargs.get(
            "initial_accumulator_value", 0.1))
    elif name == "adam":
        tx = optax.adam(lr, b1=kwargs.get("b1", 0.9),
                        b2=kwargs.get("b2", 0.999))
    elif name == "adamw":
        wd = kwargs.get("weight_decay", 0.01)
        mask = kwargs.get("decay_mask")
        decay = (optax.add_decayed_weights(wd) if mask is None
                 else masked_weight_decay(wd, mask))
        tx = optax.chain(
            optax.scale_by_adam(b1=kwargs.get("b1", 0.9),
                                b2=kwargs.get("b2", 0.999)),
            decay,
            optax.scale_by_learning_rate(lr))   # handles schedules too
    else:
        raise ValueError(
            f"unknown updater {name!r}; expected one of {UPDATERS}")
    return optax.chain(*chain, tx) if chain else tx
