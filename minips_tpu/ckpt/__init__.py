from minips_tpu.ckpt.checkpoint import Checkpointer  # noqa: F401


def convert_checkpoint(src_dir: str, dst_dir: str, tables: dict,
                       controllers: dict | None = None, *,
                       src_backend: str, dst_backend: str,
                       step: int | None = None) -> int:
    """Migrate a checkpoint between the native (npz-dir) and orbax
    (TensorStore) formats — the concrete meaning of the two backends being
    "drop-in interchangeable" (SURVEY.md §5.4): same content, so a restore
    through one and a save through the other is lossless. ``tables`` (and
    optional ``controllers``) provide the live objects whose state carries
    the checkpoint across; their state is overwritten by ``src`` and then
    persisted to ``dst``. Returns the migrated step."""
    from minips_tpu.ckpt.orbax_backend import make_checkpointer

    src = make_checkpointer(src_dir, tables, controllers,
                            backend=src_backend)
    step = src.restore(step)
    if hasattr(src, "close"):
        src.close()
    dst = make_checkpointer(dst_dir, tables, controllers,
                            backend=dst_backend)
    dst.save(step=step)
    dst.wait()
    if hasattr(dst, "close"):
        dst.close()
    return step
