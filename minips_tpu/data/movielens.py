"""MovieLens ratings reader — the real-file path for the MF workload
(BASELINE.json:9: "Matrix factorization on MovieLens-20M").

Handles both shipped formats:

- ``ratings.csv`` (ML-20M/25M): header line ``userId,movieId,rating,
  timestamp`` then comma-separated rows.
- ``ratings.dat`` (ML-1M/10M): ``UserID::MovieID::Rating::Timestamp``.
- ``u.data`` (ML-100K): tab-separated ``user item rating ts``.

Raw ids are arbitrary (1-based, sparse); they are remapped to dense
0-based indices so the SparseTables size to the number of distinct
users/items, not the max raw id.
"""

from __future__ import annotations

import numpy as np


def read_ratings(path: str) -> dict:
    """File -> {"user": [n] int32 dense ids, "item": [n] int32 dense ids,
    "rating": [n] float32, "num_users": int, "num_items": int}."""
    users: list[int] = []
    items: list[int] = []
    ratings: list[float] = []
    with open(path, "r", errors="replace") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if "::" in line:
                parts = line.split("::")
            elif "," in line:
                parts = line.split(",")
            else:
                parts = line.split()
            if len(parts) < 3:
                raise ValueError(f"{path}:{lineno}: expected >= 3 fields, "
                                 f"got {len(parts)}")
            try:
                u, i, r = int(parts[0]), int(parts[1]), float(parts[2])
            except ValueError:
                # Only ratings.csv has a header, and only on line 1 —
                # a corrupt first row in ::/tab formats must still raise.
                if lineno == 1 and "," in line:
                    continue
                raise ValueError(f"{path}:{lineno}: unparseable row "
                                 f"{line[:60]!r}") from None
            users.append(u)
            items.append(i)
            ratings.append(r)
    if not users:
        raise ValueError(f"{path}: no ratings rows")
    u_raw = np.asarray(users, np.int64)
    i_raw = np.asarray(items, np.int64)
    u_uniq, u_dense = np.unique(u_raw, return_inverse=True)
    i_uniq, i_dense = np.unique(i_raw, return_inverse=True)
    return {"user": u_dense.astype(np.int32),
            "item": i_dense.astype(np.int32),
            "rating": np.asarray(ratings, np.float32),
            "num_users": int(len(u_uniq)),
            "num_items": int(len(i_uniq))}
