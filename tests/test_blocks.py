"""Dynamic block assigner tests — local (threads-as-workers) and over the
loopback control bus (threads-as-processes), the reference's mailbox-test
style (SURVEY.md §4). Covers the FlexPS-lineage coordinator semantics:
exactly-once assignment, straggler-friendly dynamic draining, and dead-worker
block re-queue (SURVEY.md §1 L5, §5.3)."""

import threading
import time

import pytest

from minips_tpu.data.blocks import (BlockClient, BlockMaster,
                                    LocalBlockAssigner, read_block_lines,
                                    split_file_lines, split_rows)


def test_split_rows_covers_range():
    blocks = split_rows(103, 25)
    assert [b["id"] for b in blocks] == list(range(5))
    assert blocks[0] == {"id": 0, "start": 0, "end": 25}
    assert blocks[-1] == {"id": 4, "start": 100, "end": 103}
    covered = [r for b in blocks for r in range(b["start"], b["end"])]
    assert covered == list(range(103))


def test_split_file_lines_roundtrip(tmp_path):
    lines = [f"row {i} payload".encode() for i in range(37)]
    path = str(tmp_path / "d.txt")
    with open(path, "wb") as f:
        f.write(b"\n".join(lines))  # no trailing newline: tail block case
    blocks = split_file_lines(path, 10)
    assert [b["lines"] for b in blocks] == [10, 10, 10, 7]
    back = [ln for b in blocks for ln in read_block_lines(b)]
    assert back == lines
    # byte ranges tile the file exactly
    assert blocks[0]["offset"] == 0
    for a, b in zip(blocks, blocks[1:]):
        assert a["offset"] + a["nbytes"] == b["offset"]


def test_local_assigner_exactly_once_under_threads():
    blocks = split_rows(1000, 10)  # 100 blocks
    asg = LocalBlockAssigner(blocks)
    taken: list[int] = []
    lock = threading.Lock()

    def worker(wid):
        while True:
            b = asg.next_block(wid)
            if b is None:
                return
            time.sleep(0.0005 * (wid + 1))  # unequal speeds → dynamic split
            with lock:
                taken.append(b["id"])
            asg.done(wid, b["id"])

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(taken) == list(range(100))  # every block exactly once
    assert asg.remaining == 0


def test_local_assigner_requeues_dead_worker():
    asg = LocalBlockAssigner(split_rows(30, 10))
    b0 = asg.next_block(worker=1)
    b1 = asg.next_block(worker=1)
    asg.done(1, b0["id"])  # finished one, died holding the other
    assert asg.requeue_worker(1) == 1
    ids_left = {asg.next_block(2)["id"], asg.next_block(2)["id"]}
    assert b1["id"] in ids_left
    assert asg.next_block(2) is None


def test_iter_block_batches_static_shapes_across_blocks(tmp_path):
    """Out-of-core streaming: criteo file → line blocks → fixed batches."""
    import numpy as np

    from minips_tpu.data import synthetic
    from minips_tpu.data.blocks import iter_block_batches
    from minips_tpu.data.criteo import read_criteo, write_criteo

    d = synthetic.criteo_like(70, seed=2)
    dense = np.round(d["dense"]).astype(np.float32)
    path = str(tmp_path / "c.tsv")
    write_criteo(path, d["y"], dense, d["cat"])
    blocks = split_file_lines(path, 16)  # 16,16,16,16,6 lines

    def parse(block):
        sub = str(tmp_path / f"b{block['id']}.tsv")
        with open(sub, "wb") as f:
            f.write(b"\n".join(read_block_lines(block)) + b"\n")
        out = read_criteo(sub, use_native=False)
        return {"y": out["y"], "cat": out["cat"]}

    batches = list(iter_block_batches(iter(blocks), parse, batch_size=32))
    assert [len(b["y"]) for b in batches] == [32, 32]  # 70 rows, drop tail 6
    ys = np.concatenate([b["y"] for b in batches])
    np.testing.assert_array_equal(ys, d["y"][:64])  # order preserved
    # ragged tail surfaced when asked
    tail = list(iter_block_batches(iter(blocks), parse, batch_size=32,
                                   drop_last=False))[-1]
    assert len(tail["y"]) == 6


def _mk_buses(n):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n)


def test_block_master_client_over_bus():
    buses = _mk_buses(3)
    try:
        master = BlockMaster(buses[0], split_rows(120, 10))  # 12 blocks
        clients = [BlockClient(buses[0], local_master=master),
                   BlockClient(buses[1]), BlockClient(buses[2])]
        got: dict[int, list[int]] = {0: [], 1: [], 2: []}

        def drain(pid):
            for b in clients[pid]:
                got[pid].append(b["id"])
                time.sleep(0.02)  # simulate work so the split is dynamic

        threads = [threading.Thread(target=drain, args=(p,)) for p in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        all_ids = sorted(i for ids in got.values() for i in ids)
        assert all_ids == list(range(12))  # exactly once across processes
        assert master.assigner.remaining == 0
        # remote (bus-served) clients did get work — the protocol ran; the
        # local direct-call client may legitimately grab the lion's share
        assert len(got[1]) + len(got[2]) > 0
    finally:
        for b in buses:
            b.close()


def test_block_master_requeues_on_failure():
    buses = _mk_buses(2)
    try:
        master = BlockMaster(buses[0], split_rows(20, 10))  # blocks 0, 1
        remote = BlockClient(buses[1])
        b = remote.next_block()
        assert b is not None  # worker 1 holds a block, then "dies" silently
        assert master.handle_failure(1) == 1
        local = BlockClient(buses[0], local_master=master)
        ids = []
        while True:
            nb = local.next_block()
            if nb is None:
                break
            ids.append(nb["id"])
            local.done(nb)
        assert sorted(ids + [b["id"]]) == [0, 1] or sorted(ids) == [0, 1]
        assert b["id"] in ids  # the dead worker's block was re-served
    finally:
        for b in buses:
            b.close()


class _FakeBus:
    """Loopback-free stub: captures publishes, delivers nothing."""

    def __init__(self, my_id=0):
        self.my_id = my_id
        self.published = []
        self._handlers = {}

    def on(self, kind, handler):
        self._handlers[kind] = handler

    def publish(self, kind, payload, blob=None):
        self.published.append((kind, payload))


def test_master_reserves_duplicate_request_idempotently():
    """A retried req id (lost reply) gets the SAME block back — the block is
    not re-popped, so a timeout can't strand or double-assign it."""
    bus = _FakeBus()
    master = BlockMaster(bus, split_rows(30, 10))  # blocks 0,1,2
    master._on_req(sender=1, payload={"req": 1})
    master._on_req(sender=1, payload={"req": 1})  # duplicate (client retry)
    asns = [p for k, p in bus.published if k == "blk_asn"]
    assert asns[0]["block"]["id"] == asns[1]["block"]["id"]
    assert master.assigner.remaining == 2  # only one block actually popped
    master._on_req(sender=1, payload={"req": 2})  # next req → next block
    asns = [p for k, p in bus.published if k == "blk_asn"]
    assert asns[2]["block"]["id"] != asns[0]["block"]["id"]


def test_client_retries_until_answered():
    buses = _mk_buses(2)
    try:
        client = BlockClient(buses[1], timeout=10.0, retry_every=0.2)
        # master comes up LATE — first request frames are lost to the void
        result = {}

        def ask():
            result["block"] = client.next_block()

        t = threading.Thread(target=ask)
        t.start()
        time.sleep(0.6)  # client has already published >= 1 lost request
        BlockMaster(buses[0], split_rows(10, 10))
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert result["block"]["id"] == 0  # retry got the block
    finally:
        for b in buses:
            b.close()


def test_client_timeout_without_master():
    buses = _mk_buses(2)
    try:
        client = BlockClient(buses[1], timeout=0.3)  # nobody serves blk_req
        with pytest.raises(TimeoutError):
            client.next_block()
    finally:
        for b in buses:
            b.close()
