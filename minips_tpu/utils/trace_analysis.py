"""Turn captured profiler traces into numbers inside the sandbox.

``profiling.profile_trace`` writes TensorBoard-format traces, but this
environment has no TensorBoard UI and the profile plugin's generated
protos don't load under the installed protobuf — so the xplane.pb path is
a dead end here. The profiler ALSO writes a Chrome-trace
``*.trace.json.gz`` next to it (stdlib-parseable), which carries the same
per-op timeline: on TPU each device shows up as its own process
("/device:TPU:0 ...") whose complete ("X") events are XLA op/fusion
executions with microsecond durations. Summing self-time by op name gives
the op profile we'd otherwise read in the TensorBoard UI — the missing
half of the tracing subsystem (SURVEY.md §5.1): capture was first-class,
analysis now is too.

The reference family's equivalent is glog iteration timers; this is the
TPU-native upgrade: compiled-op-level attribution, not wall timestamps.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Optional


def latest_trace_file(log_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``log_dir`` (any host, any run)."""
    hits = glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_events(path: str) -> tuple[list[dict], dict[int, str]]:
    """(complete events, pid -> process name) from a Chrome trace file."""
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pids: dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e.get("args", {}).get("name", "")
    return [e for e in events if e.get("ph") == "X"], pids


def op_table(events: list[dict], pids: dict[int, str], *,
             device_only: bool = True, top: int = 15) -> dict:
    """Aggregate complete-event durations by op name.

    ``device_only`` keeps events from "/device:*" processes (TPU op
    timeline). When no device process exists (CPU backend traces carry
    only host events) it falls back to host events so the tool still
    reports something rather than an empty table.
    """
    dev_pids = {p for p, name in pids.items() if "/device:" in name}
    use_dev = device_only and bool(dev_pids)
    totals: dict[str, float] = defaultdict(float)
    counts: dict[str, int] = defaultdict(int)
    span_lo, span_hi = float("inf"), 0.0
    for e in events:
        if use_dev and e["pid"] not in dev_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))
        totals[name] += dur
        counts[name] += 1
        ts = float(e.get("ts", 0.0))
        span_lo = min(span_lo, ts)
        span_hi = max(span_hi, ts + dur)
    total_us = sum(totals.values())
    rows = sorted(totals, key=totals.get, reverse=True)[:top]
    return {
        "source": "device" if use_dev else "host",
        "span_us": round(max(0.0, span_hi - span_lo), 3),
        "busy_us": round(total_us, 3),
        "ops": [{
            "name": n,
            "total_us": round(totals[n], 3),
            "count": counts[n],
            "pct_of_busy": round(100.0 * totals[n] / total_us, 2)
            if total_us else 0.0,
        } for n in rows],
    }


def summarize(log_dir: str, *, top: int = 15) -> dict:
    """Op profile of the newest trace under ``log_dir`` (see op_table)."""
    path = latest_trace_file(log_dir)
    if path is None:
        return {"error": f"no *.trace.json.gz under {log_dir}"}
    events, pids = load_events(path)
    out = op_table(events, pids, top=top)
    out["trace_file"] = path
    return out


def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="Op-time table from a captured profiler trace dir")
    ap.add_argument("log_dir")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args(argv)
    try:
        print(json.dumps(summarize(args.log_dir, top=args.top), indent=2))
    except BrokenPipeError:  # e.g. piped into `head`
        os._exit(0)


if __name__ == "__main__":
    main()
