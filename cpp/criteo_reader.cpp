// Native Criteo TSV parser — the reference family's flagship sparse CTR
// format (SURVEY.md §2 "Data loading"; BASELINE.json:10 names Wide&Deep /
// DeepFM on Criteo-1TB). Line format (display-advertising release):
//
//   label \t I1..I13 (ints, may be empty/negative) \t C1..C26 (8-hex cats,
//   may be empty) \n
//
// Exposed as a plain C ABI consumed via ctypes (same contract style as
// libsvm_reader.cpp):
//   pass 1: criteo_count(path, &n_rows)
//   pass 2: criteo_parse(path, n_rows, y[N], dense[N*13], dense_mask[N*13],
//           cat[N*26]) — missing ints get value 0 / mask 0; categorical hex
//           values parse to uint32 and are offset by (field << 32) so every
//           column keeps a distinct int64 id space (missing → field-offset
//           0), matching the per-column-vocabulary convention the Python
//           synthetic generator uses (minips_tpu/data/synthetic.py
//           criteo_like).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "reader_common.h"

using minips::FileBuf;

namespace {

constexpr int kDense = 13;
constexpr int kCat = 26;

// Parse a decimal int field ending at tab/newline; empty → missing.
// On failure p is left UNMOVED so the caller's garbage check (*p != '\t')
// catches a lone '-' instead of recording it as missing.
inline bool parse_int_field(const char*& p, const char* line_end, long* out) {
  const char* q = p;
  if (q >= line_end || *q == '\t') return false;
  bool neg = false;
  if (*q == '-') { neg = true; ++q; }
  long v = 0;
  bool any = false;
  while (q < line_end && *q >= '0' && *q <= '9') {
    v = v * 10 + (*q - '0');
    any = true;
    ++q;
  }
  if (!any) return false;
  p = q;
  *out = neg ? -v : v;
  return true;
}

// Parse a hex categorical field ending at tab/newline; empty → missing.
// ndigits lets the caller reject >8-digit tokens (they would wrap uint32
// here while the Python oracle keeps all bits — reject in both instead).
inline bool parse_hex_field(const char*& p, const char* line_end,
                            uint32_t* out, int* ndigits) {
  uint32_t v = 0;
  int digits = 0;
  while (p < line_end) {
    char c = *p;
    uint32_t d;
    if (c >= '0' && c <= '9') d = static_cast<uint32_t>(c - '0');
    else if (c >= 'a' && c <= 'f') d = static_cast<uint32_t>(c - 'a' + 10);
    else if (c >= 'A' && c <= 'F') d = static_cast<uint32_t>(c - 'A' + 10);
    else break;
    v = (v << 4) | d;
    ++digits;
    ++p;
  }
  *ndigits = digits;
  if (digits == 0) return false;
  *out = v;
  return true;
}

// Advance past the field separator (one tab) if present.
inline void skip_tab(const char*& p, const char* line_end) {
  if (p < line_end && *p == '\t') ++p;
}

// Parse whole lines in [p, endp); writes up to max_rows rows starting at
// row 0 of the given output pointers; *rows_done reports how many rows the
// range actually held. Returns 0 ok / 3 malformed.
int parse_criteo_range(const char* p, const char* endp, int64_t max_rows,
                       float* y, float* dense, float* dense_mask,
                       int64_t* cat, int64_t* rows_done) {
  int64_t r = 0;
  while (p < endp && r < max_rows) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    const char* eol = line_end;
    if (eol > p && eol[-1] == '\r') --eol;  // tolerate CRLF
    if (p < eol) {
      long label = 0;
      parse_int_field(p, eol, &label);
      if (p < eol && *p != '\t') return 3;  // e.g. "3.5" label
      y[r] = static_cast<float>(label);
      skip_tab(p, eol);
      for (int f = 0; f < kDense; ++f) {
        long v;
        if (parse_int_field(p, eol, &v)) {
          dense[r * kDense + f] = static_cast<float>(v);
          dense_mask[r * kDense + f] = 1.0f;
        }
        if (p < eol && *p != '\t') return 3;  // unconsumed garbage in field
        skip_tab(p, eol);
      }
      for (int f = 0; f < kCat; ++f) {
        uint32_t v = 0;
        int ndigits = 0;
        parse_hex_field(p, eol, &v, &ndigits);  // missing -> 0 in the space
        if (ndigits > 8) return 3;            // would wrap uint32 silently
        if (p < eol && *p != '\t') return 3;  // non-hex byte in field
        cat[r * kCat + f] =
            (static_cast<int64_t>(f) << 32) | static_cast<int64_t>(v);
        skip_tab(p, eol);
      }
      ++r;
    }
    p = line_end + 1;
  }
  *rows_done = r;
  return 0;
}

int64_t count_rows_range(const char* p, const char* endp) {
  int64_t rows = 0;
  while (p < endp) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    if (line_end > p && !(line_end == p + 1 && *p == '\r')) ++rows;
    p = line_end + 1;
  }
  return rows;
}

}  // namespace

extern "C" {

int criteo_parse_mt(const char* path, int64_t n_rows, float* y,
                    float* dense, float* dense_mask, int64_t* cat,
                    int n_threads);

// Returns 0 on success; fills n_rows (non-empty lines).
int criteo_count(const char* path, int64_t* n_rows) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  *n_rows = count_rows_range(fb.data, fb.data + fb.size);
  return 0;
}

// Fills y[N], dense[N*13], dense_mask[N*13], cat[N*26].
// Returns 0 ok, 1 unreadable, 2 row-count mismatch, 3 malformed field —
// strict like the pure-Python oracle (which raises on garbage tokens), so
// the native fast path never silently trains on corrupted rows.
int criteo_parse(const char* path, int64_t n_rows, float* y, float* dense,
                 float* dense_mask, int64_t* cat) {
  return criteo_parse_mt(path, n_rows, y, dense, dense_mask, cat, 1);
}

// In-memory variants for streaming ingestion: the Python producer thread
// reads the file ONCE, sequentially, in line-aligned chunks and parses
// each chunk straight from its buffer — no pre-scan of the whole file, no
// re-reads, working set of one chunk (SURVEY.md §7.4.4; the Criteo-1TB
// posture). Same strict error codes as the whole-file entries.
int criteo_count_mem(const char* data, int64_t len, int64_t* n_rows) {
  if (len < 0) return 1;
  *n_rows = count_rows_range(data, data + len);
  return 0;
}

// CONTRACT: dense/dense_mask must arrive ZERO-INITIALIZED (np.zeros at
// the ctypes caller) — missing fields only skip writes, and a memset here
// would re-dirty copy-on-write-zero pages on the hot per-chunk path.
int criteo_parse_mem(const char* data, int64_t len, int64_t max_rows,
                     float* y, float* dense, float* dense_mask,
                     int64_t* cat, int64_t* rows_done) {
  if (len < 0) return 1;
  return parse_criteo_range(data, data + len, max_rows, y, dense,
                            dense_mask, cat, rows_done);
}

// Multi-threaded variant: the file is split into line-aligned chunks, row
// offsets come from a parallel counting pass, then chunks parse in
// parallel into disjoint output slices. Same strict error codes.
int criteo_parse_mt(const char* path, int64_t n_rows, float* y, float* dense,
                    float* dense_mask, int64_t* cat, int n_threads) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  std::memset(dense, 0, sizeof(float) * static_cast<size_t>(n_rows * kDense));
  std::memset(dense_mask, 0,
              sizeof(float) * static_cast<size_t>(n_rows * kDense));
  int T = minips::clamp_threads(n_threads);
  if (T == 1) {  // true single scan: no offset pass needed
    int64_t done = 0;
    int rc = parse_criteo_range(fb.data, fb.data + fb.size, n_rows, y,
                                dense, dense_mask, cat, &done);
    return rc ? rc : (done == n_rows ? 0 : 2);
  }
  std::vector<const char*> b = minips::line_chunks(fb.data, fb.size, T);
  std::vector<int64_t> counts(static_cast<size_t>(T), 0);
  minips::parallel_for(T, [&](int i) {
    counts[static_cast<size_t>(i)] = count_rows_range(b[i], b[i + 1]);
  });
  std::vector<int64_t> offs(static_cast<size_t>(T) + 1, 0);
  for (int i = 0; i < T; ++i)
    offs[static_cast<size_t>(i) + 1] =
        offs[static_cast<size_t>(i)] + counts[static_cast<size_t>(i)];
  if (offs[static_cast<size_t>(T)] != n_rows) return 2;
  std::vector<int> rcs(static_cast<size_t>(T), 0);
  minips::parallel_for(T, [&](int i) {
    int64_t off = offs[static_cast<size_t>(i)];
    int64_t done = 0;
    rcs[static_cast<size_t>(i)] = parse_criteo_range(
        b[i], b[i + 1], counts[static_cast<size_t>(i)], y + off,
        dense + off * kDense, dense_mask + off * kDense, cat + off * kCat,
        &done);
    if (rcs[static_cast<size_t>(i)] == 0 &&
        done != counts[static_cast<size_t>(i)])
      rcs[static_cast<size_t>(i)] = 2;
  });
  for (int i = 0; i < T; ++i)
    if (rcs[static_cast<size_t>(i)]) return rcs[static_cast<size_t>(i)];
  return 0;
}

}  // extern "C"
