"""Per-rank wire-event tracer — ``MINIPS_TRACE=<dir>[:opts]``, off by
default.

Four PRs of overlap, caching, retransmission, and online rebalancing
left the sharded PS with aggregate counters and mean timers but no way
to SEE one request's life across ranks — which rank a gate wait was
stuck on, whether a slow pull was parked at admission, queued behind a
retransmit, or fenced behind a migration. This module is the missing
timeline: every interesting edge of the PS stack records a typed event
into a bounded per-rank ring buffer, and each rank dumps Chrome-trace
JSON at finalize (plus an ``atexit`` hook, so a poisoned/dying run
still leaves a trace). ``minips_tpu.obs.merge`` then aligns the ranks'
clocks and links the flows into ONE timeline.

Design constraints, in order:

- **One branch when off.** The tracer is consulted from the hottest
  paths in the repo (per pull leg, per push frame, per served request).
  Call sites do ``tr = tracer.TRACER`` / ``if tr is not None:`` — a
  module-attribute load and a branch; nothing else exists on the off
  path. No event formatting, no time call, no allocation.
- **Lock-cheap when on.** Events are small tuples appended to a
  ``collections.deque(maxlen=cap)`` — the append is atomic under the
  GIL, so recording takes no lock at all; the ring bound makes a
  runaway run cost bounded memory and drop OLDEST events (the tail of
  a dying run is the part worth keeping).
- **Cross-thread spans.** A pull leg is issued on the training thread
  and completes on the bus receive thread, so spans are recorded as
  single complete ("X") events at their END, carrying the start
  timestamp the caller kept — no begin/end pairing state in the
  tracer.
- **Cross-rank flows.** A client's pull leg and the owner's serve are
  linked by a flow id that both sides can derive independently:
  ``flow_id(f"pull:{table}", client_rank, rid)`` — the client knows
  (me, rid), the owner knows (sender, req). Same trick for push frames
  via the ack seq. The table name is part of the kind because rids and
  push seqs are PER-TABLE counters: without it, two tables' rid 5
  would collide into one arrow.

Event taxonomy (cat/name — the contract ``obs/report.py`` and the
acceptance drills read; keep docs/observability.md in sync):

========== ================ ====================================
cat        name             meaning (key args)
========== ================ ====================================
pull       pull_leg         client: leg issue -> reply processed
                            (owner, rid, bytes)
pull       pull_wait        client: wait() blocked span (owners)
pull       fence_wait       client: local read fenced (blocks)
pull       cache_insert     client: rows cached (n, stamp)
serve      serve_pull       owner: request read+encode+send
                            (from, rid, rows)
serve      serve_pull_all   owner: shard assembly serve (from)
serve      pull_park        owner: request parked (from, rid, why)
serve      parked           owner: park -> serve/refuse span
                            (from, why)
serve      pull_refused     owner: psE epoch refusal (from, rid)
serve      pull_releg       client: refused leg re-split/re-sent
                            (rid, ep, relegs)
push       push_apply       owner: push frame decode+apply (from, n)
push       push_ack         client: frame send -> ack (owner, seq)
push       push_forward     owner: stale push forwarded (to, n)
clock      gate_wait        trainer: SSP gate blocked
                            (clock, behind=[ranks])
clock      tick             trainer: clock advanced (clock)
reliable   retransmit       gap open -> recovered (sender, stream,
                            seq)
reliable   nack             NACK sent (to, stream, n)
reliable   gave_up          seq abandoned (sender, stream, seq)
chaos      drop/dup/        injected fault (kind, sender, seq)
           delay/reorder
rebalance  rb_plan          coordinator: plan published
                            (table, ep, moves)
rebalance  rb_adopt         adoption span (ep, out, moved)
rebalance  rb_fence         block fenced -> released (b, ep)
rebalance  rb_ship          block state shipped (b, dst, rows)
rebalance  rb_install       block state installed (b)
hb         hb               heartbeat received (from, t_sent) —
                            the merge tool's clock-alignment data
========== ================ ====================================

Spec grammar: ``MINIPS_TRACE=/path/to/dir`` or
``MINIPS_TRACE=/path:cap=200000`` (``cap`` = ring depth in events).
Each rank writes ``<dir>/trace-rank<r>.json``.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import struct
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["Tracer", "TRACER", "maybe_init", "init", "flow_id",
           "dump_now", "reset_for_tests"]

# THE global handle every instrumented module consults:
# ``tracer.TRACER is None`` is the whole off-path cost.
TRACER: "Optional[Tracer]" = None

_init_lock = threading.Lock()
_DEFAULT_CAP = 200_000


def flow_id(kind: str, rank: int, seq: int) -> int:
    """A flow id both ends of a wire edge can derive independently —
    pure function of (kind, originating rank, wire id). Chrome wants a
    uint; 8 hash bytes keep collisions out of any real trace."""
    h = hashlib.blake2b(f"{kind}|{rank}|{seq}".encode(),
                        digest_size=8).digest()
    return struct.unpack("<Q", h)[0] & 0x7FFF_FFFF_FFFF_FFFF


class Tracer:
    """One per process. Events are tuples
    ``(ph, ts_us, dur_us, cat, name, tid, fid, args)`` — ``ph`` is the
    Chrome phase ('X' complete, 'i' instant, 's'/'f' flow), ``fid`` the
    flow id or 0, ``args`` a small dict or None (never mutated after
    recording)."""

    def __init__(self, rank: int, out_dir: str,
                 cap: int = _DEFAULT_CAP):
        self.rank = int(rank)
        self.out_dir = out_dir
        self.out_path = os.path.join(
            out_dir, f"trace-rank{self.rank}.json")
        self.cap = int(cap)
        self._ring: deque = deque(maxlen=self.cap)
        self._tids: dict = {}  # thread ident -> (small tid, name)
        self._tid_lock = threading.Lock()
        self._dump_lock = threading.Lock()
        os.makedirs(out_dir, exist_ok=True)

    # ------------------------------------------------------------- record
    def _tid(self) -> int:
        ident = threading.get_ident()
        t = self._tids.get(ident)
        if t is None:
            with self._tid_lock:
                t = self._tids.setdefault(
                    ident, (len(self._tids) + 1,
                            threading.current_thread().name))
        return t[0]

    def instant(self, cat: str, name: str, args: dict | None = None
                ) -> None:
        self._ring.append(("i", time.monotonic() * 1e6, 0.0, cat, name,
                           self._tid(), 0, args))

    def complete(self, cat: str, name: str, t0: float,
                 args: dict | None = None, *,
                 t1: float | None = None) -> None:
        """A span recorded at its END: ``t0`` (and optionally ``t1``)
        are ``time.monotonic()`` seconds the caller kept."""
        end = time.monotonic() if t1 is None else t1
        self._ring.append(("X", t0 * 1e6, max(end - t0, 0.0) * 1e6, cat,
                           name, self._tid(), 0, args))

    def flow(self, phase: str, fid: int, name: str,
             args: dict | None = None) -> None:
        """``phase`` 's' (start, at the emitting edge) or 'f' (finish,
        at the receiving edge). cat/name must match across the pair for
        Chrome to draw the arrow — everything here uses cat='flow'."""
        self._ring.append((phase, time.monotonic() * 1e6, 0.0, "flow",
                           name, self._tid(), fid, args))

    # --------------------------------------------------------------- dump
    def events_snapshot(self) -> list:
        # on CPython list(deque) copies atomically under the GIL
        # (measured: 0 failures in 3000 copies of a full 200k ring
        # under concurrent append), so the retry below is pure
        # defense against an implementation where a mutation can land
        # mid-iteration — and if even the retries lose, say so on
        # stderr rather than silently dumping a metadata-only trace
        for _ in range(16):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        import sys

        print("tracer: ring snapshot kept failing under concurrent "
              "appends; dumping without events", file=sys.stderr)
        return []

    def dump(self, path: str | None = None) -> str:
        """Write the Chrome-trace JSON (idempotent — re-dumping emits
        the current, larger ring; finalize and atexit may both run)."""
        path = path or self.out_path
        events = self.events_snapshot()
        with self._tid_lock:
            tids = dict(self._tids)
        out: list[dict] = [
            {"ph": "M", "pid": self.rank, "tid": 0,
             "name": "process_name",
             "args": {"name": f"rank {self.rank}"}},
            {"ph": "M", "pid": self.rank, "tid": 0,
             "name": "process_sort_index",
             "args": {"sort_index": self.rank}},
        ]
        for _ident, (tid, tname) in sorted(tids.items(),
                                           key=lambda kv: kv[1][0]):
            out.append({"ph": "M", "pid": self.rank, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        for ph, ts, dur, cat, name, tid, fid, args in events:
            e = {"ph": ph, "ts": round(ts, 3), "cat": cat, "name": name,
                 "pid": self.rank, "tid": tid}
            if ph == "X":
                e["dur"] = round(dur, 3)
            if ph in ("s", "f"):
                e["id"] = fid
                if ph == "f":
                    e["bp"] = "e"  # bind to enclosing slice end
            if ph == "i":
                e["s"] = "t"  # thread-scoped instant
            if args:
                e["args"] = args
            out.append(e)
        doc = {"traceEvents": out, "displayTimeUnit": "ms",
               "otherData": {"rank": self.rank,
                             "clock": "monotonic_us",
                             "events": len(events),
                             "cap": self.cap}}
        with self._dump_lock:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)  # a reader never sees a torn file
        return path


def _parse_spec(spec: str, env: str = "MINIPS_TRACE"
                ) -> tuple[str, dict]:
    """``<dir>[:k=v,...]`` — the dir may itself contain ':' only on
    platforms where that's pathological anyway; the FIRST ':' followed
    by a ``k=`` form splits. Shared with the flight recorder
    (obs/flight.py), whose ``MINIPS_FLIGHT`` speaks the same grammar —
    ``env`` only names the knob in the error."""
    out_dir, kw = spec, {}
    if ":" in spec:
        head, _, tail = spec.rpartition(":")
        if "=" in tail and head:
            out_dir = head
            for entry in filter(None, (e.strip()
                                       for e in tail.split(","))):
                k, _, v = entry.partition("=")
                if k != "cap":
                    raise ValueError(
                        f"{env}: unknown option {k!r} "
                        "(expected cap=<events>)")
                kw["cap"] = int(v)
    return out_dir, kw


def init(out_dir: str, rank: int, cap: int = _DEFAULT_CAP) -> Tracer:
    """Arm the tracer explicitly (the bench's ``--trace`` flag).
    Idempotent per process: a second init with the same rank returns
    the live tracer; a divergent one raises (two subsystems disagreeing
    about the trace target is a bug, not a preference)."""
    global TRACER
    with _init_lock:
        if TRACER is not None:
            if TRACER.rank != int(rank) or TRACER.out_dir != out_dir \
                    or TRACER.cap != int(cap):
                raise RuntimeError(
                    f"tracer already armed (rank {TRACER.rank}, dir "
                    f"{TRACER.out_dir!r}, cap {TRACER.cap}); re-init "
                    f"asked for rank {rank}, dir {out_dir!r}, cap "
                    f"{cap} — traces would silently land in the first "
                    "target")
            return TRACER
        TRACER = Tracer(rank, out_dir, cap=cap)
        atexit.register(_dump_at_exit)
        return TRACER


def maybe_init(rank: int) -> Optional[Tracer]:
    """Arm from ``$MINIPS_TRACE`` if set (the one env gate); returns the
    tracer or None. Called from every subsystem that knows the rank
    early (trainer/table construction, app bootstrap) — first caller
    wins, the rest get the same object."""
    if TRACER is not None:
        return TRACER
    spec = os.environ.get("MINIPS_TRACE", "")
    if not spec:
        return None
    out_dir, kw = _parse_spec(spec)
    return init(out_dir, rank, **kw)


def dump_now() -> Optional[str]:
    """Dump the armed tracer's ring (finalize / poison paths); no-op
    when the layer is off. NEVER raises: it runs inside finalize's
    ``finally`` and right before the bench's done line — observability
    must not kill (or mask the real exception of) the run it
    observes."""
    if TRACER is None:
        return None
    try:
        return TRACER.dump()
    except Exception as e:  # noqa: BLE001 - report, don't propagate
        import sys

        print(f"tracer: dump failed: {e!r}", file=sys.stderr)
        return None


def _dump_at_exit() -> None:
    try:
        dump_now()
    except Exception:  # noqa: BLE001 - never fail interpreter teardown
        pass


def reset_for_tests() -> None:
    """Drop the global tracer (tests arm/disarm repeatedly; production
    never calls this)."""
    global TRACER
    with _init_lock:
        TRACER = None
