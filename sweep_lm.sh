#!/bin/bash
# LM MFU frontier sweep (VERDICT r2 #7). Run on an idle chip; each line
# prints "config -> tok/s TF/s MFU". Results land in BASELINE.md.
cd "$(dirname "$0")"
run() {
  echo "=== $*"
  timeout 500 python bench.py --suite lm "$@" 2>/dev/null | python -c "
import sys, json
try:
    d = json.loads(sys.stdin.read().strip().splitlines()[-1])
    s = d['suites']['lm']
    print(' ', s['samples_per_sec_per_chip'], 'tok/s,', s['tflops_per_chip'], 'TF/s, MFU', s['mfu_vs_bf16_peak'], '('+d['device']+')')
except Exception as e:
    print('  FAILED', e)
"
}
run --lm-dim 512  --lm-depth 4 --lm-batch 64                                     # r2 baseline 26.7%
run --lm-dim 2048 --lm-depth 8 --lm-batch 64 --lm-remat --lm-head-chunk 128      # r2 35.8% + chunked head
run --lm-dim 2048 --lm-depth 8 --lm-batch 64 --lm-remat --lm-remat-mode attn --lm-head-chunk 128
run --lm-dim 2048 --lm-depth 8 --lm-batch 32 --lm-remat --lm-remat-mode attn --lm-head-chunk 128
run --lm-dim 2048 --lm-depth 8 --lm-batch 32 --lm-remat --lm-remat-mode dots --lm-head-chunk 128
run --lm-dim 2048 --lm-depth 4 --lm-batch 32 --lm-head-chunk 128                 # no remat at all
run --lm-dim 1024 --lm-depth 8 --lm-batch 32 --lm-head-chunk 128
run --lm-dim 1024 --lm-depth 8 --lm-batch 64 --lm-remat --lm-remat-mode dots --lm-head-chunk 128
run --lm-dim 4096 --lm-depth 4 --lm-batch 32 --lm-remat --lm-head-chunk 128
