"""Process launcher — rebuild of the reference's launch scripts (SURVEY.md
§1 L7, §2 "Launch scripts").

The reference parses a hostfile and ssh-spawns one process per node with
``--my_id i``. Here the same shape: ``python -m minips_tpu.launch
--hostfile hosts.txt -- python worker.py ...`` spawns one worker process
per hostfile line (locally via subprocess for 127.0.0.1/localhost lines,
via ssh otherwise) and wires each with environment variables instead of
flags, so any program can join without argparse ceremony:

- ``MINIPS_PROC_ID`` / ``MINIPS_NUM_PROCS`` — my rank / world size
  (reference ``--my_id`` + hostfile length).
- ``MINIPS_BUS_ADDRS`` — comma list of every process's control-bus PUB
  endpoint (reference: mailbox node list). Process i binds the i-th.
- ``MINIPS_COORDINATOR`` — proc 0's host:port for
  ``jax.distributed.initialize`` on real multi-host pods (unused by the
  loopback smoke tests, whose data plane is the bus).

Failure policy matches a PS job's: first nonzero exit kills the rest
(all-or-nothing restart semantics, SURVEY.md §7.4.5).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Optional

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def find_free_base_port(span: int, *, tries: int = 128,
                        extra_offsets: tuple = (1000,)) -> int:
    """A base port such that ``base .. base+span-1`` are all bindable
    RIGHT NOW, chosen by asking the OS instead of hand-maintained bump
    lists (the cross-test port-collision flake class: every multiproc
    test file kept its own ``_PORT = [...]`` counter, and two files
    landing on overlapping ranges — or a straggler process from the
    previous test still holding its socket — produced bind failures or,
    worse, frames from a stale run).

    The check binds each port on the wildcard interface (what the bus's
    ``tcp://*:port`` bind uses) and releases it, so a small TOCTOU
    window remains — but the randomized base makes two concurrent
    pickers collide with probability ~span/36000 instead of always, and
    a straggler's held port now FAILS the probe instead of silently
    swallowing frames.

    ``extra_offsets`` probes derived ports too: ``child_env`` hands out
    ``base_port + 1000`` as the jax.distributed coordinator
    (MINIPS_COORDINATOR), so a base whose +1000 neighbor is taken would
    reintroduce the multihost flavor of the very flake this kills."""
    import random
    import socket

    rng = random.Random((os.getpid() << 16) ^ time.monotonic_ns())
    ports = list(range(span)) + list(extra_offsets)  # +1000 = coordinator
    for _ in range(tries):
        base = rng.randrange(20000, 60000 - span - max(extra_offsets,
                                                       default=0))
        socks = []
        try:
            for p in ports:
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.bind(("", base + p))
                socks.append(s)
        except OSError:
            continue
        else:
            return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(
        f"find_free_base_port: no free {span}-port block after "
        f"{tries} tries")


def read_hostfile(path: str) -> list[str]:
    """One host per line; blank lines and #-comments ignored (reference
    hostfile format)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line)
    return hosts


def bus_addresses(hosts: list[str], base_port: int) -> list[str]:
    """PUB endpoint per process. Same-host processes get consecutive ports
    (colocated deployment, SURVEY.md §1). Local aliases share one port
    counter (a hostfile mixing 'localhost' and '127.0.0.1' is one machine),
    and IPv6 literals get zmq's required brackets."""
    counts: dict[str, int] = {}
    addrs = []
    for h in hosts:
        key = "127.0.0.1" if h in _LOCAL_NAMES else h
        k = counts.get(key, 0)
        counts[key] = k + 1
        ep = f"[{h}]" if ":" in h else h
        addrs.append(f"tcp://{ep}:{base_port + k}")
    return addrs


def bus_endpoint_of(rank: int,
                    addrs: Optional[list[str]] = None) -> Optional[str]:
    """The control-bus endpoint of ``rank`` as the launcher advertised
    it (``MINIPS_BUS_ADDRS``) — how a running rank turns a
    membership-table successor into an ADDRESS without respawn.

    The coordinator-succession audit this encodes: the bus is a FULL
    MESH wired at spawn (every rank binds its own slot and connects to
    all peers from the same env list), so the coordinator role was
    never an endpoint — it is a rank id, and lease succession
    (balance/control_plane.py) changes only that id. Nothing about the
    port plumbing needs renegotiating mid-run. The one genuinely
    rank-0-pinned address, ``MINIPS_COORDINATOR``, is
    ``jax.distributed``'s spawn-time rendezvous and is consumed exactly
    once at startup — a dead rank 0 after initialization does not
    invalidate it. Returns None outside a launched job (or for a rank
    beyond the address space)."""
    if addrs is None:
        addrs = [a for a in os.environ.get("MINIPS_BUS_ADDRS",
                                           "").split(",") if a]
    if 0 <= int(rank) < len(addrs):
        return addrs[int(rank)]
    return None


def child_env(rank: int, hosts: list[str], base_port: int) -> dict[str, str]:
    env = dict(os.environ)
    env["MINIPS_PROC_ID"] = str(rank)
    env["MINIPS_NUM_PROCS"] = str(len(hosts))
    # processes COLOCATED on this rank's host — what host-resource
    # divisions (e.g. native parse threads) should divide by, not the
    # world size. Local aliases normalize to one key (a hostfile mixing
    # 'localhost' and '127.0.0.1' is one machine — same rule as
    # bus_addresses; two would-be leaders would race the shared store).
    def _hkey(h):
        return "127.0.0.1" if h in _LOCAL_NAMES else h

    keys = [_hkey(h) for h in hosts]
    env["MINIPS_LOCAL_PROCS"] = str(keys.count(keys[rank]))
    # my index among those colocated processes (0 = local leader, e.g.
    # the one that parses into the shared-memory sample store)
    env["MINIPS_LOCAL_RANK"] = str(keys[:rank].count(keys[rank]))
    # one id per launcher invocation: namespaces shared-memory segments so
    # a relaunch never attaches to a crashed run's stale store
    env["MINIPS_RUN_ID"] = f"{os.getpid()}"
    env["MINIPS_BUS_ADDRS"] = ",".join(bus_addresses(hosts, base_port))
    env["MINIPS_COORDINATOR"] = f"{hosts[0]}:{base_port + 1000}"
    return env


def _sweep_shm() -> None:
    """Reclaim shared-memory leftovers of DEAD runs before spawning: a
    SIGKILLed job never reaches its atexit/close cleanup, and both the
    sample store's segments (dataset-sized) and the shm bus's ring
    files (ring-sized per link) live in tmpfs — host RAM. Each sweeper
    pid-checks the MINIPS_RUN_ID baked into the file name. The flight
    recorder's default dump dirs (obs/flight.py — small, but also
    keyed by run id in tmp) ride the same hygiene contract."""
    from minips_tpu.comm.shm_bus import \
        sweep_stale_segments as sweep_bus_segments
    from minips_tpu.data.shm_store import sweep_stale_segments
    from minips_tpu.obs.flight import sweep_stale_dirs

    sweep_stale_segments()
    sweep_bus_segments()
    sweep_stale_dirs()


def spawn(hosts: list[str], argv: list[str], base_port: int = 5700,
          stdout=None) -> list[subprocess.Popen]:
    """Spawn one process per host entry; returns live Popen handles."""
    _sweep_shm()
    procs = []
    for rank, host in enumerate(hosts):
        env = child_env(rank, hosts, base_port)
        if host in _LOCAL_NAMES:
            cmd = argv
        else:  # remote: ssh with env inlined (reference ssh-spawn path)
            import shlex
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in env.items()
                if k.startswith("MINIPS_"))
            cmd = ["ssh", host,
                   exports + " " + " ".join(shlex.quote(a) for a in argv)]
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout is not None else None))
    return procs


def wait(procs: list[subprocess.Popen], timeout: Optional[float] = None,
         kill_on_failure: bool = True) -> int:
    """Join all; on first nonzero exit (optionally) terminate the rest and
    return that code. Returns 0 when everyone exited clean."""
    deadline = None if timeout is None else time.monotonic() + timeout
    live = list(procs)
    rc = 0
    while live:
        for p in list(live):
            code = p.poll()
            if code is None:
                continue
            live.remove(p)
            if code != 0 and rc == 0:
                rc = code
                if kill_on_failure:
                    for q in live:
                        q.terminate()
        if deadline is not None and time.monotonic() > deadline:
            for q in live:
                q.kill()
            for q in live:  # reap: SIGKILLed children must not linger as zombies
                try:
                    q.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            return rc or -signal.SIGKILL
        time.sleep(0.05)
    return rc


# --------------------------------------------------------------- fork spawn
#
# Local smoke/bench jobs spawn O(100) short-lived ranks per test tier, and
# each subprocess rank pays ~2.5s just importing jax before it runs a line
# of app code — on the 1-core CI box that import bill alone was blowing the
# driver's per-tier budget. The forkserver path preloads jax ONCE in a
# clean server process (started fresh via exec, so no inherited XLA
# threads from the pytest runner) and forks ranks from it in ~100ms;
# the app module itself is imported post-fork from disk, so children run
# current code with the process isolation the drills rely on (own pid,
# own backend, killable with SIGKILL). Production spawns (`spawn()`, ssh,
# TPU-bound ranks) keep plain subprocess: PJRT plugins and fork don't mix,
# so only ranks pinned to CPU (MINIPS_FORCE_CPU) take the fast path.
# Opt out with MINIPS_SPAWN=subprocess.

_FORK_CTX = None


def _fork_ctx():
    global _FORK_CTX
    if _FORK_CTX is None:
        import multiprocessing as mp

        ctx = mp.get_context("forkserver")
        # preloading minips_tpu (not just jax) means ranks fork with the
        # whole framework imported — the app module itself is the only
        # import left post-fork. The package has no import-time state
        # that differs from a fresh import (no module-level pids/uuids/
        # clocks; atexit hooks register at runtime, and the forked rank
        # replays them at exit — see _fork_child_main's finally), so the
        # fork copy behaves like a cold import. Caveat: the server lives
        # for the parent process's lifetime, so code edits between two
        # jobs of ONE parent are invisible to the second job — a fresh
        # pytest/bench invocation gets a fresh server.
        ctx.set_forkserver_preload(["jax", "minips_tpu"])
        _FORK_CTX = ctx
    return _FORK_CTX


def _fork_child_main(argv: list[str], env: dict, out_path: str) -> None:
    """Runs inside the forked rank: adopt the launcher-built env, wire
    stdout+stderr to the harvest file (the smoke protocol reads JSON
    lines from it), then execute ``python -m <module>`` semantics via
    runpy. SystemExit propagates to multiprocessing's bootstrap, which
    maps it to the process exit code exactly like a subprocess would."""
    import runpy

    fd = os.open(out_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(fd)
    os.environ.clear()
    os.environ.update(env)
    i = argv.index("-m")
    mod, args = argv[i + 1], argv[i + 2:]
    sys.argv = [mod] + list(args)
    try:
        runpy.run_module(mod, run_name="__main__", alter_sys=True)
    finally:
        # multiprocessing's bootstrap leaves via os._exit, which skips
        # atexit — but a subprocess rank WOULD have run its atexit hooks
        # (the shm_store leader's segment unlink registers there, and so
        # do jax's own teardown hooks). Run them explicitly so the fork
        # path keeps subprocess exit semantics; then flush the block-
        # buffered file stdout so the harvester sees the result line.
        import atexit

        try:
            atexit._run_exitfuncs()
        except Exception:
            pass
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass


class _ForkProc:
    """Popen-shaped handle over a forked rank — just enough surface for
    :func:`wait` (poll/terminate/kill/wait) and the drills (.pid)."""

    def __init__(self, proc):
        self._p = proc
        self.pid = proc.pid

    def poll(self):
        return self._p.exitcode  # None while running; -signum on kill

    def terminate(self):
        self._p.terminate()

    def kill(self):
        self._p.kill()

    def wait(self, timeout=None):
        self._p.join(timeout)
        if self._p.exitcode is None:
            raise subprocess.TimeoutExpired(cmd="<forked rank>",
                                            timeout=timeout)
        return self._p.exitcode


def _spawn_rank(argv: list[str], env: dict, outfile):
    """One local rank: forked from the jax-warm server when eligible
    (CPU-pinned, ``python -m`` form), else a plain subprocess."""
    spawn_mode = (env.get("MINIPS_SPAWN")
                  or os.environ.get("MINIPS_SPAWN", "fork"))
    if (spawn_mode != "subprocess"
            and env.get("MINIPS_FORCE_CPU")
            and len(argv) >= 3 and argv[0] == sys.executable
            and argv[1] == "-m"):
        p = _fork_ctx().Process(
            target=_fork_child_main, args=(argv, env, outfile.name))
        p.start()
        return _ForkProc(p)
    return subprocess.Popen(argv, env=env, stdout=outfile,
                            stderr=subprocess.STDOUT)


def run_local_job(n: int, argv: list[str], *,
                  base_port: Optional[int] = None,
                  env_extra: Optional[dict] = None,
                  env_per_rank: Optional[dict] = None,
                  timeout: float = 240.0) -> list[dict]:
    """Spawn ``n`` local ranks of ``argv`` over loopback, wait, and harvest
    the last JSON line each rank printed (the smoke/bench protocol: every
    worker prints one result dict). Raises with the worker's captured
    output if a rank produced no JSON or the job failed — shared by
    tests/test_distributed_smoke.py and bench_ssp.py so the spawn/harvest
    protocol lives in one place. ``base_port=None`` (the default) asks
    the OS for a free block via :func:`find_free_base_port`.
    ``env_per_rank`` maps rank -> extra env for THAT rank only — the
    elastic-membership drills aim per-rank knobs (a joiner's standby
    config, a drain trigger) without giving every rank the flag."""
    import json
    import tempfile

    if base_port is None:
        base_port = find_free_base_port(n)
    _sweep_shm()
    hosts = ["localhost"] * n
    outs = [tempfile.NamedTemporaryFile("w+", delete=False) for _ in hosts]
    procs = []
    for rank in range(n):
        env = child_env(rank, hosts, base_port)
        if env_extra:
            env.update(env_extra)
        if env_per_rank and rank in env_per_rank:
            env.update(env_per_rank[rank])
        procs.append(_spawn_rank(argv, env, outs[rank]))
    rc = wait(procs, timeout=timeout)
    # read EVERY rank's output before judging any single one: the rank
    # that violates the protocol is often an innocent victim (killed by
    # the launcher after the real culprit crashed), so error messages
    # always carry all ranks' tails, not just the first bad one's
    texts = []
    for f in outs:
        f.flush()
        f.seek(0)
        texts.append(f.read())
        f.close()
        os.unlink(f.name)
    raw = "\n".join(f"--- rank {r} output tail ---\n{t[-1200:]}"
                    for r, t in enumerate(texts))
    results = []
    for text in texts:
        lines = []
        last_brace_ok = True
        for ln in text.splitlines():
            if not ln.strip().startswith("{"):
                continue
            try:  # tolerate non-JSON log lines that start with '{'
                lines.append(json.loads(ln))
                last_brace_ok = True
            except json.JSONDecodeError:
                last_brace_ok = False
        if not lines:
            raise RuntimeError(
                f"worker produced no JSON output (rc={rc}):\n{raw}")
        if not last_brace_ok:
            # the FINAL brace line is the result-dict protocol slot; if
            # it is malformed, surfacing an earlier metrics line as the
            # "result" would silently corrupt the harvest
            raise RuntimeError(
                f"worker's final brace line is not JSON (rc={rc}):\n{raw}")
        results.append(lines[-1])
    if rc != 0:
        # a rank can print its done line and STILL exit nonzero (teardown
        # failure); the parsed results alone would hide the traceback
        raise RuntimeError(f"job failed rc={rc}: {results}\n{raw}")
    return results


def run_local_job_raw(n: int, argv: list[str], *,
                      base_port: Optional[int] = None,
                      env_extra: Optional[dict] = None,
                      env_per_rank: Optional[dict] = None,
                      timeout: float = 240.0,
                      kill_on_failure: bool = False):
    """Spawn ``n`` local ranks and harvest ALL JSON lines per rank,
    tolerating failures — the fault-drill twin of :func:`run_local_job`
    (which asserts success and returns only result lines). Returns
    ``(rc, events)`` with ``events[rank]`` the rank's parsed JSON lines.
    ``kill_on_failure=False`` by default: kill drills need survivors to
    detect a death THEMSELVES, not be mercy-killed by the launcher.
    ``base_port=None`` auto-picks a free block (find_free_base_port);
    ``env_per_rank`` aims per-rank drill knobs like run_local_job's."""
    import json
    import tempfile

    if base_port is None:
        base_port = find_free_base_port(n)
    _sweep_shm()
    hosts = ["localhost"] * n
    outs = [tempfile.NamedTemporaryFile("w+", delete=False) for _ in hosts]
    procs = []
    for rank in range(n):
        env = child_env(rank, hosts, base_port)
        if env_extra:
            env.update(env_extra)
        if env_per_rank and rank in env_per_rank:
            env.update(env_per_rank[rank])
        procs.append(_spawn_rank(argv, env, outs[rank]))
    rc = wait(procs, timeout=timeout, kill_on_failure=kill_on_failure)
    events = []
    for f in outs:
        f.flush()
        f.seek(0)
        text = f.read()
        f.close()
        os.unlink(f.name)
        rank_events = []
        for ln in text.splitlines():
            if ln.strip().startswith("{"):
                try:
                    rank_events.append(json.loads(ln))
                except json.JSONDecodeError:
                    pass  # log lines that merely start with a brace
        events.append(rank_events)
    return rc, events


def init_from_env():
    """Worker-side: build my ControlBus from the launcher's env vars.
    Returns ``(proc_id, num_procs, bus)``; bus is None single-process.
    Backend honors ``$MINIPS_BUS`` (zmq | native C++ mailbox | shm
    same-host rings); head codec honors ``$MINIPS_WIRE_FMT``."""
    from minips_tpu.comm.bus import make_bus

    rank = int(os.environ.get("MINIPS_PROC_ID", "0"))
    n = int(os.environ.get("MINIPS_NUM_PROCS", "1"))
    addrs = [a for a in os.environ.get("MINIPS_BUS_ADDRS", "").split(",") if a]
    if n <= 1 or not addrs:
        return rank, 1, None
    peers = [a for i, a in enumerate(addrs) if i != rank]
    # bind on all interfaces at my advertised port; peers connect by name
    port = addrs[rank].rsplit(":", 1)[1]
    bus = make_bus(f"tcp://*:{port}", peers, my_id=rank).start()
    return rank, n, bus


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="spawn one worker process per hostfile line")
    ap.add_argument("--hostfile", help="one host per line")
    ap.add_argument("--n", type=int, default=0,
                    help="shortcut: n local processes (no hostfile)")
    ap.add_argument("--base-port", type=int, default=5700)
    ap.add_argument("--timeout", type=float, default=None)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- program args...")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no worker command given (use: -- python worker.py ...)")
    if args.hostfile:
        hosts = read_hostfile(args.hostfile)
    elif args.n > 0:
        hosts = ["localhost"] * args.n
    else:
        ap.error("need --hostfile or --n")
    procs = spawn(hosts, cmd, base_port=args.base_port)
    return wait(procs, timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
