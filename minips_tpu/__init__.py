"""minips_tpu — a TPU-native parameter-server training framework.

A ground-up rebuild of the capabilities of the C++ parameter server
``Distributed-Deep-Learning/MiniPs`` (see SURVEY.md; the reference mount was
empty this round — SURVEY.md §0 — so reference citations point at the survey's
component inventory rather than file:line), designed TPU-first:

- Worker compute is ``jax.jit``'d on TPU instead of Eigen/CUDA worker math
  (SURVEY.md §2 "Worker compute").
- The ``KVClientTable`` push/pull API (SURVEY.md §2 "KVClientTable") is kept
  as the user-facing surface, but ``pull`` compiles to an all-gather and
  ``push`` to a reduce-scatter + owner-shard optimizer update over the
  device mesh — XLA collectives over ICI/DCN replace the ZeroMQ Mailbox
  (SURVEY.md §2.3).
- Server-side KVTable + SGD/Adagrad updaters (SURVEY.md §2 "KVTable
  storage", "Updaters") live as pjit-sharded optimizer state.
- The BSP/SSP/ASP consistency controller (SURVEY.md §2 "BSPModel/SSPModel/
  ASPModel") gates collective sync steps instead of parking socket RPCs.
"""

__version__ = "0.1.0"

from minips_tpu.core.config import Config, TableConfig, TrainConfig  # noqa: F401
from minips_tpu.core.engine import Engine, Info, MLTask  # noqa: F401
from minips_tpu.consistency import ASP, BSP, SSP, make_controller  # noqa: F401
from minips_tpu.parallel.mesh import make_mesh  # noqa: F401
from minips_tpu.tables.dense import DenseTable, cast_floating  # noqa: F401
from minips_tpu.tables.sparse import SparseTable  # noqa: F401
from minips_tpu.train.loop import TrainLoop  # noqa: F401
from minips_tpu.train.ps_step import PSTrainStep  # noqa: F401
from minips_tpu.utils.evaluation import (StreamingAUC,  # noqa: F401
                                         auc_exact, evaluate_auc)
from minips_tpu.utils.metrics import MetricsLogger  # noqa: F401
from minips_tpu.comm import cluster  # noqa: F401  (multi-host bootstrap)
from minips_tpu.train.sharded_ps import (ShardedPSTrainer,  # noqa: F401
                                         ShardedTable, table_state_bytes)
from minips_tpu.train.ssp_spmd import CollectiveSSP  # noqa: F401
