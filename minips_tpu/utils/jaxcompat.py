"""Version shims for jax APIs that moved between releases.

The codebase targets the current jax surface (``jax.shard_map``,
``jax.typeof``); older releases (< 0.5) ship the same functionality
under ``jax.experimental.shard_map`` with the replication checker named
``check_rep`` instead of ``check_vma``, and avals without ``.vma``
(every caller already reads it with a ``getattr`` default). Routing the
handful of call sites through here makes the package run — and the
quarantined jax-version tests pass — on both surfaces without touching
the call-site semantics.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "typeof", "axis_size", "pcast", "sds"]

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)

if _NEW_SHARD_MAP is None:  # pre-0.5 jax: the experimental spelling
    from jax.experimental.shard_map import shard_map as _EXP_SHARD_MAP

    # checkpoint_name under shard_map: the old check_rep tracer has no
    # replication rule for the `name` primitive and raises
    # NotImplementedError ("No replication rule for name") the moment a
    # remat-annotated model runs sharded. `name` is an identity marker
    # — it neither mixes nor splits axes — so the STANDARD rules
    # (replication preserved elementwise) are exactly its semantics;
    # the newer vma tracer ships them built in. setdefault-registered:
    # a jax that grows its own rule wins.
    try:
        from jax._src.ad_checkpoint import name_p as _NAME_P
        from jax.experimental import shard_map as _SM_MOD

        _SM_MOD.register_standard_check(_NAME_P)
        _SM_MOD.register_standard_rewrite(_NAME_P)
    except (ImportError, AttributeError):  # surface moved: the tests
        pass                               # stay quarantined, loudly


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the keyword surface both lineages accept.
    ``check_vma`` maps to the old ``check_rep`` (same meaning: disable
    the replication/varying-axis checker when a collective pattern is
    sound but uninferable)."""
    if _NEW_SHARD_MAP is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _EXP_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)


def axis_size(axis_name):
    """``jax.lax.axis_size`` where it exists (0.6+); the classic
    ``psum(1, axis)`` spelling otherwise — jax constant-folds a psum of
    a Python literal over a named axis to the static axis size, so both
    return a value usable as a shape dimension inside shard_map."""
    sz = getattr(jax.lax, "axis_size", None)
    if sz is not None:
        return sz(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, *, to="varying"):
    """``jax.lax.pcast`` where it exists (the varying-manual-axes
    surface); ``jax.lax.pvary`` on releases that grew the varying cast
    under that name; IDENTITY on pre-vma jax. The identity fallback is
    semantically exact, not a approximation: the cast exists only to
    satisfy the newer tracer's varying-axis type discipline (scan/
    fori_loop carries must enter with their post-fold type) — the old
    ``check_rep`` tracer has no varying-axis type to cast, so there is
    nothing to do. Only ``to="varying"`` is routed here (the one
    direction this codebase uses); an invariant-cast caller should go
    through ``jax.lax.pcast`` directly and quarantine, because dropping
    THAT direction silently would change psum semantics."""
    if to != "varying":
        raise ValueError(
            "jaxcompat.pcast shims only to='varying' — see docstring")
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axis_name, to=to)
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, axis_name)
    return x


def typeof(x):
    """``jax.typeof`` where it exists; the aval otherwise. Callers only
    probe optional attributes (``getattr(typeof(x), "vma", ...)``), so
    the old surface's plain aval is a faithful stand-in."""
    t = getattr(jax, "typeof", None)
    if t is not None:
        return t(x)
    return jax.core.get_aval(x)


try:  # does this jax's ShapeDtypeStruct speak the vma kwarg?
    jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    _SDS_HAS_VMA = True
except TypeError:
    _SDS_HAS_VMA = False


def sds(shape, dtype, *, vma=None):
    """``jax.ShapeDtypeStruct`` with the optional varying-manual-axes
    annotation, on both lineages. Newer jax's pallas_call under the
    vma tracer needs out_shapes stamped with the inputs' varying axes
    (``vma=``); pre-vma jax (< the varying-axis type discipline) has
    no such kwarg AND no such type to annotate — dropping it there is
    semantically exact, the same identity argument as :func:`pcast`
    (the old check_rep tracer carries no varying-axis types, so there
    is nothing the annotation could change)."""
    if _SDS_HAS_VMA and vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
