"""SSPTrainer — multi-process BSP/SSP/ASP over the control bus.

This is the rebuild of the reference's *distributed* consistency mechanics
(SURVEY.md §7.4.1): one process per node, each driving its own jitted
shard-local step on its chip, with the parameter-server semantics carried by
two host-side channels instead of server threads:

- **push** ≡ publish my parameter *delta* (packed float32 blob) to all
  peers; every process applies every peer's deltas into its local replica —
  a replicated PS where "server state" is the merged replica, exactly the
  additive semantics of ``updater->Update`` on a shared KVTable
  (SURVEY.md §3.3). Additive updates commute, so all replicas converge to
  the same state once all deltas land (float-addition reorder noise aside).
- **clock gossip + gate** ≡ ``Clock()`` + the BSP/SSP/ASP admission rule:
  before starting step ``c+1`` a process waits until
  ``global_min_clock >= c + 1 - staleness`` (staleness 0 = BSP lockstep,
  s = SSP bounded staleness, ∞ = ASP never waits) — the same unified rule
  as minips_tpu/consistency/controllers.py, enforced across *processes*.

zmq PUB/SUB preserves per-publisher frame order, and a process publishes its
step-``c`` delta *before* its clock-``c`` gossip on the same socket — so
once the gate observes a peer at clock ``c``, that peer's deltas through
step ``c`` have already been received and will be merged at the next drain.
That ordering is what makes staleness the *only* inconsistency: an admitted
step at clock ``c`` has seen every peer update up to ``c - skew`` with
skew ≤ staleness, the SSP contract.

Scope: this host-relay path is the honest multi-process story for
PS-style bounded-staleness across hosts (the reference's distinctive
capability — its deltas rode ZeroMQ TCP too, SURVEY.md §2.3). Synchronous
data-parallel throughput on a pod should instead use the fused SPMD path
(PSTrainStep / DenseTable.make_step), where pushes compile to
reduce-scatter over ICI; see docs/consistency.md for when each applies.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.flatten_util import ravel_pytree

from minips_tpu.comm.bus import ClockGossip, ControlBus
from minips_tpu.consistency.gate import PeerFailureError, StalenessGate

__all__ = ["SSPTrainer", "PeerFailureError"]  # PeerFailureError re-exported

PyTree = Any


class SSPTrainer:
    """Drives ``step_fn`` locally; exchanges deltas + clocks with peers.

    Parameters
    ----------
    step_fn: jitted ``(params, batch) -> (new_params, loss)``.
    params: initial parameter pytree (identical on every process).
    bus / num_processes: the loopback/TCP control bus and peer count.
    staleness: 0 = BSP, s = SSP, ``float('inf')`` = ASP.
    push_every: publish accumulated local deltas every k steps (k=1 matches
        the reference's per-iteration Push; larger k trades freshness for
        bandwidth, the SparCML-style batching knob).
    compress: fraction of delta entries shipped per push (1.0 = dense).
        Below 1.0, each push sends only the top-``compress``-fraction of
        entries by magnitude (int32 indices + f32 values) and keeps the
        unsent mass as a residual folded into the next push — top-k
        sparsification with error feedback (SparCML lineage, PAPERS.md).
        No gradient is ever dropped, only delayed; ``finalize`` flushes
        the full residual dense so replicas still converge exactly.
    monitor: optional HeartbeatMonitor; on gate timeout its dead set turns a
        hang into a PeerFailureError and excludes corpses from the gate.
    """

    def __init__(
        self,
        step_fn: Callable[[PyTree, Any], tuple[PyTree, Any]],
        params: PyTree,
        bus: ControlBus,
        num_processes: int,
        *,
        staleness: float = 0,
        push_every: int = 1,
        gate_timeout: float = 60.0,
        monitor=None,
        compress: float = 1.0,
    ):
        if not 0.0 < compress <= 1.0:
            raise ValueError("compress must be in (0, 1]")
        self.step_fn = step_fn
        self.bus = bus
        self.num_processes = num_processes
        self.staleness = staleness
        self.push_every = max(int(push_every), 1)
        self.monitor = monitor
        self.compress = compress
        self.bytes_pushed = 0    # wire accounting (the compression payoff)

        flat, self._unravel = ravel_pytree(params)
        self._params = params
        self._nparam = flat.shape[0]
        self._dtype = flat.dtype
        self._pending_push = np.zeros(self._nparam, np.float32)
        self._inbox: deque[np.ndarray] = deque()
        self._inbox_lock = threading.Lock()
        self.clock = 0
        self.deltas_applied = 0

        self.gossip = ClockGossip(bus, num_processes, workers_per_process=1)
        self._gate_obj = StalenessGate(self.gossip, staleness,
                                       timeout=gate_timeout, monitor=monitor)
        self._flushed: set[int] = set()
        self._flush_cond = threading.Condition()
        bus.on("delta", self._on_delta)
        bus.on("flush", self._on_flush)

    # ------------------------------------------------------------- messaging
    def _on_delta(self, sender: int, payload: dict) -> None:
        if sender == self.bus.my_id:
            return  # own PUB loops back only if self-subscribed; be safe
        blob = payload.get("__blob__")
        if blob is None:
            return
        if payload.get("fmt") == "topk":
            # blob = [k int32 indices][k f32 values]
            k = int(payload.get("k", 0))
            if len(blob) != k * 8 or k > self._nparam:
                return  # malformed / stale peer; drop
            idx = np.frombuffer(blob[: 4 * k], np.int32)
            if k and (idx.min() < 0 or idx.max() >= self._nparam):
                return
            vals = np.frombuffer(blob[4 * k:], np.float32)
            vec = np.zeros(self._nparam, np.float32)
            vec[idx] = vals
        else:
            vec = np.frombuffer(blob, np.float32)
            if vec.shape[0] != self._nparam:
                return  # shape mismatch: stale peer from an old run; drop
        with self._inbox_lock:
            self._inbox.append(vec)

    def _on_flush(self, sender: int, payload: dict) -> None:
        with self._flush_cond:
            self._flushed.add(sender)
            self._flush_cond.notify_all()

    def _drain_inbox(self) -> None:
        with self._inbox_lock:
            pending = list(self._inbox)
            self._inbox.clear()
        if not pending:
            return
        total = np.sum(pending, axis=0) if len(pending) > 1 else pending[0]
        flat, _ = ravel_pytree(self._params)
        self._params = self._unravel(
            flat + jax.numpy.asarray(total, dtype=self._dtype))
        self.deltas_applied += len(pending)

    def _push(self, force: bool = False) -> None:
        if not force and self.clock % self.push_every != 0:
            return
        if not np.any(self._pending_push):
            return
        vec = self._pending_push.astype(np.float32)
        if self.compress < 1.0 and not force:
            # top-k by magnitude; the unsent tail STAYS in _pending_push
            # (error feedback) and rides a later push
            k = max(1, int(self.compress * self._nparam))
            idx = np.argpartition(np.abs(vec), -k)[-k:].astype(np.int32)
            vals = vec[idx]
            blob = idx.tobytes() + vals.tobytes()
            self.bus.publish("delta", {"clock": self.clock, "fmt": "topk",
                                       "k": int(k)}, blob=blob)
            self.bytes_pushed += len(blob)
            self._pending_push[idx] = 0.0   # residual keeps the rest
            return
        # dense: force-pushes (finalize) always take this path so the
        # full residual lands and replicas converge exactly
        blob = vec.tobytes()
        self.bus.publish("delta", {"clock": self.clock}, blob=blob)
        self.bytes_pushed += len(blob)
        self._pending_push = np.zeros(self._nparam, np.float32)

    # ------------------------------------------------------------------ gate
    def _gate(self) -> None:
        """Block until global_min >= my_clock - staleness (SSP rule) —
        shared StalenessGate (consistency/gate.py)."""
        self._gate_obj.wait(self.clock)

    @property
    def gate_waits(self) -> int:
        return self._gate_obj.gate_waits

    @property
    def max_skew_seen(self) -> int:
        return self._gate_obj.max_skew_seen

    # ------------------------------------------------------------------ step
    def step(self, batch) -> float:
        """One local step: merge peer pushes, compute, push, clock, gate."""
        self._drain_inbox()
        before, _ = ravel_pytree(self._params)
        new_params, loss = self.step_fn(self._params, batch)
        after, _ = ravel_pytree(new_params)
        self._pending_push += np.asarray(after - before, np.float32)
        self._params = new_params
        self.clock += 1
        self._push()
        self.gossip.publish_local([self.clock])
        self._gate()
        return float(loss)

    # -------------------------------------------------------------- lifecycle
    def retire(self) -> None:
        """Announce this worker is out of data: publish the shared sentinel
        clock (consistency/gate.py RETIRED_CLOCK) so peers' SSP gates never
        wait on a finished worker — dynamic block assignment makes
        per-worker step counts unequal. Call before finalize()."""
        from minips_tpu.consistency.gate import publish_clock

        self._retired = True
        publish_clock(self.gossip, self.clock, True)

    def _publish_clock(self) -> None:
        from minips_tpu.consistency.gate import publish_clock

        publish_clock(self.gossip, self.clock,
                      getattr(self, "_retired", False))

    def finalize(self, timeout: float = 30.0) -> PyTree:
        """Flush my remaining delta, wait for all live peers to reach my
        clock, merge their tail — after this every live replica holds the
        same merged parameters (up to float reorder noise)."""
        self._push(force=True)
        # "flush" is published AFTER the forced dense push on the same
        # socket, so per-publisher frame ordering guarantees that once we
        # have heard flush from a peer, every delta it ever sent —
        # including the compressed path's final residual — is already in
        # our inbox (clock gossip alone cannot promise that: a peer's last
        # clock precedes its finalize-time residual).
        self.bus.publish("flush", {"clock": self.clock})
        self._publish_clock()
        deadline = time.monotonic() + timeout
        peers = set(range(self.num_processes)) - {self.bus.my_id}
        while True:
            with self._flush_cond:
                live = peers - self.gossip.excluded
                if live <= self._flushed:
                    break
                self._flush_cond.wait(timeout=0.5)
            dead = self.monitor.check() if self.monitor is not None else set()
            for p in dead:
                self.gossip.exclude(p)
            if time.monotonic() > deadline:
                with self._flush_cond:
                    missing = sorted(peers - self._flushed
                                     - self.gossip.excluded)
                if not dead:
                    raise TimeoutError(
                        f"finalize: peers {missing} never flushed")
        self._drain_inbox()
        return self._params

    @property
    def params(self) -> PyTree:
        return self._params

    @property
    def skew(self) -> int:
        return self.gossip.skew

    # ------------------------------------------------------------ checkpoint
    # state_dict/load_state_dict make the trainer a "table" to
    # ckpt.Checkpointer — PS state = params + clock (SURVEY.md §5.4).
    def state_dict(self) -> dict:
        flat, _ = ravel_pytree(self._params)
        return {"flat": np.asarray(flat), "clock": np.asarray(self.clock)}

    def load_state_dict(self, state: dict) -> None:
        self._params = self._unravel(
            jax.numpy.asarray(state["flat"], dtype=self._dtype))
        self.clock = int(state["clock"])
        self._pending_push = np.zeros(self._nparam, np.float32)
        with self._inbox_lock:
            self._inbox.clear()
        # through the chokepoint: a restore on a retired trainer must not
        # clobber the sentinel and re-gate peers on a worker that will
        # never step again (gate.py RETIRED_CLOCK stickiness)
        self._publish_clock()
