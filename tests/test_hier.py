"""Hierarchical topology-aware push aggregation (balance/hier.py +
train/sharded_ps.py psH lane) — PR16 acceptance:

- knob grammar: parse-or-refuse-loudly + the shared seeded fuzzer
  convention (MINIPS_HEDGE/MINIPS_SLOW, PR15);
- topology/election units: host_of, group_ranks, elect;
- stamp folding: an aggregated frame's stamp is the MIN over its
  contributors' clocks, and owner-side admission with hier floors is
  identical to the worst contributor pushing alone;
- the 3-rank BSP lockstep drills: group=2 with compression off is
  BITWISE equal to the flat wire (HIER-WIN's bitwise leg), group=1
  (armed-idle) and agg=0 (accounting-only) are bitwise equal too
  (HIER-IDLE), with the per-level byte counters as engagement
  evidence;
- the slow tier: seeded SIGKILL of a LEADER mid-run — survivors
  complete bitwise with zero lost frames, and the flight boxes carry
  ``hier_leader_elect``/``hier_fallback``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from minips_tpu.balance.hier import (HierConfig, elect, group_ranks,
                                     host_of, maybe_config)
from minips_tpu.consistency.gate import RETIRED_CLOCK, admits
from minips_tpu.train.sharded_ps import ShardedTable

# ------------------------------------------------------------- grammar


def test_hier_config_parses_and_refuses():
    c = HierConfig.parse("group=2,retain=8,agg=1")
    assert (c.group, c.retain, c.agg) == (2, 8, 1)
    d = HierConfig.parse("1")
    assert (d.group, d.retain, d.agg) == (1, 64, 1)
    assert HierConfig.parse("") is None
    assert HierConfig.parse("0") is None
    assert HierConfig.parse("group=2,agg=0").agg == 0
    for bad, frag in {"explode=1": "unknown knob",
                      "group": "k=v",
                      "group=abc": "bad value",
                      "group=0": "group",
                      "retain=0": "retain",
                      "agg=2": "agg",
                      "agg=0.5": "bad value"}.items():
        with pytest.raises(ValueError, match=frag):
            HierConfig.parse(bad)


def test_hier_group_local_follows_the_launcher(monkeypatch):
    monkeypatch.delenv("MINIPS_LOCAL_PROCS", raising=False)
    # outside a launcher 'local' degrades to 1: armed-idle, never a
    # wrong tree
    assert HierConfig.parse("group=local").group == 1
    monkeypatch.setenv("MINIPS_LOCAL_PROCS", "4")
    assert HierConfig.parse("group=local").group == 4
    monkeypatch.setenv("MINIPS_HIER", "group=local,retain=7")
    c = maybe_config(None)
    assert (c.group, c.retain) == (4, 7)
    # explicit spec wins over the env
    assert maybe_config("0") is None


def test_hier_knob_fuzzer_parse_or_refuse_loudly():
    """The shared MINIPS_* spec-hygiene fuzzer (PR15 convention):
    seeded random specs from the alphabet parse or raise ValueError,
    deterministically — never a half-configured tree."""
    rng = np.random.default_rng(20260804)
    vocab = ["group", "retain", "agg", "bogus"]
    vals = ["0", "1", "3", "2.5", "-1", "abc", "", "1e9", "0.5",
            "local"]
    for _ in range(200):
        n = int(rng.integers(0, 5))
        spec = ",".join(
            f"{vocab[rng.integers(0, len(vocab))]}"
            f"={vals[rng.integers(0, len(vals))]}"
            for _ in range(n))
        outcomes = []
        for _rep in range(2):
            try:
                c = HierConfig.parse(spec)
                outcomes.append(("ok", c is None))
            except ValueError as e:
                outcomes.append(("refused", str(e)))
            except Exception as e:  # noqa: BLE001 - the contract
                pytest.fail(f"hier spec {spec!r} raised "
                            f"{type(e).__name__}: {e}")
        assert outcomes[0] == outcomes[1], spec


# ------------------------------------------------------ topology units


def test_host_of_and_group_ranks_contiguous():
    assert [host_of(r, 2) for r in range(5)] == [0, 0, 1, 1, 2]
    assert group_ranks(0, 2, 3) == [0, 1]
    assert group_ranks(1, 2, 3) == [0, 1]
    assert group_ranks(2, 2, 3) == [2]       # the tail singleton
    assert group_ranks(5, 4, 6) == [4, 5]
    assert group_ranks(0, 1, 3) == [0]       # group=1: every group


def test_elect_lowest_live_rank():
    assert elect([0, 1]) == 0
    assert elect([0, 1], excluded=[0]) == 1
    assert elect([0, 1], excluded=[0, 1]) is None
    assert elect([3, 2, 5], excluded=[2]) == 3  # deterministic order


# --------------------------------------------------------- in-proc rig


class _LockstepCons:
    """Shared lockstep clock vector (the run_bsp_lockstep stub,
    tests/test_chaos_reliable.py) widened to 3 ranks."""

    clocks = [0, 0, 0]
    staleness = 0

    def __init__(self, rank):
        self.rank = rank

    @property
    def clock(self):
        return self.clocks[self.rank]

    def admit_pull(self, clk):
        return min(self.clocks) >= clk

    def serving_clock(self, requester):
        return min(self.clocks)


def _mk_tables(buses, name, hier_spec=""):
    _LockstepCons.clocks = [0, 0, 0]
    tables = [ShardedTable(name, 96, 2, buses[i], i, 3, updater="sgd",
                           lr=0.5, pull_timeout=20.0)
              for i in range(3)]
    for i, t in enumerate(tables):
        t.bind_consistency(_LockstepCons(i))
        if hier_spec:
            t.attach_hier(HierConfig.parse(hier_spec))
        t._w[...] = np.arange(32 * 2, dtype=np.float32
                              ).reshape(32, 2) / 7.0
    return tables


# ------------------------------------------------------- stamp folding


def test_aggregate_stamp_is_min_over_contributors():
    """The flush's psP head carries hmin = min over the bucketed
    contributions' clocks, and its hfr/hfv floor claims carry exactly
    the group boundary floors that released the flush."""
    from tests.conftest import mk_loopback_buses

    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "st", "group=2")
        t0 = tables[0]                       # leader of group {0, 1}
        sent = []
        real_send = t0.bus.send

        def spy(dest, kind, head, blob=b"", **kw):
            if kind.startswith("psP:"):
                sent.append((dest, dict(head)))
            return real_send(dest, kind, head, blob=blob, **kw)

        t0.bus.send = spy
        _LockstepCons.clocks = [5, 3, 5]
        k0 = np.array([65, 70], np.int64)
        g0 = np.ones((2, 2), np.float32)
        t0._hier_contribute(0, 2, k0, g0)    # my own slice, clk 5
        # the member's contribution arrives on the psH lane at clk 3
        k1 = np.array([72, 80], np.int64)
        g1 = np.full((2, 2), 2.0, np.float32)
        blob = k1.tobytes() + g1.tobytes()
        t0._on_hier(1, {"op": "c", "o": 2, "n": 2, "clk": 3,
                        "__blob__": blob, **t0._cfg_header()})
        # both boundaries land -> group min advances -> flush
        t0._on_hier(1, {"op": "b", "f": 9})
        t0.hier_boundary()                   # own floor = clk + 1 = 6
        aggs = [h for _, h in sent if "hmin" in h]
        assert len(aggs) == 1, sent
        head = aggs[0]
        assert head["hmin"] == 3             # min(5, 3)
        floors = dict(zip(head["hfr"], head["hfv"]))
        assert floors == {0: 6, 1: 9}
        assert t0.hier_counters["agg_frames"] == 1
        assert t0.hier_counters["agg_rows"] == 4
    finally:
        for b in buses:
            b.close()


def test_owner_admission_equals_worst_contributor_alone():
    """Owner-side ``_admit_clk`` with hier floors is the shared
    ``gate.admits`` predicate evaluated at min(floors): a fleet of
    contributors admits exactly like the WORST one pushing alone, and
    a retired contributor stops gating."""
    from tests.conftest import mk_loopback_buses

    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "ad", "group=2")
        t2 = tables[2]                       # owner across the group
        assert t2._hier_floor == {0: 0, 1: 0}
        _LockstepCons.clocks = [50, 50, 50]  # gossip never the binder
        t2._on_hier(0, {"op": "f", "hfr": [0, 1], "hfv": [6, 9]})
        assert t2._hier_floor_min() == 6
        for clk in range(0, 12):
            assert t2._admit_clk(clk) == admits(6, clk, 0)
        # worst-alone: a floor dict holding ONLY the worst contributor
        # admits identically
        t2._hier_floor = {0: 6}
        for clk in range(0, 12):
            assert t2._admit_clk(clk) == admits(6, clk, 0)
        # max-merge: a zombie's stale (lower) claim cannot roll back
        t2._hier_floor = {0: 6, 1: 9}
        t2._on_hier(0, {"op": "f", "hfr": [0, 1], "hfv": [2, 2]})
        assert t2._hier_floor == {0: 6, 1: 9}
        # the member's own waiver is the only lowering path — and a
        # RETIRED contributor stops gating entirely
        t2._on_hier(0, {"op": "r"})
        t2._on_hier(1, {"op": "r"})
        assert t2._hier_floor_min() == RETIRED_CLOCK
        # floors no longer bind — only the gossip bound remains
        assert t2._admit_clk(50)
        assert not t2._admit_clk(51)
    finally:
        for b in buses:
            b.close()


# -------------------------------------------------- lockstep bitwise


def run_hier_lockstep(hier_spec: str, stats: "dict | None" = None):
    """3-rank in-proc BSP lockstep (the run_bsp_lockstep harness shape,
    tests/test_chaos_reliable.py) with host groups {0,1} and {2}:
    ranks 0 and 1 push DISJOINT key sets into rank 2's shard (the
    cross-group tree lane; rank 0 leads, rank 1 contributes over psH),
    rank 2 pushes flat into shards 0 and 1 (singleton group). Every
    shard's rows are touched by exactly one pusher, so apply order
    commutes bitwise — identical streams must produce identical state
    whatever lane carried them. Returns (final weights per rank,
    frames_lost per rank)."""
    from tests.conftest import mk_loopback_buses

    buses = mk_loopback_buses(3)
    keysets = [np.array([65, 70, 65, 79]),   # rank0 -> owner2 rows
               np.array([72, 80, 72, 88]),   # rank1 -> owner2, disjoint
               np.array([1, 40, 1, 50])]     # rank2 -> owners 0 and 1
    try:
        tables = _mk_tables(buses, "t", hier_spec)
        for _ in range(4):
            rows = [tables[r].pull(keysets[r]) for r in range(3)]
            for r in range(3):
                tables[r].push(keysets[r], 0.1 * rows[r] + 1.0)
            for r in range(3):   # read-your-own-writes, same step
                tables[r].pull(keysets[r])
            for r in range(3):   # the trainer-tick boundary slot
                tables[r].hier_boundary()
            for r in range(3):
                _LockstepCons.clocks[r] += 1
        # quiesce the tree exactly like trainer finalize: member and
        # leader rendezvous, so run concurrently
        ths = [threading.Thread(target=tables[r].hier_finalize,
                                args=(15.0,)) for r in range(3)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=30.0)
        assert not any(th.is_alive() for th in ths), "finalize wedged"
        if hier_spec and HierConfig.parse(hier_spec).group > 1 \
                and HierConfig.parse(hier_spec).agg:
            # settle: the owner's floors hit RETIRED only AFTER the
            # last aggregated frame applied (same handler, in order)
            deadline = time.monotonic() + 10.0
            while tables[2]._hier_floor_min() != RETIRED_CLOCK:
                assert time.monotonic() < deadline, \
                    tables[2]._hier_floor
                time.sleep(0.005)
        if stats is not None:
            for key in ("l1_tx_bytes", "l2_tx_bytes", "l1_frames",
                        "l2_frames", "agg_frames", "contribs",
                        "mesh_reduces", "mesh_agg_fallbacks",
                        "domain_demotions"):
                stats[key] = sum(t.hier_counters[key] for t in tables)
        lost = [b.frames_lost for b in buses]
        return [t._w.copy() for t in tables], lost
    finally:
        for b in buses:
            b.close()


@pytest.fixture(scope="module")
def flat_lockstep():
    return run_hier_lockstep("")


def test_hier_group2_exact_wire_is_bitwise_equal_to_flat(
        flat_lockstep):
    """THE tentpole bitwise pin (HIER-WIN's exactness leg): BSP with
    compression off through the two-level tree — member contributions
    summed at the leader, one aggregate per owner — lands bitwise the
    flat wire's state, with the tree demonstrably engaged."""
    flat, lost_flat = flat_lockstep
    stats: dict = {}
    hier, lost_hier = run_hier_lockstep("group=2", stats=stats)
    assert lost_flat == [0, 0, 0] and lost_hier == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], hier[r])
    # engagement evidence: the member->leader lane and the leader leg
    # both carried frames
    assert stats["contribs"] > 0
    assert stats["agg_frames"] > 0
    assert stats["l1_tx_bytes"] > 0 and stats["l2_tx_bytes"] > 0


def test_hier_armed_idle_is_bitwise_equal_to_off(flat_lockstep):
    """HIER-IDLE: group=1 arms the layer but leaves every pair flat —
    bitwise equal to off AND zero per-level counters (the
    zeros-when-idle wire_record contract)."""
    flat, _ = flat_lockstep
    stats: dict = {}
    idle, lost = run_hier_lockstep("1", stats=stats)
    assert lost == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], idle[r])
    assert all(v == 0 for v in stats.values()), stats


def test_hier_accounting_only_arm_is_bitwise_with_counters(
        flat_lockstep):
    """The HIER-WIN flat arm (group=2,agg=0): pushes stay on the flat
    wire — bitwise equal to off — while the per-level classification
    still counts, so the bench can compare leader-leg bytes against a
    like-accounted baseline."""
    flat, _ = flat_lockstep
    stats: dict = {}
    acc, lost = run_hier_lockstep("group=2,agg=0", stats=stats)
    assert lost == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], acc[r])
    assert stats["agg_frames"] == 0 and stats["contribs"] == 0
    assert stats["l2_tx_bytes"] > 0   # flat cross-group sends, counted


def test_degenerate_tree_one_worker_per_host_is_flat(flat_lockstep):
    """A fleet with one worker per host group is the degenerate tree:
    no pair is ever in hier mode, no psH frame flows, state is bitwise
    the flat wire's (the satellite's one-worker-per-host clause —
    group=1 IS that topology under contiguous grouping)."""
    from tests.conftest import mk_loopback_buses

    flat, _ = flat_lockstep
    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "t", "group=1")
        for t in tables:
            # degenerate tree: every group is a singleton, nothing
            # registered, routing always flat
            assert t._hier_floor == {}
            assert t._hier_route(2) is None or t.rank == 2
    finally:
        for b in buses:
            b.close()
    idle, _ = run_hier_lockstep("group=1")
    for r in range(3):
        np.testing.assert_array_equal(flat[r], idle[r])


def test_hier_table_refusals_and_stats_shape():
    """attach_hier's validation ladder (async push window, row cache)
    and the hier_stats off-vs-armed shape."""
    from tests.conftest import mk_loopback_buses

    buses = mk_loopback_buses(3)
    try:
        t = ShardedTable("rf", 96, 2, buses[0], 0, 3, updater="sgd",
                         lr=0.5, async_push=True)
        with pytest.raises(ValueError, match="async_push"):
            t.attach_hier(HierConfig.parse("group=2"))
        t2 = ShardedTable("rf2", 96, 2, buses[1], 1, 3, updater="sgd",
                          lr=0.5, cache_bytes=1 << 16)
        with pytest.raises(ValueError, match="RowCache"):
            t2.attach_hier(HierConfig.parse("group=2"))
        t3 = ShardedTable("rf3", 96, 2, buses[2], 2, 3, updater="sgd",
                          lr=0.5)
        assert t3.hier_stats() is None       # off: None, not zeros
        t3.attach_hier(HierConfig.parse("group=2"))
        st = t3.hier_stats()
        assert st is not None
        assert st["l2_tx_bytes"] == 0 and st["agg_frames"] == 0
        assert st["leader"] == 2             # singleton: leads itself
        assert st["floor_min"] >= 0          # contributors registered
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------------ slow tier


@pytest.mark.slow
def test_leader_death_drill_survivors_bitwise_with_flight_events(
        tmp_path):
    """The leader-death drill: seeded SIGKILL of rank 0 — the leader
    of host group {0,1} — mid-aggregation. Rank 1 falls back to direct
    push (zero lost steps, zero unrecovered frames), re-elects itself,
    survivors finish all steps and agree BITWISE; the flight boxes
    carry ``hier_leader_elect`` and ``hier_fallback``."""
    import tempfile

    from minips_tpu import launch

    run_id = str(91_000_000 + os.getpid())
    flight_dir = os.path.join(tempfile.gettempdir(),
                              f"minips-flight-{run_id}")
    ck = str(tmp_path / "ck")
    rc, events = launch.run_local_job_raw(
        3, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_example",
            "--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", "30", "--batch", "64",
            "--checkpoint-dir", ck, "--checkpoint-every", "5"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                   "MINIPS_ELASTIC": "1",
                   "MINIPS_HIER": "group=2",
                   "MINIPS_CHAOS_KILL": "7:rank=0,step=12",
                   "MINIPS_HEARTBEAT": "interval=0.1,timeout=1.0",
                   "MINIPS_RUN_ID": run_id},
        timeout=240.0, kill_on_failure=False)
    dones = {r: ev[-1] for r, ev in enumerate(events)
             if ev and ev[-1].get("event") == "done"}
    assert set(dones) == {1, 2}, (rc, events)
    for d in dones.values():
        assert d["clock"] == 30
        assert d["max_skew_seen"] <= 3           # SSP bound held
        assert d["frames_dropped"] == 0          # zero poisons
        assert d["wire_frames_lost"] == 0        # zero unrecovered
        assert np.isfinite(d["loss_last"])
        assert d["hier"] is not None
        assert d["hier_spec"] == "group=2"
    # rank 1 fell back when its leader died, then led its own group
    h1 = dones[1]["hier"]
    assert h1["fallbacks"] >= 1
    assert h1["elections"] >= 1
    assert h1["leader"] == 1
    # survivors agree BITWISE on the final table
    sums = [d["param_sum"] for d in dones.values()]
    norms = [d["param_norm"] for d in dones.values()]
    assert sums[0] == sums[1] and norms[0] == norms[1], (sums, norms)
    # the post-mortem boxes carry the election and the fallback
    kinds: list[str] = []
    for r in (1, 2):
        path = os.path.join(flight_dir, f"flight-rank{r}.json")
        assert os.path.exists(path), os.listdir(flight_dir)
        doc = json.load(open(path))
        kinds += [e["kind"] for e in doc["events"]]
    assert "hier_leader_elect" in kinds, sorted(set(kinds))
    assert "hier_fallback" in kinds, sorted(set(kinds))
    fb = next(e for r in (1, 2)
              for e in json.load(open(os.path.join(
                  flight_dir, f"flight-rank{r}.json")))["events"]
              if e["kind"] == "hier_fallback")
    assert fb["args"]["leader"] == 0
    assert fb["args"]["why"] == "leader_dead"
