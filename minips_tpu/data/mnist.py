"""MNIST idx-format reader — the real-file path for the MLP workload.

The reference's MLP example trains on actual MNIST (BASELINE.json:8); the
dataset ships as the classic idx files (`train-images-idx3-ubyte`,
`train-labels-idx1-ubyte`, optionally .gz). This is the standard big-endian
idx codec: magic ``0x00 0x00 <dtype> <ndim>`` then ndim big-endian uint32
dims, then row-major payload. Pixels normalize to [0, 1] float32 and
flatten to [N, 784], matching minips_tpu.models.mlp's input contract and
the synthetic `mnist_like` batch shape.
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
    0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64,
}


def _open(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Decode one idx file (optionally gzipped) into an ndarray. Raises
    ValueError (with the path) on any malformed/truncated input."""
    with _open(path) as f:
        head = f.read(4)
        if len(head) != 4:
            raise ValueError(f"{path}: truncated idx header")
        zero, dtype_code, ndim = struct.unpack(">HBB", head)
        if zero != 0:
            raise ValueError(f"{path}: bad idx magic (leading {zero:#x})")
        dtype = _DTYPES.get(dtype_code)
        if dtype is None:
            raise ValueError(f"{path}: unknown idx dtype {dtype_code:#x}")
        raw_dims = f.read(4 * ndim)
        if len(raw_dims) != 4 * ndim:
            raise ValueError(f"{path}: truncated idx dims")
        dims = struct.unpack(">" + "I" * ndim, raw_dims)
        payload = f.read()
    want = int(np.prod(dims)) * np.dtype(dtype).itemsize
    if len(payload) < want:
        raise ValueError(f"{path}: truncated idx payload "
                         f"({len(payload)} < {want} bytes)")
    arr = np.frombuffer(payload[:want], dtype=np.dtype(dtype).newbyteorder(">"))
    return arr.reshape(dims).astype(dtype)


def write_idx(path: str, arr: np.ndarray) -> None:
    """Encode ``arr`` as an idx file (the test/synthetic-data writer)."""
    code = {v: k for k, v in _DTYPES.items()}[np.dtype(arr.dtype).type]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, code, arr.ndim))
        f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
        f.write(np.ascontiguousarray(arr,
                                     np.dtype(arr.dtype).newbyteorder(">"))
                .tobytes())


def read_mnist(images_path: str, labels_path: str) -> dict:
    """(images idx3, labels idx1) → {"x": [N, 784] float32 in [0,1],
    "y": [N] int32} — the mlp_example batch dict."""
    images = read_idx(images_path)
    labels = read_idx(labels_path)
    if images.ndim != 3:
        raise ValueError(f"images file has ndim={images.ndim}, expected 3")
    if labels.ndim != 1 or labels.shape[0] != images.shape[0]:
        raise ValueError(
            f"labels shape {labels.shape} does not match "
            f"{images.shape[0]} images")
    x = images.reshape(images.shape[0], -1).astype(np.float32)
    if images.dtype == np.uint8:
        x /= 255.0  # uint8 pixels -> [0, 1]; float files are kept as-is
    elif np.issubdtype(images.dtype, np.integer):
        raise ValueError(
            f"images dtype {images.dtype} has no defined [0,1] scaling; "
            "MNIST images are uint8 (or pre-scaled floats)")
    return {"x": x, "y": labels.astype(np.int32)}
