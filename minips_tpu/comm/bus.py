"""ControlBus — the surviving sliver of the reference's ZeroMQ Mailbox.

The reference routes *all* traffic (push/pull payloads, clocks, barriers,
heartbeats) through a zmq ROUTER/DEALER mailbox (SURVEY.md §2.3). In the
rebuild the data plane is XLA collectives, so the only traffic that still
needs sockets is the control plane: SSP clock gossip and heartbeats, which
must stay nonblocking while a TPU step runs (SURVEY.md §2.3 "Control
plane"). This is a deliberately tiny pub/sub bus: every process binds one
PUB socket and subscribes to all peers; messages are small
``{kind, sender, payload}`` heads framed by the shared wire codec
(comm/framing.py — binary by default, the seed JSON via
``MINIPS_WIRE_FMT=json``; receivers sniff per frame).

Tested over loopback in-process (the reference tests its mailbox the same
way — threads as nodes, SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from minips_tpu.comm.framing import (decode_head, encode_head,
                                     wire_fmt_from_env)

try:
    import zmq
    _HAS_ZMQ = True
except ImportError:  # pragma: no cover - zmq is present in the target env
    _HAS_ZMQ = False


class FrameLossTracker:
    """Receiver-side wire-loss accounting (VERDICT r2 weak #3): every
    non-handshake frame a sender emits carries a per-stream sequence
    number — stream ``b`` for broadcasts (every receiver sees all of
    them) and stream ``d`` for frames directed at me. Both ride ONE
    ordered connection per (sender → receiver), so a gap in either
    stream means frames were lost on the wire (zmq HWM drop, a died
    link's tail) — exactly the loss mode zmq PUB/SUB cannot itself
    report. The FIRST frame seen per stream only synchronizes (frames
    published before a subscription lands are droppable by design; the
    handshake rendezvous bounds that window), so ``lost`` counts losses
    in ESTABLISHED streams — which must be zero in a healthy job.

    A gap is kept as an OUTSTANDING set, not a terminal verdict: a
    reordered, duplicated, or retransmitted frame whose seq eventually
    arrives reconciles ``lost`` back down — under the reliable-delivery
    layer (comm/reliable.py) a retransmit that lands late must not be
    double-booked as both 'lost' and 'delivered', and a mere adjacent
    swap (chaos reorder, a multi-path wire) was never a loss at all.
    ``dups`` counts late frames whose seq was already accounted
    delivered. The outstanding set is bounded (``GAP_CAP`` per stream);
    gaps evicted past the cap stay counted lost forever — the seed
    behavior, now only for pathological floods."""

    GAP_CAP = 4096  # outstanding gap seqs retained per (sender, stream)

    def __init__(self):
        self._next: dict[tuple, int] = {}
        self._gaps: dict[tuple, "OrderedDict[int, None]"] = {}
        self.lost = 0
        self.dups = 0
        self.malformed = 0
        self._lock = threading.Lock()

    def observe(self, sender: int, stream: str, seq: int) -> None:
        with self._lock:
            k = (sender, stream)
            exp = self._next.get(k)
            if exp is None:  # sync point: pre-subscription frames
                self._next[k] = seq + 1
                return
            if seq >= exp:
                if seq > exp:
                    self.lost += seq - exp  # O(1), like the seed
                    gaps = self._gaps.setdefault(k, OrderedDict())
                    # materialize at most GAP_CAP seqs of the jump: a
                    # stale-run/corrupt frame carrying a huge seq must
                    # not build a gap entry per missing seq under the
                    # receive thread's lock — everything below the cap
                    # stays counted lost forever (seed behavior)
                    for s in range(max(exp, seq - self.GAP_CAP), seq):
                        gaps[s] = None
                    while len(gaps) > self.GAP_CAP:
                        gaps.popitem(last=False)
                self._next[k] = seq + 1
                return
            # late frame (seq < exp): a reordered/duplicated/retransmitted
            # arrival — reconcile if its seq is an outstanding gap
            gaps = self._gaps.get(k)
            if gaps is not None and gaps.pop(seq, -1) is None:
                self.lost -= 1
            else:
                self.dups += 1

    def note_malformed(self) -> None:
        with self._lock:
            self.malformed += 1

    def prime(self, sender: int, stream: str, seq: int = 0) -> None:
        """Pin the stream's sync point (idempotent): the reliable
        channel defines every stream as starting at seq 0 — with it
        installed, a hole the journal could not repair must COUNT as
        lost even when it precedes the first delivered frame, instead
        of being forgiven by first-frame sync (which exists for the
        bare bus's pre-subscription window)."""
        with self._lock:
            self._next.setdefault((sender, stream), seq)


class ControlBus:
    """PUB/SUB gossip bus: ``publish(kind, payload)`` fans out to all peers;
    ``send(dest, ...)`` delivers to ONE peer (zmq topic-prefix subscription,
    filtered at the publisher for TCP transports — directed traffic does not
    ride every link). Handlers registered per kind run on a background
    receive thread.

    Backpressure/loss semantics (documented, VERDICT r2 weak #3): zmq PUB
    sockets DROP frames silently once a subscriber's queue hits the HWM —
    they never block the publisher. Both HWMs here default to 65536 frames
    (``$MINIPS_ZMQ_HWM``) so a flood must outrun the subscriber by ~65k
    frames before anything drops, and every frame carries a sequence
    number so a drop that does happen is COUNTED at the receiver
    (``frames_lost``) instead of silently corrupting training. The native
    backend (comm/native_bus.py) blocks the producer instead (bounded
    outbox) — same observable interface, stricter guarantee."""

    def __init__(self, my_addr: str, peer_addrs: list[str],
                 my_id: int = 0, wire_fmt: Optional[str] = None):
        import os

        if not _HAS_ZMQ:
            raise RuntimeError("pyzmq not available")
        self.my_id = my_id
        # head codec (comm/framing.py): binary by default, the seed JSON
        # framing via MINIPS_WIRE_FMT=json — receive sniffs per frame,
        # so the knob only shapes what THIS rank emits
        self.wire_fmt = wire_fmt or wire_fmt_from_env()
        self.bytes_sent = 0  # wire accounting (sharded-PS slice assertions)
        self.loss = FrameLossTracker()
        self._n_world = len(peer_addrs) + 1
        self._bseq = 0                       # broadcast-stream seq
        self._dseq = [0] * self._n_world     # per-dest directed seq
        hwm = int(os.environ.get("MINIPS_ZMQ_HWM", "65536"))
        self._ctx = zmq.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.setsockopt(zmq.SNDHWM, hwm)
        self._pub.bind(my_addr)
        self._sub = self._ctx.socket(zmq.SUB)
        self._sub.setsockopt(zmq.RCVHWM, hwm)
        for addr in peer_addrs:
            self._sub.connect(addr)
        # Two topics reach me: broadcast "b|" and my directed "d<id>|".
        # The trailing delimiter keeps "d1|" from prefix-matching "d12|".
        self._sub.setsockopt(zmq.SUBSCRIBE, b"b|")
        self._sub.setsockopt(zmq.SUBSCRIBE, f"d{my_id}|".encode())
        self._handlers: dict[str, Callable[[int, dict], None]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pub_lock = threading.Lock()

    def on(self, kind: str, handler: Callable[[int, dict], None]) -> None:
        """Register ``handler(sender_id, payload)`` for message kind."""
        self._handlers[kind] = handler

    def start(self) -> "ControlBus":
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()
        # PUB/SUB needs a beat for subscriptions to propagate (slow joiner).
        time.sleep(0.05)
        return self

    def publish(self, kind: str, payload: dict,
                blob: Optional[bytes] = None) -> None:
        """Fan out ``payload`` (small JSON) with an optional binary ``blob``
        frame (e.g. a packed ndarray of parameter deltas). Receivers find
        the blob at ``payload["__blob__"]``. JSON stays the control format
        (reference BinStream's role, SURVEY.md §2); the blob frame exists so
        host-relayed pushes need no base64 inflation."""
        self._emit(b"b|", kind, payload, blob)

    def send(self, dest: int, kind: str, payload: dict,
             blob: Optional[bytes] = None) -> None:
        """Deliver to ONE peer — the reference Mailbox's per-thread-id
        addressing (SURVEY.md §2.3), here a topic only ``dest`` subscribes
        to. Per-(publisher → subscriber) frame order still holds across
        publish() and send() on this bus: one PUB socket, one connection."""
        # validate like the native backend: a typo'd dest would otherwise
        # publish to a topic nobody subscribes and vanish silently
        if dest == self.my_id:
            raise ValueError("directed send to self (serve locally instead)")
        if not 0 <= dest < self._n_world:
            raise ValueError(f"dest rank {dest} out of range")
        self._emit(f"d{dest}|".encode(), kind, payload, blob)

    def _emit(self, topic: bytes, kind: str, payload: dict,
              blob: Optional[bytes]) -> None:
        head = {"kind": kind, "sender": self.my_id, "payload": payload}
        with self._pub_lock:
            # seq stamped under the pub lock: the stream order IS the wire
            # order. Handshake frames stay unstamped — they are the frames
            # legitimately droppable before subscriptions land.
            if not kind.startswith("__"):
                if topic == b"b|":
                    head["bs"] = self._bseq
                    self._bseq += 1
                else:
                    dest = int(topic[1:-1])
                    head["ds"] = self._dseq[dest]
                    self._dseq[dest] += 1
            msg = encode_head(head, self.wire_fmt)
            rel = getattr(self, "reliable", None)
            if rel is not None and ("bs" in head or "ds" in head):
                # journal under the pub lock: journal order == wire order,
                # so a NACKed seq is always findable or provably evicted
                rel.journal_stamped(
                    "b" if "bs" in head else "d",
                    -1 if "bs" in head else int(topic[1:-1]),
                    head.get("bs", head.get("ds")), msg, blob)
            frames = [topic, msg] if blob is None else [topic, msg, blob]
            self._pub.send_multipart(frames)
            self.bytes_sent += len(msg) + (len(blob) if blob else 0)

    @property
    def frames_lost(self) -> int:
        """Wire frames provably lost on established (sender → me) streams
        — nonzero means HWM drops or a torn link tail; see FrameLossTracker.
        With the reliable channel installed, recovered frames never count:
        this is UNRECOVERED loss."""
        return self.loss.lost

    @property
    def frames_malformed(self) -> int:
        """Undecodable control frames dropped at receive (torn JSON — a
        stale run's tail or wire corruption), counted instead of silently
        swallowed; surfaced next to frames_lost in wire_record."""
        return self.loss.malformed

    def out_queue_depth(self) -> Optional[int]:
        """zmq queues live inside the library; depth is not observable —
        the native backend reports a real number here."""
        return None

    def _recv_loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self._sub, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=50)):
                continue
            # drain the socket per wake, not one frame per poll(): each
            # poll releases the GIL, and when the main thread is busy
            # (the overlapped pipeline's whole point) a per-frame poll
            # lets it steal the timeslice between every frame — the
            # receive thread then drains at ~1 frame per GIL handoff and
            # ack/reply latency balloons from microseconds to tens of ms
            while not self._stop.is_set():
                try:
                    frames = self._sub.recv_multipart(zmq.NOBLOCK)
                except zmq.ZMQError:
                    break  # EAGAIN: queue empty, back to poll()
                if len(frames) < 2:
                    self.loss.note_malformed()
                    continue  # topic-only frame: malformed
                deliver_frame(self, frames[1],
                              frames[2] if len(frames) > 2 else None)

    def handshake(self, num_processes: int, timeout: float = 15.0) -> None:
        """Rendezvous before real traffic: PUB/SUB drops messages published
        before a subscriber's connect lands (the zmq slow-joiner problem),
        which for the delta-gossip data path would mean silent replica
        divergence — so nobody proceeds until everyone provably hears
        everyone. Reference analog: the mailbox's startup bind/connect
        barrier (SURVEY.md §3.1)."""
        run_handshake(self, num_processes, timeout)

    def close(self) -> None:
        stop_bus_layers(self)  # chaos scheduler + reliable repair thread
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        self._pub.close(linger=0)
        self._sub.close(linger=0)

    def __enter__(self) -> "ControlBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def dispatch_message(handlers: dict, raw, blob: Optional[bytes],
                     loss: Optional[FrameLossTracker] = None) -> None:
    """Shared receive-side tail for every bus backend: decode the
    control frame (format-sniffed: binary or the seed JSON,
    comm/framing.py), run it past the wire-loss tracker, attach the
    blob at ``__blob__``, invoke the handler. A malformed frame is
    COUNTED (``loss.malformed`` → ``frames_malformed``) and reported
    once to stderr instead of silently swallowed — a torn frame is a
    wire-health signal the done lines must carry. A raising handler is
    reported, not propagated — one bad handler must not kill the
    backend's receive thread (clocks/heartbeats ride the same
    thread)."""
    msg = decode_head(raw)
    if msg is None:
        _note_malformed(loss, raw)
        return
    dispatch_parsed(handlers, msg, blob, loss=loss)


def _note_malformed(loss: Optional[FrameLossTracker], raw) -> None:
    if loss is None:
        return
    loss.note_malformed()
    if loss.malformed == 1:  # first sighting: say it once, count the rest
        import sys

        head = bytes(raw[:64]) if raw is not None else b""
        print(f"bus: malformed control frame dropped (head={head!r}); "
              "counting in frames_malformed", file=sys.stderr)


def dispatch_parsed(handlers: dict, msg: dict, blob: Optional[bytes],
                    loss: Optional[FrameLossTracker] = None) -> None:
    """``dispatch_message`` minus the decode — the reliable channel's
    sequencer re-dispatches already-parsed frames through this."""
    if loss is not None:
        if "bs" in msg:
            loss.observe(msg.get("sender", -1), "b", int(msg["bs"]))
        elif "ds" in msg:
            loss.observe(msg.get("sender", -1), "d", int(msg["ds"]))
    handler = handlers.get(msg.get("kind"))
    if handler is None:
        return
    payload = msg.get("payload", {})
    if blob is not None:
        payload["__blob__"] = blob
    try:
        handler(msg.get("sender", -1), payload)
    except Exception:  # noqa: BLE001 - isolate handler faults
        import sys
        import traceback

        print(f"bus: handler for {msg.get('kind')!r} raised:",
              file=sys.stderr)
        traceback.print_exc()


def deliver_frame(bus, raw, blob: Optional[bytes]) -> None:
    """Receive chain shared by every backend, layered like the wire it
    models: (1) the chaos injector, when installed, plays the lossy
    network — it may drop, duplicate, delay, or reorder the frame;
    (2) the reliable channel, when installed, runs surviving stamped
    frames through its deliver-once in-order sequencer (gap → NACK →
    retransmit, comm/reliable.py); (3) plain handler dispatch. With
    neither installed this is byte-for-byte the seed path."""
    msg = decode_head(raw)
    if msg is None:
        _note_malformed(getattr(bus, "loss", None), raw)
        return
    chaos = getattr(bus, "chaos", None)
    if chaos is not None:
        chaos.on_wire(msg, blob)  # forwards survivors to deliver_post_wire
    else:
        deliver_post_wire(bus, msg, blob)


def deliver_post_wire(bus, msg: dict, blob: Optional[bytes]) -> None:
    """Above-the-wire half of :func:`deliver_frame` — the chaos injector
    re-enters here for frames it held (so a delayed frame is not
    re-chaosed on release)."""
    rel = getattr(bus, "reliable", None)
    if rel is not None and ("bs" in msg or "ds" in msg):
        rel.on_stamped(msg, blob)
    else:
        dispatch_parsed(bus._handlers, msg, blob, loss=bus.loss)


def stop_bus_layers(bus) -> None:
    """Quiesce the optional chaos/reliable layers before a backend tears
    its sockets down (both run their own timer threads)."""
    for attr in ("chaos", "reliable"):
        layer = getattr(bus, attr, None)
        if layer is not None:
            layer.stop()


def run_handshake(bus, num_processes: int, timeout: float = 15.0) -> None:
    """Backend-agnostic startup rendezvous over any bus exposing
    ``on``/``publish``/``my_id``/``_handlers``. Each process repeats
    ``hello``; once it has heard hello from all peers it also repeats
    ``ready``; it returns once it has heard ready from all peers (with a
    short grace of extra publishes for stragglers)."""
    import time as _time

    peers = set(range(num_processes)) - {bus.my_id}
    if not peers:
        return
    hellos: set[int] = set()
    readys: set[int] = set()
    lock = threading.Lock()

    def on_hello(sender: int, payload: dict) -> None:
        with lock:
            hellos.add(sender)

    def on_ready(sender: int, payload: dict) -> None:
        with lock:
            hellos.add(sender)
            readys.add(sender)

    bus.on("__hello", on_hello)
    bus.on("__ready", on_ready)
    deadline = _time.monotonic() + timeout
    while True:
        bus.publish("__hello", {})
        with lock:
            all_hello = hellos >= peers
            all_ready = readys >= peers
        if all_hello:
            bus.publish("__ready", {})
        if all_ready:
            break
        if _time.monotonic() > deadline:
            with lock:
                missing = peers - readys
            raise TimeoutError(
                f"bus handshake: peers {sorted(missing)} never ready")
        _time.sleep(0.02)
    for _ in range(5):  # grace: peers may still await my ready
        bus.publish("__ready", {})
        _time.sleep(0.02)
    bus._handlers.pop("__hello", None)
    bus._handlers.pop("__ready", None)


def make_bus(my_addr: str, peer_addrs: list[str], my_id: int = 0,
             backend: Optional[str] = None, *,
             chaos: Optional[str] = None,
             reliable: Optional[str] = None,
             wire_fmt: Optional[str] = None):
    """Bus factory. ``backend``: ``"zmq"`` (pyzmq PUB/SUB, default),
    ``"native"`` (the C++ TCP mailbox, cpp/mailbox.cpp — the reference's
    native-runtime analog), or ``"shm"`` (same-host shared-memory SPSC
    rings, comm/shm_bus.py — the zero-copy loopback transport); default
    from ``$MINIPS_BUS``. ``wire_fmt`` picks the head codec
    (``$MINIPS_WIRE_FMT``: ``bin`` default, ``json`` = the seed
    framing) — receivers sniff per frame, so mixed-fmt fleets decode.

    An explicit native request that cannot be satisfied raises instead of
    silently falling back: the two wire formats do not interoperate, so a
    quiet fallback on one host of a multi-host job would produce a mixed
    mesh that fails 15s later with a misleading handshake timeout. An
    shm request across hosts fails the same loud way (the ring files
    simply don't exist on the other machine — the attach times out
    naming the missing link).

    Two optional layers install on whichever backend was built (same
    observable interface either way):

    - ``reliable`` (or ``$MINIPS_RELIABLE``): the retransmission protocol
      riding the per-link seqs (comm/reliable.py) — transient wire loss
      degrades to latency instead of a timeout poison. ``"1"`` for
      defaults, or a knob string (``"journal=1024,budget=12"``).
    - ``chaos`` (or ``$MINIPS_CHAOS``): the deterministic seeded fault
      injector (comm/chaos.py), ``"<seed>:drop=0.01,dup=0.005,..."`` —
      every process must run the SAME spec for a reproducible drill.
    """
    import os

    # explicit-empty = default, like every other MINIPS_* knob (the
    # bench arms pin "" to keep an armed environment from leaking)
    backend = backend or os.environ.get("MINIPS_BUS", "").strip() or "zmq"
    if backend == "native":
        from minips_tpu.comm.native_bus import NativeControlBus

        if not NativeControlBus.available():
            raise RuntimeError(
                "MINIPS_BUS=native requested but the C++ mailbox library "
                "is unavailable (no compiler?); every host must use the "
                "same backend — set MINIPS_BUS=zmq explicitly to fall back")
        bus = NativeControlBus(my_addr, peer_addrs, my_id=my_id,
                               wire_fmt=wire_fmt)
    elif backend == "zmq":
        bus = ControlBus(my_addr, peer_addrs, my_id=my_id,
                         wire_fmt=wire_fmt)
    elif backend == "shm":
        from minips_tpu.comm.shm_bus import ShmControlBus

        bus = ShmControlBus(my_addr, peer_addrs, my_id=my_id,
                            wire_fmt=wire_fmt)
    else:
        raise ValueError(f"unknown bus backend {backend!r} "
                         "(expected 'zmq', 'native', or 'shm')")
    # layer order matters only conceptually: chaos models the wire (runs
    # first on receive), reliable rides above it. Install reliable first
    # so chaos-released frames find the sequencer already in place.
    reliable = (os.environ.get("MINIPS_RELIABLE", "")
                if reliable is None else reliable)
    if reliable and reliable != "0":
        from minips_tpu.comm.reliable import ReliableChannel

        ReliableChannel.install(bus, reliable)
    chaos = os.environ.get("MINIPS_CHAOS", "") if chaos is None else chaos
    if chaos:
        from minips_tpu.comm.chaos import ChaosBus

        ChaosBus.install(bus, chaos)
    return bus


class ClockGossip:
    """SSP clock exchange over the bus (SURVEY.md §7.4): each process
    publishes its local worker clocks; the merged global view feeds the
    host-side staleness gate."""

    def __init__(self, bus: ControlBus, num_processes: int,
                 workers_per_process: int):
        self.bus = bus
        self._clocks = {p: [0] * workers_per_process
                        for p in range(num_processes)}
        self._cond = threading.Condition()
        self._excluded: set[int] = set()
        self._listeners: list = []  # called (no locks held) on any change
        bus.on("clock", self._on_clock)

    def add_listener(self, fn) -> None:
        """``fn()`` runs after every clock/exclusion change — the server-
        side pending-buffer's re-admission hook (parked pulls re-check)."""
        self._listeners.append(fn)

    def _notify_listeners(self) -> None:
        for fn in self._listeners:
            fn()

    def _on_clock(self, sender: int, payload: dict) -> None:
        with self._cond:
            if sender not in self._clocks:
                return  # stray sender (stale run / port reuse): no ghosts
            new = list(payload.get("clocks", []))
            cur = self._clocks[sender]
            if len(cur) == len(new):
                # MONOTONE merge: clocks only advance within one bus
                # incarnation, so a clock frame arriving LATE (wire
                # reorder, a retransmit landing after fresher gossip)
                # must never regress the view — a regressed min would
                # re-park admitted pulls and stamp replies with a
                # freshness certificate older than what the rows hold
                new = [max(a, b) for a, b in zip(cur, new)]
            self._clocks[sender] = new
            self._cond.notify_all()
        self._notify_listeners()

    def publish_local(self, clocks: list[int]) -> None:
        with self._cond:
            self._clocks[self.bus.my_id] = list(clocks)
            self._cond.notify_all()
        self.bus.publish("clock", {"clocks": list(clocks)})
        self._notify_listeners()

    def exclude(self, process_id: int) -> None:
        """Drop a dead peer from min-clock computation (failure handling,
        SURVEY.md §5.3) so survivors aren't gated on a corpse forever."""
        with self._cond:
            self._excluded.add(process_id)
            self._cond.notify_all()
        self._notify_listeners()

    def include(self, process_id: int) -> None:
        """Re-admit a rank into min-clock computation — the elastic-
        membership join path (balance/membership.py): a standby rank is
        excluded at startup so its idle clock can't gate the fleet, and
        included only AFTER it published a catch-up clock (its live
        announce trails that publish on the same FIFO link, so by
        include time the stored entry is current — including a clock-0
        ghost would wedge every gate)."""
        with self._cond:
            self._excluded.discard(process_id)
            self._cond.notify_all()
        self._notify_listeners()

    def _min_locked(self) -> int:
        vals = [min(v) for p, v in self._clocks.items()
                if v and p not in self._excluded]
        return min(vals) if vals else 0

    def global_min(self) -> int:
        with self._cond:
            return self._min_locked()

    def min_excluding(self, process_id: int) -> int:
        """min clock over live processes OTHER than ``process_id`` — the
        freshness certificate an owner stamps on a pull reply to that
        process (train/sharded_ps.py row cache). The requester's own
        entry is excluded because its contribution to the reply's
        freshness is certified by a different mechanism: per-link FIFO
        means the owner has applied every push the requester sent before
        the pull, regardless of how stale the requester's *gossiped*
        clock looks here — including it would only let the slowest
        reader invalidate its own cache. With no other live process
        left to certify, fall back to the plain global min (which then
        includes the requester's own gossiped clock — conservative: a
        lower stamp only costs cache hits, never staleness)."""
        with self._cond:
            vals = [min(v) for p, v in self._clocks.items()
                    if v and p not in self._excluded and p != process_id]
            return min(vals) if vals else self._min_locked()

    @property
    def excluded(self) -> set[int]:
        with self._cond:
            return set(self._excluded)

    def wait_global_min(self, threshold: int,
                        timeout: Optional[float] = None) -> bool:
        """Block until every live process's min clock >= threshold — the
        host-side SSP gate's wait primitive (SURVEY.md §7.4.1). Returns
        False on timeout."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._min_locked() >= threshold, timeout)

    def snapshot(self) -> dict[int, list[int]]:
        with self._cond:
            return {k: list(v) for k, v in self._clocks.items()}

    @property
    def skew(self) -> int:
        """max clock − min clock over live processes (the SSP observable,
        SURVEY.md §5.5)."""
        with self._cond:
            vals = [c for p, v in self._clocks.items()
                    if v and p not in self._excluded for c in v]
            return (max(vals) - min(vals)) if vals else 0


class BlobExchange:
    """Host-side allgather of one ndarray per process per (round, tag).

    The touched-row UNION exchange for row-sparse collective syncs
    (train/cssp_ps.py): before each merge round every process publishes
    the slot ids its local steps touched; every process then holds the
    same per-rank arrays and computes the same sorted union — the index
    set the batch-rows-sized delta collective runs over. Arrays ride the
    bus's binary blob frame (no base64 inflation); the JSON head carries
    (round, tag, dtype).

    Early arrivals PARK in the store until consumed: under SSP skew a
    fast process may receive a peer's round-r+1 array while still
    draining round r — keying the store by (round, tag, sender) makes
    that reordering harmless. Hardenings against the pub/sub transport's
    nature (frames published before a peer registered its handler are
    dropped, and there is no replay):

    - a waiting ``allgather`` RE-PUBLISHES its own frame every couple of
      seconds (duplicates are idempotent — same key, same bytes);
    - a waiting ``allgather`` also REQUESTS missing frames: each
      instance retains its latest (head, blob) per tag and answers a
      ``blobx_req`` by re-sending — this covers the sender whose own
      gather already completed and who therefore stopped re-publishing
      (it no longer waits, but it still serves);
    - late/duplicate arrivals for rounds already consumed or abandoned
      are dropped at receive time by a per-tag ROUND WATERMARK (rounds
      are monotone per tag by construction).

    All publishes happen OUTSIDE the store lock: the bus receive thread
    needs that lock in ``_on``, and it also delivers clock gossip and
    heartbeats — a blocking publish (the native bus's bounded outbox)
    must never freeze failure detection. Request replies go through a
    one-shot thread for the same reason.

    A timed-out wait consults the heartbeat monitor so a dead peer
    raises PeerFailureError instead of hanging forever (the staleness
    gate's contract, SURVEY.md §5.3)."""

    KIND = "blobx"
    REQ_KIND = "blobx_req"

    def __init__(self, bus: ControlBus, num_processes: int):
        self.bus = bus
        self.n = int(num_processes)
        self._store: dict = {}
        self._done: dict = {}     # tag -> highest consumed/abandoned round
        self._sent: dict = {}     # tag -> {round: (head, blob)}, last 2
        self._cond = threading.Condition()
        bus.on(self.KIND, self._on)
        bus.on(self.REQ_KIND, self._on_req)

    def _on(self, sender: int, payload: dict) -> None:
        import numpy as np

        rnd, tag = int(payload["round"]), str(payload["tag"])
        raw = payload.get("__blob__") or b""
        arr = np.frombuffer(raw, dtype=np.dtype(payload["dtype"])).copy()
        with self._cond:
            if rnd <= self._done.get(tag, -1):
                return  # re-publish duplicate of a finished round
            self._store[(rnd, tag, sender)] = arr
            self._cond.notify_all()

    def _on_req(self, sender: int, payload: dict) -> None:
        """A peer missed our frame (registered its handler after our
        publishes, and our own gather may already be done): re-send the
        retained copy. Off-thread — the receive thread must not block
        in a publish."""
        rnd, tag = int(payload["round"]), str(payload["tag"])
        with self._cond:
            kept = self._sent.get(tag, {}).get(rnd)
        if kept is None:
            return  # nothing retained for that round (it will time out)
        head, blob = kept
        threading.Thread(target=self.bus.publish,
                         args=(self.KIND, head, blob),
                         daemon=True).start()

    def allgather(self, rnd: int, tag: str, arr, *,
                  timeout: float = 120.0, monitor=None) -> list:
        """Every process's array for (rnd, tag), ordered by rank (mine
        included). All processes must call this together — it blocks for
        the peers, like the collective it fronts."""
        import numpy as np

        arr = np.ascontiguousarray(arr)
        head = {"round": int(rnd), "tag": str(tag), "dtype": str(arr.dtype)}
        blob = arr.tobytes()
        with self._cond:
            # retain the last FOUR rounds per tag: within one round the
            # collective merges after each gather rendezvous the whole
            # group, so a peer normally lags at most one round behind a
            # server — but a round whose every union is empty launches
            # no psum (no rendezvous), and SEVERAL consecutive empty
            # rounds let a lagging peer fall further behind than a
            # 2-round window before anything re-synchronizes it. Four
            # rounds covers 3 empty rounds back-to-back; a peer lagging
            # deeper than that has missed a real rendezvous and is the
            # monitor's problem, not retention's.
            kept = self._sent.setdefault(tag, {})
            kept[int(rnd)] = (head, blob)
            for old_rnd in [r for r in kept if r < rnd - 3]:
                del kept[old_rnd]
        self.bus.publish(self.KIND, head, blob=blob)
        out: list = [None] * self.n
        out[self.bus.my_id] = arr
        peers = [p for p in range(self.n) if p != self.bus.my_id]
        deadline = time.monotonic() + timeout
        last_repair = time.monotonic()
        while True:
            with self._cond:
                missing = [p for p in peers
                           if (rnd, tag, p) not in self._store]
                if not missing:
                    for p in peers:
                        out[p] = self._store.pop((rnd, tag, p))
                    self._finish_locked(rnd, tag)
                    return out
                self._cond.wait(timeout=1.0)
                missing = [p for p in peers
                           if (rnd, tag, p) not in self._store]
                if not missing:
                    for p in peers:
                        out[p] = self._store.pop((rnd, tag, p))
                    self._finish_locked(rnd, tag)
                    return out
            # ---- lock released: monitor/deadline/repair — run EVERY
            # iteration: other traffic keeping the cond busy (peers'
            # re-publishes, other tags) must not starve failure
            # detection or let the wait overshoot its deadline
            if monitor is not None:
                dead = monitor.check()
                if dead:
                    with self._cond:
                        self._finish_locked(rnd, tag)
                    from minips_tpu.consistency.gate import \
                        PeerFailureError
                    raise PeerFailureError(dead)
            if time.monotonic() > deadline:
                with self._cond:
                    self._finish_locked(rnd, tag)
                raise TimeoutError(
                    f"BlobExchange round {rnd} tag {tag!r}: "
                    f"peers {missing} never arrived")
            if time.monotonic() - last_repair > 2.0:
                # slow-joiner repair, both directions: re-offer my frame
                # (a peer may have registered after my first publish)
                # and request theirs (a peer whose gather already
                # finished no longer re-publishes, but it still serves
                # requests from its retained copies)
                self.bus.publish(self.KIND, head, blob=blob)
                for p in missing:
                    self.bus.send(p, self.REQ_KIND,
                                  {"round": int(rnd), "tag": str(tag)})
                last_repair = time.monotonic()

    def _finish_locked(self, rnd: int, tag: str) -> None:
        """Mark the round consumed/abandoned and drop any parked leftovers
        for it: the caller never comes back for an abandoned round
        (recovery relaunches with fresh state), and re-published
        duplicates of finished rounds must not re-park — the watermark
        makes _on reject them at receive time. Caller holds the lock."""
        self._done[tag] = max(self._done.get(tag, -1), rnd)
        for key in [k for k in self._store
                    if k[0] <= rnd and k[1] == tag]:
            del self._store[key]
