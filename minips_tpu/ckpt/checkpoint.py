"""Checkpoint/recovery — rebuild of the reference's Dump/Load path.

The reference dumps KVTable contents to disk every K iterations (worker 0)
and recovers by restarting the task from the last dump (SURVEY.md §2
"Checkpoint/recovery", §3.5). PS state = parameters + optimizer state +
the clock vector, so that is exactly what a checkpoint holds here
(SURVEY.md §5.4):

- one ``.npz`` per table (dense: params + opt leaves; sparse: emb + accum),
- a JSON manifest with step, table names/kinds and controller clocks,
- atomic publish: write to ``step_K.tmp/`` then rename to ``step_K/``, so a
  crash mid-save never corrupts the latest good checkpoint,
- optional async save (a background thread snapshots host copies first —
  the device keeps training while bytes hit disk), the moral equivalent of
  orbax async checkpointing without requiring it.

Recovery = construct the same tables, ``restore()`` the newest step, resume
the loop at ``step`` (SURVEY.md §5.3: recovery is relaunch + reload at the
reference's fixed node set). Relaunching at a DIFFERENT world size is
handled a layer up: ``ckpt/elastic.py`` reshards the rank-local shard
files across partitions (beyond parity — the reference has no elastic
resize).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from typing import Any, Optional

import numpy as np


class Checkpointer:
    def __init__(self, directory: str, tables: dict[str, Any],
                 controllers: Optional[dict[str, Any]] = None,
                 *, keep: int = 3, async_save: bool = False):
        self.dir = directory
        self.tables = tables
        self.controllers = controllers or {}
        self.keep = keep
        self.async_save = async_save
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------ save
    def save(self, step: int) -> str:
        """Snapshot to host, then (a)synchronously write + atomically
        publish ``step_<step>/``."""
        snap = {name: t.state_dict() for name, t in self.tables.items()}
        clocks = {name: c.state_dict() for name, c in self.controllers.items()}
        if self.async_save:
            self.wait()  # one save in flight at a time
            self._thread = threading.Thread(
                target=self._write, args=(step, snap, clocks), daemon=True)
            self._thread.start()
        else:
            self._write(step, snap, clocks)
        return self._step_dir(step)

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def close(self) -> None:
        """Interface parity with the orbax backend: flush pending saves."""
        self.wait()

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step:010d}")

    def _write(self, step: int, snap: dict, clocks: dict) -> None:
        final = self._step_dir(step)
        tmp = final + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for name, state in snap.items():
            flat = _flatten(state)
            np.savez(os.path.join(tmp, f"{name}.npz"), **flat)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump({"step": step, "tables": sorted(snap),
                       "clocks": clocks}, f)
        shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        self._gc()

    def _gc(self) -> None:
        steps = self.list_steps()
        for s in steps[: -self.keep] if self.keep > 0 else []:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def prune_above(self, step: int) -> list[int]:
        """Delete checkpoints NEWER than ``step`` and return the pruned
        step numbers. Used after a cross-rank resume negotiation: local
        steps above the agreed step belong to a dead incarnation — left
        in place, a later crash could negotiate onto a step whose shards
        mix incarnations (a torn table nothing would detect)."""
        pruned = []
        for s in self.list_steps():
            if s > step:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
                pruned.append(s)
        return pruned

    # --------------------------------------------------------------- restore
    def list_steps(self) -> list[int]:
        out = []
        for d in os.listdir(self.dir):
            m = re.fullmatch(r"step_(\d+)", d)
            if m and os.path.exists(os.path.join(self.dir, d,
                                                 "manifest.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def _validate_step(self, step: int) -> dict:
        """Read a step's manifest and force-read EVERY table's npz —
        ONE TABLE AT A TIME, discarding each after the read — applying
        nothing. Validation before mutation: a torn checkpoint
        (truncated npz, corrupt manifest, missing table file) must
        fail HERE, while the live tables are still untouched, so the
        caller can walk back to an older step instead of relaunching
        half-loaded. Reading per-table keeps the validation pass at
        the OLD peak memory (largest single table, not the whole
        checkpoint next to the live tables)."""
        d = self._step_dir(step)
        with open(os.path.join(d, "manifest.json")) as f:
            manifest = json.load(f)
        if not isinstance(manifest, dict) or "step" not in manifest:
            raise ValueError(f"manifest.json in {d} lacks 'step'")
        for name in self.tables:
            path = os.path.join(d, f"{name}.npz")
            with np.load(path) as z:
                # dict(z.items()) forces every array to decompress NOW
                # — a truncated/corrupt member raises inside this read,
                # not later during load_state_dict — and the dict dies
                # at the end of this iteration
                _unflatten(dict(z.items()))
        return manifest

    def restore(self, step: Optional[int] = None) -> int:
        """Load the given (or newest restorable) step into the live
        tables/controllers; returns the restored step number.

        With ``step=None`` (the relaunch path) a TORN checkpoint —
        unreadable npz, corrupt manifest, a table file missing — is
        skipped with a loud stderr warning (+ flight-recorder event)
        and the walk continues to the next-newest step: a crash that
        tore the latest checkpoint must cost one checkpoint interval
        of progress, not the relaunch. An EXPLICIT ``step`` keeps the
        strict semantics (the caller asked for that step; silently
        substituting another would be worse than failing). All state
        for a step is read and validated BEFORE any of it is applied,
        so a failed candidate leaves the live tables untouched."""
        steps = self.list_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.dir}")
        explicit = step is not None
        cands = [step] if explicit else list(reversed(steps))
        skipped: list[str] = []
        for s in cands:
            try:
                manifest = self._validate_step(s)
            except Exception as e:  # noqa: BLE001 - torn-ckpt walkback
                if explicit:
                    raise
                import sys

                note = f"step_{s}: {type(e).__name__}: {e}"
                print(f"[ckpt] WARNING: skipping torn checkpoint "
                      f"{note} — walking back to the previous step",
                      file=sys.stderr, flush=True)
                try:
                    from minips_tpu.obs import flight as _fl

                    _fl.record("ckpt_skip_torn",
                               {"dir": self.dir, "step": int(s),
                                "err": str(e)[:200]})
                except Exception:  # noqa: BLE001 - obs must not block
                    pass
                skipped.append(note)
                continue
            # apply pass: re-read one table at a time (old peak
            # memory — double I/O only on the restore path, where the
            # validation read is usually still in the page cache)
            d = self._step_dir(s)
            for name, t in self.tables.items():
                with np.load(os.path.join(d, f"{name}.npz")) as z:
                    t.load_state_dict(_unflatten(dict(z.items())))
            for name, c in self.controllers.items():
                if name in manifest.get("clocks", {}):
                    c.load_state_dict(manifest["clocks"][name])
            return manifest["step"]
        raise FileNotFoundError(
            f"no restorable checkpoint under {self.dir}: every "
            f"candidate was torn ({'; '.join(skipped)})")


# --------------------------------------------------------------------- utils
def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    """Flatten a nested state dict (dicts/lists/tuples/ndarrays) to
    slash-keyed arrays for npz."""
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}_{i}/"))
    elif tree is None:
        out[prefix + "__none__"] = np.zeros(0)
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> Any:
    """Inverse of _flatten (lists come back as lists)."""
    root: dict = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = None if parts[-1] == "__none__" else val
    return _listify(root)


def _listify(node: Any) -> Any:
    if not isinstance(node, dict):
        return node
    if node.keys() and all(re.fullmatch(r"_\d+", k) for k in node):
        return [_listify(node[k]) for k in
                sorted(node, key=lambda s: int(s[1:]))]
    if set(node.keys()) == {"__none__"}:
        return None
    return {k: _listify(v) for k, v in node.items()}
