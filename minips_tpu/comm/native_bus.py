"""NativeControlBus — ctypes binding for the C++ TCP mailbox.

The reference's Mailbox is native C++ (ZeroMQ ROUTER/DEALER + per-thread
``ThreadsafeQueue`` inboxes + a Sender actor; SURVEY.md L0/L1, §2.3). This
is the rebuild's native-runtime equivalent for the surviving control plane:
``cpp/mailbox.cpp`` implements the transport (raw TCP full mesh, framed
messages, a C++ ThreadsafeQueue inbox, reader actors per connection, a
Sender actor draining an outgoing queue), and this module is the thin
Python skin exposing the exact ``ControlBus`` interface so ``ClockGossip``,
``HeartbeatMonitor``, ``BlockMaster`` etc. run unchanged on either backend.

Select with ``make_bus(..., backend="native")`` or ``MINIPS_BUS=native``.
Like the native data readers, the library builds lazily on first use and
callers degrade to the zmq backend when no compiler is available.
"""

from __future__ import annotations

import ctypes
import json
import threading
from typing import Callable, Optional

from minips_tpu.comm.bus import dispatch_message
from minips_tpu.utils.native_lib import load_native_lib


def _declare(lib: ctypes.CDLL) -> None:
    lib.mailbox_create.argtypes = [ctypes.c_int]
    lib.mailbox_create.restype = ctypes.c_void_p
    lib.mailbox_port.argtypes = [ctypes.c_void_p]
    lib.mailbox_port.restype = ctypes.c_int
    lib.mailbox_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_int]
    lib.mailbox_connect.restype = ctypes.c_int
    lib.mailbox_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64]
    lib.mailbox_publish.restype = None
    lib.mailbox_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64]
    lib.mailbox_send.restype = None
    lib.mailbox_recv.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64)]
    lib.mailbox_recv.restype = ctypes.c_int
    lib.mailbox_free_buf.argtypes = [ctypes.c_void_p]
    lib.mailbox_free_buf.restype = None
    lib.mailbox_close.argtypes = [ctypes.c_void_p]
    lib.mailbox_close.restype = None


def _load() -> Optional[ctypes.CDLL]:
    return load_native_lib("libminips_comm.so", _declare)


def _parse_addr(addr: str) -> tuple[str, int]:
    """``tcp://host:port`` → (IPv4, port); hostnames (``localhost``,
    hostfile names) resolve here so the C side only sees literals."""
    import socket

    hostport = addr.split("//", 1)[-1]
    host, port = hostport.rsplit(":", 1)
    if host in ("*", "0.0.0.0", ""):
        return "0.0.0.0", int(port)
    try:
        socket.inet_aton(host)
    except OSError:
        host = socket.gethostbyname(host)
    return host, int(port)


class NativeControlBus:
    """Same interface as ``ControlBus`` (on/start/publish/handshake/close),
    backed by the C++ mailbox instead of pyzmq. Fan-out happens over the
    full mesh of outgoing TCP connections made in ``start()``."""

    def __init__(self, my_addr: str, peer_addrs: list[str], my_id: int = 0,
                 connect_timeout: float = 15.0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native mailbox library unavailable")
        self.my_id = my_id
        self.bytes_sent = 0
        self._lib = lib
        _, port = _parse_addr(my_addr)
        self._h = lib.mailbox_create(port)
        if not self._h:
            raise OSError(f"mailbox_create: cannot bind {my_addr}")
        self._peer_addrs = [_parse_addr(a) for a in peer_addrs]
        self._connect_timeout = connect_timeout
        self._handlers: dict[str, Callable[[int, dict], None]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Serializes publish() against close(): the C publish call must
        # never run concurrently with (or after) mailbox_close freeing the
        # Mailbox — a late heartbeat publish would be a use-after-free.
        self._h_lock = threading.Lock()

    @staticmethod
    def available() -> bool:
        return _load() is not None

    @property
    def port(self) -> int:
        return self._lib.mailbox_port(self._h)

    def on(self, kind: str, handler: Callable[[int, dict], None]) -> None:
        self._handlers[kind] = handler

    def start(self) -> "NativeControlBus":
        # Outgoing connects retry in C until the peer's listener is up
        # (processes boot in arbitrary order, SURVEY.md §3.1).
        for host, port in self._peer_addrs:
            rc = self._lib.mailbox_connect(
                self._h, host.encode(), port,
                int(self._connect_timeout * 1000))
            if rc != 0:
                raise TimeoutError(
                    f"native bus: cannot reach peer {host}:{port}")
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()
        return self

    # Receive-side protocol caps (cpp/mailbox.cpp kMaxMsg/kMaxBlob). An
    # oversized frame would be written in full here but poison the peer's
    # reader thread there — the link dies silently. Reject at the source.
    MAX_MSG = 16 << 20
    MAX_BLOB = 1 << 30

    def publish(self, kind: str, payload: dict,
                blob: Optional[bytes] = None) -> None:
        """Nonblocking: enqueues onto the C++ Sender actor's queue.
        A publish after close() is a silent no-op (matches zmq's at-worst-
        an-error behavior rather than a use-after-free)."""
        self._emit(-1, kind, payload, blob)

    def send(self, dest: int, kind: str, payload: dict,
             blob: Optional[bytes] = None) -> None:
        """Directed delivery to peer rank ``dest`` over its one TCP link.
        Assumes ``peer_addrs`` was built in ascending-rank order minus my
        own entry (what launch.init_from_env produces) so the connect-order
        index is recoverable from the rank."""
        if dest == self.my_id:
            raise ValueError("directed send to self (serve locally instead)")
        idx = dest if dest < self.my_id else dest - 1
        if not 0 <= idx < len(self._peer_addrs):
            raise ValueError(f"dest rank {dest} out of range")
        self._emit(idx, kind, payload, blob)

    def _emit(self, peer_index: int, kind: str, payload: dict,
              blob: Optional[bytes]) -> None:
        msg = json.dumps({"kind": kind, "sender": self.my_id,
                          "payload": payload}).encode()
        if len(msg) > self.MAX_MSG:
            raise ValueError(f"control frame {len(msg)}B exceeds the "
                             f"{self.MAX_MSG}B protocol cap")
        if blob is not None and len(blob) > self.MAX_BLOB:
            raise ValueError(f"blob {len(blob)}B exceeds the "
                             f"{self.MAX_BLOB}B protocol cap")
        with self._h_lock:
            if self._closed:
                return
            data = None if blob is None else bytes(blob)
            blen = -1 if blob is None else len(blob)
            if peer_index < 0:
                self._lib.mailbox_publish(self._h, msg, len(msg), data, blen)
            else:
                self._lib.mailbox_send(self._h, peer_index, msg, len(msg),
                                       data, blen)
            self.bytes_sent += len(msg) + (blen if blen > 0 else 0)

    def _recv_loop(self) -> None:
        msg_p = ctypes.c_char_p()
        msg_len = ctypes.c_int64()
        blob_p = ctypes.POINTER(ctypes.c_uint8)()
        blob_len = ctypes.c_int64()
        while not self._stop.is_set():
            got = self._lib.mailbox_recv(
                self._h, 50, ctypes.byref(msg_p), ctypes.byref(msg_len),
                ctypes.byref(blob_p), ctypes.byref(blob_len))
            if not got:
                continue
            try:
                raw = ctypes.string_at(msg_p, msg_len.value)
                blob = (ctypes.string_at(blob_p, blob_len.value)
                        if blob_len.value >= 0 and blob_p else None)
            finally:
                self._lib.mailbox_free_buf(msg_p)
                if blob_p:
                    self._lib.mailbox_free_buf(blob_p)
                blob_p = ctypes.POINTER(ctypes.c_uint8)()
            dispatch_message(self._handlers, raw, blob)

    def handshake(self, num_processes: int, timeout: float = 15.0) -> None:
        """TCP never drops post-connect, but a peer may publish before OUR
        connect to it finished accepting — same rendezvous as zmq."""
        from minips_tpu.comm.bus import run_handshake

        run_handshake(self, num_processes, timeout)

    def close(self) -> None:
        with self._h_lock:  # waits out any in-flight publish, blocks new ones
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                # A handler is wedged past the grace period. mailbox_close
                # would free the C++ object under the recv thread's feet
                # (use-after-free → segfault); leaking the handle is the
                # safe failure mode.
                return
        self._lib.mailbox_close(self._h)

    def __enter__(self) -> "NativeControlBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
