"""Quantized collectives for the PS data plane (EQuARX-style, PAPERS.md).

The fused PS step's traffic is two bandwidth-bound collectives per
iteration: all-gather of the sharded parameter vector (pull) and
reduce-scatter of the gradient (push) — SURVEY.md §2.3. On ICI these are
wire-limited, so shrinking bytes-on-wire converts directly into step time;
"EQuARX: quantized all-reduce in XLA" (PAPERS.md) reports ~2x collective
speedup at negligible quality cost with dynamic block quantization. This
module is the same idea expressed at the JAX level, usable inside
``shard_map``:

- ``comm="bfloat16"``: cast → collective → cast. 2x traffic cut; the safe
  default to try first.
- ``comm="int8"``: symmetric per-shard dynamic quantization (max-abs scale
  per contiguous shard chunk), 4x traffic cut. The reduce-scatter becomes
  all-to-all of int8 chunks + local dequantized f32 accumulation, so
  precision loss stays per-hop bounded: sums accumulate in f32, never int8.

Accuracy contract (tests/test_quantized_comm.py): int8 round-trip error is
bounded by scale/2 per element (≈0.4% of the chunk max), and end-to-end LR
training converges to the f32 loss within noise.
"""

from __future__ import annotations

import jax

import jax.numpy as jnp
import numpy as np
from minips_tpu.utils.jaxcompat import axis_size as _axis_size

VALID = ("float32", "bfloat16", "int8")


# --------------------------------------------------------------- host codec
# The per-ROW absmax int8 codec for the host PS wire (train/sharded_ps.py):
# the numpy twin of the blockwise device codec below, with the row (not a
# 256-element block) as the scale unit — PS frames already move row-major
# key slices, so one f32 scale per row is the natural framing. Both the
# push leg (gradients, stochastic rounding) and the pull leg (weights,
# nearest rounding) of the sharded PS speak this codec; it lives here so
# the device collectives and the host wire share one quantization home.

def quantize_rows_int8(rows: np.ndarray,
                       rng: np.random.Generator | None = None
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Per-row absmax int8. With ``rng``, rounding is STOCHASTIC (round
    to floor with probability 1-frac, up with probability frac), making
    the codec UNBIASED: E[decode(encode(g))] = g — quantization noise
    averages out across steps instead of accumulating as drift, which is
    why the gradient push wire needs no error-feedback residual (EF
    would require a residual the size of the FULL table on every pusher,
    breaking the sharded PS's 1/N-memory-per-process claim).

    With ``rng=None``, rounding is round-to-NEAREST — the pull-wire mode
    for weights: deterministic, so every puller of an unchanged row
    decodes identical bytes, and half the worst-case per-element error.

    Returns ``(codes int8 [n, dim], scale f32 [n])``; decode is
    ``codes * scale[:, None]``. All-zero rows get scale 0."""
    rows = np.asarray(rows, np.float32)
    scale = (np.abs(rows).max(axis=1) / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0).astype(np.float32)
    x = rows / safe[:, None]
    if rng is None:
        codes = np.rint(x)
    else:
        low = np.floor(x)
        codes = low + (rng.random(rows.shape) < (x - low))
    return np.clip(codes, -127, 127).astype(np.int8), scale


def dequantize_rows_int8(codes: np.ndarray,
                         scale: np.ndarray) -> np.ndarray:
    return codes.astype(np.float32) * scale[:, None]

# ------------------------------------- sparse top-k + blockwise host codec
# The compressed push wire's two levers (SparCML + EQuARX, PAPERS.md):
# magnitude top-k ROW selection over the owner-split gradient (ship the
# mass, not the touch set) and blockwise absmax quantization at 8 or 4
# bits (one f32 scale per HOST_BLOCK flattened elements — the numpy twin
# of the device codec's ``_quantize_blocks`` below, block size tunable).
# The pusher keeps ``g - decode(encode(g))`` plus every unselected row in
# its error-feedback residual store (train/sharded_ps.ResidualStore), so
# unlike the per-row int8 codec above, BIASED nearest rounding is sound
# here: the bias is measured and re-shipped, never accumulated.

HOST_BLOCK = 64  # default blockwise-scale unit for the host topk wire
                 # (f32-scale overhead = 4/HOST_BLOCK bytes per element;
                 # at 64 that is 1/16 the 8-bit code stream)


def topk_rows(rows: np.ndarray, *, mass: float = 0.9,
              frac_cap: float = 0.5) -> np.ndarray:
    """SORTED indices of the smallest row set capturing ``mass`` of the
    squared-L2 gradient mass, capped at ``ceil(frac_cap * n)`` rows —
    'k adaptive to the touched set': a zipf push whose summed hot rows
    dominate selects a few rows; a flat push selects up to the cap and
    leaves the rest to error feedback. Deterministic (stable sort);
    always selects at least one row of a nonzero gradient."""
    n = rows.shape[0]
    if n == 0:
        return np.empty(0, np.int64)
    mag = np.einsum("ij,ij->i", rows, rows, dtype=np.float64)
    total = float(mag.sum())
    cap = max(1, int(np.ceil(frac_cap * n)))
    if total <= 0.0:
        return np.arange(min(1, n), dtype=np.int64)
    order = np.argsort(-mag, kind="stable")
    k = int(np.searchsorted(np.cumsum(mag[order]), mass * total)) + 1
    return np.sort(order[: min(k, cap)])


def _block_grid(flat: np.ndarray, block: int) -> tuple[np.ndarray, int]:
    """Zero-pad a flat f32 array up to a block multiple and view it
    ``[nb, block]`` (zeros never move an absmax)."""
    L = flat.size
    nb = -(-L // block) if L else 0
    pad = nb * block - L
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(nb, block), L


def quantize_blockwise(rows: np.ndarray, bits: int, *,
                       block: int = HOST_BLOCK,
                       rng: np.random.Generator | None = None
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Blockwise absmax quantization of ``[n, dim]`` f32 rows flattened
    row-major: one f32 scale per ``block`` elements, codes at 8 bits
    (int8 stream) or 4 bits (two codes per byte, uint8 stream, offset
    +8 so the sign needs no second pass). ``rng`` selects stochastic
    rounding (unbiased); None is round-to-nearest (deterministic — the
    serve-plane refresh mode, where every replica must decode the same
    bytes). Returns ``(codes, scales f32 [nb])``."""
    if bits not in (4, 8):
        raise ValueError("blockwise codec supports 4 or 8 bits")
    levels = 127 if bits == 8 else 7
    flat = np.ascontiguousarray(rows, np.float32).reshape(-1)
    grid, L = _block_grid(flat, block)
    scale = (np.abs(grid).max(axis=1) / levels).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0).astype(np.float32)
    x = grid / safe[:, None]
    if rng is None:
        q = np.rint(x)
    else:
        low = np.floor(x)
        q = low + (rng.random(x.shape) < (x - low))
    q = np.clip(q, -levels, levels).astype(np.int8).reshape(-1)[:L]
    if bits == 8:
        return q, scale
    u = (q.astype(np.int16) + 8).astype(np.uint8)  # 1..15, 0 unused
    if u.size % 2:
        u = np.concatenate([u, np.zeros(1, np.uint8)])
    return (u[0::2] << 4) | u[1::2], scale


def dequantize_blockwise(codes: np.ndarray, scales: np.ndarray,
                         n: int, dim: int, bits: int, *,
                         block: int = HOST_BLOCK) -> np.ndarray:
    """Inverse of :func:`quantize_blockwise` back to ``[n, dim]`` f32."""
    L = n * dim
    if bits == 8:
        q = np.frombuffer(codes, np.int8)[:L].astype(np.float32)
    else:
        packed = np.frombuffer(codes, np.uint8)
        u = np.empty(packed.size * 2, np.uint8)
        u[0::2] = packed >> 4
        u[1::2] = packed & 0x0F
        q = (u[:L].astype(np.int16) - 8).astype(np.float32)
    grid, _ = _block_grid(q, block)
    out = grid * np.asarray(scales, np.float32)[:, None]
    return out.reshape(-1)[:L].reshape(n, dim)


def blockwise_stream_bytes(n: int, dim: int, bits: int,
                           block: int = HOST_BLOCK) -> tuple[int, int]:
    """(code bytes, scale bytes) of the blockwise stream for ``n`` rows —
    the one size formula encoder, decoder, and frame validators share."""
    L = n * dim
    nb = -(-L // block) if L else 0
    code = L if bits == 8 else -(-L // 2)
    return code, 4 * nb


# ------------------------------------------- sorted-run key delta codec
# The other half of the index-stream bill (ROADMAP item 5's "cheap
# adjacent win"): the topk push wire ships SORTED unique keys (np.unique
# upstream, topk_rows returns sorted positions), and a hot zipf working
# set is near-contiguous in key space — so the gaps between adjacent
# keys fit a byte where the absolute keys need 2-8. Encode the first
# key absolute (i64) and the rest as unsigned run deltas at the
# narrowest width the largest gap fits. Strictly-increasing input only
# (deltas >= 1 by construction after dedup); the encoder is the one
# place that checks, so a caller with unsorted keys must sort first.

def delta_stream_bytes(n: int, dw: int) -> int:
    """Byte size of the delta key stream for ``n`` keys at delta width
    ``dw`` — shared by encoder and frame validators."""
    return 0 if n == 0 else 8 + (n - 1) * dw


def encode_key_deltas(keys: np.ndarray) -> tuple[int, bytes]:
    """Delta-encode strictly-increasing int64 ``keys``: 8-byte i64 base
    + ``n-1`` gaps at the narrowest unsigned width ∈ {1, 2, 4, 8} that
    fits the largest gap. Returns ``(delta_width, stream)``."""
    keys = np.ascontiguousarray(keys, np.int64)
    n = keys.size
    if n == 0:
        return 1, b""
    if n == 1:
        return 1, keys.tobytes()
    gaps = np.diff(keys)
    if gaps.min() <= 0:
        raise ValueError("delta key codec requires strictly "
                         "increasing keys")
    top = int(gaps.max())
    dw = 1 if top <= 0xFF else 2 if top <= 0xFFFF \
        else 4 if top <= 0xFFFFFFFF else 8
    dt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[dw]
    return dw, keys[:1].tobytes() + gaps.astype(dt).tobytes()


def decode_key_deltas(buf, n: int, dw: int) -> np.ndarray:
    """Inverse of :func:`encode_key_deltas` back to int64 keys."""
    if n == 0:
        return np.empty(0, np.int64)
    base = np.frombuffer(buf[:8], np.int64)
    if n == 1:
        return base.copy()
    dt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[dw]
    gaps = np.frombuffer(buf[8:8 + (n - 1) * dw], dt).astype(np.int64)
    out = np.empty(n, np.int64)
    out[0] = base[0]
    np.cumsum(gaps, out=out[1:])
    out[1:] += base[0]
    return out


BLOCK = 256  # int8 quantization block: one f32 scale per 256 elements
             # (1.6% wire overhead). Per-BLOCK scales matter because a
             # raveled model mixes magnitudes (layernorm ~1.0, attention
             # weights ~0.005); one scale per shard would flush the small
             # tensors to zero.


def _check(comm: str) -> None:
    if comm not in VALID:
        raise ValueError(f"comm must be one of {VALID}, got {comm!r}")


def _quantize_blocks(x: jnp.ndarray, block: int = BLOCK):
    """[..., L] f32 → (int8 [..., nb, block], f32 scales [..., nb]).
    L is zero-padded up to a block multiple."""
    L = x.shape[-1]
    nb = -(-L // block)
    pad = nb * block - L
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = x.reshape(*x.shape[:-1], nb, block)
    scale = jnp.maximum(jnp.max(jnp.abs(xb), axis=-1), 1e-30) / 127.0
    q = jnp.round(xb / scale[..., None]).astype(jnp.int8)
    return q, scale


def _dequantize_blocks(q: jnp.ndarray, scale: jnp.ndarray,
                       length: int) -> jnp.ndarray:
    """Inverse of ``_quantize_blocks`` over the last two dims."""
    x = (q.astype(jnp.float32) * scale[..., None])
    return x.reshape(*x.shape[:-2], -1)[..., :length]


def quantized_all_gather(x: jnp.ndarray, axis_name: str,
                         comm: str = "float32") -> jnp.ndarray:
    """All-gather a [shard] f32 vector as ``comm`` dtype; returns f32
    [n * shard] (tiled). int8 sends one f32 scale per BLOCK alongside."""
    _check(comm)
    if comm == "float32":
        return jax.lax.all_gather(x, axis_name, tiled=True)
    if comm == "bfloat16":
        g = jax.lax.all_gather(x.astype(jnp.bfloat16), axis_name, tiled=True)
        return g.astype(jnp.float32)
    shard = x.shape[0]
    q, scale = _quantize_blocks(x)
    qs = jax.lax.all_gather(q, axis_name, tiled=False)      # [n, nb, block]
    ss = jax.lax.all_gather(scale, axis_name, tiled=False)  # [n, nb]
    return _dequantize_blocks(qs, ss, shard).reshape(-1)


def a2a_reduce(chunks: jnp.ndarray, axis_name: str,
               comm: str, *, block: int = BLOCK
               ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The compressed REDUCE leg, shared by the pull/push plane and the
    CollectiveSSP sync wire: ship ``[n, c]`` per-destination chunks via
    all-to-all (same bytes on wire as a reduce-scatter ring) in the
    compressed dtype and accumulate in f32 after decompression — the
    cross-worker sum NEVER runs compressed, so error stays per-hop
    bounded instead of growing with worker count. Returns ``(reduced_c,
    sent)``: my reduced chunk and exactly what I contributed AFTER
    compression (the error-feedback hook: residual = input − sent)."""
    c = chunks.shape[1]
    if comm == "bfloat16":
        sent = chunks.astype(jnp.bfloat16).astype(jnp.float32)
        recv = jax.lax.all_to_all(chunks.astype(jnp.bfloat16), axis_name,
                                  split_axis=0, concat_axis=0, tiled=False)
        return jnp.sum(recv.astype(jnp.float32), axis=0), sent
    q, scale = _quantize_blocks(chunks, block)              # [n, nb, block]
    sent = _dequantize_blocks(q, scale, c)
    # chunk j of every device -> device j; received rows are the n devices'
    # contributions to MY chunk
    q_recv = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    s_recv = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)
    return jnp.sum(_dequantize_blocks(q_recv, s_recv, c), axis=0), sent


def gather_broadcast(chunk: jnp.ndarray, axis_name: str,
                     comm: str) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The compressed REPLICATE leg: all-gather my ``[c]`` chunk in the
    compressed dtype; every participant dequantizes the SAME bytes, so
    the assembled ``[n*c]`` result is bitwise identical everywhere.
    Returns ``(full, gap)`` with ``gap = chunk − what the others will
    decode of it`` — the second compression's error, which the chunk
    owner can fold into its error-feedback residual so BOTH legs'
    bias is compensated, not just the reduce leg's."""
    c = chunk.shape[0]
    if comm == "bfloat16":
        low = chunk.astype(jnp.bfloat16)
        g = jax.lax.all_gather(low, axis_name, tiled=False)
        return g.astype(jnp.float32).reshape(-1), \
            chunk - low.astype(jnp.float32)
    q, s = _quantize_blocks(chunk[None, :])
    decoded = _dequantize_blocks(q, s, c)[0]
    qg = jax.lax.all_gather(q, axis_name, tiled=False)
    sg = jax.lax.all_gather(s, axis_name, tiled=False)
    return _dequantize_blocks(qg[:, 0], sg[:, 0], c).reshape(-1), \
        chunk - decoded


def quantized_psum_scatter(gpad: jnp.ndarray, axis_name: str,
                           comm: str = "float32", *,
                           block: int = BLOCK) -> jnp.ndarray:
    """Reduce-scatter a [n * shard] f32 gradient to this device's [shard]
    chunk, summing over the axis (compressed modes via
    :func:`a2a_reduce`). ``block`` is the absmax scale unit — the mesh
    data plane (train/mesh_plane.py) passes the host wire's block size
    here so the collective tier and the compressed-wire tier are one
    codec with two transports (EQuARX, PAPERS.md)."""
    _check(comm)
    if comm == "float32":
        return jax.lax.psum_scatter(gpad, axis_name, tiled=True)
    n = _axis_size(axis_name)
    reduced, _ = a2a_reduce(gpad.reshape(n, -1), axis_name, comm,
                            block=block)
    return reduced


def quantized_psum_scatter_ef(gpad: jnp.ndarray, axis_name: str,
                              comm: str = "float32", *,
                              block: int = BLOCK
                              ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`quantized_psum_scatter` with the error-feedback hook kept:
    also returns this device's compression RESIDUAL — input minus what
    :func:`a2a_reduce` actually shipped after quantization, reshaped to
    ``gpad``'s layout so the caller can fold it into its next
    contribution (the leader-side ResidualStore contract,
    train/sharded_ps.py, now shared by the mesh plane's blk8 reduce
    leg). ``float32`` ships exactly, so its residual is exact zeros —
    one signature, the caller never branches on the codec."""
    _check(comm)
    if comm == "float32":
        return (jax.lax.psum_scatter(gpad, axis_name, tiled=True),
                jnp.zeros_like(gpad))
    n = _axis_size(axis_name)
    chunks = gpad.reshape(n, -1)
    reduced, sent = a2a_reduce(chunks, axis_name, comm, block=block)
    return reduced, (chunks - sent).reshape(gpad.shape)
