"""Multi-tenant tables (minips_tpu/tenant/ + the per-tenant splits in
serve/, balance/, train/) — this PR's tentpole.

Three layers of drill, the house shape:

- pure logic: the MINIPS_TENANT grammar (parse/refuse table + the
  seeded 250-spec fuzzer), deterministic tenant-id assignment, and the
  bind-time coverage/consistency refusals;
- unit protocol: the ``tb`` config stamp poisons a half-armed fleet in
  both directions, per-tenant staleness routes through the tenant's
  own ``s`` (cache validity AND owner-side admission), per-tenant
  admission buckets are distinct objects (the shared=1 contrast arm is
  ONE object), and a tenant's hedge budget rides a per-table config
  copy;
- threads-as-nodes isolation drills: the armed-idle lockstep is
  bitwise-equal to tenancy-off with zero tenant counters (TENANT-IDLE
  at test scale), and under per-tenant buckets a storming tenant sheds
  into its own budget while the quiet tenant's counters — including
  forced admits, the retried-leg valve — stay at zero; the shared
  bucket re-couples them, which is the bench's contrast arm.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from minips_tpu.serve.plane import ServeConfig, TableServeState
from minips_tpu.tenant.registry import (TenantRegistry, TenantSpec,
                                        maybe_registry)
from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


class _Bus:
    """Handler-swallowing stub for table-level unit drills."""

    supports_loopback = False

    def __init__(self):
        self.sent = []

    def on(self, *_a):
        pass

    def send(self, dest, kind, head, blob=None):
        self.sent.append((dest, kind, head))


# ------------------------------------------------------------- grammar
def test_tenant_config_parses_and_refuses():
    r = TenantRegistry.parse(
        "trn:rate=0,s=1;inf:rate=500,burst=64,s=2.5,replicas=3,"
        "hedge=0,updater=adam,wire=int8,block=16;shared=1")
    assert list(r.tenants) == ["trn", "inf"]
    assert [s.tid for s in r.tenants.values()] == [1, 2]
    assert r.shared and not r.default
    inf = r.tenants["inf"]
    assert (inf.rate, inf.burst, inf.s, inf.replicas, inf.hedge,
            inf.updater, inf.wire, inf.block) == (
        500.0, 64, 2.5, 3, 0, "adam", "int8", 16)
    assert r.tenants["trn"].overrides() == {"s": 1.0, "rate": 0.0}
    # the bare default: one tenant per table, no overrides, ids at bind
    d = TenantRegistry.parse("1")
    assert d.default and not d.tenants and not d.shared
    assert TenantRegistry.parse("a:s=inf").tenants["a"].s == float("inf")
    # off spellings live in maybe_registry, not parse
    assert maybe_registry("") is None and maybe_registry("0") is None
    assert maybe_registry("1") is not None
    for bad, frag in [
        ("a:zz=1", "unknown knob"),
        ("a:rate", "expected k=v"),
        ("a:rate=abc", "bad value for rate"),
        ("a:rate=-1", "bad value for rate"),
        ("a:s=-0.5", "bad value for s"),
        ("a:s=nan", "bad value for s"),
        ("a:burst=0", "bad value for burst"),
        ("a:block=0", "bad value for block"),
        ("a:replicas=0", "bad value for replicas"),
        ("a:hedge=-1", "bad value for hedge"),
        ("a:updater=sgdx", "bad value for updater"),
        ("a:wire=fp8", "bad value for wire"),
        ("a;a:rate=1", "duplicate tenant"),
        ("9bad", "bad tenant name"),
        ("shared=2", "bad value for shared"),
        ("turbo=1", "unknown global knob"),
        (";", "no tenants"),
    ]:
        with pytest.raises(ValueError, match=frag):
            TenantRegistry.parse(bad)


def _sig(reg):
    if reg is None:
        return None
    return (reg.shared, reg.default,
            [(s.name, s.tid, sorted(s.overrides().items()))
             for s in reg.tenants.values()])


def test_tenant_knob_fuzzer_parse_or_refuse_loudly():
    """Seeded MINIPS_TENANT fuzz (the MINIPS_CHAOS/HIER/HEDGE fuzzer
    convention): every random spec either parses — twice, to the same
    registry — or refuses with ValueError naming the offense; any
    other exception is a parser bug."""
    rng = np.random.default_rng(20260807)
    names = ["trn", "inf", "aux", "t_0", "9bad", "x y", "", "on"]
    knobs = ["updater", "wire", "s", "block", "rate", "burst",
             "replicas", "hedge", "zz", ""]
    vals = ["sgd", "adam", "f32", "int8", "1", "0", "2.5", "-1",
            "abc", "inf", "nan", ""]
    checked = 0
    for _ in range(250):
        entries = []
        for _e in range(int(rng.integers(0, 4))):
            if rng.random() < 0.2:
                entries.append(
                    f"shared={vals[int(rng.integers(len(vals)))]}")
                continue
            name = names[int(rng.integers(len(names)))]
            kvs = ",".join(
                f"{knobs[int(rng.integers(len(knobs)))]}"
                f"={vals[int(rng.integers(len(vals)))]}"
                for _k in range(int(rng.integers(0, 3))))
            entries.append(name if not kvs else f"{name}:{kvs}")
        spec = ";".join(entries)
        outcomes = []
        for _twice in range(2):
            try:
                outcomes.append(("ok", _sig(maybe_registry(spec))))
            except ValueError as e:
                assert "MINIPS_TENANT" in str(e), spec
                outcomes.append(("refused", str(e)))
            except Exception as e:  # noqa: BLE001 - the fuzzer's point
                pytest.fail(f"spec {spec!r} raised {e!r} "
                            f"(not ValueError)")
        assert outcomes[0] == outcomes[1], spec
        checked += 1
    assert checked == 250


# ------------------------------------------------ ids, bind, kwargs
def test_tid_assignment_and_bind_validation():
    b0, b1 = _Bus(), _Bus()
    ta = ShardedTable("a", 64, 2, b0, 0, 2)
    tb = ShardedTable("b", 64, 2, b1, 0, 2)
    # named mode: spec order wins, whatever the table-dict order
    r = TenantRegistry.parse("b:rate=1;a")
    r.bind({"a": ta, "b": tb})
    assert (r.spec_for("b").tid, r.spec_for("a").tid) == (1, 2)
    # default mode: sorted table-name order — every rank agrees
    d = TenantRegistry.parse("1")
    d.bind({"b": tb, "a": ta})
    assert (d.spec_for("a").tid, d.spec_for("b").tid) == (1, 2)
    # an unlisted table must refuse (it would run outside every SLO)
    with pytest.raises(ValueError, match="no tenant spec"):
        TenantRegistry.parse("a").bind({"a": ta, "b": tb})
    # spec'd updater/wire must match the constructed table
    with pytest.raises(ValueError, match="updater"):
        TenantRegistry.parse("a:updater=adam;b").bind(
            {"a": ta, "b": tb})
    with pytest.raises(ValueError, match="wire"):
        TenantRegistry.parse("a;b:wire=int8").bind({"a": ta, "b": tb})
    # table_kwargs hands the app the build overrides bind then accepts
    kw = TenantRegistry.parse("a:updater=adam,wire=int8;b"
                              ).table_kwargs("a")
    assert kw == {"updater": "adam", "pull_wire": "int8"}
    t2 = ShardedTable("a", 64, 2, _Bus(), 0, 2, **kw)
    TenantRegistry.parse("a:updater=adam,wire=int8").bind({"a": t2})


# --------------------------------------------------- wire namespace
def test_tb_stamp_poisons_half_armed_fleet_both_directions():
    """The namespace protocol's loud-failure rule: a frame whose
    tenant stamp disagrees with mine is a config drop (poison), same
    as a wrong world size — in BOTH arming directions, plus the
    divergent-order case."""
    base = {"ws": 2, "nr": 64, "dm": 2, "rb": 0}
    # unarmed me, armed peer
    t = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    assert t._check_peer_config(1, dict(base, tb=1)) is False
    assert t._fatal is not None and "tenant=1" in t._fatal
    # armed me, unarmed peer (no tb key at all)
    t2 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    sp = TenantSpec("t")
    sp.tid = 1
    t2.attach_tenant(sp)
    assert t2._cfg_header()["tb"] == 1
    assert t2._check_peer_config(1, dict(base)) is False
    assert t2._fatal is not None
    # armed both, divergent registry order
    t3 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    t3.attach_tenant(sp)
    assert t3._check_peer_config(1, dict(base, tb=2)) is False
    # agreeing stamp admits; an off table's header has no tb at all
    t4 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    t4.attach_tenant(sp)
    assert t4._check_peer_config(1, dict(base, tb=1)) is True
    assert "tb" not in ShardedTable("t", 64, 2, _Bus(), 0, 2
                                    )._cfg_header()


def test_heat_report_carries_and_rebalancer_checks_the_tenant_stamp():
    from minips_tpu.balance.heat import HeatAccountant

    h = HeatAccountant(8, 0.8, table_id=2)
    h.touch(np.array([1, 1, 3]))
    rep = h.report(np.arange(8), 4)
    assert rep["tb"] == 2 and h.global_key(3) == (2, 3)
    # tenancy off: no stamp at all (frames stay pre-tenancy identical)
    h0 = HeatAccountant(8, 0.8)
    h0.touch(np.array([1]))
    assert "tb" not in h0.report(np.arange(8), 4)


# ------------------------------------------------ per-tenant staleness
def test_per_tenant_staleness_routes_through_the_tenants_own_s():
    calls = []

    class _Cons:
        clock = 0
        staleness = 1

        def admit_pull(self, clk):
            calls.append(("fleet", clk))
            return True

        def admit_pull_s(self, clk, s):
            calls.append(("tenant", clk, s))
            return True

    # tenant with its own s: cache validity AND owner-side admission
    # judge against 3, not the fleet's 1
    t = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    sp = TenantSpec("t", s=3.0)
    sp.tid = 1
    t.attach_tenant(sp)
    t.bind_consistency(_Cons())
    assert t._cache_staleness() == 3.0
    assert t._admit_clk(5) is True
    assert calls == [("tenant", 5, 3.0)]
    # no tenant s: the fleet path, untouched
    calls.clear()
    t2 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    sp2 = TenantSpec("t")
    sp2.tid = 1
    t2.attach_tenant(sp2)
    t2.bind_consistency(_Cons())
    assert t2._cache_staleness() == 1
    assert t2._admit_clk(5) is True
    assert calls == [("fleet", 5)]
    # stub cons without admit_pull_s (lockstep drills): fallback, even
    # with a tenant s — the hasattr probe keeps old harnesses working
    calls.clear()

    class _Old:
        clock = 0
        staleness = 1

        def admit_pull(self, clk):
            calls.append(("fleet", clk))
            return True

    t3 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    t3.attach_tenant(sp)
    t3.bind_consistency(_Old())
    assert t3._admit_clk(5) is True
    assert calls == [("fleet", 5)]


def test_trainer_admit_pull_s_judges_the_given_bound():
    buses = _mk_buses(1)
    try:
        t = ShardedTable("t", 64, 2, buses[0], 0, 1)
        tr = ShardedPSTrainer({"t": t}, buses[0], 1, staleness=0)
        # global_min starts 0: clk 2 is out of a s=1 bound, inside s=5
        assert tr.admit_pull_s(2, 5) is True
        assert tr.admit_pull_s(2, 1) is False
        assert tr.admit_pull(0) is True
    finally:
        for b in buses:
            b.close()


# -------------------------------------------- buckets and hedge budget
def test_per_tenant_buckets_are_distinct_and_shared_arm_is_one():
    cfg = ServeConfig.parse("rate=100,burst=5")
    ta = ShardedTable("a", 96, 2, _Bus(), 0, 3)
    tb = ShardedTable("b", 96, 2, _Bus(), 0, 3)
    spa, spb = TenantSpec("a", rate=7.0, burst=2), TenantSpec("b")
    spa.tid, spb.tid = 1, 2
    ta.attach_tenant(spa)
    tb.attach_tenant(spb)
    sva = TableServeState(ta, None, cfg)
    svb = TableServeState(tb, None, cfg)
    assert sva.bucket is not svb.bucket
    assert (sva.bucket.rate, sva.bucket.burst) == (7.0, 2.0)  # override
    assert (svb.bucket.rate, svb.bucket.burst) == (100.0, 5.0)  # inherit
    # draining tenant a's bucket leaves tenant b's tokens untouched
    for _ in range(5):
        sva.bucket.take()
    assert not sva.bucket.take() and svb.bucket.take()

    class _Plane:
        shared_bucket = None

    from minips_tpu.serve.admission import TokenBucket

    _Plane.shared_bucket = TokenBucket(2, 1)
    sva2 = TableServeState(ta, _Plane(), cfg)
    svb2 = TableServeState(tb, _Plane(), cfg)
    assert sva2.bucket is _Plane.shared_bucket
    assert svb2.bucket is _Plane.shared_bucket  # the coupling, by design
    assert sva2._rate == cfg.rate  # per-tenant rate ignored when shared


def test_tenant_hedge_budget_rides_a_per_table_config_copy():
    from minips_tpu.serve.hedge import HedgeConfig

    cfg = HedgeConfig.parse("budget=4")
    t = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    sp = TenantSpec("t", hedge=1)
    sp.tid = 1
    t.attach_tenant(sp)
    t.attach_hedge(cfg)
    assert t._hedge.budget == 1 and cfg.budget == 4  # copy, not mutate
    # hedge=0: armed but the valve always sheds — never a crash
    t0 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    sp0 = TenantSpec("t", hedge=0)
    sp0.tid = 1
    t0.attach_tenant(sp0)
    t0.attach_hedge(cfg)
    assert t0._hedge.budget == 0
    # no tenant override: the shared config object, untouched
    t1 = ShardedTable("t", 64, 2, _Bus(), 0, 2)
    t1.attach_hedge(cfg)
    assert t1._hedge is cfg


# ------------------------------------------------------- armed idle
def test_armed_idle_lockstep_bitwise_equal_to_off_with_zero_counters():
    """TENANT-IDLE at test scale: the bare default tenant must cost
    nothing — identical final weights, zero losses, the stamp engaged
    (nonzero tids) and every attributed counter at zero."""
    from tests.test_chaos_reliable import run_bsp_lockstep

    base, lost0 = run_bsp_lockstep()
    st: dict = {}
    armed, lost1 = run_bsp_lockstep(tenant="1", stats=st)
    assert lost0 == [0, 0] and lost1 == [0, 0]
    for w0, w1 in zip(base, armed):
        np.testing.assert_array_equal(w0, w1)
    assert st["tenant_tids"] == [1, 1], "stamp never engaged — vacuous"
    assert st["tenant_counters"] == 0


# -------------------------------------------------- isolation drills
def _run_two_tenants(n, serve_spec, tenant_spec, *, staleness=2,
                     steps=25, rows=96, dim=2):
    """Threads-as-nodes two-table run: every rank pulls+pushes a hot
    range on BOTH tables each step (the inf side read-heavy), tenancy
    armed via the trainer kwarg. Returns (tables, trainers, finals)."""
    buses = _mk_buses(n, reliable="1")
    mk = lambda name, i: ShardedTable(name, rows, dim, buses[i], i, n,
                                      updater="sgd", lr=1.0,
                                      pull_timeout=20.0)
    tabs = [{"trn": mk("trn", i), "inf": mk("inf", i)}
            for i in range(n)]
    trainers = [ShardedPSTrainer(tabs[i], buses[i], n,
                                 staleness=staleness, gate_timeout=30.0,
                                 serve=serve_spec, tenant=tenant_spec)
                for i in range(n)]
    finals: list = [None] * n
    errs: list = []
    hot = np.arange(24, dtype=np.int64)

    def worker(r):
        try:
            for _i in range(steps):
                for name in ("trn", "inf"):
                    t = tabs[r][name]
                    rows_ = t.pull(hot)
                    t.push(hot, 0.01 * rows_ + 1.0)
                    t.pull(hot)
                trainers[r].tick()
                time.sleep(0.002)
            trainers[r].finalize(timeout=30.0)
            finals[r] = {k: tabs[r][k].pull_all() for k in tabs[r]}
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            import traceback

            traceback.print_exc()
            errs.append((r, repr(e)))

    try:
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(n)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(timeout=120.0)
        assert not any(th.is_alive() for th in ts), "run wedged"
        assert not errs, errs
        return tabs, trainers, finals
    finally:
        for b in buses:
            b.close()


SERVE = "replicas=2,hot=8,interval=0,min_heat=2,lease=2.0,rate=2,burst=1"


def _counters(trainers, table, key):
    return sum(tr.tables[table].tenant_counters[key] for tr in trainers)


def test_isolated_buckets_shed_the_storm_tenant_only():
    """The isolation invariant, end to end: with per-tenant buckets,
    the throttled tenant sheds into ITS budget while the rate=0 tenant
    never sheds, never throttles, and never has a leg force-admitted
    (a shed on A must not travel through B's retry valve) — and no
    read on either tenant violates its bound."""
    tabs, trainers, finals = _run_two_tenants(
        3, SERVE, "trn:rate=0;inf:rate=2,burst=1")
    inf_denied = (_counters(trainers, "inf", "shed")
                  + _counters(trainers, "inf", "throttle"))
    assert inf_denied > 0, "storm never shed — the drill is vacuous"
    for key in ("shed", "throttle", "stale_reads"):
        assert _counters(trainers, "trn", key) == 0, key
    for tr in trainers:
        assert tr.tables["trn"]._sv.counters["forced_admits"] == 0
        assert tr.tables["trn"]._sv.counters["stale_reads"] == 0
        assert tr.tables["inf"]._sv.counters["stale_reads"] == 0
        assert tr.frames_dropped == 0, tr.drop_detail()
    for name in ("trn", "inf"):
        np.testing.assert_array_equal(finals[0][name], finals[1][name])
    # the done-line block names both tenants with the right attribution
    ts = trainers[0].tenant_stats()
    assert ts["shared"] == 0 and set(ts["tenants"]) == {"trn", "inf"}
    assert ts["tenants"]["trn"]["tid"] != ts["tenants"]["inf"]["tid"]


def test_shared_bucket_recouples_the_tenants():
    """The contrast arm the bench measures: under ``shared=1`` the
    fleet has ONE bucket, so the combined load drains tokens the quiet
    tenant needed — its deny counters go nonzero. (rate=0 overrides
    are deliberately ignored when shared: the arm exists to show the
    coupling per-tenant buckets remove.)"""
    tabs, trainers, finals = _run_two_tenants(
        3, SERVE, "trn:rate=0;inf;shared=1")
    trn_denied = (_counters(trainers, "trn", "shed")
                  + _counters(trainers, "trn", "throttle"))
    assert trn_denied > 0, \
        "shared bucket never coupled — the contrast arm is vacuous"
    assert _counters(trainers, "trn", "stale_reads") == 0
    assert _counters(trainers, "inf", "stale_reads") == 0
    assert trainers[0].tenant_stats()["shared"] == 1
    for name in ("trn", "inf"):
        np.testing.assert_array_equal(finals[0][name], finals[1][name])


def test_wire_record_tenant_block_off_vs_idle():
    """The done-line convention: tenancy OFF reports None; armed with
    the bare default and nothing denied reports the zero-counter
    block (per tenant, with its tid)."""
    from minips_tpu.utils.metrics import wire_record

    buses = _mk_buses(1)
    try:
        t = ShardedTable("t", 64, 2, buses[0], 0, 1)
        tr = ShardedPSTrainer({"t": t}, buses[0], 1, staleness=0)
        assert wire_record(tr)["tenant"] is None
    finally:
        for b in buses:
            b.close()
    buses = _mk_buses(1)
    try:
        t = ShardedTable("t", 64, 2, buses[0], 0, 1)
        tr = ShardedPSTrainer({"t": t}, buses[0], 1, staleness=0,
                              tenant="1")
        blk = wire_record(tr)["tenant"]
        assert blk["shared"] == 0
        ten = blk["tenants"]["t"]
        assert ten["tid"] == 1 and ten["overrides"] == {}
        for k in ("shed", "throttle", "stale_reads", "hedge_denied"):
            assert ten[k] == 0
    finally:
        for b in buses:
            b.close()
