"""Elastic resume — reshard rank-local checkpoints across world sizes.

Beyond-parity capability (the reference restarts at a FIXED node count,
SURVEY.md §3.5); the elastic path lets a job checkpointed by N processes
relaunch at M != N by reassembling each new rank's row range from the
old shard files (ckpt/elastic.py), parameters and optimizer state alike.

Unit tier: the reshard slicing rule, the layout filter that keeps
old-world steps out of the same-size negotiation, and the
partition-fit-aware elastic-step scan (one step number can carry MIXED
layouts after a previous elastic republish).

Slow tier: the real drill — 3-rank training with shard checkpoints, a
2-rank relaunch whose pure restore reproduces the 3-rank run's final
parameter sum exactly, then continued training and a GROW relaunch at 4.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

# hypothesis drives only the reshard roundtrip property below; the unit
# and slow tiers must keep running (and the module keep collecting)
# in environments without it — pip install -e .[test] brings it in.
try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    HAS_HYPOTHESIS = True
except ImportError:  # pragma: no cover - exercised on bare environments
    HAS_HYPOTHESIS = False

    def given(*a, **k):  # noqa: D103 - no-op decorator stand-ins
        return lambda f: f

    def settings(*a, **k):
        return lambda f: f

    class _StrategyStub:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _StrategyStub()

from minips_tpu import launch
from minips_tpu.ckpt import elastic

APP = "minips_tpu.apps.sharded_ps_example"


class _FakeTable:
    """Just enough surface for the elastic helpers: partition geometry."""

    def __init__(self, num_rows: int, nprocs: int, rank: int):
        class _P:
            shard_size = -(-num_rows // nprocs)

        self.num_rows = num_rows
        self.part = _P()
        self.shard_lo = rank * _P.shard_size


def _write_step(ckdir, rank, step, name, num_rows, nprocs, *, value_of,
                extra=None):
    """Handcraft one rank's shard file in Checkpointer's on-disk layout:
    rows carry ``value_of(global_row_index)`` so reshards are checkable."""
    sz = -(-num_rows // nprocs)
    lo = rank * sz
    d = os.path.join(ckdir, f"rank{rank}", f"step_{step:010d}")
    os.makedirs(d, exist_ok=True)
    w = np.zeros((sz, 2), np.float32)
    for i in range(max(0, min(num_rows - lo, sz))):
        w[i] = value_of(lo + i)
    state = {"w": w, "lo": np.asarray(lo)}
    if extra:
        state.update(extra)
    np.savez(os.path.join(d, f"{name}.npz"), **state)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({"step": step, "tables": [name], "clocks": {}}, f)


def test_reshard_slices_rows_and_repads(tmp_path):
    """10 rows over 3 old shards (size 4, last padded) → 2 new shards
    (size 5): every new row must carry its global row's value, for the
    params AND a row-aligned optimizer leaf; the new last shard is
    zero-padded back to shard_size."""
    ck = str(tmp_path)
    rows = 10
    for r in range(3):
        sz = 4
        lo = r * sz
        m = np.zeros((sz, 2), np.float32)
        for i in range(max(0, min(rows - lo, sz))):
            m[i] = 100 + lo + i
        _write_step(ck, r, 5, "w", rows, 3,
                    value_of=lambda g: g, extra={"m": m})

    for new_rank in range(2):
        new_sz = 5
        st = elastic.reshard_table_state(ck, 5, 3, "w", rows,
                                         new_rank * new_sz, new_sz)
        assert int(st["lo"]) == new_rank * new_sz
        assert st["w"].shape == (new_sz, 2)
        for i in range(new_sz):
            g = new_rank * new_sz + i
            want = g if g < rows else 0.0   # pad rows zeroed
            assert st["w"][i, 0] == want, (new_rank, i)
            want_m = 100 + g if g < rows else 0.0
            assert st["m"][i, 0] == want_m, (new_rank, i)


def test_layout_filter_and_elastic_scan(tmp_path):
    """step_matches_layout rejects old-world steps; find_elastic_step
    picks the newest CONSISTENT world, including when one step number
    carries mixed layouts (the post-republish state) and when the newest
    step's holder set is torn."""
    ck = str(tmp_path)
    rows = 12
    # a complete 3-world at step 5
    for r in range(3):
        _write_step(ck, r, 5, "w", rows, 3, value_of=lambda g: g)
    # step 9 exists only on ranks 0 and 2 — torn (rank 1 lost it)
    _write_step(ck, 0, 9, "w", rows, 3, value_of=lambda g: g)
    _write_step(ck, 2, 9, "w", rows, 3, value_of=lambda g: g)

    t2 = {"w": _FakeTable(rows, 2, 1)}
    # rank 1's old step 5 (3-world layout) must NOT look resumable at 2
    assert not elastic.step_matches_layout(
        os.path.join(ck, "rank1"), 5, t2)
    # the scan skips torn step 9 and lands on the complete 3-world at 5
    assert elastic.find_elastic_step(ck, t2) == (5, 3)

    # mixed layouts at ONE step number: ranks 0-1 republish step 5 under
    # a 2-world partition (what an elastic resume does); rank 2 still
    # holds its 3-world file. k=3 no longer fits at step 5; k=2 does.
    for r in range(2):
        _write_step(ck, r, 5, "w", rows, 2, value_of=lambda g: 50 + g)
    assert elastic.find_elastic_step(ck, t2) == (5, 2)
    # and the republished 2-world rows (not the stale 3-world ones) are
    # what a 3-world regrow reshards from
    st = elastic.reshard_table_state(ck, 5, 2, "w", rows, 0, 4)
    assert st["w"][0, 0] == 50


def test_reshard_all_padding_shard(tmp_path):
    """A grown world's last shard can lie entirely in padding
    (shard_lo >= num_rows): the reshard must still produce full-shape
    zero leaves, mirroring what the same-size save/restore does with the
    padded arrays."""
    ck = str(tmp_path)
    rows = 9
    for r in range(3):
        _write_step(ck, r, 7, "w", rows, 3, value_of=lambda g: g,
                    extra={"m": np.ones((3, 2), np.float32)})
    # 4-world: shard_size=3, rank 3's range [9, 12) is all padding
    st = elastic.reshard_table_state(ck, 7, 3, "w", rows, 9, 3)
    assert int(st["lo"]) == 9
    assert st["w"].shape == (3, 2) and not st["w"].any()
    assert st["m"].shape == (3, 2) and not st["m"].any()


@pytest.mark.skipif(not HAS_HYPOTHESIS,
                    reason="needs hypothesis (pip install -e .[test])")
@settings(max_examples=40, deadline=None)
@given(num_rows=st.integers(1, 60), old_n=st.integers(1, 6),
       new_n=st.integers(1, 6), seed=st.integers(0, 2**31))
def test_reshard_roundtrip_property(num_rows, old_n, new_n, seed):
    """PROPERTY: for any (rows, N, M), saving a random table as N shards
    and resharding every M-shard reassembles the ORIGINAL table exactly
    — params and a row-aligned optimizer leaf — with zeroed padding
    beyond num_rows. The padded last shard, all-padding shards (M >
    rows), N==M, and single-shard worlds all fall out of the same
    rule."""
    import tempfile

    rng = np.random.default_rng(seed)
    table = rng.normal(size=(num_rows, 2)).astype(np.float32)
    moments = rng.normal(size=(num_rows, 2)).astype(np.float32)
    old_sz = -(-num_rows // old_n)
    with tempfile.TemporaryDirectory() as ck:
        for r in range(old_n):
            lo = r * old_sz
            m = np.zeros((old_sz, 2), np.float32)
            valid = max(0, min(num_rows - lo, old_sz))
            m[:valid] = moments[lo:lo + valid]
            _write_step(ck, r, 3, "w", num_rows, old_n,
                        value_of=lambda g: table[g], extra={"m": m})

        new_sz = -(-num_rows // new_n)
        got_w = np.zeros((num_rows, 2), np.float32)
        got_m = np.zeros((num_rows, 2), np.float32)
        for r in range(new_n):
            st_ = elastic.reshard_table_state(ck, 3, old_n, "w",
                                              num_rows, r * new_sz,
                                              new_sz)
            assert st_["w"].shape == (new_sz, 2)
            valid = max(0, min(num_rows - r * new_sz, new_sz))
            # padding rows must be zero for EVERY row-aligned leaf
            # (never stale foreign rows)
            assert not st_["w"][valid:].any()
            assert not st_["m"][valid:].any()
            got_w[r * new_sz:r * new_sz + valid] = st_["w"][:valid]
            got_m[r * new_sz:r * new_sz + valid] = st_["m"][:valid]
        np.testing.assert_array_equal(got_w, table)
        np.testing.assert_array_equal(got_m, moments)


def _write_rebalanced_world(ck, step, table_vals, moments, old_n, blk,
                            overlay):
    """Handcraft a REBALANCED world's shard files: every rank records
    the same routing metadata; each overlay block's LIVE rows sit in
    its owner's flat ``xtra/<b>/...`` section while the home slab keeps
    garbage (a dead copy, as the live system leaves it)."""
    rows = table_vals.shape[0]
    old_sz = -(-rows // old_n)
    bps = -(-old_sz // blk)
    meta = {"ep": np.asarray(3), "rb_block": np.asarray(blk),
            "ovb": np.asarray(sorted(overlay), np.int64),
            "ovo": np.asarray([overlay[b] for b in sorted(overlay)],
                              np.int64)}
    for r in range(old_n):
        lo = r * old_sz
        w = np.zeros((old_sz, 2), np.float32)
        m = np.zeros((old_sz, 2), np.float32)
        valid = max(0, min(rows - lo, old_sz))
        w[:valid] = table_vals[lo:lo + valid]
        m[:valid] = moments[lo:lo + valid]
        extra = dict(meta)
        extra["m"] = m
        for b, o in overlay.items():
            shard, loc = divmod(b, bps)
            blo = shard * old_sz + loc * blk
            bln = min(blk, old_sz - loc * blk)
            if shard == r:  # home slab: poison the dead copy
                w[loc * blk:loc * blk + bln] = -777.0
            if o == r:      # owner: the live rows ride xtra
                bv = np.zeros((bln, 2), np.float32)
                bm = np.zeros((bln, 2), np.float32)
                v = max(0, min(rows - blo, bln))
                bv[:v] = table_vals[blo:blo + v]
                bm[:v] = moments[blo:blo + v]
                extra[f"xtra/{b}/w"] = bv
                extra[f"xtra/{b}/m"] = bm
        _write_step(ck, r, step, "w", rows, old_n,
                    value_of=lambda g: 0.0, extra={"w": w, **extra})


def test_reshard_through_overlay_matches_unmigrated_oracle(tmp_path):
    """The overlay-aware elastic restore (membership satellite): a
    checkpoint saved MID-REBALANCE at 3 ranks reshards to 2 AND to 4
    with every row (params and optimizer leaf) BITWISE equal to the
    unmigrated oracle table — overlay blocks read from their owners'
    xtra sections, dead home copies ignored, no routing metadata
    surviving the resize."""
    ck = str(tmp_path)
    rows, old_n, blk = 24, 3, 2
    rng = np.random.default_rng(11)
    oracle_w = rng.normal(size=(rows, 2)).astype(np.float32)
    oracle_m = rng.normal(size=(rows, 2)).astype(np.float32)
    # blocks 0 (rank0 home) -> rank 2, and 9 (rank2 home) -> rank 1
    _write_rebalanced_world(ck, 5, oracle_w, oracle_m, old_n, blk,
                            overlay={0: 2, 9: 1})
    for new_n in (2, 4):
        new_sz = -(-rows // new_n)
        got_w = np.zeros((rows, 2), np.float32)
        got_m = np.zeros((rows, 2), np.float32)
        for r in range(new_n):
            st = elastic.reshard_table_state(ck, 5, old_n, "w", rows,
                                             r * new_sz, new_sz)
            assert not ({"ep", "ovb", "ovo", "rb_block"} & set(st))
            valid = max(0, min(rows - r * new_sz, new_sz))
            got_w[r * new_sz:r * new_sz + valid] = st["w"][:valid]
            got_m[r * new_sz:r * new_sz + valid] = st["m"][:valid]
        np.testing.assert_array_equal(got_w, oracle_w)
        np.testing.assert_array_equal(got_m, oracle_m)


def test_load_block_state_reads_through_saved_overlay(tmp_path):
    """The death path's restore unit: block state reads from wherever
    the save-time overlay parked it — the owner's xtra for a moved
    block, the home slab otherwise — and refuses a block-granularity
    mismatch loudly."""
    ck = str(tmp_path)
    rows, old_n, blk = 24, 3, 2
    old_sz = 8
    rng = np.random.default_rng(12)
    oracle_w = rng.normal(size=(rows, 2)).astype(np.float32)
    oracle_m = rng.normal(size=(rows, 2)).astype(np.float32)
    _write_rebalanced_world(ck, 5, oracle_w, oracle_m, old_n, blk,
                            overlay={0: 2})
    # block 0 (home rank 0, keys [0, 2)) lives in rank 2's xtra
    st = elastic.load_block_state(ck, 5, "w", 0, 0, 2, 0, old_sz, blk)
    np.testing.assert_array_equal(st["w"], oracle_w[:2])
    np.testing.assert_array_equal(st["m"], oracle_m[:2])
    # block 5 (home rank 1, keys [10, 12)) never moved: slab read
    st5 = elastic.load_block_state(ck, 5, "w", 5, 10, 2, 1, old_sz,
                                   blk)
    np.testing.assert_array_equal(st5["w"], oracle_w[10:12])
    with pytest.raises(ValueError, match="granularity"):
        elastic.load_block_state(ck, 5, "w", 0, 0, 4, 0, old_sz, 4)


def test_find_live_step_newest_complete_current_partition(tmp_path):
    """The death-plan step pick: newest step ALL n ranks hold under
    the caller's partition — torn steps skipped, other-world layouts
    rejected."""
    ck = str(tmp_path)
    rows = 12
    for r in range(3):
        _write_step(ck, r, 5, "w", rows, 3, value_of=lambda g: g)
        _write_step(ck, r, 10, "w", rows, 3, value_of=lambda g: g)
    # step 12 torn (rank 2 missing)
    for r in range(2):
        _write_step(ck, r, 12, "w", rows, 3, value_of=lambda g: g)
    t3 = {"w": _FakeTable(rows, 3, 0)}
    assert elastic.find_live_step(ck, t3, 3) == 10
    # a 2-way caller rejects every 3-way layout
    t2 = {"w": _FakeTable(rows, 2, 0)}
    assert elastic.find_live_step(ck, t2, 2) is None
    # a never-checkpointed standby (required but dir-less) must not
    # veto recovery: its home range lives in live ranks' files
    t4 = {"w": _FakeTable(rows, 4, 0)}
    assert elastic.find_live_step(ck, t3, 3,
                                  required={0, 1, 2, 3}) == 10
    # ...but a world with NO dirs at all has nothing to restore from
    assert elastic.find_live_step(str(tmp_path / "empty"), t4, 4) \
        is None


# --- the successor path's edge cases (control-plane PR): a coordinator
# lease successor calls find_live_step with required = live ∪ {corpse}
# and must get a DETERMINISTIC verdict — a step, or None — never a hang
# and never a torn pick.
def test_find_live_step_zero_complete_steps_is_none_not_hang(tmp_path):
    """Rank dirs exist but no step is common to every required rank
    (disjoint saves — e.g. a fleet killed before its first aligned
    boundary): the verdict is None, the caller's honest rstep=-1
    gang-restart path, not a scan that spins or picks a torn step."""
    ck = str(tmp_path)
    rows = 24
    _write_step(ck, 0, 5, "w", rows, 3, value_of=lambda g: g)
    _write_step(ck, 1, 10, "w", rows, 3, value_of=lambda g: g)
    _write_step(ck, 2, 15, "w", rows, 3, value_of=lambda g: g)
    t3 = {"w": _FakeTable(rows, 3, 0)}
    assert elastic.find_live_step(ck, t3, 3) is None
    # a step dir without its manifest is a torn save-in-progress: it
    # must not count as held (the crash-mid-save case)
    os.makedirs(os.path.join(ck, "rank0", "step_0000000010"),
                exist_ok=True)
    os.makedirs(os.path.join(ck, "rank2", "step_0000000010"),
                exist_ok=True)
    assert elastic.find_live_step(ck, t3, 3) is None


def test_find_live_step_partial_corpse_falls_back_to_older(tmp_path):
    """The newest step is complete on the SURVIVORS but partial on the
    corpse (it died mid-save: manifest written, table file torn away).
    The verdict must fall back to the newest step the corpse's files
    genuinely complete — restoring its blocks from a half-written step
    would be silent corruption."""
    ck = str(tmp_path)
    rows = 24
    for r in range(3):
        _write_step(ck, r, 10, "w", rows, 3, value_of=lambda g: g)
        _write_step(ck, r, 15, "w", rows, 3, value_of=lambda g: g)
    # the corpse (rank 2) holds step 15's manifest but not its table
    os.unlink(os.path.join(ck, "rank2", "step_0000000015", "w.npz"))
    t3 = {"w": _FakeTable(rows, 3, 0)}
    assert elastic.find_live_step(ck, t3, 3,
                                  required={0, 1, 2}) == 10
    # survivors alone would be happy with 15 — the corpse's membership
    # in `required` is what forces the honest older verdict
    assert elastic.find_live_step(ck, t3, 3, required={0, 1}) == 15


def test_find_live_step_accepts_rebalance_overlay_checkpoint(tmp_path):
    """A checkpoint saved mid-rebalance (routing epoch > 0, overlay
    metadata + xtra sections in every shard) still fits the slab
    layout: the scan must return it — the death path then reads block
    state THROUGH the overlay via load_block_state, which is exactly
    the save-time-owner indirection the xtra sections exist for."""
    ck = str(tmp_path)
    rows = 24
    old_n, blk = 3, 2
    rng = np.random.default_rng(3)
    w = rng.normal(size=(rows, 2)).astype(np.float32)
    m = rng.normal(size=(rows, 2)).astype(np.float32)
    _write_rebalanced_world(ck, 20, w, m, old_n, blk, overlay={0: 2})
    t3 = {"w": _FakeTable(rows, 3, 0)}
    assert elastic.find_live_step(ck, t3, 3,
                                  required={0, 1, 2}) == 20
    # and the block the overlay moved restores from its save-time
    # owner's xtra — the slab's dead copy never leaks
    old_sz = -(-rows // old_n)
    st = elastic.load_block_state(ck, 20, "w", 0, 0, blk, 0, old_sz,
                                  blk)
    np.testing.assert_array_equal(st["w"], w[:blk])


@pytest.mark.slow
def test_elastic_shrink_then_grow_end_to_end(tmp_path):
    """The drill: 3 ranks train 20 iters with shard checkpoints; a
    2-rank relaunch reshards — its pure restore (iters == saved step)
    reproduces the same-size restore's parameter sum; continued 2-rank
    training resumes from the step and keeps replica agreement; a
    REGROW back to 3 ranks must prefer the 2-world's NEWER checkpoint
    over the stale-but-layout-compatible 3-world steps the surviving
    ranks still hold (the silent-rollback hazard)."""
    ck = str(tmp_path / "eck")
    base = ["--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--batch", "128", "--checkpoint-dir", ck,
            "--checkpoint-every", "5"]

    def run(n, iters):
        return launch.run_local_job(
            n, [sys.executable, "-m", APP] + base + ["--iters",
                                                     str(iters)],
            base_port=None,
            env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
            timeout=240.0)

    res3 = run(3, 20)
    assert all(r["event"] == "done" and r["clock"] == 20 for r in res3)

    # oracle: a SAME-SIZE pure restore (iters == saved step → zero
    # training) reports the snapshot's partition-invariant parameter
    # sum. (The live run's final sum is NOT that oracle: peers' in-
    # flight pushes land after the step-20 save and before finalize.)
    res3r = run(3, 20)
    for r in res3r:
        assert r["event"] == "done"
        assert r["resumed_from"] == 20, r
    snap_sum = res3r[0]["param_sum"]

    # SHRINK, pure restore: 2 ranks reshard the same snapshot — the sum
    # must match the same-size restore up to float summation order
    res2 = run(2, 20)
    for r in res2:
        assert r["event"] == "done"
        assert r["resumed_from"] == 20, r
    assert abs(res2[0]["param_sum"] - snap_sum) < 1e-3, (
        res2[0]["param_sum"], snap_sum)

    # SHRINK, continue: training picks up at 20 and carries to 30 with
    # replica agreement and the SSP bound intact. (30, not further: the
    # retention GC keeps 3 steps per dir, and the REGROW below needs the
    # surviving ranks to still hold a 3-layout step alongside the
    # 2-world's newer ones.)
    res2b = run(2, 30)
    for r in res2b:
        assert r["event"] == "done"
        assert r["resumed_from"] == 20, r
        assert r["clock"] == 30
        assert r["max_skew_seen"] <= 3
    assert abs(res2b[0]["param_sum"] - res2b[1]["param_sum"]) < 1e-4

    # REGROW to 3 — the silent-rollback hazard, exercised for real: all
    # three ranks still hold 3-layout step 20 (ranks 0-1 kept it through
    # the GC, rank 2 untouched), so the same-size negotiation agrees on
    # 20 — but the 2-world trained to 30, and restoring 20 would roll
    # training back and prune the newer checkpoint. The newest complete
    # checkpoint (30, 2-world) must win.
    res3b = run(3, 50)
    for r in res3b:
        assert r["event"] == "done"
        assert r["resumed_from"] == 30, r
        assert r["clock"] == 50
    assert abs(res3b[0]["param_sum"] - res3b[2]["param_sum"]) < 1e-4


@pytest.mark.slow
def test_elastic_resume_wd_flagship(tmp_path):
    """Elastic resume on the FLAGSHIP workload: three partitioned tables
    at once (hashed wide + field-embedding SparseTables, dense deep
    tower) reshard 3 → 2 through the same generic path; training
    continues with replica agreement and a sane AUC."""
    ck = str(tmp_path / "wdck")
    base = ["--exec", "multiproc", "--consistency", "ssp",
            "--staleness", "2", "--num_slots", "16384",
            "--batch_size", "256", "--checkpoint_dir", ck,
            "--checkpoint_every", "5"]
    app = "minips_tpu.apps.wide_deep_example"

    def run(n, iters):
        return launch.run_local_job(
            n, [sys.executable, "-m", app] + base + ["--num_iters",
                                                     str(iters)],
            base_port=None,
            env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
            timeout=240.0)

    res3 = run(3, 20)
    assert all(r["event"] == "done" and r["clock"] == 20 for r in res3)

    res2 = run(2, 40)
    for r in res2:
        assert r["event"] == "done"
        assert r["resumed_from"] == 20, r
        assert r["clock"] == 40
        assert r["auc"] > 0.6, r["auc"]
    fps = [r["param_fingerprint"] for r in res2]
    assert max(fps) - min(fps) < 1e-4, fps
