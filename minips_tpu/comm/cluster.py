"""Multi-host bootstrap — the rebuild of the launch-script + mailbox bind.

The reference spawns one process per node via ssh with ``--my_id i`` and a
hostfile; the mailbox binds zmq ROUTER sockets (SURVEY.md §1 L7, §3.1). On
TPU pods the moral equivalent is ``jax.distributed.initialize`` — the
coordination service wires processes into one JAX runtime, after which the
*data plane* is XLA collectives over ICI/DCN and needs no sockets at all
(SURVEY.md §2.3). Only the SSP clock gossip + heartbeats keep a socket bus
(minips_tpu/comm/bus.py).

The launcher (minips_tpu/launch.py) exports ``MINIPS_COORDINATOR`` +
``MINIPS_PROC_ID``/``MINIPS_NUM_PROCS`` for every rank, so a worker that
calls :func:`initialize` with no arguments joins the job it was spawned
into; single-process (this sandbox, no launcher) everything degrades to
no-ops. The 2-process loopback smoke (tests/test_multihost.py) runs this
exact path on the CPU backend — the "threads as nodes" trick one level up:
processes as hosts.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the cluster. Mirrors the reference's ``--my_id`` flag surface:
    pass explicit args, or rely on the launcher's ``MINIPS_*`` env (or
    JAX's own ``JAX_COORDINATOR_ADDRESS``); single-process if none is
    present. Returns True iff a multi-process runtime was initialized.

    On the CPU loopback smoke each process fakes its local devices via
    ``xla_force_host_platform_device_count`` BEFORE calling this (see
    apps/multihost_example.py); jax.distributed then registers them with
    the coordination service automatically.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("MINIPS_COORDINATOR") \
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "MINIPS_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["MINIPS_NUM_PROCS"])
    if process_id is None and "MINIPS_PROC_ID" in os.environ:
        process_id = int(os.environ["MINIPS_PROC_ID"])
    if coordinator_address is None:
        return False  # single-process (no launcher, no JAX cluster env)
    if num_processes is not None and num_processes <= 1:
        return False  # launcher run with --n 1
    # num_processes/process_id may legitimately still be None here (pure
    # JAX-standard env: JAX_NUM_PROCESSES/JAX_PROCESS_ID) — pass through
    # and let jax.distributed resolve them itself rather than silently
    # degrading a pod job to N independent single-process runs
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def barrier(name: str = "minips_barrier", timeout_s: int = 120) -> None:
    """Cluster-wide barrier (reference Engine::Barrier, SURVEY.md §3.4)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def shutdown() -> None:
    """Leave the cluster COORDINATED: barrier, then disconnect from the
    coordination service. Without the explicit disconnect, ranks race at
    interpreter exit — the coordinator (process 0) can die while a
    follower's error-polling thread is still attached, and that follower
    then terminates itself with a fatal 'leader task died' error AFTER
    its work (and its result line) completed: a clean run reported as
    rc!=0. Call this as the last cluster op of every multi-process job;
    single-process it is a no-op."""
    if jax.process_count() == 1:
        return
    barrier("minips_shutdown")
    jax.distributed.shutdown()


def global_batch(mesh, batch: dict, axis: str = "data",
                 spec=None) -> dict:
    """Per-process local batch leaves → ONE global array dict — the
    multi-host feeding step (each host contributes the slice it loaded;
    SURVEY.md §1 L5 "data shards per worker"). Default: rows sharded
    along ``axis`` (axis 0); pass ``spec`` (a PartitionSpec, or a dict of
    them keyed like ``batch``) to shard other axes — e.g.
    ``P(None, "data")`` feeds per-process SEQUENCE slices for ring-
    attention sequence parallelism. Single-process this is a plain
    device_put with the same sharding."""
    from jax.sharding import NamedSharding, PartitionSpec

    def sharding_for(k):
        if isinstance(spec, dict):
            if k not in spec:  # a typo'd key must not silently row-shard
                raise KeyError(
                    f"global_batch spec has no entry for batch key {k!r} "
                    f"(spec keys: {sorted(spec)})")
            s = spec[k]
        else:
            s = spec
        return NamedSharding(mesh, s if s is not None
                             else PartitionSpec(axis))

    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding_for(k))
                for k, v in batch.items()}
    return {k: jax.make_array_from_process_local_data(sharding_for(k), v)
            for k, v in batch.items()}


def host_copy(x):
    """Full host value of a (possibly non-addressable, multi-process
    sharded) array — the multi-host-safe ``np.asarray``. Collective: every
    process must call it on the same array."""
    import numpy as np

    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
