// Native libsvm/Criteo-text parser — the rebuild of the reference's C++
// data-loading layer (SURVEY.md §2 "Data loading": AbstractDataLoader +
// line parsers feeding per-worker sample stores; §2.1 item 6 marks this as
// the one host-side component where native code earns its keep for
// samples/sec targets).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Two-pass contract over a whole file:
//   pass 1: libsvm_count()  -> rows + max features/row
//   pass 2: libsvm_parse()  -> fills caller-allocated padded arrays
//           y[N], idx[N*W], val[N*W], mask[N*W]  (row-major, zero padded)
// Parsing is hand-rolled (no iostream/sscanf): one linear scan, no
// allocation per token.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  bool ok = false;
  explicit FileBuf(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n < 0) { std::fclose(f); return; }
    data = static_cast<char*>(std::malloc(static_cast<size_t>(n) + 1));
    if (!data) { std::fclose(f); return; }
    size = std::fread(data, 1, static_cast<size_t>(n), f);
    data[size] = '\0';
    std::fclose(f);
    ok = true;
  }
  ~FileBuf() { std::free(data); }
};

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

// Fast non-locale float parse for "123", "-1", "0.5", "1e-3" style tokens.
inline float parse_float(const char*& p) {
  char* end = nullptr;
  float v = std::strtof(p, &end);
  p = end;
  return v;
}

inline long parse_long(const char*& p) {
  char* end = nullptr;
  long v = std::strtol(p, &end, 10);
  p = end;
  return v;
}

}  // namespace

extern "C" {

// Returns 0 on success; fills n_rows and max_width (max nnz on any row).
int libsvm_count(const char* path, int64_t* n_rows, int64_t* max_width) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  int64_t rows = 0, maxw = 0;
  const char* p = fb.data;
  const char* endp = fb.data + fb.size;
  while (p < endp) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    p = skip_ws(p);
    if (p < line_end) {
      ++rows;
      int64_t w = 0;
      for (const char* q = p; q < line_end; ++q)
        if (*q == ':') ++w;
      if (w > maxw) maxw = w;
    }
    p = line_end + 1;
  }
  *n_rows = rows;
  *max_width = maxw;
  return 0;
}

// Fills y[N], idx[N*W], val[N*W], mask[N*W]; width W truncates longer rows.
// Labels in {-1,1} are normalized to {0,1}; other labels pass through.
int libsvm_parse(const char* path, int64_t n_rows, int64_t width,
                 float* y, int32_t* idx, float* val, float* mask) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  std::memset(idx, 0, sizeof(int32_t) * static_cast<size_t>(n_rows * width));
  std::memset(val, 0, sizeof(float) * static_cast<size_t>(n_rows * width));
  std::memset(mask, 0, sizeof(float) * static_cast<size_t>(n_rows * width));
  const char* p = fb.data;
  const char* endp = fb.data + fb.size;
  int64_t r = 0;
  bool saw_negative_label = false;
  while (p < endp && r < n_rows) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    p = skip_ws(p);
    if (p < line_end) {
      float label = parse_float(p);
      if (label < 0.0f) saw_negative_label = true;
      y[r] = label;
      int64_t c = 0;
      while (p < line_end && c < width) {
        p = skip_ws(p);
        if (p >= line_end || *p == '\n') break;
        long feature = parse_long(p);
        if (*p != ':') break;  // malformed token: stop this row
        ++p;
        float v = parse_float(p);
        int64_t off = r * width + c;
        idx[off] = static_cast<int32_t>(feature);
        val[off] = v;
        mask[off] = 1.0f;
        ++c;
      }
      ++r;
    }
    p = line_end + 1;
  }
  if (saw_negative_label) {  // {-1,1} -> {0,1} (a9a convention)
    for (int64_t i = 0; i < n_rows; ++i) y[i] = y[i] > 0.0f ? 1.0f : 0.0f;
  }
  return r == n_rows ? 0 : 2;
}

}  // extern "C"
