from minips_tpu.comm.bus import ControlBus  # noqa: F401
from minips_tpu.comm.heartbeat import HeartbeatMonitor  # noqa: F401

# The optional bus layers (comm/chaos.py ChaosBus, comm/reliable.py
# ReliableChannel) are deliberately NOT re-exported here: make_bus
# imports them lazily only when MINIPS_CHAOS / MINIPS_RELIABLE arm
# them, and the plain bus path must not depend on their import.
