"""The jax_compat quarantine's CI contract (satellite): the manifest of
pre-existing jax-version failures (tests/jax_compat_failures.txt) may
only SHRINK — fixing a test deletes its line; a new failure must never
hide behind the marker. The ceiling below is the seed count measured
the day the quarantine landed; anyone deleting lines should lower it
to match (it is an upper bound, so forgetting merely loosens nothing
that matters — adding a line is what it catches)."""

from __future__ import annotations

import subprocess
import sys

from tests.conftest import load_jax_compat_manifest

# the byte-identical failure set every Tier-1 run since seed carried
# (CHANGES.md PR1-PR5: "failure set identical, 146 pre-existing
# jax-version failures") — the manifest may never grow past it. PR7
# fixed 63 for real (the utils/jaxcompat.py shard_map/typeof shims:
# checkpoint, cssp, dense-table, ssp_spmd, engine, mnist, transformer,
# flash-attention, apps); PR12's pcast shim (identity on pre-vma jax)
# fixed 15 more (ring_attention, gpipe, ring-flash); PR14 registered
# the standard shard_map replication rules for the `name` primitive
# (checkpoint_name is an identity marker — the old check_rep tracer
# just lacked the rule the vma tracer ships built in), fixing 23 more
# (a2a, pipeline, tensor-parallel, transformer remat/rope/gqa, lm
# apps); PR 15's `jaxcompat.sds` shim (ShapeDtypeStruct's vma= kwarg
# dropped on pre-vma jax — the same identity argument as pcast: the
# old tracer carries no varying-axis types for the annotation to
# change) fixed 15 more flash-kernel entries; PR 17 fixed the
# ring-flash SPMD PartitionId compile drift for real (causal=False
# left the axis_index-derived offsets dead inside the kernel, so the
# lowered partition-id had no dataflow path to a manual-sharded
# operand and sharding propagation could not mark it {manual} — the
# ring now mints axis_index only when masking consumes it) — the
# ceiling only moves down. The 2 left are deeper remat/compose
# mismatches.
SEED_FAILURE_COUNT = 2


def test_manifest_only_shrinks():
    entries = load_jax_compat_manifest()
    assert len(entries) <= SEED_FAILURE_COUNT, (
        f"jax_compat manifest grew to {len(entries)} entries "
        f"(seed ceiling {SEED_FAILURE_COUNT}): a NEW failure is a "
        "regression to fix, never a line to quarantine")


def test_manifest_has_no_duplicates_and_sane_nodeids():
    entries = load_jax_compat_manifest()
    assert entries, "manifest missing or empty — quarantine disarmed"
    assert len(entries) == len(set(entries)), "duplicate manifest lines"
    for e in entries:
        assert e.startswith("tests/") and "::" in e, (
            f"manifest line is not a pytest nodeid: {e!r}")


def test_manifest_entries_match_collected_tests():
    """Every manifest FILE must still exist and collect — a deleted or
    renamed test leaves a dead manifest line that silently shrinks the
    quarantine's coverage claim. (File-level check: a full collection
    here would re-pay the suite's import cost.)"""
    import os

    here = os.path.dirname(__file__)
    files = {e.split("::", 1)[0] for e in load_jax_compat_manifest()}
    for f in sorted(files):
        assert os.path.exists(os.path.join(here, "..", f)), (
            f"manifest names a test file that no longer exists: {f}")


def test_quarantined_test_reports_xfail_not_failed():
    """End-to-end: running ONE manifested test under the tier-1 flags
    reports xfailed (clean signal), not failed."""
    entries = load_jax_compat_manifest()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", entries[0], "-q",
         "-p", "no:cacheprovider", "--no-header", "-rxX"],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-2000:]
    assert "xfailed" in out or "xpassed" in out, out[-2000:]
