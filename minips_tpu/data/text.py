"""Byte-level text loader for the LM family.

The sandbox has no network, so there is no tokenizer download path — any
local text/binary file becomes LM training data at the byte level
(vocab 256), the honest equivalent of the reference's "read the local
shard" loaders (SURVEY.md §2 "Data loading"). Windows are sampled with a
stride so a small file still yields many distinct sequences.
"""

from __future__ import annotations

import numpy as np


def read_bytes(path: str) -> np.ndarray:
    """File -> uint8 token stream."""
    with open(path, "rb") as f:
        return np.frombuffer(f.read(), dtype=np.uint8)


def byte_windows(tokens: np.ndarray, seq_len: int, *,
                 max_windows: int | None = None,
                 stride: int | None = None) -> dict:
    """Token stream -> {"tokens": [n, seq_len+1] int32} next-token windows.

    ``stride`` defaults to seq_len // 2 (half-overlapping windows); the
    stream must hold at least one full window.
    """
    need = seq_len + 1
    if len(tokens) < need:
        raise ValueError(f"need at least {need} tokens, file has "
                         f"{len(tokens)}")
    stride = stride or max(seq_len // 2, 1)
    starts = np.arange(0, len(tokens) - need + 1, stride)
    if max_windows is not None:
        starts = starts[:max_windows]
    idx = starts[:, None] + np.arange(need)[None, :]
    return {"tokens": tokens[idx].astype(np.int32)}


def read_lm_file(path: str, seq_len: int, *,
                 max_windows: int | None = None) -> dict:
    """Convenience: file path -> LM windows dict."""
    return byte_windows(read_bytes(path), seq_len, max_windows=max_windows)
