from minips_tpu.ckpt.checkpoint import Checkpointer  # noqa: F401
