// Shared plumbing for the native readers: whole-file buffer, line-aligned
// chunking, and a tiny parallel-for — the pieces that turn the single-scan
// parsers into multi-threaded ones (SURVEY.md §7.4.4: the input pipeline
// must keep a pod fed; parsing parallelizes embarrassingly once chunk
// boundaries land on line starts).
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace minips {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  bool ok = false;
  explicit FileBuf(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n < 0) { std::fclose(f); return; }
    data = static_cast<char*>(std::malloc(static_cast<size_t>(n) + 1));
    if (!data) { std::fclose(f); return; }
    size = std::fread(data, 1, static_cast<size_t>(n), f);
    data[size] = '\0';
    std::fclose(f);
    ok = true;
  }
  ~FileBuf() { std::free(data); }
  FileBuf(const FileBuf&) = delete;
  FileBuf& operator=(const FileBuf&) = delete;
};

// n_chunks+1 boundaries into [data, data+size); every boundary except the
// first sits just past a '\n', so chunks hold whole lines. Chunks may be
// empty when lines are long relative to size/n_chunks.
inline std::vector<const char*> line_chunks(const char* data, size_t size,
                                            int n_chunks) {
  std::vector<const char*> b;
  b.reserve(static_cast<size_t>(n_chunks) + 1);
  const char* endp = data + size;
  b.push_back(data);
  for (int i = 1; i < n_chunks; ++i) {
    const char* target = data + size * static_cast<size_t>(i) /
                                    static_cast<size_t>(n_chunks);
    if (target < b.back()) target = b.back();
    const char* nl = static_cast<const char*>(std::memchr(
        target, '\n', static_cast<size_t>(endp - target)));
    b.push_back(nl ? nl + 1 : endp);
  }
  b.push_back(endp);
  return b;
}

template <typename Fn>
inline void parallel_for(int n, Fn&& fn) {
  if (n <= 1) { for (int i = 0; i < n; ++i) fn(i); return; }
  std::vector<std::thread> ts;
  ts.reserve(static_cast<size_t>(n));
  for (int i = 0; i < n; ++i) ts.emplace_back([&fn, i] { fn(i); });
  for (auto& t : ts) t.join();
}

inline int clamp_threads(int n_threads) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  if (n_threads <= 0) n_threads = hw;
  return n_threads > 64 ? 64 : n_threads;
}

}  // namespace minips
