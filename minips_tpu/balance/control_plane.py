"""Coordinator lease + deterministic succession — half one of the
production control plane (ROADMAP item 3).

Until this PR the coordinator was a RANK: ``Membership`` and
``Rebalancer`` both hardcoded rank 0 as the planner, and a heartbeat-dead
verdict against it was the documented unrecoverable case — exit 42, gang
restart — even though every survivor already held the state a successor
needs (the membership table from the broadcast protocol, heat reports
re-gossiped every rbH tick, the newest complete checkpoint step via
``ckpt/elastic.find_live_step``). This module makes the coordinator a
LEASE over that rank space instead.

**The succession rule — no election wire protocol.** The lease is a
``(term, holder)`` pair every rank tracks. On a heartbeat-dead verdict
against the holder, every rank advances the lease LOCALLY and
identically: term += 1, holder = the lowest-ranked live rank
(:func:`successor_of`). The heartbeat verdict plus the membership table
already give every rank the same inputs, so no ballots ride the wire —
the "election" is a pure function, exactly like ``KillSpec.resolve``.
The successor then reconstructs coordinator state from what survivors
re-advertise: heat reports re-arrive on the next ``rbH`` tick (the
rebalancer re-gossips every clock), the membership table was never
centralized to begin with, and the newest complete step is re-derived
from the shared checkpoint dir when the death plan needs it. In-flight
``mbJ``/``mbQ`` conversations re-target automatically because their
retry loops address ``membership.coord``, which succession updates.

**Fencing — why the term exists.** A partitioned ex-coordinator that
comes back must not be able to broadcast a conflicting plan. Two
complementary fences:

- RECEIVE fence (:meth:`CoordinatorLease.admit`): every coordinator
  broadcast (``rbP`` plans, ``mbA`` admits, ``mbD`` verdicts) is stamped
  with the issuer's ``lt``/``lh``; receivers DROP frames whose term is
  below their own (counted in ``fenced``). A stale ex-coordinator's
  post-partition plan dies at every receiver.
- SELF fence (:meth:`CoordinatorLease.observe`): lease stamps also ride
  every heartbeat (``HeartbeatMonitor.payload_extra``), max-merged on
  receive — the returning ex-coordinator learns the newer term from the
  first beat it hears and stops planning on its own (``_coord_step``
  checks ``rank != coord``), before it can even try.

The lease holder at term 0 is rank 0 (the launch-time default), so an
armed-but-idle fleet behaves exactly as before — the lockstep harness
pins armed-idle bitwise-equal to off. The successor's ENDPOINT needs no
renegotiation either: the control bus is a full mesh wired at spawn
(``launch.bus_endpoint_of`` maps the membership-table rank back to the
address the launcher advertised), so succession is a rank-id change, not
a respawn.

What still gang-restarts, honestly: a holder death with NO live rank
left to succeed, and a successor that finds no complete checkpoint for
the corpse's owned blocks (``rstep=-1`` — the simultaneous
coordinator+owner death with no checkpoint case docs/fault_tolerance.md
names). The lease narrows the unrecoverable set; it does not pretend to
empty it.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

from minips_tpu.obs import flight as _fl

__all__ = ["CoordinatorLease", "successor_of"]


def successor_of(live: Iterable[int]) -> Optional[int]:
    """THE succession rule: the lowest-ranked live rank, or None when
    nobody is left to hold the lease. A pure function of the membership
    table so every rank computes the same successor without a ballot."""
    live = set(live)
    return min(live) if live else None


class CoordinatorLease:
    """``(term, holder)`` with max-merge observation and stale-term
    fencing — one instance per rank, shared by the membership plane and
    the rebalancer's plan wire. Thread-safe: the monitor's sweep thread
    advances it while bus receive threads admit/observe."""

    def __init__(self, initial_holder: int = 0):
        self._lock = threading.Lock()
        self.term = 0
        self.holder = int(initial_holder)
        self.successions = 0   # times THIS rank advanced the lease
        self.fenced = 0        # stale-term frames dropped at this rank

    # ------------------------------------------------------------- stamps
    def stamp(self) -> dict:
        """The wire stamp coordinator broadcasts (and every heartbeat)
        carry: current term + holder. Receivers :meth:`admit` against
        the term and :meth:`observe` the pair."""
        with self._lock:
            return {"lt": self.term, "lh": self.holder}

    def current(self) -> tuple[int, int]:
        with self._lock:
            return self.term, self.holder

    # ------------------------------------------------------------- fences
    def admit(self, payload: dict) -> bool:
        """The receive fence: False (and counted) for a frame stamped
        with a STALE term — a partitioned ex-coordinator's plan must die
        at every receiver. Unstamped frames pass: they predate the lease
        (mixed fleet) or come from unit rigs that never armed it."""
        lt = payload.get("lt")
        if lt is None:
            return True
        with self._lock:
            if int(lt) < self.term:
                self.fenced += 1
                term = self.term
            else:
                return True
        # the fence DECISION and its why (stale term vs held term) into
        # the black box — rare by construction (a partitioned
        # ex-coordinator's tail), so the record is off the hot path
        _fl.record("lease_fenced",
                   {"lt": int(lt), "lh": payload.get("lh"),
                    "term": term})
        return False

    def observe(self, payload: dict) -> bool:
        """Max-merge a term seen on the wire (heartbeat stamps, plan
        stamps). Returns True when the payload taught us a NEWER term —
        the caller re-targets its coordinator view; an ex-holder that
        gets True here has just been fenced out of the role it thinks it
        still holds (the partition-return self fence)."""
        lt, lh = payload.get("lt"), payload.get("lh")
        if lt is None or lh is None:
            return False
        with self._lock:
            if int(lt) > self.term:
                self.term, self.holder = int(lt), int(lh)
                return True
        return False

    # --------------------------------------------------------- succession
    def succeed(self, dead_holder: int, live: Iterable[int]) -> Optional[int]:
        """Advance the lease past a dead holder: term += 1, holder = the
        lowest-ranked live rank. Returns the new holder, the current
        holder unchanged when ``dead_holder`` no longer holds the lease
        (a second verdict racing the first rank's advance), or None when
        no live rank remains (genuinely unrecoverable)."""
        with self._lock:
            if int(dead_holder) != self.holder:
                return self.holder
            succ = successor_of(set(live) - {int(dead_holder)})
            if succ is None:
                return None
            self.term += 1
            self.holder = int(succ)
            self.successions += 1
            return self.holder

    def stats(self) -> dict:
        with self._lock:
            return {"term": self.term, "holder": self.holder,
                    "successions": self.successions,
                    "fenced": self.fenced}
