"""SparseTable: hashing, gather/scatter-add, per-row updaters (SURVEY.md §7.1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.tables.sparse import SparseTable, hash_to_slots


def test_hash_range_and_determinism():
    keys = jnp.arange(10_000)
    slots = hash_to_slots(keys, 1024)
    s = np.asarray(slots)
    assert s.min() >= 0 and s.max() < 1024
    np.testing.assert_array_equal(s, np.asarray(hash_to_slots(keys, 1024)))
    # rough uniformity: all slots hit for 10k keys into 1k slots
    assert len(np.unique(s)) > 900


def test_pull_shape(mesh8):
    t = SparseTable(256, 8, mesh8)
    rows = t.pull(jnp.arange(12))
    assert rows.shape == (12, 8)
    rows2 = t.pull(jnp.arange(12).reshape(3, 4))
    assert rows2.shape == (3, 4, 8)


def test_push_sgd_accumulates_duplicates(mesh8):
    t = SparseTable(256, 4, mesh8, updater="sgd", lr=1.0, init_scale=0.0)
    keys = jnp.array([7, 7, 3])
    grads = jnp.stack([jnp.ones(4), 2 * jnp.ones(4), 3 * jnp.ones(4)])
    t.push(keys, grads)
    got7 = np.asarray(t.pull(jnp.array([7])))[0]
    got3 = np.asarray(t.pull(jnp.array([3])))[0]
    np.testing.assert_allclose(got7, -3.0)  # 1+2 summed then -lr*
    np.testing.assert_allclose(got3, -3.0)


def test_push_adagrad_matches_oracle(mesh8):
    lr, acc0 = 0.5, 0.1
    t = SparseTable(128, 2, mesh8, updater="adagrad", lr=lr,
                    init_scale=0.0, adagrad_init=acc0)
    keys = jnp.array([5, 5, 9])
    grads = jnp.array([[1.0, 0.0], [1.0, 0.0], [2.0, 2.0]])
    t.push(keys, grads)
    # slot for key 5 sees summed grad [2, 0]; slot for 9 sees [2, 2]
    acc5 = acc0 + np.array([4.0, 0.0])
    exp5 = -lr * np.array([2.0, 0.0]) / np.sqrt(acc5)
    acc9 = acc0 + np.array([4.0, 4.0])
    exp9 = -lr * np.array([2.0, 2.0]) / np.sqrt(acc9)
    np.testing.assert_allclose(np.asarray(t.pull(jnp.array([5])))[0], exp5,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(t.pull(jnp.array([9])))[0], exp9,
                               rtol=1e-5)


def test_adagrad_second_push_uses_accumulator(mesh8):
    lr, acc0 = 1.0, 1.0
    t = SparseTable(64, 1, mesh8, updater="adagrad", lr=lr,
                    init_scale=0.0, adagrad_init=acc0)
    k = jnp.array([3])
    g = jnp.array([[3.0]])
    t.push(k, g)   # acc: 1+9=10, step -3/sqrt(10)
    t.push(k, g)   # acc: 10+9=19, step -3/sqrt(19)
    expect = -3.0 / np.sqrt(10.0) - 3.0 / np.sqrt(19.0)
    np.testing.assert_allclose(np.asarray(t.pull(k))[0, 0], expect, rtol=1e-5)


def test_state_dict_roundtrip(mesh8):
    t = SparseTable(64, 4, mesh8, updater="adagrad", seed=1)
    t.push(jnp.array([1, 2]), jnp.ones((2, 4)))
    s = t.state_dict()
    t2 = SparseTable(64, 4, mesh8, updater="adagrad", seed=2)
    t2.load_state_dict(s)
    np.testing.assert_allclose(np.asarray(t2.emb), np.asarray(t.emb))


def test_adagrad_zero_init_zero_grad_no_nan(mesh8):
    """Regression: adagrad_init=0 + zero grad dim must not scatter NaN."""
    t = SparseTable(64, 2, mesh8, updater="adagrad", lr=0.5,
                    init_scale=0.0, adagrad_init=0.0)
    t.push(jnp.array([5]), jnp.array([[1.0, 0.0]]))
    row = np.asarray(t.pull(jnp.array([5])))[0]
    assert np.isfinite(row).all()
    assert row[1] == 0.0 and row[0] < 0.0


def test_row_adagrad_dense_and_sorted_paths_agree():
    """The dense-accumulate fast path and the sort-dedup big-table path
    are the same update, bit-for-bit within float tolerance — duplicates,
    untouched rows, accumulator state and all."""
    import numpy as np

    from minips_tpu.ops.sparse_update import row_adagrad

    rng = np.random.default_rng(3)
    S, D = 64, 4
    emb = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    accum = jnp.asarray(rng.uniform(0, 2, size=(S, D)), jnp.float32)
    slots = jnp.asarray(rng.integers(0, S, size=(32,)))  # many duplicates
    grads = jnp.asarray(rng.normal(size=(32, D)), jnp.float32)

    e_d, a_d = row_adagrad(emb, accum, slots, grads, 0.1, prefer_dense=True)
    e_s, a_s = row_adagrad(emb, accum, slots, grads, 0.1, prefer_dense=False)
    np.testing.assert_allclose(np.asarray(e_d), np.asarray(e_s), atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_d), np.asarray(a_s), atol=1e-5)
    # untouched rows identical to the originals on both paths
    untouched = np.setdiff1d(np.arange(S), np.asarray(slots))
    np.testing.assert_array_equal(np.asarray(e_d)[untouched],
                                  np.asarray(emb)[untouched])
    np.testing.assert_array_equal(np.asarray(a_d)[untouched],
                                  np.asarray(accum)[untouched])


def test_row_adam_matches_manual_oracle():
    """One push with duplicate keys == textbook Adam (t=1) applied to the
    per-row SUMMED gradients; untouched rows completely untouched (lazy)."""
    import numpy as np

    from minips_tpu.ops.sparse_update import row_adam

    rng = np.random.default_rng(0)
    S, D = 32, 4
    emb = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    m = jnp.zeros((S, D)); v = jnp.zeros((S, D))
    steps = jnp.zeros((S,), jnp.int32)
    slots = jnp.asarray([3, 5, 3])             # 3 pushed twice
    grads = jnp.asarray(rng.normal(size=(3, D)), jnp.float32)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8

    e1, m1, v1, s1 = row_adam(emb, m, v, steps, slots, grads, lr)
    g3 = np.asarray(grads[0] + grads[2])       # summed duplicates
    for row, g in [(3, g3), (5, np.asarray(grads[1]))]:
        m_exp = (1 - b1) * g
        v_exp = (1 - b2) * g * g
        upd = lr * (m_exp / (1 - b1)) / (np.sqrt(v_exp / (1 - b2)) + eps)
        np.testing.assert_allclose(np.asarray(e1[row]),
                                   np.asarray(emb[row]) - upd, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m1[row]), m_exp, rtol=1e-6)
        assert int(s1[row]) == 1
    untouched = [i for i in range(S) if i not in (3, 5)]
    np.testing.assert_array_equal(np.asarray(e1)[untouched],
                                  np.asarray(emb)[untouched])
    np.testing.assert_array_equal(np.asarray(m1)[untouched], 0.0)
    np.testing.assert_array_equal(np.asarray(s1)[untouched], 0)


def test_row_adam_dense_and_sorted_paths_agree():
    import numpy as np

    from minips_tpu.ops.sparse_update import row_adam

    rng = np.random.default_rng(4)
    S, D = 64, 4
    emb = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    m = jnp.asarray(rng.normal(size=(S, D)) * 0.1, jnp.float32)
    v = jnp.asarray(rng.uniform(0, 0.1, size=(S, D)), jnp.float32)
    steps = jnp.asarray(rng.integers(0, 5, size=S), jnp.int32)
    slots = jnp.asarray(rng.integers(0, S, size=(48,)))
    grads = jnp.asarray(rng.normal(size=(48, D)), jnp.float32)
    outs = [row_adam(emb, m, v, steps, slots, grads, 0.01,
                     prefer_dense=pd) for pd in (True, False)]
    for a, b in zip(*outs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


def test_sparse_adam_trains_and_checkpoints(mesh8, tmp_path):
    """SparseTable(updater='adam') end to end: fused-step LR converges,
    moments+steps survive a checkpoint roundtrip bit-for-bit."""
    import numpy as np

    from minips_tpu.ckpt.checkpoint import Checkpointer
    from minips_tpu.train.ps_step import PSTrainStep

    rng = np.random.default_rng(1)
    w_true = rng.normal(size=64)
    idx = rng.integers(0, 64, size=(2048, 6)).astype(np.int32)
    val = np.abs(rng.normal(size=(2048, 6))).astype(np.float32)
    y = ((w_true[idx] * val).sum(-1) > 0).astype(np.float32)
    t = SparseTable(128, 1, mesh8, updater="adam", lr=0.02, init_scale=0.0)

    def loss_fn(dp, rows, batch):
        logits = jnp.sum(rows["w"][..., 0] * batch["val"], axis=-1)
        return jnp.mean(jnp.logaddexp(0.0, logits) - batch["y"] * logits)

    ps = PSTrainStep(loss_fn, sparse={"w": t},
                     key_fns={"w": lambda b: b["idx"]})
    batch = ps.shard_batch({"idx": idx, "val": val, "y": y})
    losses = [float(ps(batch)) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.85, (losses[0], losses[-1])
    assert int(np.asarray(t.steps).max()) == 40  # per-row t advanced

    ck = Checkpointer(str(tmp_path), {"w": t})
    ck.save(step=40)
    t2 = SparseTable(128, 1, mesh8, updater="adam", lr=0.02, init_scale=0.0)
    Checkpointer(str(tmp_path), {"w": t2}).restore()
    for a, b in [(t.emb, t2.emb), (t.m, t2.m), (t.v, t2.v),
                 (t.steps, t2.steps)]:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # push-path parity after restore: same push -> same state
    t.push(jnp.array([1, 2]), jnp.ones((2, 1)))
    t2.push(jnp.array([1, 2]), jnp.ones((2, 1)))
    np.testing.assert_allclose(np.asarray(t.emb), np.asarray(t2.emb),
                               rtol=1e-6)


def test_sparse_updater_mismatch_rejected(mesh8, tmp_path):
    from minips_tpu.ckpt.checkpoint import Checkpointer

    t_sgd = SparseTable(64, 2, mesh8, updater="sgd")
    Checkpointer(str(tmp_path), {"s": t_sgd}).save(step=1)
    t_adam = SparseTable(64, 2, mesh8, updater="adam")
    with pytest.raises(ValueError, match="different"):
        Checkpointer(str(tmp_path), {"s": t_adam}).restore()


def test_next_pow2():
    from minips_tpu.tables.sparse import next_pow2

    assert next_pow2(1) == 1
    assert next_pow2(1024) == 1024
    assert next_pow2(1025) == 2048
    assert next_pow2(6040) == 8192
    assert next_pow2(3706) == 4096
    assert next_pow2(3, floor=1 << 10) == 1024


def test_identity_mapping_exact_rows(mesh8):
    """identity=True: dense 0-based ids get their own row — exact per-key
    MapStorage semantics, no collisions (ADVICE round 1)."""
    t = SparseTable(128, 4, mesh8, updater="sgd", lr=1.0, init_scale=0.0,
                    identity=True)
    keys = jnp.arange(128)
    slots = np.asarray(t.slots_of(keys))
    np.testing.assert_array_equal(slots, np.arange(128))  # no collisions
    t.push(jnp.array([5]), jnp.ones((1, 4)))
    emb = np.asarray(t.emb)
    np.testing.assert_allclose(emb[5], -1.0)
    assert np.all(emb[np.arange(128) != 5] == 0.0)  # only row 5 touched


def test_hash_to_slots_np_matches_jax_twin():
    """hash_to_slots_np routes multiproc keys host-side; it must stay
    bit-identical to the jax version it mirrors (incl. negative ids and
    nonzero salts — both wrap through uint32 the same way)."""
    from minips_tpu.tables.sparse import hash_to_slots_np

    rng = np.random.default_rng(7)
    keys = rng.integers(-2**62, 2**62, size=4096)
    for slots in (1 << 10, 1 << 18):
        for salt in (0, 1, 2, 12345):
            got = hash_to_slots_np(keys, slots, salt)
            want = np.asarray(hash_to_slots(jnp.asarray(keys), slots, salt))
            np.testing.assert_array_equal(got, want.astype(np.int64))


def test_hash_to_slots_np_identity_matches_jax_twin():
    from minips_tpu.tables.sparse import hash_to_slots_np

    keys = np.array([0, 5, 127, 128, 300, -1])
    got = hash_to_slots_np(keys, 128, identity=True)
    want = np.asarray(hash_to_slots(jnp.asarray(keys), 128, identity=True))
    np.testing.assert_array_equal(got, want.astype(np.int64))


def test_collision_stats_identity_dense_ids_zero():
    """Identity mapping on a dense 0-based id space that fits the table =
    the reference's exact per-key MapStorage semantics: measured collision
    rate must be exactly 0 (VERDICT r2 #5 done-criterion)."""
    from minips_tpu.tables.sparse import collision_stats

    st = collision_stats(np.arange(1000), 1 << 10, identity=True)
    assert st["collision_rate"] == 0.0
    assert st["expected_rate"] == 0.0
    assert st["unique_keys"] == st["unique_slots"] == 1000
    assert st["sampled"] is False


def test_collision_stats_hashed_tracks_uniform_expectation():
    """The multiplicative hash's measured rate must sit near the uniform-
    hash expectation 1 - S(1-(1-1/S)^U)/U — a clumpy hash (or a sizing
    bug) shows up as measured >> expected."""
    from minips_tpu.tables.sparse import collision_stats

    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 40, size=20000)
    st = collision_stats(keys, 1 << 16, salt=2)
    assert 0 < st["collision_rate"] < 1
    assert st["expected_rate"] > 0
    # within 2x either way of the uniform model (binomial fluctuation at
    # U=20k is far tighter; 2x headroom keeps the test hash-seed-proof)
    assert st["expected_rate"] / 2 < st["collision_rate"] \
        < st["expected_rate"] * 2, st


def test_collision_stats_sampling_path():
    from minips_tpu.tables.sparse import collision_stats

    keys = np.arange(5000) % 700  # duplicates: U=700
    st = collision_stats(keys, 1 << 12, max_sample=1000)
    assert st["sampled"] is True
    assert st["unique_keys"] <= 700
