"""Hedged pull legs — the READ mitigation rung of the fail-slow ladder.

A pull leg aimed at a slow-but-alive owner rides to the pull deadline:
the owner's beats land (no death verdict), its reply eventually comes
(no loss), and meanwhile the requester's step — and through the SSP
gate, the fleet's — waits. The tail-tolerant answer is the classic
hedged request: once a leg has been outstanding past a hedge delay
(or its owner carries a fleet SLOW VERDICT, in which case immediately),
re-issue JUST THAT LEG to a replica holder of its blocks and let the
first admissible reply win.

Why the semantics are provably unchanged (docs/fault_tolerance.md):

- The hedge rides the serving plane's ``svP`` wire to a holder whose
  snapshot is stamped with the owner's ``global_min`` — the holder
  serves only when ``consistency.gate.admits(stamp, clk, s)``, the
  IDENTICAL predicate the owner-side park runs, so any reply that
  arrives (owner's or hedge's) satisfies the same staleness bound a
  sole owner reply would. First-ADMISSIBLE-reply-wins is therefore
  first-reply-wins; the loser is discarded by its wire rid.
- Hedges are issued from the pull-WAIT loop (the training/reader
  thread polling its own legs), never from the bus receive thread —
  a recv-thread send is the PR 7 deadlock class this plane must not
  reintroduce.
- Hedges are counted and budget-bounded (at most one hedge per leg,
  at most ``budget`` outstanding per table): a sick fleet degrades to
  the unhedged path, never to a hedge storm.
- Armed-but-idle is bitwise-equal to off (SLOW-IDLE): with no slow
  link, no leg outlives ``max(min_ms, factor x windowed pull p99)``
  and no hedge ever fires — the drill pins it.

Honest limit: a hedge needs a REPLICA HOLDER covering the leg's
blocks (the PR 6 serving plane). With the plane off, or the slow
owner's blocks cold/unreplicated, there is no second copy to read —
the leg waits exactly as before, and ``no_holder`` counts how often
that ceiling was hit.

Armed by ``MINIPS_HEDGE`` (off by default)::

    MINIPS_HEDGE="1"                       # every default
    MINIPS_HEDGE="delay_ms=0,factor=3,min_ms=25,budget=4"

``delay_ms=0`` (the default) derives the delay from the windowed pull
p99 (obs/window.py) at hedge time — the p99-derived delay of the
hedged-request literature; a fixed ``delay_ms`` pins it for drills.
Knob table: docs/api.md "Fail-slow plane".
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["HedgeConfig", "maybe_config"]


class HedgeConfig:
    """Parsed ``MINIPS_HEDGE`` knobs (``k=v`` comma list; the bare
    string ``"1"`` = every default)."""

    def __init__(self, *, delay_ms: float = 0.0, factor: float = 3.0,
                 min_ms: float = 25.0, budget: int = 4):
        if delay_ms < 0:
            raise ValueError("MINIPS_HEDGE: delay_ms must be >= 0 "
                             "(0 = derive from the windowed pull p99)")
        if factor < 1.0:
            raise ValueError("MINIPS_HEDGE: factor must be >= 1 (a "
                             "hedge below the p99 fires on healthy "
                             "tails)")
        if min_ms <= 0:
            raise ValueError("MINIPS_HEDGE: min_ms must be > 0 — the "
                             "floor is what keeps armed-idle loopback "
                             "runs hedge-free (SLOW-IDLE)")
        if budget < 1:
            raise ValueError("MINIPS_HEDGE: budget must be >= 1 "
                             "outstanding hedge")
        self.delay_ms = float(delay_ms)  # fixed hedge delay (0 = auto)
        self.factor = float(factor)      # auto: p99 multiple
        self.min_ms = float(min_ms)      # auto: absolute floor
        self.budget = int(budget)        # max outstanding hedges/table

    @classmethod
    def parse(cls, spec: str) -> "Optional[HedgeConfig]":
        """None = hedging OFF (empty/``"0"``); a config otherwise —
        unknown knobs and bad values refuse loudly (the shared
        MINIPS_* spec hygiene, fuzzer-pinned)."""
        spec = (spec or "").strip()
        if not spec or spec == "0":
            return None
        if spec in ("1", "on", "true"):
            return cls()
        kw: dict = {}
        casts = {"delay_ms": float, "factor": float, "min_ms": float,
                 "budget": int}
        for item in filter(None, (e.strip() for e in spec.split(","))):
            if "=" not in item:
                raise ValueError(
                    f"MINIPS_HEDGE: expected k=v, got {item!r}")
            k, _, v = item.partition("=")
            k = k.strip()
            if k not in casts:
                raise ValueError(f"MINIPS_HEDGE: unknown knob {k!r}")
            try:
                kw[k] = casts[k](v)
            except ValueError as e:
                raise ValueError(
                    f"MINIPS_HEDGE: bad value for {k}: {v!r}") from e
        return cls(**kw)


def maybe_config(spec: Optional[str] = None) -> "Optional[HedgeConfig]":
    """Config from an explicit spec or ``$MINIPS_HEDGE`` (explicit
    wins); None when hedging is off."""
    if spec is None:
        spec = os.environ.get("MINIPS_HEDGE", "")
    return HedgeConfig.parse(spec)
