"""Scripted Add/Get/Clock admission tests — the reference's most-tested
surface, tested the same way (SURVEY.md §4): pure logic, no devices."""

import threading
import time

import pytest

from minips_tpu.consistency import ASP, BSP, SSP, PendingBuffer, ProgressTracker, make_controller


# ------------------------------------------------------------- ProgressTracker
def test_tracker_advance_and_changed_min():
    t = ProgressTracker(3)
    assert t.min_clock == 0 and t.skew == 0
    assert t.advance(0) is None          # clocks [1,0,0] — min unchanged
    assert t.advance(1) is None          # [1,1,0]
    assert t.skew == 1
    assert t.advance(2) == 1             # [1,1,1] — min moved to 1
    assert t.advance(2) is None          # [1,1,2]
    assert t.max_clock == 2


def test_pending_buffer_fifo_by_clock():
    b = PendingBuffer()
    b.park(2, "a")
    b.park(1, "b")
    b.park(2, "c")
    assert b.num_parked == 3
    assert b.pop_ready(0) == []
    assert b.pop_ready(1) == ["b"]
    assert b.pop_ready(2) == ["a", "c"]
    assert b.num_parked == 0


# ----------------------------------------------------------------- controllers
def test_bsp_admission_matrix():
    c = BSP(2)
    # both at clock 0: both admitted
    assert c.admit(0) and c.admit(1)
    c.clock(0)  # worker0 -> 1
    # worker0 must wait for worker1 (min=0 < 1-0)
    assert not c.admit(0)
    assert c.admit(1)
    c.clock(1)
    assert c.admit(0) and c.admit(1)


def test_ssp_staleness_window():
    c = SSP(2, staleness=2)
    for _ in range(2):
        c.clock(0)
    assert c.admit(0)            # my=2, min=0, 0 >= 2-2
    c.clock(0)                   # my=3
    assert not c.admit(0)        # 0 < 3-2
    c.clock(1)                   # min=1
    assert c.admit(0)
    assert c.skew == 2


def test_asp_never_blocks():
    c = ASP(2, sync_every=0)
    for _ in range(100):
        c.clock(0)
    assert c.admit(0) and c.admit(1)
    assert not c.should_sync(0)


def test_asp_sync_every():
    c = ASP(2, sync_every=4)
    assert not c.should_sync(0)  # clock 0
    for _ in range(4):
        c.clock(0)
    assert c.should_sync(0)      # clock 4 % 4 == 0
    c.clock(0)
    assert not c.should_sync(0)


def test_blocked_pull_wakes_on_clock():
    """The AppBlocker rendezvous (SURVEY.md §2): a BSP worker parked on a
    pull is woken when the laggard clocks."""
    c = BSP(2)
    c.clock(0)  # worker0 ahead
    admitted = []

    def waiter():
        admitted.append(c.wait_until_admitted(0, timeout=5.0))

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.05)
    assert admitted == []        # still parked
    c.clock(1)                   # laggard catches up -> min moves
    th.join(timeout=5.0)
    assert admitted == [True]


def test_stop_unblocks_waiters():
    c = SSP(2, staleness=0)
    c.clock(0)
    res = []
    th = threading.Thread(target=lambda: res.append(
        c.wait_until_admitted(0, timeout=5.0)))
    th.start()
    time.sleep(0.05)
    c.stop()
    th.join(timeout=5.0)
    assert res == [False]


def test_make_controller_kinds():
    assert make_controller("bsp", 2).kind == "bsp"
    assert make_controller("ssp", 2, staleness=3).staleness == 3
    assert make_controller("asp", 2).kind == "asp"
    with pytest.raises(ValueError):
        make_controller("nope", 2)


def test_ssp_state_roundtrip():
    c = SSP(3, staleness=4)
    c.clock(0); c.clock(0); c.clock(1)
    state = c.state_dict()
    c2 = SSP(3, staleness=4)
    c2.load_state_dict(state)
    assert c2.tracker.snapshot() == [2, 1, 0]
