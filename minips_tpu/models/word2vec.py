"""Word2Vec skip-gram with negative sampling — the reference's w2v workload
(BASELINE.json:11: "Word2Vec skip-gram on enwiki, negative sampling, async
push").

Input ("center") and output ("context") embeddings live in two SparseTables
keyed by vocab id. A training example is (center, positive context, K
negatives); SGNS loss = log σ(u·v⁺) + Σ log σ(−u·v⁻). Negative sampling is
done host-side from a unigram^0.75 table (the reference samples host-side
too); the device sees fixed-shape [B], [B], [B, K] id arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sgns_loss(center_rows, pos_rows, neg_rows):
    """center [B, k], pos [B, k], neg [B, K, k] → scalar SGNS loss."""
    pos_score = jnp.sum(center_rows * pos_rows, axis=-1)              # [B]
    neg_score = jnp.einsum("bk,bnk->bn", center_rows, neg_rows)       # [B, K]
    pos_loss = jnp.logaddexp(0.0, -pos_score)
    neg_loss = jnp.sum(jnp.logaddexp(0.0, neg_score), axis=-1)
    return jnp.mean(pos_loss + neg_loss)


def grad_fn(center_rows, pos_rows, neg_rows):
    def f(rows):
        return sgns_loss(*rows)
    l, (gc, gp, gn) = jax.value_and_grad(f)((center_rows, pos_rows, neg_rows))
    return l, gc, gp, gn


def subsample_frequent(ids: np.ndarray, counts: np.ndarray,
                       t: float = 1e-5, seed: int = 0) -> np.ndarray:
    """Classic w2v frequent-word subsampling: token occurrences of word w
    are KEPT with probability ``min(1, sqrt(t / f(w)))`` where ``f`` is
    w's relative frequency — very frequent words ("the") are mostly
    dropped, rare words always kept, which both speeds training and
    improves rare-word vectors. ``t`` is the classic 1e-5 for real
    corpora (1e-3..1e-4 for small ones); the returned stream is the
    filtered ``ids``."""
    if t <= 0:
        return ids
    counts = np.asarray(counts, np.float64)
    freq = counts / counts.sum()
    keep_p = np.minimum(1.0, np.sqrt(t / np.maximum(freq, 1e-300)))
    rng = np.random.default_rng(seed)
    kept = ids[rng.random(ids.shape[0]) < keep_p[ids]]
    if kept.size == 0:
        raise ValueError(
            f"subsample t={t} dropped the whole stream; raise t")
    return kept


class UnigramSampler:
    """Host-side negative sampler over unigram counts^0.75, via a Walker
    alias table: O(vocab) setup, O(1) per draw — ``np.random.choice(p=...)``
    is O(vocab) per call, which at enwiki-scale vocab makes the host
    sampler the bottleneck of the whole input pipeline."""

    def __init__(self, counts: np.ndarray, power: float = 0.75, seed: int = 0):
        p = np.asarray(counts, np.float64) ** power
        self._p = p / p.sum()
        self._rng = np.random.default_rng(seed)
        n = len(self._p)
        scaled = self._p * n
        self._prob = np.ones(n)
        self._alias = np.arange(n)
        small = [i for i in range(n) if scaled[i] < 1.0]
        large = [i for i in range(n) if scaled[i] >= 1.0]
        while small and large:
            s, l = small.pop(), large.pop()
            self._prob[s] = scaled[s]
            self._alias[s] = l
            scaled[l] -= 1.0 - scaled[s]
            (small if scaled[l] < 1.0 else large).append(l)
        # leftovers are 1.0 within float error; keep prob=1 (self-alias)

    def sample(self, shape) -> np.ndarray:
        idx = self._rng.integers(0, len(self._p), size=shape)
        accept = self._rng.random(np.shape(idx)) < self._prob[idx]
        return np.where(accept, idx, self._alias[idx])
